"""Postmortem flight recorder — a ring buffer of step records + crash dumps.

A NaN at step 40k used to leave no record of which layer, which host, or
what the preceding steps looked like: the process died (or the loss curve
flat-lined) and the evidence died with it.  The recorder keeps the last N
structured step records (metrics, per-group health stats, loss-scale state,
span durations) on the host and, when something trips — a non-finite loss,
an overflow streak, an uncaught exception, or an explicit
``engine.dump_postmortem()`` — writes a timestamped bundle:

    <dump_dir>/<YYYYmmdd-HHMMSS>-step<N>-<reason>/
        records.jsonl    # the ring buffer, oldest record first
        meta.json        # reason, trigger step, span summary, fleet info
        config.json      # the resolved engine config
        snapshot.prom    # Prometheus text exposition of every registry
        trace.json       # Chrome-trace spans (when the tracer is on)
        env.txt          # environment report (ds_report analog)

``python -m deepspeed_tpu.telemetry.postmortem <dir>`` summarizes a bundle.

Dump-once semantics: each automatic trigger reason fires at most once per
recorder (a NaN loss persists for every remaining step — one bundle is
evidence, five hundred are a disk-filler); explicit dumps always write.
Bundle writers are registered callbacks so the recorder never imports the
exporter/config machinery itself, and a writer failure degrades to a
warning — the postmortem path must never be the thing that kills training.
"""

from __future__ import annotations

import json
import os
import sys
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

DUMPS = "postmortem_dumps_total"


class FlightRecorder:
    def __init__(self, capacity: int = 64,
                 dump_dir: str = "./telemetry/postmortem",
                 write_files: bool = True, registry=None):
        self.capacity = int(capacity)
        self.records: deque = deque(maxlen=self.capacity)
        self.dump_dir = dump_dir
        # multi-host: only process 0 writes bundles (same contract as the
        # snapshot exporter); every process still keeps its buffer
        self.write_files = bool(write_files)
        self.registry = registry
        self.dumps: List[str] = []
        self._dumped_reasons: set = set()
        # name -> fn(bundle_dir): extra bundle artifacts (config, prom, ...)
        self._writers: Dict[str, Callable[[str], None]] = {}
        self._meta_fn: Optional[Callable[[], dict]] = None

    # ------------------------------------------------------------- feeding

    def record(self, rec: dict) -> None:
        self.records.append(rec)

    def add_bundle_writer(self, name: str,
                          fn: Callable[[str], None]) -> None:
        self._writers[name] = fn

    def set_meta_fn(self, fn: Callable[[], dict]) -> None:
        self._meta_fn = fn

    # ------------------------------------------------------------- dumping

    def dump(self, reason: str = "manual", note: Optional[str] = None,
             force: Optional[bool] = None) -> Optional[str]:
        """Write the bundle; returns its directory (None when skipped).

        Automatic reasons are one-shot per recorder; ``reason="manual"`` (or
        ``force=True``) always writes.
        """
        if force is None:
            force = reason == "manual"
        if not force and reason in self._dumped_reasons:
            return None
        if not self.write_files:
            # non-writing process (rank != 0): the trigger is still handled
            # (one-shot) and counted, there is just no local bundle
            self._dumped_reasons.add(reason)
            self._count(reason)
            return None
        last_step = self.records[-1].get("step", 0) if self.records else 0
        base = f"{time.strftime('%Y%m%d-%H%M%S')}-step{last_step}-{reason}"
        out = os.path.join(self.dump_dir, base)
        n = 1
        while os.path.exists(out):       # two dumps in one second
            out = os.path.join(self.dump_dir, f"{base}.{n}")
            n += 1
        try:
            os.makedirs(out, exist_ok=True)
            with open(os.path.join(out, "records.jsonl"), "w") as f:
                for rec in self.records:
                    f.write(json.dumps(rec, default=_json_default) + "\n")
            meta = {
                "reason": reason,
                "note": note,
                "unix_time": time.time(),
                "num_records": len(self.records),
                "last_step": last_step,
            }
            if self._meta_fn is not None:
                try:
                    meta.update(self._meta_fn() or {})
                except Exception as e:  # noqa: BLE001
                    meta["meta_error"] = repr(e)
            with open(os.path.join(out, "meta.json"), "w") as f:
                json.dump(meta, f, indent=1, sort_keys=True,
                          default=_json_default)
        except Exception as e:  # noqa: BLE001 — never kill training
            # the reason is NOT marked handled: a transient write failure
            # (disk full, permissions) must not suppress every later dump
            # for this reason, and the counter must not report a bundle
            # that does not exist
            logger.warning(f"flight recorder: bundle write failed: {e!r}")
            return None
        self._dumped_reasons.add(reason)
        self._count(reason)
        for name, fn in self._writers.items():
            try:
                fn(out)
            except Exception as e:  # noqa: BLE001
                logger.warning(f"flight recorder: bundle artifact "
                               f"'{name}' failed: {e!r}")
        self.dumps.append(out)
        logger.warning(f"postmortem bundle ({reason}) written to {out} — "
                       f"summarize with: python -m "
                       f"deepspeed_tpu.telemetry.postmortem {out}")
        return out

    def _count(self, reason: str) -> None:
        if self.registry is not None:
            self.registry.counter(
                DUMPS, "postmortem bundles written, per trigger reason").inc(
                    1, reason=reason)


def _json_default(obj):
    """Last-resort JSON encoder: numpy scalars → python, else repr."""
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:  # noqa: BLE001
            pass
    return repr(obj)


# ---------------------------------------------------------------- crash hook

# Recorders register weakly: the hook must not keep a dead engine (and its
# device arrays) alive for the rest of the process.
_crash_recorders: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()
_prev_excepthook = None


def _crash_excepthook(exc_type, exc_value, exc_tb) -> None:
    """Dump every live recorder, then chain to the previous hook — the
    traceback the user sees is unchanged; a bundle now sits next to it."""
    for rec in list(_crash_recorders):
        try:
            rec.dump("exception",
                     note=f"{exc_type.__name__}: {exc_value}")
        except Exception:  # noqa: BLE001 — the original traceback wins
            pass
    (_prev_excepthook or sys.__excepthook__)(exc_type, exc_value, exc_tb)


_hook_installed = False


def install_crash_handler(recorder: FlightRecorder) -> None:
    """Register ``recorder`` for dump-on-uncaught-exception.  The process
    excepthook is wrapped ONCE per process and chains to whatever was
    installed before.  Later installs only add the recorder: if another
    library has since wrapped sys.excepthook (and chains to us), re-wrapping
    would capture that wrapper as our "previous" hook and crash time would
    recurse wrapper -> us -> wrapper forever."""
    global _prev_excepthook, _hook_installed
    _crash_recorders.add(recorder)
    if not _hook_installed:
        _prev_excepthook = sys.excepthook
        sys.excepthook = _crash_excepthook
        _hook_installed = True
