"""In-graph numerics health statistics + anomaly rules.

T3-style fine-grained attribution (arXiv 2401.16677) and ZeRO++-style
precision tricks (arXiv 2306.10209) both need per-group numerics visibility:
a NaN at step 40k is useless information unless the record says WHICH module
group went non-finite and what the preceding steps looked like.  This module
provides the device half of that story:

- ``compute_group_health`` runs INSIDE the jitted train step and reduces the
  grad/param trees to a small per-module-group pytree of scalars — grad/param
  global norms, NaN/Inf element counts, update-to-param ratio.  It is traced
  once with the step program (one extra output, no recompile) and costs a few
  bandwidth-bound passes over the parameters.
- ``AnomalyDetector`` runs on the HOST over the fetched scalars and fires
  one-shot watchdog-style warnings (loss spike z-score, grad-norm explosion,
  loss-scale collapse) plus a labeled counter for the snapshot exporter.

The host ring buffer + dump machinery lives in flight_recorder.py; the
engine-facing orchestration is ``StepTelemetry.health_step``.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

ANOMALIES = "numerics_anomalies_total"

# metrics whose per-group values are element counts, not norms
_COUNT_KEYS = ("grad_nan", "grad_inf")


def _path_segment(entry) -> str:
    """One pytree path entry → its plain string key."""
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def group_name(path, depth: int = 2) -> str:
    """Module-group label for a leaf path: the first ``depth`` segments,
    skipping a leading flax collection key ("params")."""
    segs = [_path_segment(e) for e in path]
    if segs and segs[0] == "params":
        segs = segs[1:]
    return "/".join(segs[:depth]) or "<root>"


def group_names(tree, depth: int = 2) -> List[str]:
    """The (sorted) group labels ``compute_group_health`` will emit."""
    import jax
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return sorted({group_name(p, depth) for p, _ in flat})


def compute_group_health(params, grads, new_params=None, *,
                         depth: int = 2) -> Dict[str, Dict[str, Any]]:
    """Per-module-group numerics stats, computed in-graph.

    Returns ``{group: {grad_norm, param_norm, grad_nan, grad_inf
    [, update_ratio]}}`` — all 0-d jax arrays.  ``update_ratio`` (the
    reference's effective-update health signal, ||Δp|| / ||p||) is emitted
    only when ``new_params`` is given; on overflow-skipped steps Δp == 0 so
    the ratio reads 0 there.  Group labels are static strings fixed at trace
    time, so the output pytree structure never changes between steps.
    """
    import jax
    import jax.numpy as jnp

    flat_g = jax.tree_util.tree_flatten_with_path(grads)[0]
    p_leaves = jax.tree_util.tree_leaves(params)
    q_leaves = (jax.tree_util.tree_leaves(new_params)
                if new_params is not None else [None] * len(p_leaves))
    acc: Dict[str, Dict[str, Any]] = {}
    for (path, g), p, q in zip(flat_g, p_leaves, q_leaves):
        name = group_name(path, depth)
        a = acc.setdefault(name, {
            "g_sq": jnp.float32(0.0), "p_sq": jnp.float32(0.0),
            "d_sq": jnp.float32(0.0), "nan": jnp.int32(0),
            "inf": jnp.int32(0)})
        # int params get float0 grads from jax.grad — nothing to measure
        if (hasattr(g, "dtype") and hasattr(g, "ndim")
                and jnp.issubdtype(g.dtype, jnp.floating)):
            g32 = g.astype(jnp.float32)
            a["g_sq"] = a["g_sq"] + jnp.sum(g32 * g32)
            a["nan"] = a["nan"] + jnp.sum(jnp.isnan(g32)).astype(jnp.int32)
            a["inf"] = a["inf"] + jnp.sum(jnp.isinf(g32)).astype(jnp.int32)
        if (hasattr(p, "dtype")
                and jnp.issubdtype(p.dtype, jnp.floating)):
            p32 = p.astype(jnp.float32)
            a["p_sq"] = a["p_sq"] + jnp.sum(p32 * p32)
            if q is not None:
                d = q.astype(jnp.float32) - p32
                a["d_sq"] = a["d_sq"] + jnp.sum(d * d)
    out: Dict[str, Dict[str, Any]] = {}
    for name, a in acc.items():
        p_norm = jnp.sqrt(a["p_sq"])
        rec = {
            "grad_norm": jnp.sqrt(a["g_sq"]),
            "param_norm": p_norm,
            "grad_nan": a["nan"],
            "grad_inf": a["inf"],
        }
        if new_params is not None:
            rec["update_ratio"] = jnp.sqrt(a["d_sq"]) / (p_norm + 1e-12)
        out[name] = rec
    return out


def to_python(health) -> Dict[str, Dict[str, float]]:
    """Host (device_get) health pytree → plain float/int dict (JSON-safe)."""
    if not health:
        return {}
    out: Dict[str, Dict[str, float]] = {}
    for group, stats in health.items():
        rec = {}
        for key, val in stats.items():
            rec[key] = int(val) if key in _COUNT_KEYS else float(val)
        out[group] = rec
    return out


def flatten_health(health: Dict[str, Dict[str, float]],
                   prefix: str = "") -> Dict[str, float]:
    """{group: {stat: v}} → {"group/stat": v} — the flat scalar form the
    cross-host aggregation helper consumes."""
    flat: Dict[str, float] = {}
    for group, stats in (health or {}).items():
        for key, val in stats.items():
            flat[f"{prefix}{group}/{key}"] = float(val)
    return flat


class AnomalyDetector:
    """Rolling-window anomaly rules over the per-step host scalars.

    Mirrors the recompile watchdog's disclosure contract: every detection
    bumps the labeled ``numerics_anomalies_total{rule=...}`` counter, but the
    log WARNING fires once per rule per run (a diverging run would otherwise
    print the same line every step).  ``last_warning`` keeps the latest text
    for tests and callers that swallow logs.
    """

    RULES = ("loss_spike", "grad_norm_explosion", "loss_scale_collapse")

    def __init__(self, window: int = 32, loss_spike_zscore: float = 6.0,
                 grad_norm_factor: float = 10.0,
                 scale_collapse_factor: float = 16.0,
                 min_history: int = 8, registry=None,
                 emit_warnings: bool = True):
        self.loss_spike_zscore = float(loss_spike_zscore)
        self.grad_norm_factor = float(grad_norm_factor)
        self.scale_collapse_factor = float(scale_collapse_factor)
        self.min_history = int(min_history)
        self.registry = registry
        self.emit_warnings = emit_warnings
        self._losses: deque = deque(maxlen=int(window))
        self._gnorms: deque = deque(maxlen=int(window))
        self._scales: deque = deque(maxlen=int(window))
        self.warned: set = set()
        self.last_warning: Optional[str] = None

    def observe(self, step: int, loss: float, grad_norm: float,
                loss_scale: float) -> List[str]:
        """Feed one step's scalars; returns the rules that fired."""
        fired: List[str] = []
        if math.isfinite(loss) and len(self._losses) >= self.min_history:
            n = len(self._losses)
            mean = sum(self._losses) / n
            var = sum((x - mean) ** 2 for x in self._losses) / n
            # std floor: a perfectly flat window would flag any wiggle
            std = max(math.sqrt(var), 1e-3 * abs(mean) + 1e-8)
            z = (loss - mean) / std
            if z > self.loss_spike_zscore:
                fired.append("loss_spike")
                self._warn("loss_spike", step,
                           f"loss {loss:.6g} is {z:.1f} sigma above the "
                           f"rolling mean {mean:.6g} (window {n})")
        if (math.isfinite(grad_norm) and grad_norm > 0
                and len(self._gnorms) >= self.min_history):
            mean_g = sum(self._gnorms) / len(self._gnorms)
            if mean_g > 0 and grad_norm > self.grad_norm_factor * mean_g:
                fired.append("grad_norm_explosion")
                self._warn("grad_norm_explosion", step,
                           f"grad norm {grad_norm:.6g} exceeds "
                           f"{self.grad_norm_factor:g}x the rolling mean "
                           f"{mean_g:.6g}")
        if (self._scales and loss_scale > 0
                and loss_scale * self.scale_collapse_factor
                <= max(self._scales)):
            fired.append("loss_scale_collapse")
            self._warn("loss_scale_collapse", step,
                       f"loss scale collapsed to {loss_scale:g} from a "
                       f"recent peak of {max(self._scales):g} — persistent "
                       f"overflows are eating the dynamic range")
        # append AFTER the checks so a step never masks its own anomaly
        if math.isfinite(loss):
            self._losses.append(float(loss))
        if math.isfinite(grad_norm) and grad_norm > 0:
            self._gnorms.append(float(grad_norm))
        if loss_scale > 0:
            self._scales.append(float(loss_scale))
        if fired and self.registry is not None:
            c = self.registry.counter(
                ANOMALIES, "numerics anomaly detections, per rule "
                "(loss_spike / grad_norm_explosion / loss_scale_collapse)")
            for rule in fired:
                c.inc(1, rule=rule)
        return fired

    def _warn(self, rule: str, step: int, detail: str) -> None:
        msg = (f"NUMERICS anomaly '{rule}' at step {step}: {detail}.  "
               f"Further '{rule}' detections are counted "
               f"({ANOMALIES}{{rule={rule}}}) but not re-warned.")
        self.last_warning = msg
        if rule in self.warned:
            return
        self.warned.add(rule)
        if self.emit_warnings:
            logger.warning(msg)
