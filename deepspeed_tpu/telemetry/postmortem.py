"""Postmortem bundle summarizer — ``python -m deepspeed_tpu.telemetry.postmortem <dir>``.

Reads a flight-recorder bundle (flight_recorder.py) and prints the triage
view a NaN hunt starts from: the trigger, the last recorded steps' loss /
grad-norm / loss-scale trajectory, which module groups carried non-finite
gradients, the worst per-group norms, anomaly detections, and which bundle
artifacts are present for deeper digging.  Pure stdlib + file reads — it
must run on a machine with no accelerator (or no jax) at all.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import List, Optional


def _load_records(bundle_dir: str) -> List[dict]:
    path = os.path.join(bundle_dir, "records.jsonl")
    records = []
    if not os.path.exists(path):
        return records
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue                     # a torn line must not kill triage
    return records


def _fmt(v, nd: int = 5) -> str:
    if v is None:
        return "-"
    try:
        v = float(v)
    except (TypeError, ValueError):
        return str(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return f"{v:.{nd}g}"


def summarize(bundle_dir: str, tail: int = 8) -> str:
    lines: List[str] = []
    add = lines.append
    add("=" * 72)
    add(f"postmortem bundle: {bundle_dir}")
    add("=" * 72)

    meta = {}
    meta_path = os.path.join(bundle_dir, "meta.json")
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            add(f"meta.json unreadable: {e!r}")
    if meta:
        add(f"trigger ........... {meta.get('reason', '?')}"
            + (f" ({meta['note']})" if meta.get("note") else ""))
        add(f"last step ......... {meta.get('last_step', '?')}")
        add(f"records ........... {meta.get('num_records', '?')}")
        if "process_index" in meta:
            add(f"process ........... {meta['process_index']}")

    records = _load_records(bundle_dir)
    if not records:
        add("records.jsonl ..... MISSING or empty — nothing was recorded "
            "before the trigger")
    else:
        add("")
        add(f"last {min(tail, len(records))} of {len(records)} step records "
            f"(loss / grad_norm / loss_scale / skipped / anomalies):")
        for rec in records[-tail:]:
            anom = ",".join(rec.get("anomalies") or []) or "-"
            add(f"  step {rec.get('step', '?'):>8}: "
                f"loss={_fmt(rec.get('loss'))} "
                f"gnorm={_fmt(rec.get('grad_norm'))} "
                f"scale={_fmt(rec.get('loss_scale'), 6)} "
                f"skipped={rec.get('skipped_steps', '-')} "
                f"anomalies={anom}")

        # ---- per-group attribution across the whole buffer ----
        nonfinite: dict = {}
        worst_norm: dict = {}
        for rec in records:
            for group, stats in (rec.get("health") or {}).items():
                bad = (int(stats.get("grad_nan", 0) or 0)
                       + int(stats.get("grad_inf", 0) or 0))
                if bad:
                    nonfinite[group] = nonfinite.get(group, 0) + bad
                gn = stats.get("grad_norm")
                if gn is not None and math.isfinite(float(gn)):
                    worst_norm[group] = max(worst_norm.get(group, 0.0),
                                            float(gn))
        add("")
        if nonfinite:
            add("module groups with non-finite gradient elements "
                "(summed over the buffer):")
            for group, count in sorted(nonfinite.items(),
                                       key=lambda kv: -kv[1]):
                add(f"  {group:<40} {count}")
        else:
            add("no non-finite gradient elements recorded per group "
                "(health stats absent or clean)")
        if worst_norm:
            add("largest finite per-group grad norms seen:")
            top = sorted(worst_norm.items(), key=lambda kv: -kv[1])[:5]
            for group, norm in top:
                add(f"  {group:<40} {_fmt(norm)}")

        fired: dict = {}
        for rec in records:
            for rule in rec.get("anomalies") or []:
                fired[rule] = fired.get(rule, 0) + 1
        if fired:
            add("anomaly detections in the buffer: "
                + ", ".join(f"{r}x{c}" for r, c in sorted(fired.items())))

        fleet = records[-1].get("fleet")
        if fleet:
            add("")
            add("fleet aggregates on the trigger record (min/mean/max, "
                "tripping process):")
            for key in sorted(fleet)[:12]:
                agg = fleet[key]
                add(f"  {key:<44} {_fmt(agg.get('min'))} / "
                    f"{_fmt(agg.get('mean'))} / {_fmt(agg.get('max'))} "
                    f"(p{agg.get('argmax_process', '?')})")

    add("")
    add("bundle artifacts:")
    for name, hint in (("records.jsonl", "step records"),
                       ("meta.json", "trigger metadata"),
                       ("config.json", "resolved engine config"),
                       ("snapshot.prom", "Prometheus metric snapshot"),
                       ("trace.json", "Chrome trace (ui.perfetto.dev)"),
                       ("env.txt", "environment report")):
        present = os.path.exists(os.path.join(bundle_dir, name))
        add(f"  [{'x' if present else ' '}] {name:<16} {hint}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.postmortem",
        description="Summarize a flight-recorder postmortem bundle")
    ap.add_argument("bundle", help="bundle directory (or a parent "
                    "postmortem/ dir — the newest bundle is picked)")
    ap.add_argument("--tail", type=int, default=8,
                    help="step records to print (default 8)")
    args = ap.parse_args(argv)
    bundle = args.bundle
    if not os.path.isdir(bundle):
        print(f"error: {bundle} is not a directory", file=sys.stderr)
        return 2
    if not os.path.exists(os.path.join(bundle, "records.jsonl")):
        # a parent dir full of bundles: pick the newest one
        subs = sorted(
            d for d in os.listdir(bundle)
            if os.path.exists(os.path.join(bundle, d, "records.jsonl")))
        if subs:
            bundle = os.path.join(bundle, subs[-1])
    print(summarize(bundle, tail=args.tail))
    return 0


if __name__ == "__main__":
    sys.exit(main())
