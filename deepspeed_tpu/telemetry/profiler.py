"""Step-time attribution: decompose MEASURED wall time into an MFU budget.

The roofline (telemetry/roofline.py) says how fast a step COULD run; this
module says where the measured step time actually WENT, using only signals
the telemetry layer already exports — no new instrumentation on the hot
path:

- **compute**   — the roofline compute floor, ``flops / peak_flops``
  (``xla_cost_flops`` × the accelerator spec).  By construction
  ``compute_ms / measured_ms`` IS the achieved MFU.
- **hbm_bound** — extra time over the compute floor because some op
  classes are HBM-bandwidth-bound (roofline attainable time minus its
  compute-only floor).
- **exposed_comm** — collective wall time NOT hidden under compute:
  ``comm_total_ms × collective_exposed_ratio`` (the profiled per-
  collective latency from ``engine.profile_comms`` × the compiled-HLO
  overlap walk's bytes-weighted exposed fraction — the same product
  bench.py has reported as ``comm_exposed_ms`` since PR 4).
- **host_gap**  — host-side phase time serialized with the device: the
  per-step means of the ``batch_input`` / ``host_to_device`` /
  ``step_bookkeeping`` spans (zero when the async input pipeline or
  trace-off benching hides them — then the host gap shows up in the
  residual instead).
- **dispatch_floor** — the residual: measured − everything above.  On the
  relay this is dominated by the per-dispatch floor (~0.8 ms/call, ~210 µs
  per scan iteration — docs/RELAY_LOG_r05.md); the r05 "regressions"
  (wq 0.91×, spec 0.77×) were exactly this term, misread as algorithm
  failures for a full relay cycle because nothing computed it.

The terms plus achieved compute sum to the measured step time by
construction (the residual closes the budget); a NEGATIVE residual means
the model over-attributed (e.g. double-counted comm that was actually
hidden) and is reported as ``overattributed_ms`` instead of being
silently clamped away.

Gauges (per jitted function): ``mfu_achieved{fn}`` and
``mfu_lost{fn, cause=exposed_comm|hbm_bound|host_gap|dispatch_floor}`` —
each cause's share of the step normalized so achieved + lost sums to 1.
``scripts/perf_report.py`` renders the same budget as a report.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# host-side span phases serialized with the device (dispatch and
# device_complete overlap device execution and are NOT budget terms)
HOST_GAP_SPANS = ("batch_input", "host_to_device", "step_bookkeeping")

LOST_CAUSES = ("exposed_comm", "hbm_bound", "host_gap", "dispatch_floor")


def _gauge_value(snapshot: dict, name: str, **labels) -> Optional[float]:
    """Read one gauge sample out of a snapshot dict (exporter schema)."""
    metric = snapshot.get("gauges", {}).get(name)
    if not metric:
        return None
    for s in metric.get("samples", []):
        slab = s.get("labels") or {}
        if all(slab.get(k) == v for k, v in labels.items()):
            return float(s["value"])
    return None


def span_mean_ms(snapshot: dict, name: str) -> float:
    """Per-occurrence mean of one span phase from the snapshot's span
    summary (0 when the phase was never recorded — trace off)."""
    spans = snapshot.get("spans") or {}
    rec = spans.get(name)
    return float(rec.get("mean_ms", 0.0)) if rec else 0.0


def step_time_budget(snapshot: dict, *, step_ms: float,
                     fn: str = "train_batch",
                     comm_total_ms: Optional[float] = None,
                     peak_flops: Optional[float] = None,
                     registry=None) -> Dict[str, object]:
    """Decompose one measured step time against a telemetry snapshot.

    ``snapshot`` is the exporter's dict (``engine.telemetry.export()`` /
    ``snapshot.json``); ``step_ms`` the measured wall time per step;
    ``comm_total_ms`` the profiled per-step collective latency
    (``engine.profile_comms`` summed — None degrades exposed_comm to 0
    with a disclosure).  ``registry`` (a MetricRegistry) receives the
    ``mfu_achieved`` / ``mfu_lost`` gauges when given.
    """
    exe = (snapshot.get("executables") or {}).get(fn, {})
    notes: List[str] = []

    flops = float((exe.get("cost_analysis") or {}).get("flops", 0.0))
    if peak_flops is None:
        spec = (exe.get("roofline") or {}).get("spec")
        if spec:
            peak_flops = float(spec["flops"])
        else:
            from deepspeed_tpu.telemetry.roofline import detect_peak_spec
            peak_flops = float(detect_peak_spec()["flops"])
            notes.append("peak_flops detected from attached device "
                         "(no roofline spec in snapshot)")
    compute_ms = flops / peak_flops * 1e3 if flops else 0.0
    if not flops:
        notes.append(f"no cost_analysis flops for fn={fn!r}: compute term "
                     "is 0 (hlo_stats off?)")

    # hbm_bound: the roofline attainable time above the pure compute floor
    roof = exe.get("roofline") or {}
    hbm_bound_ms = 0.0
    if roof:
        # per HBM-bound class: its time over its own compute floor
        hbm_bound_ms = sum(
            max(0.0, c["attainable_ms"] - c["t_compute_ms"])
            for c in roof.get("classes", {}).values()
            if c.get("bound") == "hbm")
    else:
        notes.append("no roofline in snapshot: hbm_bound term is 0")

    exposed_ratio = _gauge_value(snapshot, "collective_exposed_ratio",
                                 fn=fn)
    exposed_comm_ms = 0.0
    if comm_total_ms is not None and exposed_ratio is not None:
        exposed_comm_ms = float(comm_total_ms) * float(exposed_ratio)
    elif comm_total_ms is None:
        notes.append("no profiled comm_total_ms: exposed_comm term is 0")
    elif exposed_ratio is None:
        notes.append(f"collective_exposed_ratio{{fn={fn!r}}} not set: "
                     "exposed_comm term is 0")

    host_gap_ms = sum(span_mean_ms(snapshot, s) for s in HOST_GAP_SPANS)
    if not (snapshot.get("spans") or {}):
        notes.append("no span summary in snapshot (trace off): host work "
                     "lands in the dispatch_floor residual")

    attributed = compute_ms + hbm_bound_ms + exposed_comm_ms + host_gap_ms
    residual = step_ms - attributed
    dispatch_floor_ms = max(0.0, residual)
    overattributed_ms = max(0.0, -residual)
    if overattributed_ms:
        notes.append(f"terms exceed measured step time by "
                     f"{overattributed_ms:.3f} ms — some attributed time "
                     "is actually overlapped (budget floor, not a sum)")

    mfu_achieved = compute_ms / step_ms if step_ms else 0.0
    lost_ms = {"exposed_comm": exposed_comm_ms, "hbm_bound": hbm_bound_ms,
               "host_gap": host_gap_ms,
               "dispatch_floor": dispatch_floor_ms}
    mfu_lost = {cause: (ms / step_ms if step_ms else 0.0)
                for cause, ms in lost_ms.items()}

    if registry is not None:
        registry.gauge(
            "mfu_achieved",
            "achieved model flops utilization of the measured step "
            "(roofline compute floor / measured wall time), per jitted "
            "function").set(mfu_achieved, fn=fn)
        g = registry.gauge(
            "mfu_lost",
            "fraction of the measured step time lost to each cause "
            "(exposed_comm / hbm_bound / host_gap / dispatch_floor), per "
            "jitted function; achieved + lost sums to 1")
        for cause, frac in mfu_lost.items():
            g.set(frac, fn=fn, cause=cause)

    return {
        "fn": fn,
        "measured_step_ms": float(step_ms),
        "compute_ms": compute_ms,
        "terms_ms": lost_ms,
        "attributed_ms": attributed + dispatch_floor_ms,
        "overattributed_ms": overattributed_ms,
        "mfu_achieved": mfu_achieved,
        "mfu_lost": mfu_lost,
        "flops_per_step": flops,
        "peak_flops": peak_flops,
        "exposed_ratio": exposed_ratio,
        "comm_total_ms": comm_total_ms,
        "notes": notes,
    }


def render(budget: Dict[str, object]) -> str:
    """Human-readable step-time-budget table (perf_report's main
    section)."""
    step = budget["measured_step_ms"]
    lines = [
        f"step-time budget — fn={budget['fn']!r}, measured "
        f"{step:.3f} ms/step (MFU {budget['mfu_achieved']:.3f})",
        f"  {'term':<16}{'ms':>10}{'share':>8}   reading",
    ]
    readings = {
        "compute": "roofline compute floor (== achieved MFU)",
        "exposed_comm": "collective time NOT hidden under compute",
        "hbm_bound": "op classes pinned to HBM bandwidth, not flops",
        "host_gap": "host phases serialized with the device",
        "dispatch_floor": "residual: per-dispatch/relay floor + "
                          "unattributed",
    }

    def row(name, ms):
        share = ms / step if step else 0.0
        lines.append(f"  {name:<16}{ms:>10.3f}{share:>8.1%}   "
                     f"{readings.get(name, '')}")

    row("compute", budget["compute_ms"])
    for cause in LOST_CAUSES:
        row(cause, budget["terms_ms"][cause])
    if budget["overattributed_ms"]:
        lines.append(f"  (overattributed {budget['overattributed_ms']:.3f} "
                     f"ms — see notes)")
    lines.append(f"  {'sum':<16}{budget['attributed_ms']:>10.3f}"
                 f"{(budget['attributed_ms'] / step if step else 0):>8.1%}")
    for n in budget["notes"]:
        lines.append(f"  note: {n}")
    return "\n".join(lines)
