"""Bench regression sentinel — diff a bench record against a baseline ledger.

The r05 round burned a full relay cycle manually diagnosing two
"regressions" that a trajectory check would have framed in seconds —
and nothing today compares one ``BENCH_r*.json`` to the next at all.
This module is the comparison: a committed **baseline ledger**
(``BENCH_BASELINE.json``, seeded from the r05 record) holding one value +
noise band per metric, and a ``compare()`` that classifies each current
metric as ok / regressed / improved with direction awareness (tokens/s
up is good; ``*_ms`` up is bad).

Input formats (``load_bench_file`` sniffs all three):

- a bench metric line / ``BENCH_r*.json`` wrapper (``{"metric", "value",
  "extra": {...}}``, optionally nested under ``"parsed"``),
- the per-leg JSONL records bench.py / bench_serving.py append
  (``{"metric", "value", "env", "unix_time"}`` per line —
  :func:`append_bench_records` is the writer),
- a flat ``{metric: value}`` dict.

Comparison rules:

- config echoes and workload descriptors (``params_m``, ``slots``,
  ``n_requests``, arrival rates, …) are excluded — they are identity, not
  performance;
- a baseline of exactly 0 is never ratio-compared (division blowup; a
  counter that SHOULD stay 0, like ``prefetch_starvation``, is flagged on
  any nonzero current value instead);
- a delta beyond the metric's noise band in the BAD direction is a
  regression; beyond it in the good direction an improvement (reported,
  never failing);
- metrics missing from the current record are listed (a silently dropped
  leg is itself a regression signal) but only fail with ``strict``.

``scripts/check_bench.py`` is the CLI gate (nonzero exit on regression);
bench.py / bench_serving.py run the same compare non-fatally and surface a
``bench_regressions`` column.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

BASELINE_SCHEMA = "deepspeed_tpu.bench_baseline.v1"
DEFAULT_NOISE_BAND = 0.08

# metrics that are identity / workload echo, not performance — never compared
_IGNORE_EXACT = frozenset((
    "params_m", "loss", "slots", "n_requests", "legs_complete", "model",
    "telemetry_snapshot", "serving_telemetry_dir", "open_loop_slo",
    "fleet_trace",
))
_IGNORE_SUBSTR = ("arrival_rate", "kill_at", "replicas", "num_chunks",
                  "params_m", "train_loss", "error", "_dir", "_path")

# lower-is-better name patterns (everything else defaults to higher-better)
_LOWER_SUFFIX = ("_ms", "_s", "_bytes", "_bytes_per_step")
_LOWER_SUBSTR = ("step_time", "exposed", "fragmentation", "misses",
                 "starvation", "anomalies", "dumps", "regressions",
                 "padding_waste", "drop_rate")
# zero-baseline metrics where ANY nonzero current value is a trip
_ZERO_SENTINELS = ("starvation", "anomalies", "dumps", "misses_after_warm")


def is_perf_metric(name: str, value) -> bool:
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return False
    if name in _IGNORE_EXACT:
        return False
    return not any(s in name for s in _IGNORE_SUBSTR)


def metric_direction(name: str) -> int:
    """+1 when a bigger value is better (throughput, MFU, ratios), -1 when
    smaller is better (latencies, exposed time, failure counters)."""
    if name.endswith(_LOWER_SUFFIX) and not name.endswith(
            ("_per_s", "_per_sec")):
        return -1
    if any(s in name for s in _LOWER_SUBSTR):
        return -1
    return +1


# ---------------------------------------------------------------------------
# record loading
# ---------------------------------------------------------------------------

def flatten_bench_record(obj) -> Dict[str, float]:
    """Bench metric-line dict (or ``BENCH_r*.json`` wrapper) → flat
    ``{metric: value}`` including every numeric ``extra`` entry."""
    if "parsed" in obj and isinstance(obj["parsed"], dict):
        obj = obj["parsed"]
    out: Dict[str, float] = {}
    if "metric" in obj and isinstance(obj.get("value"), (int, float)):
        out[str(obj["metric"])] = float(obj["value"])
    for k, v in (obj.get("extra") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[str(k)] = float(v)
    for k, v in obj.items():
        if k in ("metric", "value", "extra", "unit", "vs_baseline",
                 "schema"):
            continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[str(k)] = float(v)
    return out


def load_bench_file(path: str) -> Dict[str, float]:
    """Sniff + flatten one bench artifact: JSON (metric line, BENCH_r*
    wrapper, or flat dict) or JSONL of per-leg records (last write per
    metric wins)."""
    with open(path) as f:
        text = f.read().strip()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        if "metric" in obj or "parsed" in obj or "extra" in obj:
            return flatten_bench_record(obj)
        if all(isinstance(v, (int, float, bool)) for v in obj.values()):
            return {k: float(v) for k, v in obj.items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)}
        return flatten_bench_record(obj)
    # JSONL: one record per line
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec \
                and isinstance(rec.get("value"), (int, float)):
            out[str(rec["metric"])] = float(rec["value"])
    return out


# ---------------------------------------------------------------------------
# baseline ledger
# ---------------------------------------------------------------------------

def seed_baseline(current: Dict[str, float], source: str = "",
                  default_band: float = DEFAULT_NOISE_BAND) -> dict:
    """Build a baseline ledger dict from a flat metric map."""
    return {
        "schema": BASELINE_SCHEMA,
        "seeded_from": source,
        "seeded_unix_time": time.time(),
        "default_noise_band": float(default_band),
        "metrics": {
            name: {"value": float(v)}
            for name, v in sorted(current.items())
            if is_perf_metric(name, v)
        },
    }


def load_baseline(path: str) -> dict:
    with open(path) as f:
        ledger = json.load(f)
    if ledger.get("schema") != BASELINE_SCHEMA:
        raise ValueError(f"{path}: not a {BASELINE_SCHEMA} ledger "
                         f"(schema={ledger.get('schema')!r})")
    return ledger


def save_baseline(ledger: dict, path: str) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ledger, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------

def compare(current: Dict[str, float], baseline: dict,
            band: Optional[float] = None,
            strict_missing: bool = False) -> dict:
    """Diff ``current`` against a baseline ledger.

    Returns ``{"regressions", "improvements", "ok", "missing", "new",
    "failed"}`` where each finding is ``{metric, baseline, current,
    delta, band, direction}`` and ``delta`` is the signed relative change
    (positive = metric went up).  ``band`` overrides the ledger's
    default noise band (per-metric ``band`` entries always win).
    """
    default_band = (float(band) if band is not None
                    else float(baseline.get("default_noise_band",
                                            DEFAULT_NOISE_BAND)))
    metrics = baseline.get("metrics", {})
    regressions: List[dict] = []
    improvements: List[dict] = []
    ok: List[dict] = []
    missing: List[str] = []
    for name, entry in sorted(metrics.items()):
        base = float(entry["value"])
        mband = float(entry.get("band", default_band))
        if name not in current:
            missing.append(name)
            continue
        cur = float(current[name])
        direction = metric_direction(name)
        finding = {"metric": name, "baseline": base, "current": cur,
                   "band": mband, "direction": direction}
        if base == 0.0:
            # ratio-free path: counters that must stay 0 trip on any
            # nonzero; anything else with a 0 baseline is uncheckable
            if cur != 0.0 and direction < 0 \
                    and any(s in name for s in _ZERO_SENTINELS):
                finding["delta"] = float("inf")
                regressions.append(finding)
            else:
                finding["delta"] = 0.0
                ok.append(finding)
            continue
        delta = (cur - base) / abs(base)
        finding["delta"] = delta
        goodness = delta * direction          # positive = got better
        if goodness < -mband:
            regressions.append(finding)
        elif goodness > mband:
            improvements.append(finding)
        else:
            ok.append(finding)
    new = sorted(n for n, v in current.items()
                 if n not in metrics and is_perf_metric(n, v))
    failed = bool(regressions) or (strict_missing and bool(missing))
    return {"regressions": regressions, "improvements": improvements,
            "ok": ok, "missing": missing, "new": new, "failed": failed,
            "checked": len(metrics) - len(missing)}


def render(result: dict, baseline_name: str = "baseline") -> str:
    lines: List[str] = []

    def fmt(f: dict) -> str:
        arrow = "↓" if f["delta"] < 0 else "↑"
        return (f"    {f['metric']}: {f['baseline']:g} -> "
                f"{f['current']:g}  ({arrow}{abs(f['delta']):.1%}, "
                f"band ±{f['band']:.0%}, "
                f"{'higher' if f['direction'] > 0 else 'lower'}-is-better)")

    lines.append(f"check_bench: {result['checked']} metrics checked "
                 f"against {baseline_name}")
    if result["regressions"]:
        lines.append(f"  REGRESSIONS ({len(result['regressions'])}):")
        lines.extend(fmt(f) for f in result["regressions"])
    if result["improvements"]:
        lines.append(f"  improvements ({len(result['improvements'])}):")
        lines.extend(fmt(f) for f in result["improvements"])
    if result["missing"]:
        lines.append(f"  missing from current record "
                     f"({len(result['missing'])}): "
                     + ", ".join(result["missing"]))
    if result["new"]:
        lines.append(f"  new metrics not in the ledger "
                     f"({len(result['new'])}): " + ", ".join(result["new"]))
    lines.append("  verdict: "
                 + ("REGRESSED" if result["failed"] else "ok"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# canned fixtures (sentinel self-test: trips on a 10% slowdown, quiet on
# in-band noise)
# ---------------------------------------------------------------------------

def make_fixture(baseline: dict, kind: str) -> Dict[str, float]:
    """Synthesize a current-record fixture from a ledger:
    ``kind="regression"`` shifts every metric 10% in its BAD direction,
    ``kind="noise"`` jitters deterministically by a quarter of each
    metric's own noise band (strictly inside it, whatever per-metric
    bands the ledger carries)."""
    if kind not in ("regression", "noise"):
        raise ValueError(f"unknown fixture kind {kind!r}")
    default_band = float(baseline.get("default_noise_band",
                                      DEFAULT_NOISE_BAND))
    out: Dict[str, float] = {}
    for i, (name, entry) in enumerate(sorted(
            baseline.get("metrics", {}).items())):
        base = float(entry["value"])
        direction = metric_direction(name)
        if kind == "regression":
            out[name] = base * (1.0 - 0.10 * direction)
        else:
            jitter = 0.25 * float(entry.get("band", default_band))
            out[name] = base * (1.0 + (jitter if i % 2 else -jitter))
    return out


# ---------------------------------------------------------------------------
# per-leg JSONL records (the sentinel's native input; bench.py /
# bench_serving.py append these next to their stdout JSON line)
# ---------------------------------------------------------------------------

def append_bench_records(path: str, metrics: Dict[str, float],
                         env: Optional[dict] = None,
                         unit: str = "") -> int:
    """Append one JSONL record per numeric metric: ``{"metric", "value",
    "unit", "env", "unix_time"}``.  Returns the number of lines written;
    failures must be caught by the caller (bench output must never die on
    telemetry bookkeeping)."""
    now = time.time()
    env = env or {}
    lines = []
    for name, value in sorted(metrics.items()):
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        lines.append(json.dumps({
            "metric": str(name), "value": float(value), "unit": unit,
            "env": env, "unix_time": now}, sort_keys=True))
    if not lines:
        return 0
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write("\n".join(lines) + "\n")
    return len(lines)
