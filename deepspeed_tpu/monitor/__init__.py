from deepspeed_tpu.monitor.monitor import (MonitorMaster, TensorBoardMonitor,
                                           WandbMonitor, csvMonitor)

__all__ = ["MonitorMaster", "TensorBoardMonitor", "WandbMonitor", "csvMonitor"]
