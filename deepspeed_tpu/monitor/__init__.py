from deepspeed_tpu.monitor.monitor import (CometMonitor, CSVMonitor,
                                           MonitorMaster, TensorBoardMonitor,
                                           WandbMonitor, csvMonitor)

__all__ = ["MonitorMaster", "TensorBoardMonitor", "WandbMonitor",
           "CSVMonitor", "CometMonitor", "csvMonitor"]
