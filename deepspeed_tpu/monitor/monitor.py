"""Experiment monitors — TensorBoard / CSV / W&B fan-out.

Analog of the reference monitor subsystem (monitor/monitor.py:30 MonitorMaster,
monitor/{tensorboard,csv_monitor,wandb}.py): the engine emits scalar events as
``(name, value, step)`` tuples and ``MonitorMaster`` fans them out to every
enabled writer on process rank 0 (multi-host: exactly one process writes).

Differences from the reference: rank filtering uses ``jax.process_index()``
instead of torch.distributed; TensorBoard rides tensorboardX when present
(torch's bundled SummaryWriter as fallback — importing torch costs seconds
and gigabytes on a TPU-native stack, so it is the last resort); a missing
backend package degrades to a loud warning instead of an ImportError so a
shared ds_config doesn't kill training on machines without wandb.
"""

from __future__ import annotations

import csv
import os
import re
import warnings
from typing import List, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]  # (name, scalar value, global step)


def _is_rank0() -> bool:
    import jax
    return jax.process_index() == 0


class Monitor:
    """Writer interface (reference monitor/monitor.py:13)."""

    def __init__(self, config):
        self.config = config
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, event_list: Sequence[Event]) -> None:
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    """reference monitor/tensorboard.py (SummaryWriter.add_scalar per event)."""

    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if not self.enabled:
            return
        try:
            try:
                # tensorboardX first: pulling in torch just for a
                # SummaryWriter is a multi-second, multi-GB import on a
                # stack that otherwise never touches it
                from tensorboardX import SummaryWriter
            except ImportError:  # pragma: no cover
                from torch.utils.tensorboard import SummaryWriter
        except ImportError:  # pragma: no cover
            logger.warning(
                "tensorboard monitor enabled but no SummaryWriter backend "
                "(tensorboardX / torch.utils.tensorboard) is importable — "
                "tensorboard events will be dropped")
            self.enabled = False
            return
        log_dir = os.path.join(config.output_path or "./runs", config.job_name)
        os.makedirs(log_dir, exist_ok=True)
        self.summary_writer = SummaryWriter(log_dir=log_dir)

    def write_events(self, event_list: Sequence[Event]) -> None:
        if not self.enabled or self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, float(value), int(step))
        self.summary_writer.flush()


class CSVMonitor(Monitor):
    """reference monitor/csv_monitor.py — one csv file per event name.

    Filenames sanitize EVERY non-alphanumeric character to ``_`` (not just
    ``/`` and spaces): event names flow in from config-driven series
    (telemetry label fan-out included) and may carry ``=``, ``:``, or
    anything else that is unsafe or ambiguous in a path.
    """

    def __init__(self, config):
        super().__init__(config)
        self.log_dir = None
        self._seen = set()
        if not self.enabled:
            return
        self.log_dir = os.path.join(config.output_path or "./csv_monitor",
                                    config.job_name)
        os.makedirs(self.log_dir, exist_ok=True)

    @staticmethod
    def _sanitize(name: str) -> str:
        return re.sub(r"[^0-9a-zA-Z]", "_", name)

    def write_events(self, event_list: Sequence[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in event_list:
            fname = os.path.join(self.log_dir,
                                 self._sanitize(name) + ".csv")
            header = name.split("/")[-1]
            new = fname not in self._seen and not os.path.exists(fname)
            self._seen.add(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", header])
                w.writerow([int(step), float(value)])


class csvMonitor(CSVMonitor):  # noqa: N801
    """Deprecated alias (the reference's lowercase class name, kept so
    configs/imports naming it keep working)."""

    def __init__(self, config):
        warnings.warn("csvMonitor is deprecated; use CSVMonitor",
                      DeprecationWarning, stacklevel=2)
        super().__init__(config)


class WandbMonitor(Monitor):
    """reference monitor/wandb.py."""

    def __init__(self, config):
        super().__init__(config)
        self._wandb = None
        if not self.enabled:
            return
        try:
            import wandb
        except ImportError:
            logger.warning(
                "wandb monitor enabled but the wandb package is not installed "
                "— wandb events will be dropped")
            self.enabled = False
            return
        self._wandb = wandb
        wandb.init(project=config.project, group=config.group,
                   entity=config.team)

    def write_events(self, event_list: Sequence[Event]) -> None:
        if not self.enabled or self._wandb is None:
            return
        for name, value, step in event_list:
            self._wandb.log({name: float(value)}, step=int(step))


class CometMonitor(Monitor):
    """reference monitor/comet.py (CometMonitor: experiment.__internal_api__
    log_metric per event)."""

    def __init__(self, config):
        super().__init__(config)
        self._experiment = None
        if not self.enabled:
            return
        try:
            import comet_ml
        except ImportError:
            logger.warning(
                "comet monitor enabled but the comet_ml package is not "
                "installed — comet events will be dropped")
            self.enabled = False
            return
        kw = {}
        if getattr(config, "api_key", None):
            kw["api_key"] = config.api_key
        self._experiment = comet_ml.Experiment(
            project_name=config.project or None, **kw)
        if getattr(config, "experiment_name", None):
            self._experiment.set_name(config.experiment_name)

    def write_events(self, event_list: Sequence[Event]) -> None:
        if not self.enabled or self._experiment is None:
            return
        for name, value, step in event_list:
            self._experiment.log_metric(name, float(value), step=int(step))


class MonitorMaster(Monitor):
    """Fan-out writer (reference monitor/monitor.py:30): rank 0 only."""

    def __init__(self, config):
        # config is the top-level DeepSpeedTPUConfig (carries .tensorboard,
        # .csv_monitor, .wandb, .comet sub-blocks)
        self.tb_monitor = None
        self.csv_monitor = None
        self.wandb_monitor = None
        self.comet_monitor = None
        self.enabled = (config.tensorboard.enabled or config.csv_monitor.enabled
                        or config.wandb.enabled or config.comet.enabled)
        if not _is_rank0():
            self.enabled = False
            return
        if config.tensorboard.enabled:
            self.tb_monitor = TensorBoardMonitor(config.tensorboard)
        if config.csv_monitor.enabled:
            self.csv_monitor = CSVMonitor(config.csv_monitor)
        if config.wandb.enabled:
            self.wandb_monitor = WandbMonitor(config.wandb)
        if config.comet.enabled:
            self.comet_monitor = CometMonitor(config.comet)

    def write_events(self, event_list: Sequence[Event]) -> None:
        if not self.enabled:
            return
        for m in (self.tb_monitor, self.csv_monitor, self.wandb_monitor,
                  self.comet_monitor):
            if m is not None:
                m.write_events(event_list)
