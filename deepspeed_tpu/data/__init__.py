"""Data stack — memory-mapped indexed datasets + batch assembly."""

from deepspeed_tpu.data.indexed_dataset import (  # noqa: F401
    MMapIndexedDataset, TokenBatchDataset, write_indexed_dataset)
