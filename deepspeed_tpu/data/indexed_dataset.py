"""Memory-mapped indexed token datasets (Megatron ``.bin``/``.idx`` format).

Reference analog: the Megatron-DeepSpeed data stack the reference's training
examples run on (``megatron/data/indexed_dataset.py`` MMapIndexedDataset —
the de-facto public pretraining-data format) plus its C++ helpers.  Reading
the ESTABLISHED format means real tokenized corpora drop in unchanged.

Format (``.idx``):
    magic b"MMIDIDX\\x00\\x00" | version u64=1 | dtype_code u8 |
    n_sequences u64 | n_docs u64 |
    sizes i32[n_sequences] | pointers i64[n_sequences] | doc_idx i64[n_docs]
``.bin`` is the flat token stream the pointers index into.

The batch-assembly hot path (gather N token spans into a [N, T] array) goes
through the native threaded memcpy op (csrc/indexed_dataset.cpp) with a
numpy-memmap fallback.
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import List, Optional, Sequence

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
# Megatron dtype codes (megatron-core indexed_dataset: 6 = float64,
# 7 = float32 — the float codes are NOT in size order)
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float64, 7: np.float32, 8: np.uint16}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}

_lib = None
_native_failed = False


def _load_native():
    global _lib, _native_failed
    if _native_failed:
        raise RuntimeError("native indexed_dataset op failed to build "
                           "earlier this session")
    if _lib is None:
        from deepspeed_tpu.ops.builder import load_op
        lib = load_op("indexed_dataset")
        lib.ds_ids_open.argtypes = [ctypes.c_char_p]
        lib.ds_ids_open.restype = ctypes.c_int
        lib.ds_ids_size.argtypes = [ctypes.c_int]
        lib.ds_ids_size.restype = ctypes.c_int64
        lib.ds_ids_gather.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int]
        lib.ds_ids_gather.restype = ctypes.c_int
        lib.ds_ids_close.argtypes = [ctypes.c_int]
        _lib = lib
    return _lib


def native_available() -> bool:
    global _native_failed
    try:
        _load_native()
        return True
    except Exception:  # noqa: BLE001
        _native_failed = True    # don't re-spawn a failing g++ per dataset
        return False


def write_indexed_dataset(docs: Sequence[np.ndarray], path_prefix: str,
                          dtype=np.uint16) -> None:
    """Write ``docs`` (1-D token arrays) as ``<prefix>.bin`` + ``<prefix>.idx``
    (Megatron builder analog; used for fixtures and tokenizer pipelines)."""
    dtype = np.dtype(dtype)
    if dtype not in _CODES:
        raise ValueError(f"unsupported dtype {dtype}")
    sizes, pointers = [], []
    ptr = 0
    with open(path_prefix + ".bin", "wb") as f:
        for d in docs:
            arr = np.ascontiguousarray(d, dtype=dtype)
            f.write(arr.tobytes())
            sizes.append(len(arr))
            pointers.append(ptr)
            ptr += arr.nbytes
    with open(path_prefix + ".idx", "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", 1))
        f.write(struct.pack("<B", _CODES[dtype]))
        f.write(struct.pack("<Q", len(docs)))
        f.write(struct.pack("<Q", len(docs) + 1))
        f.write(np.asarray(sizes, np.int32).tobytes())
        f.write(np.asarray(pointers, np.int64).tobytes())
        f.write(np.arange(len(docs) + 1, dtype=np.int64).tobytes())


class MMapIndexedDataset:
    """Read-only view over ``<prefix>.bin``/``.idx``."""

    def __init__(self, path_prefix: str, use_native: Optional[bool] = None):
        idx_path = path_prefix + ".idx"
        self.bin_path = path_prefix + ".bin"
        with open(idx_path, "rb") as f:
            if f.read(len(_MAGIC)) != _MAGIC:
                raise ValueError(f"{idx_path}: bad magic (not an MMIDIDX "
                                 f"indexed dataset)")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != 1:
                raise ValueError(f"unsupported idx version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(_DTYPES[code])
            (n,) = struct.unpack("<Q", f.read(8))
            (nd,) = struct.unpack("<Q", f.read(8))
            buf = f.read()
        self.sizes = np.frombuffer(buf, np.int32, n)
        self.pointers = np.frombuffer(buf, np.int64, n, offset=4 * n)
        self.doc_idx = np.frombuffer(buf, np.int64, nd, offset=4 * n + 8 * n)
        self._mm = np.memmap(self.bin_path, dtype=self.dtype, mode="r")
        self._h = None
        if use_native or (use_native is None and native_available()):
            self._h = _load_native().ds_ids_open(
                self.bin_path.encode())
            if self._h < 0:
                self._h = None

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i: int) -> np.ndarray:
        start = self.pointers[i] // self.dtype.itemsize
        return np.asarray(self._mm[start:start + self.sizes[i]])

    @property
    def total_tokens(self) -> int:
        return int(self.sizes.sum())

    def gather(self, offsets_tokens: np.ndarray, length: int,
               nthreads: int = 4) -> np.ndarray:
        """Assemble [N, length] token spans starting at flat-token offsets —
        the batch hot path (native threaded memcpy; memmap fallback)."""
        offs = np.asarray(offsets_tokens, np.int64)
        total = self._mm.shape[0]
        if offs.size and (offs.min() < 0 or offs.max() + length > total):
            raise IndexError("token span out of range")
        out = np.empty((len(offs), length), self.dtype)
        if self._h is not None:
            lib = _load_native()
            byte_offs = (offs * self.dtype.itemsize).astype(np.int64)
            nbytes = np.full(len(offs), length * self.dtype.itemsize,
                             np.int64)
            rc = lib.ds_ids_gather(
                self._h,
                byte_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                nbytes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(offs), out.ctypes.data_as(ctypes.c_void_p),
                out.strides[0], int(nthreads))
            if rc == 0:
                return out
            if rc == -2:
                raise IndexError("token span out of range")
        for i, o in enumerate(offs):
            out[i] = self._mm[o:o + length]
        return out

    def close(self):
        if self._h is not None:
            _load_native().ds_ids_close(self._h)
            self._h = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class TokenBatchDataset:
    """Fixed-length LM samples over the flat token stream (the GPTDataset
    essentials: contiguous [seq_len+1] windows, deterministic per-epoch
    shuffle) — ``__getitem__`` returns {"input_ids": [seq_len]} batches ready
    for the engine/dataloader."""

    def __init__(self, dataset: MMapIndexedDataset, seq_len: int,
                 seed: int = 0):
        self.ds = dataset
        self.seq_len = int(seq_len)
        n = dataset.total_tokens // self.seq_len
        if n == 0:
            raise ValueError(f"dataset has {dataset.total_tokens} tokens, "
                             f"fewer than seq_len={seq_len}")
        self._n = n
        self._order = np.random.default_rng(seed).permutation(n)

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> dict:
        start = int(self._order[i]) * self.seq_len
        row = self.ds.gather(np.asarray([start]), self.seq_len, nthreads=1)[0]
        return {"input_ids": row.astype(np.int32)}

    def batch(self, indices: Sequence[int], nthreads: int = 4) -> dict:
        starts = self._order[np.asarray(indices, np.int64)] * self.seq_len
        toks = self.ds.gather(starts, self.seq_len, nthreads=nthreads)
        return {"input_ids": toks.astype(np.int32)}
