"""Diffusers checkpoint import — UNet2DConditionModel / AutoencoderKL.

Reference parity: ``module_inject/containers/unet.py`` + ``vae.py`` consume
diffusers modules in-place; here the diffusers ``diffusion_pytorch_model.
safetensors`` + ``config.json`` pair loads directly into the pure-function
models in ``models/diffusion.py``.

Import policy matches ``checkpoint/hf.py``: STRICT — every tensor in the
checkpoint must be consumed and every leaf the model needs must be filled;
anything else raises instead of silently serving wrong images.

Layout transforms (torch → TPU-native):
- conv  [O, I, kh, kw] → HWIO [kh, kw, I, O]
- linear [O, I]        → [I, O]
- norm weight/bias     → scale/bias
Old-style VAE attention names (query/key/value/proj_attn) are accepted.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Tuple

import numpy as np

from deepspeed_tpu.models.diffusion import UNetConfig, VAEConfig


def _conv(w):
    return np.ascontiguousarray(np.transpose(np.asarray(w), (2, 3, 1, 0)))


def _lin(w):
    return np.ascontiguousarray(np.asarray(w).T)


def _read_json(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def _load_safetensors(d: str) -> Dict[str, np.ndarray]:
    import safetensors.numpy
    for name in ("diffusion_pytorch_model.safetensors",
                 "model.safetensors"):
        p = os.path.join(d, name)
        if os.path.exists(p):
            return dict(safetensors.numpy.load_file(p))
    raise FileNotFoundError(f"no safetensors weights under {d}")


def _place(tree: Dict[str, Any], dotted: str, value) -> None:
    """'down_blocks.0.resnets.1.conv1.kernel' → nested dict/list write."""
    parts = dotted.split(".")
    node: Any = tree
    for i, part in enumerate(parts[:-1]):
        idx = int(part) if part.isdigit() else part
        nxt_is_index = parts[i + 1].isdigit() if i + 1 < len(parts) else False
        if isinstance(idx, int):
            while len(node) <= idx:
                node.append([] if nxt_is_index else {})
            if node[idx] == {} and nxt_is_index:
                node[idx] = []
            node = node[idx]
        else:
            if idx not in node:
                node[idx] = [] if nxt_is_index else {}
            node = node[idx]
    node[parts[-1]] = value


_OLD_VAE_ATTN = {"query": "to_q", "key": "to_k", "value": "to_v",
                 "proj_attn": "to_out"}


def _translate(name: str) -> Tuple[str, Any]:
    """diffusers tensor name → (tree path, transform fn)."""
    is_weight = name.endswith(".weight")
    base = name.rsplit(".", 1)[0]
    leaf = name.rsplit(".", 1)[1]

    # norm layers: weight/bias → scale/bias
    norm_like = re.search(
        r"(?:^|\.)(norm\d?|group_norm|conv_norm_out|norm_out)$", base)
    if norm_like:
        if base.endswith("norm_out") and not base.endswith("conv_norm_out"):
            base = base[: -len("norm_out")] + "conv_norm_out"
        return (base + (".scale" if is_weight else ".bias"), np.asarray)

    # structural renames
    base = re.sub(r"downsamplers\.0\.conv$", "downsampler", base)
    base = re.sub(r"upsamplers\.0\.conv$", "upsampler", base)
    base = re.sub(r"\.to_out\.0$", ".to_out", base)
    base = re.sub(r"\.ff\.net\.0\.proj$", ".ff_proj", base)
    base = re.sub(r"\.ff\.net\.2$", ".ff_out", base)
    for old, new in _OLD_VAE_ATTN.items():
        base = re.sub(rf"\.{old}$", f".{new}", base)

    conv_like = re.search(
        r"(conv_in|conv_out|conv1|conv2|conv_shortcut|downsampler|upsampler|"
        r"quant_conv|post_quant_conv)$", base)
    if leaf == "bias":
        return base + ".bias", np.asarray
    if conv_like:
        return base + ".kernel", _conv
    # everything else with a .weight is a linear (attention projections,
    # time_emb_proj, ff, proj_in/proj_out under use_linear_projection)
    return base + ".kernel", _lin


def _import_tree(weights: Dict[str, np.ndarray],
                 proj_is_conv: bool) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for name, w in sorted(weights.items()):
        path, fn = _translate(name)
        if proj_is_conv and re.search(r"proj_(in|out)\.kernel$", path):
            fn = _conv if np.asarray(w).ndim == 4 else _lin
        # old VAE attention stored projections as 1x1 convs [O, I, 1, 1]
        if (np.asarray(w).ndim == 4 and fn is _lin):
            w = np.asarray(w)[:, :, 0, 0]
        _place(tree, path, fn(w))
    return tree


def _leaf_paths(node, prefix="") -> Dict[str, Tuple[int, ...]]:
    out: Dict[str, Tuple[int, ...]] = {}
    if isinstance(node, dict):
        for k, v in node.items():
            out.update(_leaf_paths(v, f"{prefix}.{k}" if prefix else k))
    elif isinstance(node, list):
        for i, v in enumerate(node):
            out.update(_leaf_paths(v, f"{prefix}.{i}"))
    else:
        out[prefix] = tuple(np.asarray(node).shape) \
            if not hasattr(node, "shape") else tuple(node.shape)
    return out


def _check_structure(tree, expected_tree, what: str) -> None:
    """The REAL strict check: the imported tree must have exactly the leaf
    paths and shapes the config-derived abstract structure promises — a
    truncated, padded, or misrouted checkpoint fails HERE, not as an opaque
    KeyError inside the jitted forward."""
    got = _leaf_paths(tree)
    want = _leaf_paths(expected_tree)
    missing = sorted(set(want) - set(got))
    extra = sorted(set(got) - set(want))
    if missing or extra:
        raise ValueError(
            f"{what} checkpoint does not match the config structure: "
            f"missing={missing[:8]}{'...' if len(missing) > 8 else ''} "
            f"unexpected={extra[:8]}{'...' if len(extra) > 8 else ''}")
    bad = [(p, got[p], want[p]) for p in want if got[p] != want[p]]
    if bad:
        p, g, w = bad[0]
        raise ValueError(f"{what} checkpoint shape mismatch at {p}: "
                         f"{g} != expected {w} ({len(bad)} total)")


def load_hf_unet(model_dir: str, dtype=None):
    """diffusers UNet2DConditionModel dir (config.json + safetensors) →
    (UNetConfig, params tree for models.diffusion.unet_forward)."""
    import jax.numpy as jnp
    hf = _read_json(os.path.join(model_dir, "config.json"))
    cls = hf.get("_class_name", "UNet2DConditionModel")
    if cls != "UNet2DConditionModel":
        raise ValueError(f"{model_dir}: expected UNet2DConditionModel, "
                         f"got {cls}")
    cfg = UNetConfig.from_hf(hf, dtype=dtype or jnp.float32)
    weights = _load_safetensors(model_dir)
    tree = _import_tree(weights, proj_is_conv=not cfg.use_linear_projection)
    import jax
    from deepspeed_tpu.models.diffusion import init_unet_params
    expected = jax.eval_shape(
        lambda k: init_unet_params(k, cfg), jax.random.PRNGKey(0))
    _check_structure(tree, expected, "UNet")
    return cfg, tree


def load_hf_vae(model_dir: str, dtype=None):
    """diffusers AutoencoderKL dir → (VAEConfig, params tree)."""
    import jax.numpy as jnp
    hf = _read_json(os.path.join(model_dir, "config.json"))
    cls = hf.get("_class_name", "AutoencoderKL")
    if cls != "AutoencoderKL":
        raise ValueError(f"{model_dir}: expected AutoencoderKL, got {cls}")
    cfg = VAEConfig.from_hf(hf, dtype=dtype or jnp.float32)
    weights = _load_safetensors(model_dir)
    tree = _import_tree(weights, proj_is_conv=False)
    import jax
    from deepspeed_tpu.models.diffusion import init_vae_params
    expected = jax.eval_shape(
        lambda k: init_vae_params(k, cfg), jax.random.PRNGKey(0))
    _check_structure(tree, expected, "VAE")
    return cfg, tree


def is_diffusers_model_dir(path) -> bool:
    if not isinstance(path, (str, os.PathLike)):
        return False
    cfg = os.path.join(str(path), "config.json")
    if not os.path.exists(cfg):
        return False
    try:
        cls = _read_json(cfg).get("_class_name", "")
    except (OSError, json.JSONDecodeError):
        return False
    return cls in ("UNet2DConditionModel", "AutoencoderKL")
