"""Universal checkpointing — per-parameter fp32 fragment export/import.

Reference: checkpoint/ds_to_universal.py (shard extract/merge pipeline into
``zero/<param_name>/fp32.pt`` fragment dirs), checkpoint/universal_checkpoint.py
(load_hp_checkpoint_state), utils/zero_to_fp32.py (offline consolidation).

The TPU engine's orbax checkpoints already reshard freely on load (named
shardings), so the reference's *topology* motivation disappears — what this
module adds is the other half of "universal": a framework-neutral on-disk
layout that

- any tool can read without orbax/jax (one little-endian ``.npy`` per tensor),
- carries TRUE fp32 master weights + optimizer moments (not the bf16 params),
- and can ingest reference-style torch fragments (``fp32.pt``) for
  cross-framework migration.

Layout (mirrors ds_to_universal's output shape)::

    out_dir/
      meta.json                      # step, format tag, param manifest
      zero/
        <dotted.param.path>/         # e.g. backbone.block_0.Attention_0.wq
          fp32.npy                   # master weights (fp32)
          exp_avg.npy                # Adam first moment, when present
          exp_avg_sq.npy             # Adam second moment, when present
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

FORMAT = "deepspeed_tpu_universal/1"
_FRAGMENT_KEYS = ("fp32", "exp_avg", "exp_avg_sq")


# ---------------------------------------------------------------------------
# crash-safe commit protocol (same ordering as the orbax tag commit in
# checkpoint/__init__.py): .in_progress marker → every fragment byte + meta
# durable → marker off → 'latest_universal' pointer moves.  A death at any
# point leaves either a torn export that load_universal REFUSES (marker
# present / meta missing) and latest_universal() skips, or a committed
# export the pointer may trail — the previous complete export resumes
# either way.
# ---------------------------------------------------------------------------

def _begin_export(out_dir: str) -> str:
    from deepspeed_tpu.checkpoint import IN_PROGRESS_FILE
    from deepspeed_tpu.runtime import faults
    os.makedirs(out_dir, exist_ok=True)
    marker = os.path.join(out_dir, IN_PROGRESS_FILE)
    with open(marker, "w") as f:
        f.write(str(time.time()))
    faults.fire("universal.pre_fragments", out_dir=out_dir)
    return marker


def _commit_export(out_dir: str, marker: str,
                   run_dir: Optional[str] = None) -> str:
    from deepspeed_tpu.checkpoint import UNIVERSAL_LATEST_FILE
    from deepspeed_tpu.runtime import faults
    faults.fire("universal.pre_commit", out_dir=out_dir)
    os.remove(marker)                    # data durable → marker off
    if run_dir:
        faults.fire("universal.pre_pointer", out_dir=out_dir)
        ptr = os.path.join(run_dir, UNIVERSAL_LATEST_FILE)
        rel = os.path.relpath(os.path.abspath(out_dir),
                              os.path.abspath(run_dir))
        target = out_dir if rel.startswith(os.pardir) else rel
        with open(ptr + ".tmp", "w") as f:
            f.write(target)
        os.replace(ptr + ".tmp", ptr)    # pointer moves last, atomically
    return out_dir


def _write_meta_json(out_dir: str, step: int, manifest: dict,
                     layout: Optional[dict]) -> None:
    from deepspeed_tpu.runtime import faults
    faults.fire("universal.pre_meta", out_dir=out_dir)
    meta = {"format": FORMAT, "step": int(step), "params": manifest}
    if layout:
        # logical layout metadata: how the SOURCE engine laid these params
        # out (pipeline stages, zero stage, mesh) — restore-time relayout
        # (checkpoint/reshard.py) keys on it.  Fragments on disk are always
        # in the LOGICAL (per-layer, unstacked) namespace.
        meta["layout"] = layout
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)


# ---------------------------------------------------------------------------
# generic pytree surgery: find / rewrite optimizer sub-states by type
# ---------------------------------------------------------------------------

def _is_namedtuple(x) -> bool:
    return isinstance(x, tuple) and hasattr(x, "_fields")


def _find_nodes(node, pred, out):
    """Collect all sub-nodes matching ``pred`` (no descent into matches)."""
    if pred(node):
        out.append(node)
        return out
    if _is_namedtuple(node):
        for f in node._fields:
            _find_nodes(getattr(node, f), pred, out)
    elif isinstance(node, (tuple, list)):
        for x in node:
            _find_nodes(x, pred, out)
    elif isinstance(node, dict):
        for x in node.values():
            _find_nodes(x, pred, out)
    return out


def _rewrite_nodes(node, visit):
    """Rebuild the tree, replacing any node where ``visit`` returns non-None."""
    new = visit(node)
    if new is not None:
        return new
    if _is_namedtuple(node):
        return type(node)(*[_rewrite_nodes(getattr(node, f), visit)
                            for f in node._fields])
    if isinstance(node, tuple):
        return tuple(_rewrite_nodes(x, visit) for x in node)
    if isinstance(node, list):
        return [_rewrite_nodes(x, visit) for x in node]
    if isinstance(node, dict):
        return {k: _rewrite_nodes(v, visit) for k, v in node.items()}
    return node


def _adam_states(opt_state):
    """ScaleByAdamState nodes — typed (live engine state) or the dict form an
    orbax restore-without-target produces."""
    import optax

    def pred(n):
        return (isinstance(n, optax.ScaleByAdamState)
                or (isinstance(n, dict) and set(n) == {"count", "mu", "nu"}))

    return [{"mu": n["mu"], "nu": n["nu"]} if isinstance(n, dict)
            else {"mu": n.mu, "nu": n.nu}
            for n in _find_nodes(opt_state, pred, [])]


def _master_states(opt_state):
    from deepspeed_tpu.runtime.zero import MasterWeightsState

    def pred(n):
        return (isinstance(n, MasterWeightsState)
                or (isinstance(n, dict) and set(n) == {"master", "inner"}))

    return [{"master": n["master"]} if isinstance(n, dict)
            else {"master": n.master}
            for n in _find_nodes(opt_state, pred, [])]


# ---------------------------------------------------------------------------
# path helpers
# ---------------------------------------------------------------------------

def _flatten_params(params) -> Dict[str, Any]:
    """Nested dict tree → {"a.b.c": leaf} with deterministic dotted paths."""
    flat = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], prefix + (str(k),))
        elif isinstance(node, (list, tuple)):   # infinity layout: layers list
            for i, v in enumerate(node):
                walk(v, prefix + (str(i),))
        else:
            flat[".".join(prefix)] = node

    walk(params, ())
    return flat


def _unflatten_params(flat: Dict[str, Any]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, leaf in flat.items():
        parts = path.split(".")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return root


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def state_fragments(state) -> Dict[str, Dict[str, np.ndarray]]:
    """The in-memory form of a universal checkpoint: {dotted_path: {fp32,
    exp_avg?, exp_avg_sq?}} host numpy fragments pulled from a TrainState
    (or any (params, opt_state) carrier).  Master weights come from the
    optimizer's ``MasterWeightsState`` when present (true fp32 masters,
    reference _create_fp32_partitions), else params are upcast."""
    flat = _flatten_params(state.params)
    opt_state = state.opt_state
    masters = _master_states(opt_state)
    master_flat = _flatten_params(masters[0]["master"]) if masters else flat
    adams = _adam_states(opt_state)
    mu_flat = _flatten_params(adams[0]["mu"]) if adams else None
    nu_flat = _flatten_params(adams[0]["nu"]) if adams else None

    frags: Dict[str, Dict[str, np.ndarray]] = {}
    for p in flat:
        w = np.asarray(jax.device_get(master_flat[p]))
        # bf16 needs the explicit dtype compare — numpy's kind for ml_dtypes
        # bfloat16 is not "f"
        if w.dtype != np.float32 and (w.dtype.kind == "f"
                                      or w.dtype == jax.numpy.bfloat16):
            w = w.astype(np.float32)
        entry = {"fp32": w}
        if mu_flat is not None:
            entry["exp_avg"] = np.asarray(jax.device_get(mu_flat[p]),
                                          np.float32)
            entry["exp_avg_sq"] = np.asarray(jax.device_get(nu_flat[p]),
                                             np.float32)
        frags[p] = entry
    return frags


def write_fragments(frags: Dict[str, Dict[str, np.ndarray]], out_dir: str,
                    *, step: int, layout: Optional[dict] = None,
                    run_dir: Optional[str] = None) -> str:
    """Write fragments to disk under the crash-safe commit protocol
    (marker → fragments + meta durable → marker off → pointer)."""
    from deepspeed_tpu.runtime import faults
    marker = _begin_export(out_dir)
    zdir = os.path.join(out_dir, "zero")
    os.makedirs(zdir, exist_ok=True)
    manifest = {}
    half = len(frags) // 2
    for i, p in enumerate(sorted(frags)):
        if i == half:
            faults.fire("universal.mid_fragments", out_dir=out_dir)
        entry = frags[p]
        d = os.path.join(zdir, p)
        os.makedirs(d, exist_ok=True)
        for key in _FRAGMENT_KEYS:
            if key in entry:
                np.save(os.path.join(d, key + ".npy"),
                        np.asarray(entry[key]))
        w = np.asarray(entry["fp32"])
        manifest[p] = {"shape": list(w.shape), "dtype": str(w.dtype),
                       "has_moments": "exp_avg" in entry}
    _write_meta_json(out_dir, step, manifest, layout)
    return _commit_export(out_dir, marker, run_dir)


def export_universal(state, out_dir: str, *, step: Optional[int] = None,
                     layout: Optional[dict] = None,
                     run_dir: Optional[str] = None) -> str:
    """Write a TrainState (or any (params, opt_state) carrier) as universal
    fp32 fragments under the crash-safe commit protocol.

    ``layout`` (checkpoint/reshard.py layout descriptor) converts the
    source engine's physical parameter layout (e.g. pipeline-stacked
    leaves) into the LOGICAL per-layer namespace before writing, and is
    recorded in meta.json.  ``run_dir`` additionally moves the
    ``latest_universal`` pointer post-commit, making this export the
    fleet's newest COMPLETE resume source."""
    if step is None:
        step = int(jax.device_get(state.step)) if hasattr(state, "step") else 0
    frags = state_fragments(state)
    if layout is not None:
        from deepspeed_tpu.checkpoint import reshard
        frags = reshard.to_logical(frags, layout)
    return write_fragments(frags, out_dir, step=int(step), layout=layout,
                           run_dir=run_dir)


# ---------------------------------------------------------------------------
# import
# ---------------------------------------------------------------------------

def _read_fragment(d: str, key: str):
    """Read one tensor fragment — native ``.npy``, or reference-style torch
    ``.pt`` (checkpoint/ds_to_universal.py writes fp32.pt/exp_avg.pt/...)."""
    from deepspeed_tpu.checkpoint import CheckpointCorrupt
    npy = os.path.join(d, key + ".npy")
    if os.path.exists(npy):
        try:
            return np.load(npy)
        except (OSError, ValueError, EOFError) as e:
            raise CheckpointCorrupt(
                f"{npy}: unreadable fragment ({e}) — torn write?") from e
    pt = os.path.join(d, key + ".pt")
    if os.path.exists(pt):
        import torch
        t = torch.load(pt, map_location="cpu", weights_only=True)
        return t.detach().to(torch.float32).numpy()
    return None


def load_universal(universal_dir: str,
                   name_map: Optional[Callable[[str], Optional[str]]] = None,
                   ) -> Tuple[Dict[str, Dict[str, np.ndarray]], dict]:
    """Read a universal dir → ({dotted_path: {fp32, exp_avg?, exp_avg_sq?}},
    meta).  ``name_map`` renames fragment dirs (e.g. torch module names from a
    reference-produced checkpoint → flax paths); returning None skips one.

    Raises :class:`~deepspeed_tpu.checkpoint.CheckpointNotFound` when the
    dir is not a universal checkpoint, and
    :class:`~deepspeed_tpu.checkpoint.CheckpointCorrupt` when it is one
    whose export never committed (in-progress marker still present) or
    whose fragments are torn — a crashed writer must never be mistaken for
    a resume source."""
    from deepspeed_tpu.checkpoint import (IN_PROGRESS_FILE, CheckpointCorrupt,
                                          CheckpointNotFound)
    if not os.path.isdir(universal_dir):
        raise CheckpointNotFound(
            f"{universal_dir}: no such universal checkpoint dir")
    if os.path.exists(os.path.join(universal_dir, IN_PROGRESS_FILE)):
        raise CheckpointCorrupt(
            f"{universal_dir} carries {IN_PROGRESS_FILE}: its export never "
            f"committed (writer died mid-export) — fragments may be torn.  "
            f"Resume from the previous complete export "
            f"(checkpoint.latest_universal skips this one).")
    zdir = os.path.join(universal_dir, "zero")
    if not os.path.isdir(zdir):
        raise CheckpointNotFound(f"{universal_dir}: no zero/ fragment dir "
                                 "(not a universal checkpoint)")
    frags: Dict[str, Dict[str, np.ndarray]] = {}
    for name in sorted(os.listdir(zdir)):
        d = os.path.join(zdir, name)
        if not os.path.isdir(d):
            continue
        path = name_map(name) if name_map else name
        if path is None:
            continue
        entry = {}
        for key in _FRAGMENT_KEYS:
            arr = _read_fragment(d, key)
            if arr is not None:
                entry[key] = arr
        if "fp32" not in entry:
            raise CheckpointCorrupt(
                f"{d}: no fp32 fragment (.npy or .pt) — torn export?")
        frags[path] = entry
    meta = {}
    mpath = os.path.join(universal_dir, "meta.json")
    if os.path.exists(mpath):
        with open(mpath) as f:
            meta = json.load(f)
    return frags, meta


def apply_universal(state, frags: Dict[str, Dict[str, np.ndarray]],
                    *, strict: bool = True, step: Optional[int] = None):
    """Return a new TrainState with params / masters / Adam moments replaced
    by the fragments (host arrays — caller device_puts with its shardings).

    The fragment set must cover the param tree exactly under ``strict``
    (reference universal_checkpoint.load_hp_checkpoint_state does the same
    per-fragment existence check).  ``step`` also resets the Adam bias-
    correction count — restored mature moments must not be re-bias-corrected
    as if at step 0.
    """
    import optax

    from deepspeed_tpu.runtime.zero import MasterWeightsState

    flat = _flatten_params(state.params)
    missing = [p for p in flat if p not in frags]
    extra = [p for p in frags if p not in flat]
    if strict and (missing or extra):
        raise ValueError(
            f"universal checkpoint does not match the model: missing "
            f"{missing[:4]}{'...' if len(missing) > 4 else ''}, unexpected "
            f"{extra[:4]}{'...' if len(extra) > 4 else ''}")

    def cast_like(arr, like):
        return np.asarray(arr).astype(np.asarray(like).dtype) \
            if hasattr(like, "dtype") else arr

    new_params = _unflatten_params(
        {p: cast_like(frags[p]["fp32"], flat[p]) if p in frags else flat[p]
         for p in flat})

    have_moments = any("exp_avg" in frags.get(p, {}) for p in flat)

    def visit(node):
        if isinstance(node, MasterWeightsState):
            flat_master = _flatten_params(node.master)
            m = _unflatten_params(
                {p: np.asarray(frags[p]["fp32"], np.float32)
                 if p in frags else flat_master[p] for p in flat})
            return MasterWeightsState(
                master=m, inner=_rewrite_nodes(node.inner, visit))
        if isinstance(node, optax.ScaleByAdamState) and have_moments:
            flat_mu = _flatten_params(node.mu)
            flat_nu = _flatten_params(node.nu)

            def moment(p, key, fallback):
                f = frags.get(p)
                if f is not None and key in f:
                    return np.asarray(f[key], np.float32)
                return fallback[p]       # moment-less leaf (e.g. int param)

            mu = _unflatten_params(
                {p: moment(p, "exp_avg", flat_mu) for p in flat})
            nu = _unflatten_params(
                {p: moment(p, "exp_avg_sq", flat_nu) for p in flat})
            count = (node.count if step is None
                     else np.asarray(step, np.asarray(node.count).dtype))
            return optax.ScaleByAdamState(count=count, mu=mu, nu=nu)
        return None

    new_opt = _rewrite_nodes(state.opt_state, visit)
    return state._replace(params=new_params, opt_state=new_opt)


def export_universal_offload(params, offload_opt, out_dir: str, *,
                             step: int = 0, layout: Optional[dict] = None,
                             run_dir: Optional[str] = None) -> str:
    """Export when the masters/moments live host-side in the ZeRO-Offload
    optimizer (runtime/offload.py OffloadAdam) — the reference's
    ds_to_universal likewise pulls fp32 state out of the swap tier."""
    flat = _flatten_params(params)
    sd = offload_opt.state_dict()
    frags: Dict[str, Dict[str, np.ndarray]] = {}
    for path, leaf in flat.items():
        key = path.replace(".", "/")         # offload keys are "/"-joined
        shape = np.asarray(leaf).shape
        if f"{key}::master" in sd:
            frags[path] = {
                "fp32": np.asarray(sd[f"{key}::master"],
                                   np.float32).reshape(shape),
                "exp_avg": np.asarray(sd[f"{key}::m"],
                                      np.float32).reshape(shape),
                "exp_avg_sq": np.asarray(sd[f"{key}::v"],
                                         np.float32).reshape(shape),
            }
        else:                                 # non-trainable leaf
            frags[path] = {"fp32": np.asarray(leaf)}
    if layout is not None:
        from deepspeed_tpu.checkpoint import reshard
        frags = reshard.to_logical(frags, layout)
    return write_fragments(frags, out_dir, step=int(step), layout=layout,
                           run_dir=run_dir)


def offload_state_dict_from_fragments(params,
                                      frags: Dict[str, Dict[str, np.ndarray]],
                                      step: int) -> Dict[str, Any]:
    """Build an OffloadAdam ``load_state_dict`` payload from fragments."""
    sd: Dict[str, Any] = {"step_count": int(step)}
    for path in _flatten_params(params):
        if path not in frags or "exp_avg" not in frags[path]:
            continue
        key = path.replace(".", "/")
        sd[f"{key}::master"] = frags[path]["fp32"].ravel()
        sd[f"{key}::m"] = frags[path]["exp_avg"].ravel()
        sd[f"{key}::v"] = frags[path]["exp_avg_sq"].ravel()
    return sd


# ---------------------------------------------------------------------------
# CLI (reference: ds_to_universal.py script)
# ---------------------------------------------------------------------------

def _restore_ckpt(ckpt_dir: str, tag: Optional[str]):
    """Resolve tag (falling back to the 'latest' file) and restore the orbax
    state on host.  Returns (state, tag) or (None, None) if no tag."""
    from deepspeed_tpu.checkpoint import latest_tag
    import orbax.checkpoint as ocp
    tag = tag or latest_tag(ckpt_dir)
    if tag is None:
        return None, None
    path = os.path.join(os.path.abspath(ckpt_dir), tag, "state")
    return ocp.StandardCheckpointer().restore(path), tag


def _cli(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.checkpoint.universal",
        description="Export an engine checkpoint to universal fp32 fragments "
                    "(reference checkpoint/ds_to_universal.py)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    ex = sub.add_parser("export", help="orbax checkpoint dir -> universal dir")
    ex.add_argument("ckpt_dir")
    ex.add_argument("out_dir")
    ex.add_argument("--tag", default=None)
    ins = sub.add_parser("inspect", help="print a universal dir's manifest")
    ins.add_argument("universal_dir")
    fp32 = sub.add_parser(
        "zero_to_fp32",
        help="orbax checkpoint dir -> ONE consolidated fp32 safetensors "
             "(reference utils/zero_to_fp32.py offline converter)")
    fp32.add_argument("ckpt_dir")
    fp32.add_argument("out_file")
    fp32.add_argument("--tag", default=None)
    args = ap.parse_args(argv)

    if args.cmd == "export":
        state, tag = _restore_ckpt(args.ckpt_dir, args.tag)
        if state is None:
            print(f"no 'latest' file in {args.ckpt_dir}; pass --tag")
            return 1

        class _Carrier:
            pass

        c = _Carrier()
        c.params = state["params"]
        c.opt_state = state["opt_state"]
        c.step = state.get("step", 0)
        export_universal(c, args.out_dir)
        print(f"exported {args.ckpt_dir}@{tag} -> {args.out_dir}")
        return 0
    if args.cmd == "zero_to_fp32":
        import safetensors.numpy
        state, tag = _restore_ckpt(args.ckpt_dir, args.tag)
        if state is None:
            print(f"no 'latest' file in {args.ckpt_dir}; pass --tag")
            return 1
        masters = _master_states(state["opt_state"])
        src = masters[0]["master"] if masters else state["params"]
        flat = {}
        for k, v in _flatten_params(src).items():
            arr = np.asarray(v)
            if arr.dtype != np.float32 and (arr.dtype.kind == "f"
                                            or arr.dtype
                                            == jax.numpy.bfloat16):
                arr = arr.astype(np.float32)
            flat[k] = arr
        os.makedirs(os.path.dirname(os.path.abspath(args.out_file)),
                    exist_ok=True)
        safetensors.numpy.save_file(flat, args.out_file)
        print(f"consolidated {len(flat)} tensors "
              f"({'fp32 masters' if masters else 'params'}) -> "
              f"{args.out_file}")
        return 0
    frags, meta = load_universal(args.universal_dir)
    print(json.dumps({"format": meta.get("format"),
                      "step": meta.get("step"),
                      "num_params": len(frags),
                      "total_elems": int(sum(f["fp32"].size
                                             for f in frags.values()))},
                     indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(_cli())
