"""Resharding restore — checkpoint relayout as a sharding-spec transform.

Per "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv:2004.13336), retargeting a checkpoint at a new topology is
a transform on the sharding/layout SPEC, not a checkpoint-format special
case.  Named shardings already make the *mesh* half of that free (orbax
restores any leaf into any sharding of the same global shape); this module
supplies the other half — the *structural* relayout between physical
parameter layouts that shape the pytree itself:

- the plain engine's per-layer tree (``backbone.block_{i}.*``),
- the pipeline engine's stage-stacked tree (``blocks.*`` leaves of shape
  ``[S, L/S, ...]`` with the stage dim sharded over ``pp``).

Every checkpoint is reduced to one LOGICAL namespace — the per-layer
(unstacked) dotted paths of the plain model — plus a ``layout`` descriptor
saying how the source engine physically laid those tensors out.  Restore
re-lays the logical fragments out for the TARGET engine and lets the
target's own shardings place them on its mesh, so any (dp, fsdp, pp, tp,
ZeRO-stage) source restores into any other (reference: the whole
checkpoint/ds_to_universal.py extract/merge pipeline exists to do this for
torch checkpoints).

Layout descriptors (stored in universal meta.json ``layout`` and in the
orbax checkpoint's ``client_state``):

- ``{"kind": "flat"}``                      — tree paths ARE logical paths
- ``{"kind": "pipe", "num_stages": S, "num_layers": L}``
                                            — pipeline-stacked (PipeGPT)
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import numpy as np

Fragments = Dict[str, Dict[str, np.ndarray]]

# physical pipe path → logical path for the non-stacked parameters
# (the same correspondence pipe/module.py gpt_params_to_pipe encodes for
# live params)
_PIPE_TO_LOGICAL = {
    "params.embed": "params.backbone.wte",
    "params.wpe": "params.backbone.wpe",
    "params.final_norm_scale": "params.backbone.final_norm.scale",
    "params.final_norm_bias": "params.backbone.final_norm.bias",
    "params.head": "params.lm_head",
}
_LOGICAL_TO_PIPE = {v: k for k, v in _PIPE_TO_LOGICAL.items()}
_PIPE_BLOCK_PREFIX = "params.blocks."
_LOGICAL_BLOCK_RE = re.compile(r"^params\.backbone\.block_(\d+)\.(.+)$")


def flat_layout() -> dict:
    return {"kind": "flat"}


def engine_layout(engine) -> dict:
    """The physical-layout descriptor of an engine's parameter tree."""
    model = engine.model
    if getattr(model, "is_pipeline", False) and hasattr(model, "num_stages"):
        return {"kind": "pipe", "num_stages": int(model.num_stages),
                "num_layers": int(model.cfg.num_layers)}
    return flat_layout()


def _pipe_dims(layout: dict) -> Tuple[int, int, int]:
    S = int(layout["num_stages"])
    L = int(layout["num_layers"])
    if S <= 0 or L % S:
        raise ValueError(f"bad pipe layout {layout}: num_layers must divide "
                         f"into num_stages")
    return S, L, L // S


def to_logical(frags: Fragments, layout: Optional[dict]) -> Fragments:
    """Source-physical fragments → logical per-layer fragments."""
    if not layout or layout.get("kind", "flat") == "flat":
        return frags
    if layout["kind"] != "pipe":
        raise ValueError(f"unknown checkpoint layout kind "
                         f"{layout['kind']!r}")
    S, L, Lps = _pipe_dims(layout)
    out: Fragments = {}
    for path, entry in frags.items():
        if path.startswith(_PIPE_BLOCK_PREFIX):
            sub = path[len(_PIPE_BLOCK_PREFIX):]
            for i in range(L):
                s, li = divmod(i, Lps)
                out[f"params.backbone.block_{i}.{sub}"] = {
                    k: np.asarray(v)[s, li] for k, v in entry.items()}
        else:
            out[_PIPE_TO_LOGICAL.get(path, path)] = entry
    return out


def from_logical(frags: Fragments, layout: Optional[dict]) -> Fragments:
    """Logical fragments → the TARGET engine's physical layout."""
    if not layout or layout.get("kind", "flat") == "flat":
        return frags
    if layout["kind"] != "pipe":
        raise ValueError(f"unknown checkpoint layout kind "
                         f"{layout['kind']!r}")
    S, L, Lps = _pipe_dims(layout)
    out: Fragments = {}
    blocks: Dict[str, Dict[int, Dict[str, np.ndarray]]] = {}
    for path, entry in frags.items():
        m = _LOGICAL_BLOCK_RE.match(path)
        if m:
            i, sub = int(m.group(1)), m.group(2)
            blocks.setdefault(sub, {})[i] = entry
        else:
            out[_LOGICAL_TO_PIPE.get(path, path)] = entry
    for sub, per_layer in blocks.items():
        missing = [i for i in range(L) if i not in per_layer]
        if missing:
            raise ValueError(
                f"checkpoint covers layers {sorted(per_layer)} of "
                f"'{sub}' but the pipeline layout needs all {L} "
                f"(missing {missing[:4]}{'...' if len(missing) > 4 else ''})")
        keys = per_layer[0].keys()
        entry = {}
        for k in keys:
            arrs = [np.asarray(per_layer[i][k]) for i in range(L)]
            entry[k] = np.stack(arrs).reshape((S, Lps) + arrs[0].shape)
        out[_PIPE_BLOCK_PREFIX + sub] = entry
    return out


def relayout(frags: Fragments, src_layout: Optional[dict],
             dst_layout: Optional[dict]) -> Fragments:
    """source physical → logical → target physical (identity when both are
    flat; a pipe→pipe restore across different stage counts unstacks and
    restacks through the logical view)."""
    return from_logical(to_logical(frags, src_layout), dst_layout)


# ---------------------------------------------------------------------------
# cross-topology orbax restore (engine.load_checkpoint fallback)
# ---------------------------------------------------------------------------

class _Carrier:
    """Duck-typed TrainState for universal.state_fragments over a raw
    (target-less) orbax restore."""

    def __init__(self, raw: Dict[str, Any]):
        self.params = raw["params"]
        self.opt_state = raw.get("opt_state", ())
        self.step = raw.get("step", 0)


def fragments_from_orbax(load_dir: str, tag: str) -> Fragments:
    """Restore an orbax tag WITHOUT a target structure (host numpy) and
    reduce it to universal fragments — fp32 masters + Adam moments when the
    saved optimizer carried them, raw params otherwise."""
    import os

    from deepspeed_tpu import checkpoint as ckpt
    from deepspeed_tpu.checkpoint import universal
    path = os.path.join(os.path.abspath(load_dir), tag, "state")
    # the package's long-lived checkpointer — a fresh instance per restore
    # would serialize on its own setup (see checkpoint/__init__.py)
    raw = ckpt._checkpointer().restore(path)
    return universal.state_fragments(_Carrier(raw))
