"""Guarded checkpoint ring — K rolling universal exports with
health-verified rollback-eligibility stamps.

The guardian (runtime/guardian.py) can only roll back to a checkpoint it
can TRUST: an export taken two steps before a NaN burst may already carry
the poisoned optimizer moments, and "the newest export" is exactly the
wrong rollback target.  The ring therefore separates two properties:

- **complete** — the export committed under the crash-safe protocol
  (checkpoint/universal.py: ``.in_progress`` marker → fragments + meta
  durable → marker off).  Completeness is what PR 6's resume path already
  checks; a torn ring entry is never selected for anything.
- **rollback-eligible** — the export's TRAILING anomaly window was clean:
  the guardian observed ``clean_window`` further steps with no anomaly
  before stamping it.  The stamp (``rollback_eligible.json``) is written
  atomically (tmp + rename) INSIDE the committed export dir, so it is
  either absent or whole; an export that never earns its stamp is just a
  regular resume candidate, never a rollback target.

Entries are named ``ring_<step>`` under the run dir — ordinary universal
exports, so the elastic-agent resume scan (``universal_candidates``) sees
them too.  ``prune`` keeps the newest ``keep`` entries plus, always, the
newest ELIGIBLE entry (the guardian must never be left without a rollback
source); deletion drops the ``.in_progress`` marker back into the doomed
dir first, so a crash mid-delete leaves a directory every reader already
refuses, not a half-present export.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import List, NamedTuple, Optional

from deepspeed_tpu.checkpoint import (IN_PROGRESS_FILE, _universal_step,
                                      universal_complete)
from deepspeed_tpu.utils.logging import logger

RING_PREFIX = "ring_"
ELIGIBLE_FILE = "rollback_eligible.json"
RING_SIZE_GAUGE = "checkpoint_ring_size"


class RingEntry(NamedTuple):
    step: int
    path: str
    eligible: bool


def is_eligible(path: str) -> bool:
    """True iff ``path`` is a COMPLETE universal export carrying a whole
    eligibility stamp."""
    if not universal_complete(path):
        return False
    stamp = os.path.join(path, ELIGIBLE_FILE)
    try:
        with open(stamp) as f:
            json.load(f)
        return True
    except (OSError, ValueError):
        return False


class CheckpointRing:
    """K rolling universal exports under ``run_dir``, stamped
    rollback-eligible by the guardian once their trailing anomaly window
    proves clean."""

    def __init__(self, run_dir: str, keep: int = 3, registry=None):
        if keep < 1:
            raise ValueError(f"ring keep must be >= 1, got {keep}")
        self.run_dir = run_dir
        self.keep = int(keep)
        self.registry = registry
        os.makedirs(run_dir, exist_ok=True)

    # ------------------------------------------------------------ exports

    def path_for(self, step: int) -> str:
        return os.path.join(self.run_dir, f"{RING_PREFIX}{int(step):08d}")

    def export(self, engine) -> str:
        """Commit a ring entry for the engine's current step (crash-safe —
        the same ``export_universal_checkpoint`` protocol as drains) and
        prune.  Idempotent: an already-committed same-step entry is reused,
        never re-marked in-progress (the drain-path lesson)."""
        step = engine.global_steps
        path = self.path_for(step)
        if not (universal_complete(path) and _universal_step(path) == step):
            # a fresh commit must never inherit a stale eligibility stamp
            # (a dir left torn by a crash mid-prune/discard still carries
            # its rollback_eligible.json): eligibility is earned by THIS
            # export's trailing window only
            try:
                os.remove(os.path.join(path, ELIGIBLE_FILE))
            except OSError:
                pass
            engine.export_universal_checkpoint(path, run_dir=self.run_dir)
        self.prune()
        return path

    # ------------------------------------------------------- eligibility

    def stamp(self, path: str, *, step: int, stamped_at_step: int,
              clean_window: int) -> None:
        """Mark a COMPLETE entry rollback-eligible.  Atomic (tmp + rename):
        readers see no stamp or a whole one, and a crash between the
        export commit and the stamp merely leaves a valid-but-ineligible
        entry."""
        if not universal_complete(path):
            raise ValueError(
                f"refusing to stamp {path}: not a COMPLETE universal "
                f"export (torn or foreign)")
        stamp = os.path.join(path, ELIGIBLE_FILE)
        tmp = f"{stamp}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"step": int(step),
                       "stamped_at_step": int(stamped_at_step),
                       "clean_window": int(clean_window),
                       "unix_time": time.time()}, f)
        os.replace(tmp, stamp)
        self._export_gauge()

    # ------------------------------------------------------------ queries

    def entries(self) -> List[RingEntry]:
        """COMPLETE ring entries, oldest step first."""
        out = []
        if not os.path.isdir(self.run_dir):
            return out
        for name in sorted(os.listdir(self.run_dir)):
            if not name.startswith(RING_PREFIX):
                continue
            path = os.path.join(self.run_dir, name)
            if not universal_complete(path):
                continue
            step = _universal_step(path)
            if step is None:
                continue
            out.append(RingEntry(step=step, path=path,
                                 eligible=is_eligible(path)))
        out.sort(key=lambda e: e.step)
        return out

    def latest_eligible(self, *, max_step: Optional[int] = None
                        ) -> Optional[RingEntry]:
        """Newest rollback-eligible entry (optionally at/below
        ``max_step``), or None — the guardian's rollback target."""
        best = None
        for e in self.entries():
            if not e.eligible:
                continue
            if max_step is not None and e.step > max_step:
                continue
            if best is None or e.step > best.step:
                best = e
        return best

    def discard_after(self, step: int) -> List[str]:
        """Delete every ring entry NEWER than ``step`` — after a rollback
        those entries belong to the abandoned timeline, and a later
        re-export at the same step number must never silently reuse them
        (the replayed run skips a data window, so same-step params
        differ).  Same crash-safe deletion as prune.  Returns the deleted
        paths."""
        deleted = []
        for e in self.entries():
            if e.step <= step:
                continue
            try:
                with open(os.path.join(e.path, IN_PROGRESS_FILE), "w") as f:
                    f.write("discarded: post-rollback timeline")
                shutil.rmtree(e.path)
                deleted.append(e.path)
            except OSError as exc:
                logger.warning(f"checkpoint ring: discard of {e.path} "
                               f"failed: {exc!r}")
        self._export_gauge()
        return deleted

    # ------------------------------------------------------------ pruning

    def prune(self) -> List[str]:
        """Delete entries beyond the newest ``keep``, always retaining the
        newest ELIGIBLE entry even when it falls off the tail.  Returns the
        deleted paths."""
        entries = self.entries()
        kept = entries[-self.keep:]
        protected = {e.path for e in kept}
        newest_eligible = self.latest_eligible()
        if newest_eligible is not None:
            protected.add(newest_eligible.path)
        deleted = []
        for e in entries:
            if e.path in protected:
                continue
            try:
                # mark torn FIRST: a crash mid-rmtree must leave a dir
                # every complete-export check already rejects
                with open(os.path.join(e.path, IN_PROGRESS_FILE), "w") as f:
                    f.write("pruning")
                shutil.rmtree(e.path)
                deleted.append(e.path)
            except OSError as exc:
                logger.warning(f"checkpoint ring: prune of {e.path} "
                               f"failed: {exc!r}")
        self._export_gauge()
        return deleted

    def _export_gauge(self) -> None:
        if self.registry is None:
            return
        entries = self.entries()
        g = self.registry.gauge(
            RING_SIZE_GAUGE,
            "guarded checkpoint ring entries on disk, by eligibility "
            "(eligible = trailing anomaly window verified clean)")
        g.set(float(sum(1 for e in entries if e.eligible)),
              eligible="true")
        g.set(float(sum(1 for e in entries if not e.eligible)),
              eligible="false")
