"""HF checkpoint engine — stream safetensors checkpoints into the flax tree.

TPU-native analog of the reference's checkpoint engines + injection-policy
model zoo: ``HuggingFaceCheckpointEngine`` (inference/v2/checkpoint/
huggingface_engine.py:124) iterates safetensors shards and yields tensors;
``replace_module`` (module_inject/replace_module.py:183) + the per-arch
containers (module_inject/containers/) map them onto fused modules.  Here the
zoo is a NAME MAP per architecture onto the one GPT-family flax tree
(models/gpt.py) — llama/mistral/qwen2/gpt2 are all config points of the same
module, so "injection" is a dict of weight transposes, not graph surgery.

Entry points:
- ``config_from_hf(path)``   → GPTConfig from an HF ``config.json``
- ``load_hf_checkpoint(path)`` → (GPTConfig, params tree) streaming shards
- ``deepspeed_tpu.init_inference("path/to/hf")`` and the v2 engine accept an
  HF model directory directly.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist

# architectures served by the GPT-family tree (reference zoo:
# inference/v2/model_implementations/{llama_v2,mistral,mixtral,qwen_v2,opt,
# phi,falcon}, module_inject/containers/{gpt2,opt}.py)
_LLAMA_LIKE = {"LlamaForCausalLM", "MistralForCausalLM", "Qwen2ForCausalLM",
               "MixtralForCausalLM"}
_GPT2_LIKE = {"GPT2LMHeadModel"}
_OPT_LIKE = {"OPTForCausalLM"}
_PHI_LIKE = {"PhiForCausalLM"}
_FALCON_LIKE = {"FalconForCausalLM"}
_GPTJ_LIKE = {"GPTJForCausalLM"}
_NEOX_LIKE = {"GPTNeoXForCausalLM"}
_GPTNEO_LIKE = {"GPTNeoForCausalLM"}
_STABLELM_LIKE = {"StableLmForCausalLM"}
_BIGCODE_LIKE = {"GPTBigCodeForCausalLM"}
_GEMMA_LIKE = {"GemmaForCausalLM"}
_PHI3_LIKE = {"Phi3ForCausalLM"}
_BLOOM_LIKE = {"BloomForCausalLM"}
SUPPORTED_ARCHITECTURES = sorted(_LLAMA_LIKE | _GPT2_LIKE | _OPT_LIKE
                                 | _PHI_LIKE | _FALCON_LIKE | _GPTJ_LIKE
                                 | _NEOX_LIKE | _BLOOM_LIKE | _GPTNEO_LIKE
                                 | _STABLELM_LIKE | _BIGCODE_LIKE
                                 | _GEMMA_LIKE | _PHI3_LIKE)


# HF ACT2FN name → models.gpt.mlp_activation name (HF "gelu" is exact erf;
# "gelu_new"/"gelu_pytorch_tanh" are the tanh approximation)
_HF_ACT = {"gelu": "gelu_exact", "gelu_new": "gelu",
           "gelu_pytorch_tanh": "gelu", "relu": "relu",
           "quick_gelu": "quick_gelu"}


def _map_activation(arch: str, name: str) -> str:
    try:
        return _HF_ACT[name]
    except KeyError:
        raise ValueError(f"{arch}: activation {name!r} is not implemented; "
                         f"supported: {sorted(_HF_ACT)}") from None


def _read_json(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def _arch_of(hf: Dict[str, Any]) -> str:
    archs = hf.get("architectures") or []
    return archs[0] if archs else hf.get("model_type", "?")


def _reject_unsupported_semantics(hf: Dict[str, Any], arch: str,
                                  max_seq_len: Optional[int]) -> None:
    """Raise rather than silently serve a DIFFERENT model: config fields that
    change the math must be implemented or rejected (round-2 review)."""
    scaling = hf.get("rope_scaling")
    if scaling and scaling.get("rope_type", scaling.get("type")) not in (
            "default", "llama3", "linear", "longrope"):
        raise ValueError(
            f"{arch}: rope_scaling={scaling!r} is not implemented "
            f"(yarn/dynamic); logits would be silently wrong")
    if hf.get("mlp_bias"):
        raise ValueError(
            f"{arch}: mlp_bias=true (gate/up/down biases) is not implemented "
            f"in the SwiGLU body; logits would be silently wrong")


def _rope_scaling_of(hf: Dict[str, Any]):
    """HF rope_scaling dict → GPTConfig.rope_scaling tuple (llama-3.1
    piecewise scheme and linear position interpolation; anything else was
    rejected by _reject_unsupported_semantics)."""
    scaling = hf.get("rope_scaling")
    if not scaling:
        return None
    kind = scaling.get("rope_type", scaling.get("type"))
    try:
        if kind == "llama3":
            return ("llama3", float(scaling["factor"]),
                    float(scaling["low_freq_factor"]),
                    float(scaling["high_freq_factor"]),
                    float(scaling["original_max_position_embeddings"]))
        if kind == "linear":
            return ("linear", float(scaling["factor"]))
        if kind == "longrope":
            # phi-3 long-context (HF _compute_longrope_parameters): per-
            # channel short/long factors + the paper's attention factor.
            # HF precedence: a (top-level or scaling-dict) original_max
            # overrides rope_scaling["factor"] via msl/orig; with neither
            # the extension ratio is underivable — reject, don't guess.
            import math as _math
            short = tuple(float(x) for x in scaling["short_factor"])
            long_ = tuple(float(x) for x in scaling["long_factor"])
            msl = float(hf.get("max_position_embeddings", 2048))
            orig = (hf.get("original_max_position_embeddings")
                    or scaling.get("original_max_position_embeddings"))
            if orig is not None:
                orig = float(orig)
                factor = msl / orig
            elif scaling.get("factor") is not None:
                orig = msl            # HF fallback: orig = max_position
                factor = float(scaling["factor"])
            else:
                raise ValueError(
                    "rope_scaling longrope needs "
                    "original_max_position_embeddings (top-level or in "
                    "rope_scaling) or a 'factor' — neither present; the "
                    "attention factor and regime boundary are underivable")
            att = scaling.get("attention_factor")
            if att is None:
                att = (1.0 if factor <= 1.0 else
                       _math.sqrt(1.0 + _math.log(factor)
                                  / _math.log(orig)))
            return ("longrope", float(att), short, long_, orig)
    except KeyError as e:
        raise ValueError(
            f"rope_scaling type {kind!r} is missing required key {e} "
            f"(got keys {sorted(scaling)}) — corrupt or hand-edited "
            f"config.json") from None
    return None
def _sliding_window_of(hf: Dict[str, Any],
                       max_seq_len: Optional[int]) -> Optional[int]:
    """Effective sliding window (mistral/qwen2): None when disabled or when
    the window never binds at the serving length."""
    window = hf.get("sliding_window")
    uses_window = window is not None and (
        hf.get("use_sliding_window", True) if "use_sliding_window" in hf
        else True)
    if not uses_window:
        return None
    msl = hf.get("max_position_embeddings", 2048)
    eff = min(msl, max_seq_len or msl)
    return int(window) if window < eff else None


def config_from_hf(model_path: str, *, max_seq_len: Optional[int] = None,
                   dtype=None):
    """Build a GPTConfig from ``<model_path>/config.json``.

    max_seq_len caps the (often huge) HF ``max_position_embeddings`` — it only
    sizes KV caches here, rope needs no table.
    """
    from deepspeed_tpu.models.gpt import GPTConfig

    hf = _read_json(os.path.join(model_path, "config.json"))
    arch = _arch_of(hf)

    if arch in _LLAMA_LIKE:
        _reject_unsupported_semantics(hf, arch, max_seq_len)
        hidden = hf["hidden_size"]
        heads = hf["num_attention_heads"]
        head_dim = hf.get("head_dim") or hidden // heads
        msl = hf.get("max_position_embeddings", 2048)
        attn_bias = bool(hf.get("attention_bias", False))
        # sliding window (mistral/qwen2); qwen2 gates SWA to layers
        # >= max_window_layers (modeling_qwen2 per-layer check)
        swa = _sliding_window_of(hf, max_seq_len)
        swa_layers: tuple = ()
        mwl = hf.get("max_window_layers")
        if swa and mwl is not None:
            mwl = int(mwl)
            if mwl >= hf["num_hidden_layers"]:
                swa = None                 # no layer ever windows
            elif mwl > 0:
                swa_layers = tuple(range(mwl, hf["num_hidden_layers"]))
        moe_kw = {}
        if arch == "MixtralForCausalLM":
            # every layer is MoE with SwiGLU experts (modeling_mixtral.py
            # MixtralSparseMoeBlock); gated_mlp=True drives the per-expert
            # gate in moe/layer.py
            # dropless routing: inference must never drop tokens (the
            # capacity path is a training trade-off), and it matches HF's
            # exact top-k + renormalize semantics
            moe_kw = dict(num_experts=hf["num_local_experts"],
                          moe_k=hf["num_experts_per_tok"],
                          moe_every=1, moe_dropless=True)
        return GPTConfig(
            **moe_kw,
            vocab_size=hf["vocab_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=heads,
            head_dim=head_dim,
            hidden_size=hidden,
            mlp_dim_override=hf["intermediate_size"],
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=True, use_rmsnorm=True, gated_mlp=True,
            num_kv_heads=hf.get("num_key_value_heads", heads),
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rope_scaling=_rope_scaling_of(hf),
            norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
            qkv_bias=(arch == "Qwen2ForCausalLM") or attn_bias,
            attn_out_bias=attn_bias,
            sliding_window=swa, local_attn_layers=swa_layers,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _GPT2_LIKE:
        hidden = hf["n_embd"]
        n_inner = hf.get("n_inner") or 4 * hidden
        msl = hf.get("n_positions", 1024)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["n_layer"],
            num_heads=hf["n_head"],
            head_dim=hidden // hf["n_head"],
            hidden_size=hidden,
            mlp_dim_override=n_inner,
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=False, use_rmsnorm=False, gated_mlp=False,
            tie_embeddings=True,
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            qkv_bias=True, attn_out_bias=True, mlp_bias=True,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _OPT_LIKE:
        # reference module_inject/containers/opt.py (HFOPTLayerPolicy):
        # learned positions (offset-2 table, sliced at load), LayerNorm,
        # ReLU MLP, biases everywhere, tied embeddings
        hidden = hf["hidden_size"]
        if hf.get("word_embed_proj_dim", hidden) != hidden:
            raise ValueError(
                f"{arch}: word_embed_proj_dim != hidden_size (opt-350m-style "
                "embedding projections) is not implemented")
        if not hf.get("do_layer_norm_before", True):
            raise ValueError(
                f"{arch}: do_layer_norm_before=false (post-norm opt-350m) "
                "is not implemented; logits would be silently wrong")
        if not hf.get("enable_bias", True) or not hf.get(
                "layer_norm_elementwise_affine", True):
            raise ValueError(f"{arch}: enable_bias/layer_norm_elementwise_"
                             "affine=false variants are not implemented")
        act = _map_activation(arch, hf.get("activation_function", "relu"))
        msl = hf.get("max_position_embeddings", 2048)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            head_dim=hidden // hf["num_attention_heads"],
            hidden_size=hidden,
            mlp_dim_override=hf["ffn_dim"],
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=False, use_rmsnorm=False, gated_mlp=False,
            activation=act,
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
            norm_eps=1e-5,
            qkv_bias=True, attn_out_bias=True, mlp_bias=True,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _PHI_LIKE:
        # reference inference/v2/model_implementations/phi: parallel
        # attention+MLP off one shared LayerNorm, partial rotary, biased
        # projections and lm_head
        _reject_unsupported_semantics(hf, arch, max_seq_len)
        if hf.get("qk_layernorm"):
            raise ValueError(f"{arch}: qk_layernorm=true is not implemented")
        hidden = hf["hidden_size"]
        heads = hf["num_attention_heads"]
        msl = hf.get("max_position_embeddings", 2048)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=heads,
            head_dim=hidden // heads,
            hidden_size=hidden,
            mlp_dim_override=hf["intermediate_size"],
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=True, use_rmsnorm=False, gated_mlp=False,
            activation=_map_activation(arch, hf.get("hidden_act",
                                                    "gelu_new")),
            parallel_block=True, parallel_norms=1,
            rope_pct=float(hf.get("partial_rotary_factor", 0.5)),
            num_kv_heads=hf.get("num_key_value_heads") or heads,
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rope_scaling=_rope_scaling_of(hf),
            norm_eps=float(hf.get("layer_norm_eps", 1e-5)),
            qkv_bias=True, attn_out_bias=True, mlp_bias=True,
            unembed_bias=True,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _FALCON_LIKE:
        # reference inference/v2/model_implementations/falcon: rotary + MQA/
        # GQA, LayerNorm, bias-free projections, parallel attention (7b: one
        # shared input norm; 40b new_decoder_architecture: ln_attn + ln_mlp)
        _reject_unsupported_semantics(hf, arch, max_seq_len)
        use_alibi = bool(hf.get("alibi", False))     # falcon-rw lineage
        has_bias = bool(hf.get("bias", False))
        hidden = hf["hidden_size"]
        heads = hf["num_attention_heads"]
        new_arch = bool(hf.get("new_decoder_architecture", False))
        if new_arch:
            # HF FalconConfig defaults num_kv_heads to num_attention_heads
            nkv = hf.get("num_kv_heads") or heads
        elif hf.get("multi_query", True):
            nkv = 1
        else:
            nkv = heads
        # HF Falcon ignores parallel_attn entirely when
        # new_decoder_architecture is set (modeling_falcon: the new layout is
        # always parallel ln_attn/ln_mlp) — honoring a parallel_attn=false
        # there would silently serve a sequential-residual model
        parallel = new_arch or bool(hf.get("parallel_attn", True))
        # falcon-40b pairs ln_attn/ln_mlp; falcon-11B (num_ln_in_parallel_attn
        # =1) shares one input_layernorm like the 7b layout
        num_ln = hf.get("num_ln_in_parallel_attn")
        two_norms = new_arch and (num_ln is None or num_ln == 2)
        msl = hf.get("max_position_embeddings", 2048)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=heads,
            head_dim=hidden // heads,
            hidden_size=hidden,
            mlp_dim_override=hf.get("ffn_hidden_size") or 4 * hidden,
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=not use_alibi, use_alibi=use_alibi,
            alibi_prescale=use_alibi,
            use_rmsnorm=False, gated_mlp=False,
            activation=_map_activation(arch, hf.get("activation", "gelu")),
            parallel_block=parallel,
            parallel_norms=2 if (parallel and two_norms) else 1,
            num_kv_heads=nkv,
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rope_scaling=_rope_scaling_of(hf),
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            qkv_bias=has_bias, attn_out_bias=has_bias, mlp_bias=has_bias,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _GPTJ_LIKE:
        # reference module_inject/containers/gptj.py: parallel residual off
        # one shared ln, partial INTERLEAVED rotary (converted to half-split
        # by a head-dim permutation in _gptj_tree), bias-free attention,
        # biased fc + lm_head
        hidden = hf["n_embd"]
        heads = hf["n_head"]
        hd = hidden // heads
        msl = hf.get("n_positions", 2048)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["n_layer"],
            num_heads=heads,
            head_dim=hd,
            hidden_size=hidden,
            mlp_dim_override=hf.get("n_inner") or 4 * hidden,
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=True, use_rmsnorm=False, gated_mlp=False,
            activation=_map_activation(arch, hf.get("activation_function",
                                                    "gelu_new")),
            parallel_block=True, parallel_norms=1,
            rope_pct=(hf.get("rotary_dim") or hd) / hd,  # null = full rotary
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            mlp_bias=True, unembed_bias=True,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _NEOX_LIKE:
        # reference module_inject/containers/gptneox.py: fused per-head qkv,
        # half-split partial rotary (native layout), dual-norm parallel
        # residual when use_parallel_residual
        _reject_unsupported_semantics(hf, arch, max_seq_len)
        hidden = hf["hidden_size"]
        heads = hf["num_attention_heads"]
        parallel = bool(hf.get("use_parallel_residual", True))
        msl = hf.get("max_position_embeddings", 2048)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=heads,
            head_dim=hidden // heads,
            hidden_size=hidden,
            mlp_dim_override=hf["intermediate_size"],
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=True, use_rmsnorm=False, gated_mlp=False,
            activation=_map_activation(arch, hf.get("hidden_act", "gelu")),
            parallel_block=parallel,
            parallel_norms=2 if parallel else 1,
            rope_pct=float(hf.get("rotary_pct", 0.25)),
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            rope_theta=float(hf.get("rotary_emb_base", 10000.0)),
            rope_scaling=_rope_scaling_of(hf),
            norm_eps=float(hf.get("layer_norm_eps", 1e-5)),
            qkv_bias=True, attn_out_bias=True, mlp_bias=True,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _GPTNEO_LIKE:
        # reference module_inject/containers/gptneo.py: learned positions,
        # UNSCALED attention logits, alternating global/local layers with a
        # 256-token window, bias-free qkv
        hidden = hf["hidden_size"]
        heads = hf["num_heads"] if "num_heads" in hf else hf["num_attention_heads"]  # noqa: E501
        layers = hf.get("num_layers") or hf["num_hidden_layers"]
        att_types = hf.get("attention_types") or [[["global", "local"],
                                                   layers // 2]]
        layer_kinds: list = []
        for kinds, rep in att_types:
            layer_kinds += list(kinds) * rep
        local_ids = tuple(i for i, k in enumerate(layer_kinds)
                          if k == "local")
        msl = hf.get("max_position_embeddings", 2048)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=layers,
            num_heads=heads,
            head_dim=hidden // heads,
            hidden_size=hidden,
            mlp_dim_override=hf.get("intermediate_size") or 4 * hidden,
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=False, use_rmsnorm=False, gated_mlp=False,
            activation=_map_activation(arch, hf.get("activation_function",
                                                    "gelu_new")),
            attn_scale=1.0,               # gpt-neo does not scale by 1/√d
            sliding_window=(int(hf.get("window_size", 256))
                            if local_ids else None),
            local_attn_layers=local_ids,
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            attn_out_bias=True, mlp_bias=True,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _STABLELM_LIKE:
        # stablelm-2/zephyr: llama weight layout with LayerNorm (scale+bias)
        # and partial rotary; SwiGLU MLP
        _reject_unsupported_semantics(hf, arch, max_seq_len)
        if hf.get("use_parallel_residual"):
            raise ValueError(f"{arch}: use_parallel_residual=true "
                             "(stablelm-alpha) is not implemented")
        if hf.get("qk_layernorm"):
            raise ValueError(f"{arch}: qk_layernorm=true is not implemented")
        if hf.get("hidden_act", "silu") != "silu":
            raise ValueError(
                f"{arch}: hidden_act={hf['hidden_act']!r} is not implemented "
                "(the gated MLP gate is silu); logits would be silently "
                "wrong")
        hidden = hf["hidden_size"]
        heads = hf["num_attention_heads"]
        msl = hf.get("max_position_embeddings", 4096)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=heads,
            head_dim=hidden // heads,
            hidden_size=hidden,
            mlp_dim_override=hf["intermediate_size"],
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=True, use_rmsnorm=False, gated_mlp=True,
            rope_pct=float(hf.get("partial_rotary_factor", 0.25)),
            num_kv_heads=hf.get("num_key_value_heads", heads),
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rope_scaling=_rope_scaling_of(hf),
            norm_eps=float(hf.get("layer_norm_eps", 1e-5)),
            qkv_bias=bool(hf.get("use_qkv_bias", False)),
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _PHI3_LIKE:
        # phi-3 (reference inference/v2/model_implementations/phi3): llama
        # semantics with FUSED qkv_proj and gate_up_proj (split in the tree
        # builder); longrope scaling is LIVE (short/long factor tables
        # selected in-graph by sequence length, models/gpt.py rope)
        _reject_unsupported_semantics(hf, arch, max_seq_len)
        hidden = hf["hidden_size"]
        heads = hf["num_attention_heads"]
        msl = hf.get("max_position_embeddings", 4096)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=heads,
            head_dim=hidden // heads,
            hidden_size=hidden,
            mlp_dim_override=hf["intermediate_size"],
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=True, use_rmsnorm=True, gated_mlp=True,
            rope_pct=float(hf.get("partial_rotary_factor", 1.0)),
            num_kv_heads=hf.get("num_key_value_heads", heads),
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rope_scaling=_rope_scaling_of(hf),
            norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
            sliding_window=_sliding_window_of(hf, max_seq_len),
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _GEMMA_LIKE:
        # gemma: llama layout with (1+w) RMSNorm scales (absorbed at load),
        # √H-scaled embeddings (unembed unscaled), GeGLU, explicit head_dim
        _reject_unsupported_semantics(hf, arch, max_seq_len)
        hidden = hf["hidden_size"]
        heads = hf["num_attention_heads"]
        msl = hf.get("max_position_embeddings", 8192)
        # HF IGNORES gemma's legacy hidden_act field and forces
        # gelu_pytorch_tanh when hidden_activation is absent (GemmaMLP warns)
        act = hf.get("hidden_activation") or "gelu_pytorch_tanh"
        gemma_bias = bool(hf.get("attention_bias", False))
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=heads,
            head_dim=hf.get("head_dim") or hidden // heads,
            hidden_size=hidden,
            mlp_dim_override=hf["intermediate_size"],
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=True, use_rmsnorm=True, gated_mlp=True,
            gate_act=_map_activation(arch, act),
            embed_scale=float(hidden) ** 0.5,
            num_kv_heads=hf.get("num_key_value_heads", heads),
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rope_scaling=_rope_scaling_of(hf),
            norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
            qkv_bias=gemma_bias, attn_out_bias=gemma_bias,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _BIGCODE_LIKE:
        # starcoder/santacoder (reference v1 injection served these as
        # gpt2-family): gpt2 layout with torch-Linear weights, MQA fused
        # q|k|v rows, tanh-gelu
        hidden = hf["n_embd"]
        heads = hf["n_head"]
        msl = hf.get("n_positions", 2048)
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["n_layer"],
            num_heads=heads,
            head_dim=hidden // heads,
            hidden_size=hidden,
            mlp_dim_override=hf.get("n_inner") or 4 * hidden,
            max_seq_len=min(msl, max_seq_len or msl),
            use_rope=False, use_rmsnorm=False, gated_mlp=False,
            activation=_map_activation(arch, hf.get("activation_function",
                                                    "gelu_pytorch_tanh")),
            num_kv_heads=1 if hf.get("multi_query", True) else heads,
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            qkv_bias=True, attn_out_bias=True, mlp_bias=True,
            dtype=dtype or jnp.bfloat16,
        )
    if arch in _BLOOM_LIKE:
        # reference module_inject/containers/bloom.py: alibi positions (no
        # table), embedding LayerNorm, fused per-head qkv, tied embeddings
        hidden = hf.get("hidden_size") or hf["n_embed"]  # bloom legacy key
        heads = hf.get("n_head") or hf["num_attention_heads"]
        layers = hf.get("n_layer") or hf["num_hidden_layers"]
        msl = max_seq_len or 2048      # alibi: no positional table to bound
        return GPTConfig(
            vocab_size=hf["vocab_size"],
            num_layers=layers,
            num_heads=heads,
            head_dim=hidden // heads,
            hidden_size=hidden,
            mlp_dim_override=4 * hidden,
            max_seq_len=msl,
            use_rope=False, use_rmsnorm=False, gated_mlp=False,
            use_alibi=True, embed_norm=True,
            activation="gelu",          # BloomGelu = tanh approximation
            tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
            norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
            qkv_bias=True, attn_out_bias=True, mlp_bias=True,
            dtype=dtype or jnp.bfloat16,
        )
    raise ValueError(
        f"unsupported HF architecture {arch!r}; supported: "
        f"{SUPPORTED_ARCHITECTURES} (reference zoo: module_inject/"
        f"replace_module.py replace_policies)")


class _ShardReader:
    """Iterate tensors across safetensors shards without loading a shard twice
    (reference huggingface_engine.py:124 parameters() generator)."""

    def __init__(self, model_path: str):
        self.path = model_path
        index = os.path.join(model_path, "model.safetensors.index.json")
        single = os.path.join(model_path, "model.safetensors")
        if os.path.exists(index):
            weight_map = _read_json(index)["weight_map"]
            self.name_to_file = {k: os.path.join(model_path, v)
                                 for k, v in weight_map.items()}
        elif os.path.exists(single):
            from safetensors import safe_open
            with safe_open(single, framework="np") as f:
                names = list(f.keys())
            self.name_to_file = {k: single for k in names}
        else:
            raise FileNotFoundError(
                f"no model.safetensors[.index.json] under {model_path} "
                f"(torch .bin checkpoints are not supported — convert with "
                f"save_pretrained(safe_serialization=True))")
        self._open: Dict[str, Any] = {}

    def names(self):
        return self.name_to_file.keys()

    def get(self, name: str) -> np.ndarray:
        # framework="pt" + a zero-copy bf16 view keeps tensors HOST-resident
        # (framework="flax" would commit every tensor to device-0 HBM before
        # the engine gets to shard/cast it; framework="np" rejects bf16)
        from safetensors import safe_open
        file = self.name_to_file[name]
        if file not in self._open:
            self._open[file] = safe_open(file, framework="pt")
        t = self._open[file].get_tensor(name)
        import torch
        if t.dtype == torch.bfloat16:
            import ml_dtypes
            return t.view(torch.int16).numpy().view(ml_dtypes.bfloat16)
        return t.numpy()

    def has(self, name: str) -> bool:
        return name in self.name_to_file


def _llama_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    H, nh, nkv, hd = (cfg.hidden_size, cfg.num_heads, cfg.kv_heads,
                      cfg.head_dim)

    def lin(name, out_first=True):
        w = r.get(name)          # torch Linear: [out, in]
        return w.T               # → [in, out]

    def norm(name):
        # rmsnorm = scale only; stablelm-style LayerNorm adds a bias
        out = {"scale": r.get(name + ".weight")}
        if not cfg.use_rmsnorm:
            out["bias"] = r.get(name + ".bias")
        return out

    bb: Dict[str, Any] = {"wte": r.get("model.embed_tokens.weight"),
                          "final_norm": norm("model.norm")}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        att = {
            "wq": lin(p + "self_attn.q_proj.weight").reshape(H, nh, hd),
            "wk": lin(p + "self_attn.k_proj.weight").reshape(H, nkv, hd),
            "wv": lin(p + "self_attn.v_proj.weight").reshape(H, nkv, hd),
            "wo": lin(p + "self_attn.o_proj.weight").reshape(nh, hd, H),
        }
        if cfg.qkv_bias:
            att["bq"] = r.get(p + "self_attn.q_proj.bias").reshape(nh, hd)
            att["bk"] = r.get(p + "self_attn.k_proj.bias").reshape(nkv, hd)
            att["bv"] = r.get(p + "self_attn.v_proj.bias").reshape(nkv, hd)
        if cfg.attn_out_bias:
            att["bo"] = r.get(p + "self_attn.o_proj.bias")
        blk = {
            "Attention_0": att,
            "Norm_0": norm(p + "input_layernorm"),
            "Norm_1": norm(p + "post_attention_layernorm"),
        }
        if cfg.num_experts and i % cfg.moe_every == cfg.moe_every - 1:
            # Mixtral MoE block (modeling_mixtral.py MixtralSparseMoeBlock):
            # gate router + per-expert w1(gate)/w3(up)/w2(down)
            m = p + "block_sparse_moe."
            blk["moe"] = {
                "gate": lin(m + "gate.weight"),                  # [H, E]
                "wge": np.stack([lin(m + f"experts.{e}.w1.weight")
                                 for e in range(cfg.num_experts)]),
                "wi": np.stack([lin(m + f"experts.{e}.w3.weight")
                                for e in range(cfg.num_experts)]),
                "wo": np.stack([lin(m + f"experts.{e}.w2.weight")
                                for e in range(cfg.num_experts)]),
            }
        else:
            blk["MLP_0"] = {
                "wi": lin(p + "mlp.up_proj.weight"),
                "wg": lin(p + "mlp.gate_proj.weight"),
                "wo": lin(p + "mlp.down_proj.weight"),
            }
        bb[f"block_{i}"] = blk
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        if r.has("lm_head.weight"):
            tree["lm_head"] = r.get("lm_head.weight").T      # [H, V]
        else:   # tie flag missing but head absent → tied in practice
            tree["lm_head"] = bb["wte"].T
    return tree


def _gpt2_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim

    def g(name):
        # checkpoints saved from GPT2LMHeadModel prefix with "transformer."
        return r.get(name if r.has(name) else "transformer." + name)

    bb: Dict[str, Any] = {
        "wte": g("wte.weight"),
        "wpe": g("wpe.weight")[:cfg.max_seq_len],
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"h.{i}."
        # Conv1D stores [in, out] — no transpose (module_inject/containers/
        # gpt2.py marks these via HFGPT2LayerPolicy)
        ca = g(p + "attn.c_attn.weight")                     # [H, 3H]
        cb = g(p + "attn.c_attn.bias")                       # [3H]
        bb[f"block_{i}"] = {
            "Attention_0": {
                "wq": ca[:, :H].reshape(H, nh, hd),
                "wk": ca[:, H:2 * H].reshape(H, nh, hd),
                "wv": ca[:, 2 * H:].reshape(H, nh, hd),
                "bq": cb[:H].reshape(nh, hd),
                "bk": cb[H:2 * H].reshape(nh, hd),
                "bv": cb[2 * H:].reshape(nh, hd),
                "wo": g(p + "attn.c_proj.weight").reshape(nh, hd, H),
                "bo": g(p + "attn.c_proj.bias"),
            },
            "Norm_0": {"scale": g(p + "ln_1.weight"),
                       "bias": g(p + "ln_1.bias")},
            "Norm_1": {"scale": g(p + "ln_2.weight"),
                       "bias": g(p + "ln_2.bias")},
            "MLP_0": {
                "wi": g(p + "mlp.c_fc.weight"),
                "bi": g(p + "mlp.c_fc.bias"),
                "wo": g(p + "mlp.c_proj.weight"),
                "bo": g(p + "mlp.c_proj.bias"),
            },
        }
    return {"backbone": bb}


def _opt_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """OPT → flax tree (reference module_inject/containers/opt.py maps the
    same q/k/v/out + fc1/fc2 + twin-LayerNorm layout).  The learned position
    table carries OPT's +2 offset in rows; slicing it off here lets the model
    keep plain arange positions."""
    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim

    def g(name):
        return r.get("model." + name if r.has("model." + name) else name)

    bb: Dict[str, Any] = {
        "wte": g("decoder.embed_tokens.weight"),
        "wpe": g("decoder.embed_positions.weight")[2:2 + cfg.max_seq_len],
        "final_norm": {"scale": g("decoder.final_layer_norm.weight"),
                       "bias": g("decoder.final_layer_norm.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"decoder.layers.{i}."
        bb[f"block_{i}"] = {
            "Attention_0": {
                "wq": g(p + "self_attn.q_proj.weight").T.reshape(H, nh, hd),
                "wk": g(p + "self_attn.k_proj.weight").T.reshape(H, nh, hd),
                "wv": g(p + "self_attn.v_proj.weight").T.reshape(H, nh, hd),
                "bq": g(p + "self_attn.q_proj.bias").reshape(nh, hd),
                "bk": g(p + "self_attn.k_proj.bias").reshape(nh, hd),
                "bv": g(p + "self_attn.v_proj.bias").reshape(nh, hd),
                "wo": g(p + "self_attn.out_proj.weight").T.reshape(nh, hd, H),
                "bo": g(p + "self_attn.out_proj.bias"),
            },
            "Norm_0": {"scale": g(p + "self_attn_layer_norm.weight"),
                       "bias": g(p + "self_attn_layer_norm.bias")},
            "Norm_1": {"scale": g(p + "final_layer_norm.weight"),
                       "bias": g(p + "final_layer_norm.bias")},
            "MLP_0": {
                "wi": g(p + "fc1.weight").T,
                "bi": g(p + "fc1.bias"),
                "wo": g(p + "fc2.weight").T,
                "bo": g(p + "fc2.bias"),
            },
        }
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        tree["lm_head"] = (r.get("lm_head.weight").T
                           if r.has("lm_head.weight") else bb["wte"].T)
    return tree


def _phi_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """Phi → flax tree (reference inference/v2/model_implementations/phi):
    parallel attention+MLP sharing one input LayerNorm, biased projections,
    biased untied lm_head."""
    H, nh, nkv, hd = (cfg.hidden_size, cfg.num_heads, cfg.kv_heads,
                      cfg.head_dim)

    bb: Dict[str, Any] = {
        "wte": r.get("model.embed_tokens.weight"),
        "final_norm": {"scale": r.get("model.final_layernorm.weight"),
                       "bias": r.get("model.final_layernorm.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        bb[f"block_{i}"] = {
            "Attention_0": {
                "wq": r.get(p + "self_attn.q_proj.weight").T.reshape(H, nh,
                                                                     hd),
                "wk": r.get(p + "self_attn.k_proj.weight").T.reshape(H, nkv,
                                                                     hd),
                "wv": r.get(p + "self_attn.v_proj.weight").T.reshape(H, nkv,
                                                                     hd),
                "bq": r.get(p + "self_attn.q_proj.bias").reshape(nh, hd),
                "bk": r.get(p + "self_attn.k_proj.bias").reshape(nkv, hd),
                "bv": r.get(p + "self_attn.v_proj.bias").reshape(nkv, hd),
                "wo": r.get(p + "self_attn.dense.weight").T.reshape(nh, hd,
                                                                    H),
                "bo": r.get(p + "self_attn.dense.bias"),
            },
            "Norm_0": {"scale": r.get(p + "input_layernorm.weight"),
                       "bias": r.get(p + "input_layernorm.bias")},
            "MLP_0": {
                "wi": r.get(p + "mlp.fc1.weight").T,
                "bi": r.get(p + "mlp.fc1.bias"),
                "wo": r.get(p + "mlp.fc2.weight").T,
                "bo": r.get(p + "mlp.fc2.bias"),
            },
        }
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        tree["lm_head"] = (r.get("lm_head.weight").T
                           if r.has("lm_head.weight") else bb["wte"].T)
    if cfg.unembed_bias:
        tree["lm_head_bias"] = (r.get("lm_head.bias")
                                if r.has("lm_head.bias")
                                else np.zeros(cfg.vocab_size, np.float32))
    return tree


def _falcon_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """Falcon → flax tree (reference inference/v2/model_implementations/
    falcon).  The fused query_key_value weight is grouped kv-major:
    [nkv, g+2, hd, H] with g query heads then one k and one v row per group —
    matching the model's group-major GQA head order."""
    H, nh, nkv, hd = (cfg.hidden_size, cfg.num_heads, cfg.kv_heads,
                      cfg.head_dim)
    g_per = nh // nkv

    bb: Dict[str, Any] = {
        "wte": r.get("transformer.word_embeddings.weight"),
        "final_norm": {"scale": r.get("transformer.ln_f.weight"),
                       "bias": r.get("transformer.ln_f.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        w = r.get(p + "self_attention.query_key_value.weight")   # [out, H]
        # grouped kv-major fused layout; nkv == nh degenerates to the
        # falcon-rw interleaved [nh, 3, hd] layout (g_per == 1)
        w4 = w.reshape(nkv, g_per + 2, hd, H)
        wq_ = w4[:, :g_per].reshape(nh, hd, H)
        wk_, wv_ = w4[:, g_per], w4[:, g_per + 1]                # [nkv, hd, H]
        att = {
            "wq": np.transpose(wq_, (2, 0, 1)),
            "wk": np.transpose(wk_, (2, 0, 1)),
            "wv": np.transpose(wv_, (2, 0, 1)),
            "wo": r.get(p + "self_attention.dense.weight").T.reshape(nh, hd,
                                                                     H),
        }
        mlp = {"wi": r.get(p + "mlp.dense_h_to_4h.weight").T,
               "wo": r.get(p + "mlp.dense_4h_to_h.weight").T}
        if cfg.qkv_bias:         # falcon-rw bias=true
            b4 = r.get(p + "self_attention.query_key_value.bias"
                       ).reshape(nkv, g_per + 2, hd)
            att["bq"] = b4[:, :g_per].reshape(nh, hd)
            att["bk"], att["bv"] = b4[:, g_per], b4[:, g_per + 1]
            att["bo"] = r.get(p + "self_attention.dense.bias")
            mlp["bi"] = r.get(p + "mlp.dense_h_to_4h.bias")
            mlp["bo"] = r.get(p + "mlp.dense_4h_to_h.bias")
        blk = {
            "Attention_0": att,
            "MLP_0": mlp,
        }
        if cfg.parallel_block and cfg.parallel_norms == 2:
            blk["Norm_0"] = {"scale": r.get(p + "ln_attn.weight"),
                             "bias": r.get(p + "ln_attn.bias")}
            blk["Norm_1"] = {"scale": r.get(p + "ln_mlp.weight"),
                             "bias": r.get(p + "ln_mlp.bias")}
        else:
            blk["Norm_0"] = {"scale": r.get(p + "input_layernorm.weight"),
                             "bias": r.get(p + "input_layernorm.bias")}
            if not cfg.parallel_block:
                blk["Norm_1"] = {
                    "scale": r.get(p + "post_attention_layernorm.weight"),
                    "bias": r.get(p + "post_attention_layernorm.bias")}
        bb[f"block_{i}"] = blk
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        tree["lm_head"] = (r.get("lm_head.weight").T
                           if r.has("lm_head.weight") else bb["wte"].T)
    return tree


def _rope_interleave_perm(head_dim: int, rot: int) -> np.ndarray:
    """Head-dim permutation converting gpt-j's INTERLEAVED rotary pairing
    ((0,1),(2,3),…) to this model's half-split pairing ((0,rot/2),…).

    Valid because attention scores are invariant under a shared q/k head-dim
    permutation and half_rope(x[perm]) == interleaved_rope(x)[perm] — so
    permuting wq/wk rows once at load time makes the native kernel exact."""
    return np.concatenate([np.arange(0, rot, 2), np.arange(1, rot, 2),
                           np.arange(rot, head_dim)])


def _gptj_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """GPT-J → flax tree (reference module_inject/containers/gptj.py)."""
    from deepspeed_tpu.models.gpt import rotary_dim
    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    perm = _rope_interleave_perm(hd, rotary_dim(hd, cfg.rope_pct))

    bb: Dict[str, Any] = {
        "wte": r.get("transformer.wte.weight"),
        "final_norm": {"scale": r.get("transformer.ln_f.weight"),
                       "bias": r.get("transformer.ln_f.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        wq = r.get(p + "attn.q_proj.weight").T.reshape(H, nh, hd)
        wk = r.get(p + "attn.k_proj.weight").T.reshape(H, nh, hd)
        bb[f"block_{i}"] = {
            "Attention_0": {
                "wq": wq[:, :, perm],
                "wk": wk[:, :, perm],
                "wv": r.get(p + "attn.v_proj.weight").T.reshape(H, nh, hd),
                "wo": r.get(p + "attn.out_proj.weight").T.reshape(nh, hd, H),
            },
            "Norm_0": {"scale": r.get(p + "ln_1.weight"),
                       "bias": r.get(p + "ln_1.bias")},
            "MLP_0": {
                "wi": r.get(p + "mlp.fc_in.weight").T,
                "bi": r.get(p + "mlp.fc_in.bias"),
                "wo": r.get(p + "mlp.fc_out.weight").T,
                "bo": r.get(p + "mlp.fc_out.bias"),
            },
        }
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        tree["lm_head"] = (r.get("lm_head.weight").T
                           if r.has("lm_head.weight") else bb["wte"].T)
    if cfg.unembed_bias:
        tree["lm_head_bias"] = (r.get("lm_head.bias")
                                if r.has("lm_head.bias")
                                else np.zeros(cfg.vocab_size, np.float32))
    return tree


def _neox_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """GPT-NeoX → flax tree (reference module_inject/containers/gptneox.py).
    Fused qkv is per-head interleaved: rows [h·3hd:(h+1)·3hd] hold head h's
    q, k, v stripes."""
    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim

    bb: Dict[str, Any] = {
        "wte": r.get("gpt_neox.embed_in.weight"),
        "final_norm": {"scale": r.get("gpt_neox.final_layer_norm.weight"),
                       "bias": r.get("gpt_neox.final_layer_norm.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"gpt_neox.layers.{i}."
        w4 = r.get(p + "attention.query_key_value.weight"
                   ).reshape(nh, 3, hd, H)
        b3 = r.get(p + "attention.query_key_value.bias").reshape(nh, 3, hd)
        bb[f"block_{i}"] = {
            "Attention_0": {
                "wq": np.transpose(w4[:, 0], (2, 0, 1)),
                "wk": np.transpose(w4[:, 1], (2, 0, 1)),
                "wv": np.transpose(w4[:, 2], (2, 0, 1)),
                "bq": b3[:, 0], "bk": b3[:, 1], "bv": b3[:, 2],
                "wo": r.get(p + "attention.dense.weight").T.reshape(nh, hd,
                                                                    H),
                "bo": r.get(p + "attention.dense.bias"),
            },
            "Norm_0": {"scale": r.get(p + "input_layernorm.weight"),
                       "bias": r.get(p + "input_layernorm.bias")},
            "Norm_1": {
                "scale": r.get(p + "post_attention_layernorm.weight"),
                "bias": r.get(p + "post_attention_layernorm.bias")},
            "MLP_0": {
                "wi": r.get(p + "mlp.dense_h_to_4h.weight").T,
                "bi": r.get(p + "mlp.dense_h_to_4h.bias"),
                "wo": r.get(p + "mlp.dense_4h_to_h.weight").T,
                "bo": r.get(p + "mlp.dense_4h_to_h.bias"),
            },
        }
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        tree["lm_head"] = (r.get("embed_out.weight").T
                           if r.has("embed_out.weight") else bb["wte"].T)
    return tree


def _gptneo_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """GPT-Neo → flax tree (reference module_inject/containers/gptneo.py).
    torch Linear layout everywhere (unlike gpt2's Conv1D), bias-free qkv."""
    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim

    def g(name):
        # prefixed (GPTNeoForCausalLM) first; bare GPTNeoModel keys otherwise
        return r.get(name if r.has(name)
                     else name[len("transformer."):])

    bb: Dict[str, Any] = {
        "wte": g("transformer.wte.weight"),
        "wpe": g("transformer.wpe.weight")[:cfg.max_seq_len],
        "final_norm": {"scale": g("transformer.ln_f.weight"),
                       "bias": g("transformer.ln_f.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        bb[f"block_{i}"] = {
            "Attention_0": {
                "wq": g(p + "attn.attention.q_proj.weight").T.reshape(
                    H, nh, hd),
                "wk": g(p + "attn.attention.k_proj.weight").T.reshape(
                    H, nh, hd),
                "wv": g(p + "attn.attention.v_proj.weight").T.reshape(
                    H, nh, hd),
                "wo": g(p + "attn.attention.out_proj.weight").T.reshape(
                    nh, hd, H),
                "bo": g(p + "attn.attention.out_proj.bias"),
            },
            "Norm_0": {"scale": g(p + "ln_1.weight"),
                       "bias": g(p + "ln_1.bias")},
            "Norm_1": {"scale": g(p + "ln_2.weight"),
                       "bias": g(p + "ln_2.bias")},
            "MLP_0": {
                "wi": g(p + "mlp.c_fc.weight").T,
                "bi": g(p + "mlp.c_fc.bias"),
                "wo": g(p + "mlp.c_proj.weight").T,
                "bo": g(p + "mlp.c_proj.bias"),
            },
        }
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        tree["lm_head"] = (r.get("lm_head.weight").T
                           if r.has("lm_head.weight") else bb["wte"].T)
    return tree


def _phi3_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """Phi-3 → flax tree: llama layout with fused qkv_proj
    (q[nh·hd] | k[nkv·hd] | v[nkv·hd] rows) and gate_up_proj
    (gate[M] | up[M] rows)."""
    H, nh, nkv, hd = (cfg.hidden_size, cfg.num_heads, cfg.kv_heads,
                      cfg.head_dim)
    M = cfg.mlp_dim
    qw, kvw = nh * hd, nkv * hd

    bb: Dict[str, Any] = {"wte": r.get("model.embed_tokens.weight"),
                          "final_norm": {"scale": r.get("model.norm.weight")}}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        w = r.get(p + "self_attn.qkv_proj.weight").T   # [H, qw + 2·kvw]
        gu = r.get(p + "mlp.gate_up_proj.weight").T    # [H, 2M]
        bb[f"block_{i}"] = {
            "Attention_0": {
                "wq": w[:, :qw].reshape(H, nh, hd),
                "wk": w[:, qw:qw + kvw].reshape(H, nkv, hd),
                "wv": w[:, qw + kvw:].reshape(H, nkv, hd),
                "wo": r.get(p + "self_attn.o_proj.weight").T.reshape(nh, hd,
                                                                     H),
            },
            "Norm_0": {"scale": r.get(p + "input_layernorm.weight")},
            "Norm_1": {
                "scale": r.get(p + "post_attention_layernorm.weight")},
            "MLP_0": {
                "wg": gu[:, :M],
                "wi": gu[:, M:],
                "wo": r.get(p + "mlp.down_proj.weight").T,
            },
        }
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        tree["lm_head"] = (r.get("lm_head.weight").T
                           if r.has("lm_head.weight") else bb["wte"].T)
    return tree


def _gemma_absorb_norm_offset(tree: Dict[str, Any]) -> Dict[str, Any]:
    """Gemma's RMSNorm multiplies by (1 + weight) in fp32
    (modeling_gemma GemmaRMSNorm) — absorb the +1 into the stored scales
    (fp32 so the offset is exact) and the stock rms_norm serves it."""
    def walk(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if k.startswith(("Norm_", "final_norm")) and "scale" in v:
                    out[k] = dict(v, scale=np.asarray(v["scale"],
                                                      np.float32) + 1.0)
                else:
                    out[k] = walk(v)
            return out
        return node

    return walk(tree)


def _bigcode_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """GPT-BigCode (starcoder) → flax tree: fused c_attn rows are
    q[H] | k[nkv·hd] | v[nkv·hd] (MQA: nkv=1)."""
    H, nh, nkv, hd = (cfg.hidden_size, cfg.num_heads, cfg.kv_heads,
                      cfg.head_dim)

    def g(name):
        return r.get(name if r.has(name) else name[len("transformer."):])

    bb: Dict[str, Any] = {
        "wte": g("transformer.wte.weight"),
        "wpe": g("transformer.wpe.weight")[:cfg.max_seq_len],
        "final_norm": {"scale": g("transformer.ln_f.weight"),
                       "bias": g("transformer.ln_f.bias")},
    }
    kvw = nkv * hd
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        w = g(p + "attn.c_attn.weight").T          # [H, H + 2·nkv·hd]
        b = g(p + "attn.c_attn.bias")
        if nkv == nh:
            # MHA variant interleaves q|k|v WITHIN each head ([nh, 3, hd])
            w4 = w.reshape(H, nh, 3, hd)
            b3 = b.reshape(nh, 3, hd)
            att = {"wq": w4[:, :, 0], "wk": w4[:, :, 1], "wv": w4[:, :, 2],
                   "bq": b3[:, 0], "bk": b3[:, 1], "bv": b3[:, 2]}
        else:
            # MQA: flat q rows then one k stripe and one v stripe
            att = {"wq": w[:, :H].reshape(H, nh, hd),
                   "wk": w[:, H:H + kvw].reshape(H, nkv, hd),
                   "wv": w[:, H + kvw:].reshape(H, nkv, hd),
                   "bq": b[:H].reshape(nh, hd),
                   "bk": b[H:H + kvw].reshape(nkv, hd),
                   "bv": b[H + kvw:].reshape(nkv, hd)}
        att["wo"] = g(p + "attn.c_proj.weight").T.reshape(nh, hd, H)
        att["bo"] = g(p + "attn.c_proj.bias")
        bb[f"block_{i}"] = {
            "Attention_0": att,
            "Norm_0": {"scale": g(p + "ln_1.weight"),
                       "bias": g(p + "ln_1.bias")},
            "Norm_1": {"scale": g(p + "ln_2.weight"),
                       "bias": g(p + "ln_2.bias")},
            "MLP_0": {
                "wi": g(p + "mlp.c_fc.weight").T,
                "bi": g(p + "mlp.c_fc.bias"),
                "wo": g(p + "mlp.c_proj.weight").T,
                "bo": g(p + "mlp.c_proj.bias"),
            },
        }
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        tree["lm_head"] = (r.get("lm_head.weight").T
                           if r.has("lm_head.weight") else bb["wte"].T)
    return tree


def _bloom_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """BLOOM → flax tree (reference module_inject/containers/bloom.py).
    Fused qkv interleaves q/k/v WITHIN each head: [nh, 3, hd]."""
    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim

    def g(name):
        return r.get("transformer." + name
                     if r.has("transformer." + name) else name)

    bb: Dict[str, Any] = {
        "wte": g("word_embeddings.weight"),
        "embed_norm": {"scale": g("word_embeddings_layernorm.weight"),
                       "bias": g("word_embeddings_layernorm.bias")},
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"h.{i}."
        w4 = g(p + "self_attention.query_key_value.weight"
               ).reshape(nh, 3, hd, H)
        b3 = g(p + "self_attention.query_key_value.bias").reshape(nh, 3, hd)
        bb[f"block_{i}"] = {
            "Attention_0": {
                "wq": np.transpose(w4[:, 0], (2, 0, 1)),
                "wk": np.transpose(w4[:, 1], (2, 0, 1)),
                "wv": np.transpose(w4[:, 2], (2, 0, 1)),
                "bq": b3[:, 0], "bk": b3[:, 1], "bv": b3[:, 2],
                "wo": g(p + "self_attention.dense.weight").T.reshape(nh, hd,
                                                                     H),
                "bo": g(p + "self_attention.dense.bias"),
            },
            "Norm_0": {"scale": g(p + "input_layernorm.weight"),
                       "bias": g(p + "input_layernorm.bias")},
            "Norm_1": {"scale": g(p + "post_attention_layernorm.weight"),
                       "bias": g(p + "post_attention_layernorm.bias")},
            "MLP_0": {
                "wi": g(p + "mlp.dense_h_to_4h.weight").T,
                "bi": g(p + "mlp.dense_h_to_4h.bias"),
                "wo": g(p + "mlp.dense_4h_to_h.weight").T,
                "bo": g(p + "mlp.dense_4h_to_h.bias"),
            },
        }
    tree: Dict[str, Any] = {"backbone": bb}
    if not cfg.tie_embeddings:
        tree["lm_head"] = (r.get("lm_head.weight").T
                           if r.has("lm_head.weight") else bb["wte"].T)
    return tree


_DISTILBERT_LIKE = {"DistilBertForMaskedLM", "DistilBertModel",
                    "DistilBertForSequenceClassification"}
_CLIP_LIKE = {"CLIPTextModel", "CLIPTextModelWithProjection", "CLIPModel"}
_ROBERTA_LIKE = {"RobertaForMaskedLM", "RobertaModel",
                 "RobertaForSequenceClassification",
                 "XLMRobertaForMaskedLM", "XLMRobertaModel",
                 "XLMRobertaForSequenceClassification"}
_BERT_LIKE = ({"BertForMaskedLM", "BertModel", "BertForPreTraining",
               "BertForSequenceClassification"}
              | _DISTILBERT_LIKE | _ROBERTA_LIKE)


def _distilbert_tree(r: _ShardReader, cfg) -> Dict[str, Any]:
    """DistilBERT → the same flax encoder tree (reference
    module_inject/containers/distil_bert.py): q/k/v/out lin, sa_layer_norm +
    output_layer_norm, no token types, tied vocab_projector head."""
    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim

    def g(name):
        return r.get("distilbert." + name
                     if r.has("distilbert." + name) else name)

    enc: Dict[str, Any] = {
        "wte": g("embeddings.word_embeddings.weight"),
        "wpe": g("embeddings.position_embeddings.weight"),
        "embed_norm": {"scale": g("embeddings.LayerNorm.weight"),
                       "bias": g("embeddings.LayerNorm.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"transformer.layer.{i}."
        enc[f"block_{i}"] = {
            "attn": {
                "wq": g(p + "attention.q_lin.weight").T.reshape(H, nh, hd),
                "bq": g(p + "attention.q_lin.bias").reshape(nh, hd),
                "wk": g(p + "attention.k_lin.weight").T.reshape(H, nh, hd),
                "bk": g(p + "attention.k_lin.bias").reshape(nh, hd),
                "wv": g(p + "attention.v_lin.weight").T.reshape(H, nh, hd),
                "bv": g(p + "attention.v_lin.bias").reshape(nh, hd),
                "wo": g(p + "attention.out_lin.weight").T.reshape(nh, hd, H),
                "bo": g(p + "attention.out_lin.bias"),
            },
            "attn_norm": {"scale": g(p + "sa_layer_norm.weight"),
                          "bias": g(p + "sa_layer_norm.bias")},
            "mlp": {
                "wi": g(p + "ffn.lin1.weight").T,
                "bi": g(p + "ffn.lin1.bias"),
                "wo": g(p + "ffn.lin2.weight").T,
                "bo": g(p + "ffn.lin2.bias"),
            },
            "mlp_norm": {"scale": g(p + "output_layer_norm.weight"),
                         "bias": g(p + "output_layer_norm.bias")},
        }
    tree: Dict[str, Any] = {"encoder": enc}
    if r.has("vocab_transform.weight"):
        tree.update({
            "transform_w": r.get("vocab_transform.weight").T,
            "transform_b": r.get("vocab_transform.bias"),
            "transform_norm": {"scale": r.get("vocab_layer_norm.weight"),
                               "bias": r.get("vocab_layer_norm.bias")},
            "decoder_bias": r.get("vocab_projector.bias"),
        })
    elif r.has("classifier.weight"):     # DistilBertForSequenceClassification
        tree.update({
            "pooler_w": r.get("pre_classifier.weight").T,
            "pooler_b": r.get("pre_classifier.bias"),
            "cls_w": r.get("classifier.weight").T,
            "cls_b": r.get("classifier.bias"),
        })
    return tree


def load_hf_clip_text(model_path: str, *, dtype=None):
    """CLIP text encoder → (GPTConfig, tree, extras) (reference
    module_inject/containers/clip.py — the text-encoder leg of the stable-
    diffusion serving stack).

    CLIP's text tower IS a pre-LN causal transformer with learned positions,
    quick-gelu MLPs and biases everywhere — exactly the GPT backbone — so the
    weights stream into the same tree and the TPU attention paths serve it
    unchanged.  extras: {"text_projection": [H, P] or None, "eos_token_id"}.
    """
    from deepspeed_tpu.models.gpt import GPTConfig

    full = _read_json(os.path.join(model_path, "config.json"))
    # CLIPModel nests the text config ("text_config_dict" on legacy openai
    # hub checkpoints, CLIPConfig back-compat)
    tc = full.get("text_config") or full.get("text_config_dict") or full
    hidden = tc["hidden_size"]
    heads = tc["num_attention_heads"]
    cfg = GPTConfig(
        vocab_size=tc["vocab_size"],
        num_layers=tc["num_hidden_layers"],
        num_heads=heads,
        head_dim=hidden // heads,
        hidden_size=hidden,
        mlp_dim_override=tc["intermediate_size"],
        max_seq_len=tc.get("max_position_embeddings", 77),
        use_rope=False, use_rmsnorm=False, gated_mlp=False,
        activation=_map_activation("CLIPText", tc.get("hidden_act",
                                                      "quick_gelu")),
        norm_eps=float(tc.get("layer_norm_eps", 1e-5)),
        qkv_bias=True, attn_out_bias=True, mlp_bias=True,
        tie_embeddings=True,
        dtype=dtype or jnp.float32,
    )
    r = _ShardReader(model_path)

    def g(name):
        return r.get("text_model." + name
                     if r.has("text_model." + name) else name)

    H, nh, hd = hidden, heads, cfg.head_dim
    bb: Dict[str, Any] = {
        "wte": g("embeddings.token_embedding.weight"),
        "wpe": g("embeddings.position_embedding.weight"),
        "final_norm": {"scale": g("final_layer_norm.weight"),
                       "bias": g("final_layer_norm.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"encoder.layers.{i}."
        bb[f"block_{i}"] = {
            "Attention_0": {
                "wq": g(p + "self_attn.q_proj.weight").T.reshape(H, nh, hd),
                "bq": g(p + "self_attn.q_proj.bias").reshape(nh, hd),
                "wk": g(p + "self_attn.k_proj.weight").T.reshape(H, nh, hd),
                "bk": g(p + "self_attn.k_proj.bias").reshape(nh, hd),
                "wv": g(p + "self_attn.v_proj.weight").T.reshape(H, nh, hd),
                "bv": g(p + "self_attn.v_proj.bias").reshape(nh, hd),
                "wo": g(p + "self_attn.out_proj.weight").T.reshape(nh, hd,
                                                                   H),
                "bo": g(p + "self_attn.out_proj.bias"),
            },
            "Norm_0": {"scale": g(p + "layer_norm1.weight"),
                       "bias": g(p + "layer_norm1.bias")},
            "Norm_1": {"scale": g(p + "layer_norm2.weight"),
                       "bias": g(p + "layer_norm2.bias")},
            "MLP_0": {
                "wi": g(p + "mlp.fc1.weight").T,
                "bi": g(p + "mlp.fc1.bias"),
                "wo": g(p + "mlp.fc2.weight").T,
                "bo": g(p + "mlp.fc2.bias"),
            },
        }
    extras = {
        "text_projection": (r.get("text_projection.weight").T
                            if r.has("text_projection.weight") else None),
        "eos_token_id": int(tc.get("eos_token_id", 49407)),
    }
    log_dist(f"loaded HF CLIP text checkpoint {model_path} "
             f"({cfg.num_layers}L/{H}H)", ranks=[0])
    return cfg, {"backbone": bb}, extras


def load_hf_bert(model_path: str, *, dtype=None) -> Tuple[Any,
                                                          Dict[str, Any]]:
    """BERT-family encoder checkpoint → (BertConfig, flax params tree)
    (reference module_inject/containers/{bert,distil_bert}.py)."""
    from deepspeed_tpu.models.bert import BertConfig

    hf = _read_json(os.path.join(model_path, "config.json"))
    arch = _arch_of(hf)
    if arch in _DISTILBERT_LIKE:
        cfg = BertConfig(
            vocab_size=hf["vocab_size"],
            num_layers=hf["n_layers"],
            num_heads=hf["n_heads"],
            hidden_size=hf["dim"],
            mlp_dim=hf["hidden_dim"],
            max_seq_len=hf.get("max_position_embeddings", 512),
            type_vocab_size=0,
            norm_eps=1e-12,
            activation=_map_activation(arch, hf.get("activation", "gelu")),
            pooler_act="relu",       # distilbert pre_classifier uses relu
            dtype=dtype or jnp.float32,
        )
        tree = _distilbert_tree(_ShardReader(model_path), cfg)
        log_dist(f"loaded HF DistilBERT checkpoint {model_path} "
                 f"({cfg.num_layers}L/{cfg.hidden_size}H)", ranks=[0])
        return cfg, tree
    is_roberta = arch in _ROBERTA_LIKE
    # roberta positions start at padding_idx+1; the table keeps its offset
    # rows (pad tokens take row padding_idx), so only the USABLE length
    # shrinks
    rob_pad = int(hf.get("pad_token_id") or 1) if is_roberta else None
    pos_off = (rob_pad + 1) if is_roberta else 0
    cfg = BertConfig(
        vocab_size=hf["vocab_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        hidden_size=hf["hidden_size"],
        mlp_dim=hf["intermediate_size"],
        max_seq_len=hf.get("max_position_embeddings", 512) - pos_off,
        type_vocab_size=hf.get("type_vocab_size", 2),
        norm_eps=float(hf.get("layer_norm_eps", 1e-12)),
        activation=_map_activation(_arch_of(hf), hf.get("hidden_act",
                                                        "gelu")),
        pos_pad_token=rob_pad,
        dtype=dtype or jnp.float32,
    )
    r = _ShardReader(model_path)

    def g(name):
        # headed checkpoints prefix with "bert."/"roberta."; bare models don't
        for pre in ("bert.", "roberta."):
            if r.has(pre + name):
                return r.get(pre + name)
        return r.get(name)

    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    enc: Dict[str, Any] = {
        "wte": g("embeddings.word_embeddings.weight"),
        "wpe": g("embeddings.position_embeddings.weight"),
        "wtt": g("embeddings.token_type_embeddings.weight"),
        "embed_norm": {
            "scale": g("embeddings.LayerNorm.weight"),
            "bias": g("embeddings.LayerNorm.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"encoder.layer.{i}."
        enc[f"block_{i}"] = {
            "attn": {
                "wq": g(p + "attention.self.query.weight").T.reshape(
                    H, nh, hd),
                "bq": g(p + "attention.self.query.bias").reshape(nh, hd),
                "wk": g(p + "attention.self.key.weight").T.reshape(
                    H, nh, hd),
                "bk": g(p + "attention.self.key.bias").reshape(nh, hd),
                "wv": g(p + "attention.self.value.weight").T.reshape(
                    H, nh, hd),
                "bv": g(p + "attention.self.value.bias").reshape(nh, hd),
                "wo": g(p + "attention.output.dense.weight").T.reshape(
                    nh, hd, H),
                "bo": g(p + "attention.output.dense.bias"),
            },
            "attn_norm": {
                "scale": g(p + "attention.output.LayerNorm.weight"),
                "bias": g(p + "attention.output.LayerNorm.bias")},
            "mlp": {
                "wi": g(p + "intermediate.dense.weight").T,
                "bi": g(p + "intermediate.dense.bias"),
                "wo": g(p + "output.dense.weight").T,
                "bo": g(p + "output.dense.bias"),
            },
            "mlp_norm": {
                "scale": g(p + "output.LayerNorm.weight"),
                "bias": g(p + "output.LayerNorm.bias")},
        }
    tree: Dict[str, Any] = {"encoder": enc}
    if r.has("cls.predictions.transform.dense.weight"):
        tree.update({
            "transform_w": r.get("cls.predictions.transform.dense.weight").T,
            "transform_b": r.get("cls.predictions.transform.dense.bias"),
            "transform_norm": {
                "scale": r.get(
                    "cls.predictions.transform.LayerNorm.weight"),
                "bias": r.get("cls.predictions.transform.LayerNorm.bias")},
            "decoder_bias": r.get("cls.predictions.bias"),
        })
    elif r.has("lm_head.dense.weight"):  # roberta MLM head naming
        tree.update({
            "transform_w": r.get("lm_head.dense.weight").T,
            "transform_b": r.get("lm_head.dense.bias"),
            "transform_norm": {"scale": r.get("lm_head.layer_norm.weight"),
                               "bias": r.get("lm_head.layer_norm.bias")},
            "decoder_bias": r.get("lm_head.bias"),
        })
    elif r.has("classifier.out_proj.weight"):
        # roberta classification head: dense→tanh→out_proj on [CLS]
        tree.update({
            "pooler_w": r.get("classifier.dense.weight").T,
            "pooler_b": r.get("classifier.dense.bias"),
            "cls_w": r.get("classifier.out_proj.weight").T,
            "cls_b": r.get("classifier.out_proj.bias"),
        })
    elif r.has("classifier.weight"):     # BertForSequenceClassification
        tree.update({
            "pooler_w": g("pooler.dense.weight").T,
            "pooler_b": g("pooler.dense.bias"),
            "cls_w": r.get("classifier.weight").T,
            "cls_b": r.get("classifier.bias"),
        })
    log_dist(f"loaded HF BERT checkpoint {model_path} "
             f"({cfg.num_layers}L/{H}H)", ranks=[0])
    return cfg, tree


def load_hf_checkpoint(model_path: str, *, max_seq_len: Optional[int] = None,
                       dtype=None) -> Tuple[Any, Dict[str, Any]]:
    """Load an HF model directory → (GPTConfig, flax params tree).

    Weights keep their checkpoint dtype (engines cast to their serving dtype);
    ``dtype`` sets the config's COMPUTE dtype only.
    """
    cfg = config_from_hf(model_path, max_seq_len=max_seq_len, dtype=dtype)
    r = _ShardReader(model_path)
    arch = _arch_of(_read_json(os.path.join(model_path, "config.json")))
    if arch in _GPT2_LIKE:
        tree = _gpt2_tree(r, cfg)
    elif arch in _OPT_LIKE:
        tree = _opt_tree(r, cfg)
    elif arch in _PHI_LIKE:
        tree = _phi_tree(r, cfg)
    elif arch in _FALCON_LIKE:
        tree = _falcon_tree(r, cfg)
    elif arch in _GPTJ_LIKE:
        tree = _gptj_tree(r, cfg)
    elif arch in _NEOX_LIKE:
        tree = _neox_tree(r, cfg)
    elif arch in _BLOOM_LIKE:
        tree = _bloom_tree(r, cfg)
    elif arch in _GPTNEO_LIKE:
        tree = _gptneo_tree(r, cfg)
    elif arch in _BIGCODE_LIKE:
        tree = _bigcode_tree(r, cfg)
    elif arch in _GEMMA_LIKE:
        tree = _gemma_absorb_norm_offset(_llama_tree(r, cfg))
    elif arch in _PHI3_LIKE:
        tree = _phi3_tree(r, cfg)
    else:
        tree = _llama_tree(r, cfg)
    n = sum(int(np.prod(l.shape))
            for l in jax.tree_util.tree_leaves(tree))
    log_dist(f"loaded HF checkpoint {model_path} ({arch}): {n/1e6:.1f}M params",
             ranks=[0])
    return cfg, tree


def is_hf_model_dir(path: Any) -> bool:
    return (isinstance(path, (str, os.PathLike))
            and os.path.isdir(path)
            and os.path.exists(os.path.join(path, "config.json")))


# ----------------------------------------------------------- export direction
def save_hf_checkpoint(cfg, params, model_path: str) -> None:
    """Export a flax GPT tree as an HF model directory (config.json +
    model.safetensors) — the cross-framework leg of universal checkpointing
    (reference checkpoint/ds_to_universal.py exports framework-neutral
    fragments; here the neutral format IS the HF layout, so the exported
    model loads straight into ``transformers`` or back through
    ``load_hf_checkpoint``).

    Supports the llama family (rope+rmsnorm+SwiGLU) and gpt2 config points of
    the GPT module — the same coverage as the import direction.
    """
    import torch
    from safetensors.torch import save_file

    if getattr(cfg, "embed_scale", None) or \
            getattr(cfg, "gate_act", "silu") != "silu":
        raise ValueError(
            "export supports llama/gpt2 semantics only: embed_scale/GeGLU "
            "(gemma) configs would silently export a DIFFERENT model under "
            "a llama architecture tag")
    params = dict(params)
    if "params" in params:
        params = params["params"]
    bb = params["backbone"]
    H, nh, nkv, hd = (cfg.hidden_size, cfg.num_heads, cfg.kv_heads,
                      cfg.head_dim)
    os.makedirs(model_path, exist_ok=True)

    def t(x):
        arr = np.asarray(jax.device_get(x))
        if arr.dtype.name == "bfloat16":
            return torch.from_numpy(
                arr.view(np.int16).copy()).view(torch.bfloat16)
        return torch.from_numpy(np.ascontiguousarray(arr))

    tensors: Dict[str, Any] = {}
    if cfg.use_rope and cfg.use_rmsnorm and cfg.gated_mlp:
        moe = bool(cfg.num_experts)
        if moe and cfg.moe_every != 1:
            raise ValueError("Mixtral export requires MoE on every layer "
                             "(moe_every=1)")
        if moe:
            arch = "MixtralForCausalLM"
        else:
            arch = "Qwen2ForCausalLM" if cfg.qkv_bias else "LlamaForCausalLM"
        hf_cfg = {
            "architectures": [arch],
            "model_type": arch.replace("ForCausalLM", "").lower(),
            "vocab_size": cfg.vocab_size,
            "hidden_size": H,
            "intermediate_size": cfg.mlp_dim,
            "num_hidden_layers": cfg.num_layers,
            "num_attention_heads": nh,
            "num_key_value_heads": nkv,
            "head_dim": hd,
            "max_position_embeddings": cfg.max_seq_len,
            "rms_norm_eps": cfg.norm_eps or 1e-6,
            "rope_theta": cfg.rope_theta,
            "tie_word_embeddings": bool(cfg.tie_embeddings),
            "hidden_act": "silu",
            "torch_dtype": "float32",
        }
        if moe:
            hf_cfg["num_local_experts"] = cfg.num_experts
            hf_cfg["num_experts_per_tok"] = cfg.moe_k
        tensors["model.embed_tokens.weight"] = t(bb["wte"])
        tensors["model.norm.weight"] = t(bb["final_norm"]["scale"])
        for i in range(cfg.num_layers):
            blk = bb[f"block_{i}"]
            ap = blk["Attention_0"]
            p = f"model.layers.{i}."
            tensors[p + "self_attn.q_proj.weight"] = t(
                np.asarray(ap["wq"]).reshape(H, nh * hd).T)
            tensors[p + "self_attn.k_proj.weight"] = t(
                np.asarray(ap["wk"]).reshape(H, nkv * hd).T)
            tensors[p + "self_attn.v_proj.weight"] = t(
                np.asarray(ap["wv"]).reshape(H, nkv * hd).T)
            tensors[p + "self_attn.o_proj.weight"] = t(
                np.asarray(ap["wo"]).reshape(nh * hd, H).T)
            if cfg.qkv_bias:
                tensors[p + "self_attn.q_proj.bias"] = t(
                    np.asarray(ap["bq"]).reshape(-1))
                tensors[p + "self_attn.k_proj.bias"] = t(
                    np.asarray(ap["bk"]).reshape(-1))
                tensors[p + "self_attn.v_proj.bias"] = t(
                    np.asarray(ap["bv"]).reshape(-1))
            tensors[p + "input_layernorm.weight"] = t(blk["Norm_0"]["scale"])
            tensors[p + "post_attention_layernorm.weight"] = t(
                blk["Norm_1"]["scale"])
            if moe:
                m = p + "block_sparse_moe."
                mo = blk["moe"]
                tensors[m + "gate.weight"] = t(np.asarray(mo["gate"]).T)
                for e in range(cfg.num_experts):
                    tensors[m + f"experts.{e}.w1.weight"] = t(
                        np.asarray(mo["wge"][e]).T)
                    tensors[m + f"experts.{e}.w3.weight"] = t(
                        np.asarray(mo["wi"][e]).T)
                    tensors[m + f"experts.{e}.w2.weight"] = t(
                        np.asarray(mo["wo"][e]).T)
            else:
                mp = blk["MLP_0"]
                tensors[p + "mlp.up_proj.weight"] = t(np.asarray(mp["wi"]).T)
                tensors[p + "mlp.gate_proj.weight"] = t(
                    np.asarray(mp["wg"]).T)
                tensors[p + "mlp.down_proj.weight"] = t(
                    np.asarray(mp["wo"]).T)
        if not cfg.tie_embeddings:
            tensors["lm_head.weight"] = t(np.asarray(params["lm_head"]).T)
    elif not cfg.use_rope and not cfg.use_rmsnorm and not cfg.gated_mlp:
        if not cfg.tie_embeddings:
            raise ValueError(
                "GPT2LMHeadModel always ties wte/lm_head — an untied "
                "gpt2-point model cannot round-trip through the gpt2 "
                "architecture; train with tie_embeddings=True to export")
        hf_cfg = {
            "architectures": ["GPT2LMHeadModel"],
            "model_type": "gpt2",
            "vocab_size": cfg.vocab_size,
            "n_embd": H, "n_layer": cfg.num_layers, "n_head": nh,
            "n_positions": cfg.max_seq_len, "n_ctx": cfg.max_seq_len,
            "n_inner": cfg.mlp_dim,
            "layer_norm_epsilon": cfg.norm_eps or 1e-5,
            "torch_dtype": "float32",
        }
        tensors["wte.weight"] = t(bb["wte"])
        tensors["wpe.weight"] = t(bb["wpe"])
        tensors["ln_f.weight"] = t(bb["final_norm"]["scale"])
        tensors["ln_f.bias"] = t(bb["final_norm"]["bias"])
        for i in range(cfg.num_layers):
            blk = bb[f"block_{i}"]
            ap, mp = blk["Attention_0"], blk["MLP_0"]
            p = f"h.{i}."
            ca = np.concatenate([np.asarray(ap[k]).reshape(H, -1)
                                 for k in ("wq", "wk", "wv")], axis=1)
            cb = np.concatenate([np.asarray(ap[k]).reshape(-1)
                                 for k in ("bq", "bk", "bv")])
            tensors[p + "attn.c_attn.weight"] = t(ca)        # Conv1D [in,out]
            tensors[p + "attn.c_attn.bias"] = t(cb)
            tensors[p + "attn.c_proj.weight"] = t(
                np.asarray(ap["wo"]).reshape(nh * hd, H))
            tensors[p + "attn.c_proj.bias"] = t(ap["bo"])
            tensors[p + "ln_1.weight"] = t(blk["Norm_0"]["scale"])
            tensors[p + "ln_1.bias"] = t(blk["Norm_0"]["bias"])
            tensors[p + "ln_2.weight"] = t(blk["Norm_1"]["scale"])
            tensors[p + "ln_2.bias"] = t(blk["Norm_1"]["bias"])
            tensors[p + "mlp.c_fc.weight"] = t(mp["wi"])
            tensors[p + "mlp.c_fc.bias"] = t(mp["bi"])
            tensors[p + "mlp.c_proj.weight"] = t(mp["wo"])
            tensors[p + "mlp.c_proj.bias"] = t(mp["bo"])
    else:
        raise ValueError(
            "export supports llama-family (rope+rmsnorm+SwiGLU) and gpt2 "
            "(learned-pos+LN+GELU) config points; got a mixed configuration")

    with open(os.path.join(model_path, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)
    save_file(tensors, os.path.join(model_path, "model.safetensors"))
    log_dist(f"exported HF checkpoint → {model_path} "
             f"({hf_cfg['architectures'][0]}, {len(tensors)} tensors)",
             ranks=[0])
