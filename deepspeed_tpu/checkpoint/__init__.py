"""Checkpointing.

Reference parity (runtime/engine.py:2710-3554 save/load + checkpoint/ universal
checkpointing): orbax async sharded checkpointing over the global jax.Array view.
Key simplification the TPU design buys (SURVEY.md §5): the reference needs an
offline universal-checkpoint pipeline (checkpoint/ds_to_universal.py) to retarget a
(tp,pp,dp)-sharded checkpoint at a new topology; with named shardings, restore-time
resharding is native — orbax restores into whatever sharding the new mesh asks for.

Layout mirrors the reference's ``save_dir/tag/...`` + ``latest`` tag file
(engine.py:3056 save_checkpoint, _get_ckpt_name):

    save_dir/
      latest                  # text file with the newest tag
      <tag>/state/...         # orbax pytree (params, opt_state, step, loss_scale)
      <tag>/client_state.json
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

LATEST_FILE = "latest"


def __getattr__(name):
    # lazy: universal-checkpoint helpers (checkpoint/universal.py) without
    # importing torch/optax at package import
    if name in ("export_universal", "load_universal", "apply_universal",
                "export_universal_offload"):
        from deepspeed_tpu.checkpoint import universal
        return getattr(universal, name)
    raise AttributeError(name)

# one long-lived async checkpointer (orbax guidance; a fresh instance per save
# would serialize on its own setup) + a waiter thread for deferred metadata
_CKPTR: Optional[ocp.StandardCheckpointer] = None
_PENDING: Optional[threading.Thread] = None
_PENDING_ERROR: Optional[BaseException] = None


def _checkpointer() -> ocp.StandardCheckpointer:
    global _CKPTR
    if _CKPTR is None:
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def wait_pending() -> None:
    """Block until any in-flight async save fully commits (metadata
    included) and RE-RAISE any failure from the background write — a lost
    checkpoint must not look like a successful one.  Registered atexit so
    in-flight saves flush even when the caller forgets."""
    global _PENDING, _PENDING_ERROR
    if _PENDING is not None:
        _PENDING.join()
        _PENDING = None
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()
    if _PENDING_ERROR is not None:
        err, _PENDING_ERROR = _PENDING_ERROR, None
        raise RuntimeError("async checkpoint save failed") from err


atexit.register(wait_pending)


def _ckpt_path(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), tag, "state")


def _write_meta(save_dir: str, tag: str, client_state: dict) -> None:
    if jax.process_index() == 0:
        with open(os.path.join(save_dir, tag, "client_state.json"), "w") as f:
            json.dump(client_state or {}, f)
        # reference: 'latest' tag file (engine.py _save_checkpoint) — written
        # only once the checkpoint is committed, so 'latest' never points at
        # a partial save
        with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
            f.write(tag)


def save_train_state(save_dir: str, tag: str, state, client_state: dict = None,
                     block: bool = True) -> str:
    """Save the train state.  ``block=False`` returns as soon as the on-device
    arrays are snapshotted — the write streams in the background while
    training continues (reference async_io/decoupled checkpointing; orbax
    AsyncCheckpointer), and the 'latest' pointer lands on commit."""
    global _PENDING
    wait_pending()                       # serialize with any previous save
    path = _ckpt_path(save_dir, tag)
    ckptr = _checkpointer()
    ckptr.save(path, state, force=True)
    if block:
        ckptr.wait_until_finished()
        _write_meta(save_dir, tag, client_state)
        return path

    def _finish():
        global _PENDING_ERROR
        try:
            ckptr.wait_until_finished()
            _write_meta(save_dir, tag, client_state)
        except BaseException as e:  # noqa: BLE001 — surfaced by wait_pending
            _PENDING_ERROR = e

    # non-daemon: the atexit wait_pending() must be able to join it
    _PENDING = threading.Thread(target=_finish, daemon=False)
    _PENDING.start()
    return path


def latest_tag(load_dir: str) -> Optional[str]:
    p = os.path.join(load_dir, LATEST_FILE)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return f.read().strip()


def restore_train_state(load_dir: str, tag: str, shardings, like_state
                        ) -> Tuple[Any, dict]:
    """Restore into the given shardings (resharding on load is free — this is the
    universal-checkpoint capability, reference checkpoint/ds_to_universal.py)."""
    wait_pending()                       # a racing async save must commit
    path = _ckpt_path(load_dir, tag)
    abstract = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        like_state, shardings)
    state = _checkpointer().restore(path, abstract)
    cs_path = os.path.join(load_dir, tag, "client_state.json")
    client_state = {}
    if os.path.exists(cs_path):
        with open(cs_path) as f:
            client_state = json.load(f)
    return state, client_state
