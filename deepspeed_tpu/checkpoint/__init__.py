"""Checkpointing.

Reference parity (runtime/engine.py:2710-3554 save/load + checkpoint/ universal
checkpointing): orbax async sharded checkpointing over the global jax.Array view.
Key simplification the TPU design buys (SURVEY.md §5): the reference needs an
offline universal-checkpoint pipeline (checkpoint/ds_to_universal.py) to retarget a
(tp,pp,dp)-sharded checkpoint at a new topology; with named shardings, restore-time
resharding is native — orbax restores into whatever sharding the new mesh asks for.

Layout mirrors the reference's ``save_dir/tag/...`` + ``latest`` tag file
(engine.py:3056 save_checkpoint, _get_ckpt_name):

    save_dir/
      latest                  # text file with the newest tag
      <tag>/state/...         # orbax pytree (params, opt_state, step, loss_scale)
      <tag>/client_state.json
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

LATEST_FILE = "latest"
# newest COMPLETE universal export in a run dir (written post-commit by
# export_universal; latest_universal falls back to a scan when absent)
UNIVERSAL_LATEST_FILE = "latest_universal"
# exists inside <tag>/ from before the first byte of an asynchronous write
# until its commit — a crash mid-write leaves the marker behind, 'latest'
# still points at the previous committed tag, and restore of the marked tag
# fails loudly instead of loading a torn state
IN_PROGRESS_FILE = ".in_progress"


class CheckpointNotFound(FileNotFoundError):
    """No checkpoint at the requested path/tag.  Replaces the grab-bag of
    backend exceptions (orbax FileNotFoundError, KeyError on a missing
    'latest', bare OSError) so elastic restart logic can catch ONE type and
    fall back to the previous export / cold start."""


class CheckpointCorrupt(RuntimeError):
    """The checkpoint exists but must not be restored: its write never
    committed (``.in_progress`` marker still present) or its payload is
    torn/unreadable.  Restart logic treats this exactly like NotFound for
    resume-source selection, but the distinct type keeps the operator
    signal: data WAS lost here, look at the dead host."""


def __getattr__(name):
    # lazy: universal-checkpoint helpers (checkpoint/universal.py) without
    # importing torch/optax at package import
    if name in ("export_universal", "load_universal", "apply_universal",
                "export_universal_offload"):
        from deepspeed_tpu.checkpoint import universal
        return getattr(universal, name)
    raise AttributeError(name)


def universal_complete(path: str) -> bool:
    """A universal export is COMPLETE iff its meta.json landed and its
    in-progress marker came off — the commit order export_universal
    enforces.  Anything else (marker present, meta missing, not a dir) is
    torn or foreign."""
    return (os.path.isdir(os.path.join(path, "zero"))
            and os.path.exists(os.path.join(path, "meta.json"))
            and not os.path.exists(os.path.join(path, IN_PROGRESS_FILE)))


def _universal_step(path: str) -> Optional[int]:
    try:
        with open(os.path.join(path, "meta.json")) as f:
            return int(json.load(f).get("step", -1))
    except (OSError, ValueError):
        return None


def universal_candidates(run_dir: str) -> list:
    """Every COMPLETE universal export under ``run_dir`` (plus the
    ``latest_universal`` pointer's target, which may live outside it),
    newest ``meta.json`` step first.  The pointer is a candidate, never an
    authority: a host that died BETWEEN the export commit and the pointer
    move leaves a stale pointer, and the newest complete DATA must still
    win (chaos leg: fault at ``universal.pre_pointer``).  Torn exports
    (in-progress marker, missing meta) never qualify.  Resume logic walks
    this list so a corrupt-but-committed newest export degrades to the one
    before it instead of crash-looping."""
    candidates = []
    ptr = os.path.join(run_dir, UNIVERSAL_LATEST_FILE)
    if os.path.exists(ptr):
        with open(ptr) as f:
            cand = f.read().strip()
        if cand and not os.path.isabs(cand):
            cand = os.path.join(run_dir, cand)
        if cand:
            candidates.append(cand)
    if os.path.isdir(run_dir):
        candidates.extend(os.path.join(run_dir, name)
                          for name in sorted(os.listdir(run_dir)))
    scored = {}
    seen = set()
    for d in candidates:
        key = os.path.abspath(d)
        if key in seen:
            continue
        seen.add(key)
        if not universal_complete(d):
            continue
        step = _universal_step(d)
        if step is not None:
            scored[d] = step
    return sorted(scored, key=lambda d: scored[d], reverse=True)


def latest_universal(run_dir: str) -> Optional[str]:
    """Path of the newest COMPLETE universal export under ``run_dir``, or
    None — the head of :func:`universal_candidates`.  This is the library
    home of the scan the elastic worker contract requires (previously
    hand-rolled in tests/elastic_train_script.py)."""
    cands = universal_candidates(run_dir)
    return cands[0] if cands else None

# one long-lived async checkpointer (orbax guidance; a fresh instance per save
# would serialize on its own setup) + a waiter thread for deferred metadata
_CKPTR: Optional[ocp.StandardCheckpointer] = None
_PENDING: Optional[threading.Thread] = None
_PENDING_ERROR: Optional[BaseException] = None


def _checkpointer() -> ocp.StandardCheckpointer:
    global _CKPTR
    if _CKPTR is None:
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def wait_pending() -> None:
    """Block until any in-flight async save fully commits (metadata
    included) and RE-RAISE any failure from the background write — a lost
    checkpoint must not look like a successful one.  Registered atexit so
    in-flight saves flush even when the caller forgets."""
    global _PENDING, _PENDING_ERROR
    if _PENDING is not None:
        _PENDING.join()
        _PENDING = None
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()
    if _PENDING_ERROR is not None:
        err, _PENDING_ERROR = _PENDING_ERROR, None
        raise RuntimeError("async checkpoint save failed") from err


atexit.register(wait_pending)


def _ckpt_path(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), tag, "state")


def mark_in_progress(save_dir: str, tag: str) -> None:
    """Drop the IN_PROGRESS marker into <tag>/ (creating the dir) BEFORE the
    first checkpoint byte is written.  Process 0 only — the marker protects
    the shared directory, not per-process state."""
    if jax.process_index() == 0:
        os.makedirs(os.path.join(save_dir, tag), exist_ok=True)
        with open(os.path.join(save_dir, tag, IN_PROGRESS_FILE), "w") as f:
            f.write(str(time.time()))


def in_progress(load_dir: str, tag: str) -> bool:
    return os.path.exists(os.path.join(load_dir, tag, IN_PROGRESS_FILE))


def commit_latest(save_dir: str, tag: str) -> None:
    """The metadata commit point — call only once every checkpoint byte is
    durable.  Commit order: marker comes off → 'latest' moves.  A crash
    before the marker removal leaves 'latest' at the previous tag and the
    marked tag un-restorable; a crash between the two steps leaves a
    committed tag that 'latest' doesn't point at — the previous checkpoint
    still loads either way (reference: 'latest' tag file, engine.py
    _save_checkpoint, written only post-commit).  Shared by the device
    engine's save path and InfinityEngine's writer thread."""
    marker = os.path.join(save_dir, tag, IN_PROGRESS_FILE)
    if os.path.exists(marker):
        os.remove(marker)
    with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
        f.write(tag)


def check_not_in_progress(load_dir: str, tag: str) -> None:
    """Refuse to restore a tag whose async write never committed."""
    if in_progress(load_dir, tag):
        raise CheckpointCorrupt(
            f"checkpoint {os.path.join(load_dir, tag)} carries "
            f"{IN_PROGRESS_FILE}: its async write never committed (crash "
            f"mid-write) — the state under it may be torn.  Load the "
            f"previous committed tag ('latest' still points there) or "
            f"delete the directory.")


def _write_meta(save_dir: str, tag: str, client_state: dict) -> None:
    if jax.process_index() == 0:
        with open(os.path.join(save_dir, tag, "client_state.json"), "w") as f:
            json.dump(client_state or {}, f)
        commit_latest(save_dir, tag)


def save_train_state(save_dir: str, tag: str, state, client_state: dict = None,
                     block: bool = True,
                     on_commit: Optional[Callable[[float], None]] = None,
                     pre_commit: Optional[Callable[[], None]] = None
                     ) -> str:
    """Save the train state.  ``block=False`` returns as soon as the on-device
    arrays are snapshotted — the write streams in the background while
    training continues (reference async_io/decoupled checkpointing; orbax
    AsyncCheckpointer), and the 'latest' pointer lands on commit.
    ``pre_commit()`` (if given) runs after the orbax write is durable but
    BEFORE the metadata commit ('latest' move / marker removal) — on the
    waiter thread for async saves — so sidecar files the restore path
    requires (e.g. the ZeRO-Offload masters npz) land strictly inside the
    in-progress window; a failure there aborts the commit.
    ``on_commit(write_seconds)`` (if given) runs right after the metadata
    commit — on THIS thread for ``block=True``, on the waiter thread
    otherwise (the engine uses it to close its ``checkpoint_write`` span and
    zero the backlog gauge)."""
    global _PENDING
    wait_pending()                       # serialize with any previous save
    path = _ckpt_path(save_dir, tag)
    mark_in_progress(save_dir, tag)
    t0 = time.perf_counter()
    ckptr = _checkpointer()
    ckptr.save(path, state, force=True)
    if block:
        ckptr.wait_until_finished()      # sync-ok: caller asked block=True
        if pre_commit is not None:
            pre_commit()
        _write_meta(save_dir, tag, client_state)
        if on_commit is not None:
            on_commit(time.perf_counter() - t0)
        return path

    def _finish():
        global _PENDING_ERROR
        try:
            ckptr.wait_until_finished()
            if pre_commit is not None:
                pre_commit()
            _write_meta(save_dir, tag, client_state)
            if on_commit is not None:
                on_commit(time.perf_counter() - t0)
        except BaseException as e:  # noqa: BLE001 — surfaced by wait_pending
            _PENDING_ERROR = e

    # non-daemon: the atexit wait_pending() must be able to join it
    _PENDING = threading.Thread(target=_finish, daemon=False)
    _PENDING.start()
    return path


def latest_tag(load_dir: str) -> Optional[str]:
    p = os.path.join(load_dir, LATEST_FILE)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return f.read().strip()


def restore_train_state(load_dir: str, tag: str, shardings, like_state
                        ) -> Tuple[Any, dict]:
    """Restore into the given shardings (resharding on load is free — this is the
    universal-checkpoint capability, reference checkpoint/ds_to_universal.py)."""
    wait_pending()                       # a racing async save must commit
    check_not_in_progress(load_dir, tag)
    path = _ckpt_path(load_dir, tag)
    if not os.path.isdir(path):
        raise CheckpointNotFound(
            f"no checkpoint state under {os.path.join(load_dir, tag)} "
            f"(expected {path})")
    abstract = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        like_state, shardings)
    state = _checkpointer().restore(path, abstract)
    cs_path = os.path.join(load_dir, tag, "client_state.json")
    client_state = {}
    if os.path.exists(cs_path):
        with open(cs_path) as f:
            client_state = json.load(f)
    return state, client_state
