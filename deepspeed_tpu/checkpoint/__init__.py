"""Checkpointing.

Reference parity (runtime/engine.py:2710-3554 save/load + checkpoint/ universal
checkpointing): orbax async sharded checkpointing over the global jax.Array view.
Key simplification the TPU design buys (SURVEY.md §5): the reference needs an
offline universal-checkpoint pipeline (checkpoint/ds_to_universal.py) to retarget a
(tp,pp,dp)-sharded checkpoint at a new topology; with named shardings, restore-time
resharding is native — orbax restores into whatever sharding the new mesh asks for.

Layout mirrors the reference's ``save_dir/tag/...`` + ``latest`` tag file
(engine.py:3056 save_checkpoint, _get_ckpt_name):

    save_dir/
      latest                  # text file with the newest tag
      <tag>/state/...         # orbax pytree (params, opt_state, step, loss_scale)
      <tag>/client_state.json
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

LATEST_FILE = "latest"


def _ckpt_path(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), tag, "state")


def save_train_state(save_dir: str, tag: str, state, client_state: dict = None
                     ) -> str:
    path = _ckpt_path(save_dir, tag)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()
    if jax.process_index() == 0:
        with open(os.path.join(save_dir, tag, "client_state.json"), "w") as f:
            json.dump(client_state or {}, f)
        # reference: 'latest' tag file (engine.py _save_checkpoint)
        with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
            f.write(tag)
    return path


def latest_tag(load_dir: str) -> Optional[str]:
    p = os.path.join(load_dir, LATEST_FILE)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return f.read().strip()


def restore_train_state(load_dir: str, tag: str, shardings, like_state
                        ) -> Tuple[Any, dict]:
    """Restore into the given shardings (resharding on load is free — this is the
    universal-checkpoint capability, reference checkpoint/ds_to_universal.py)."""
    path = _ckpt_path(load_dir, tag)
    abstract = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        like_state, shardings)
    ckptr = ocp.StandardCheckpointer()
    state = ckptr.restore(path, abstract)
    cs_path = os.path.join(load_dir, tag, "client_state.json")
    client_state = {}
    if os.path.exists(cs_path):
        with open(cs_path) as f:
            client_state = json.load(f)
    return state, client_state
