"""Flops profiler — per-module flops/params/latency from jaxpr analysis.

Analog of the reference flops profiler (profiling/flops_profiler/profiler.py:28):
the reference hooks every ``nn.Module`` and patches ``torch.nn.functional`` to
count MACs as the model executes; here the model is a pure function, so the
profiler instead

1. walks the traced jaxpr, attributing matmul/conv flops to the flax module
   path carried by each equation's name stack (flax wraps module methods in
   ``jax.named_scope``), with ``scan`` bodies multiplied by trip count — the
   per-module tree ``print_model_profile`` renders (reference :282), and
2. cross-checks totals against XLA's own compiled-program cost analysis
   (``compiled.cost_analysis()["flops"]``) when available, and
3. times the actual jitted step for latency / achieved FLOPS.

Elementwise work is ignored (as in the reference, which counts MACs): on TPU
the matmuls are >99% of the arithmetic for transformer workloads.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist


def _dot_flops(eqn) -> int:
    """2*M*N*K flops for a dot_general from its operand shapes."""
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs_size = int(np.prod(lhs.shape)) if lhs.shape else 1
    rhs_free = [d for i, d in enumerate(rhs.shape) if i not in set(rc) | set(rb)]
    return 2 * lhs_size * int(np.prod(rhs_free)) if rhs_free else 2 * lhs_size


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval  # kernel
    out_size = int(np.prod(out.shape))
    # per output element: 2 * (kernel spatial * in-channels) MAC-flops
    kernel_work = 2 * int(np.prod(rhs.shape)) // max(rhs.shape[-1], 1)
    return out_size * kernel_work


def _walk(jaxpr, scale: int, acc: Dict[str, int],
          meta: Optional[dict] = None) -> None:
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if prim == "scan":
            _walk(eqn.params["jaxpr"].jaxpr, scale * int(eqn.params["length"]),
                  acc, meta)
        elif prim == "while":
            # trip count unknown at trace time: count ONE body iteration and
            # flag the undercount so the report can disclose it (decode loops
            # — lax.while_loop generation — are undercounted by their trip
            # count; transformer train steps contain no while)
            if meta is not None:
                meta["has_while"] = True
            _walk(eqn.params["body_jaxpr"].jaxpr, scale, acc, meta)
        elif prim == "cond":
            for br in eqn.params["branches"]:
                _walk(br.jaxpr, scale, acc, meta)  # upper bound over branches
        elif prim in ("custom_vjp_call", "custom_jvp_call",
                      "custom_vjp_call_jaxpr", "remat", "checkpoint"):
            inner = (eqn.params.get("fun_jaxpr") or eqn.params.get("call_jaxpr")
                     or eqn.params.get("jaxpr"))
            if inner is not None:
                _walk(getattr(inner, "jaxpr", inner), scale, acc, meta)
        elif sub is not None:  # pjit / closed_call / named calls
            _walk(getattr(sub, "jaxpr", sub), scale, acc, meta)
        elif prim == "dot_general":
            path = str(eqn.source_info.name_stack)
            acc[path] = acc.get(path, 0) + scale * _dot_flops(eqn)
        elif prim == "conv_general_dilated":
            path = str(eqn.source_info.name_stack)
            acc[path] = acc.get(path, 0) + scale * _conv_flops(eqn)


def jaxpr_flops_by_module(fn, *args, meta: Optional[dict] = None,
                          **kwargs) -> Dict[str, int]:
    """Trace ``fn(*args)`` and return {module-path: matmul/conv flops}.

    Paths come from equation name stacks (flax module scopes); an empty path
    collects top-level ops.  Pass a ``meta`` dict to receive trace flags
    (``has_while``: the count visits while bodies once, undercounting
    data-dependent loops).
    """
    closed = jax.make_jaxpr(fn, **kwargs)(*args)
    acc: Dict[str, int] = {}
    _walk(closed.jaxpr, 1, acc, meta)
    return acc


def _tree_rollup(flat: Dict[str, int], depth: int) -> List[Tuple[str, int]]:
    """Aggregate flat paths to at most ``depth`` components (depth<0 = leaf)."""
    agg: Dict[str, int] = {}
    for path, fl in flat.items():
        parts = [p for p in path.split("/") if p]
        key = "/".join(parts[:depth]) if depth >= 0 else path
        agg[key or "<top>"] = agg.get(key or "<top>", 0) + fl
    return sorted(agg.items(), key=lambda kv: -kv[1])


def _num(x: float, suffix: str = "") -> str:
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(x) >= div:
            return f"{x/div:.2f} {unit}{suffix}"
    return f"{x:.2f} {suffix}"


class FlopsProfiler:
    """Profile one jitted step (reference FlopsProfiler, used by the engine at
    ``flops_profiler.profile_step``)."""

    def __init__(self, config=None):
        self.config = config
        self.flops = 0              # per-step matmul/conv flops (jaxpr count)
        self.xla_flops = None       # XLA cost-analysis flops, if available
        self.latency = 0.0          # measured seconds per step
        self.by_module: Dict[str, int] = {}
        self.has_while = False      # report must disclose loop undercount

    def count(self, fn, *args, static_kwargs: Optional[dict] = None):
        """Trace-only flop count (no execution, safe with donated jit args)."""
        meta: dict = {}
        self.by_module = jaxpr_flops_by_module(fn, *args, meta=meta,
                                               **(static_kwargs or {}))
        self.has_while = bool(meta.get("has_while"))
        self.flops = sum(self.by_module.values())
        return self

    def profile(self, fn, *args, jit_fn=None, n_timing_runs: int = 3,
                static_kwargs: Optional[dict] = None):
        """fn: traceable step; jit_fn: its jitted form (timed; defaults to
        jax.jit(fn)).  Returns self."""
        self.count(fn, *args, static_kwargs=static_kwargs)
        jitted = jit_fn if jit_fn is not None else jax.jit(fn)
        try:
            lowered = jitted.lower(*args)
            ca = lowered.compile().cost_analysis()
            if ca:
                ca = ca[0] if isinstance(ca, (list, tuple)) else ca
                self.xla_flops = float(ca.get("flops", 0.0)) or None
        except Exception:  # pragma: no cover - backend-dependent
            self.xla_flops = None
        # timing: materialize a leaf to synchronize (axon: block_until_ready
        # is unreliable; device_get is the sync)
        out = jitted(*args)
        jax.tree_util.tree_map(
            lambda l: jax.device_get(l) if hasattr(l, "dtype") else l,
            jax.tree_util.tree_leaves(out)[:1])
        times = []
        for _ in range(n_timing_runs):
            t0 = time.perf_counter()
            out = jitted(*args)
            jax.tree_util.tree_map(
                lambda l: jax.device_get(l) if hasattr(l, "dtype") else l,
                jax.tree_util.tree_leaves(out)[:1])
            times.append(time.perf_counter() - t0)
        self.latency = min(times)
        return self

    def as_metrics(self) -> Dict[str, float]:
        """Scalar figures for the telemetry snapshot (StepTelemetry
        ``record_flops``): the profiled step's flop cost and, when a latency
        was measured, the achieved rate."""
        out: Dict[str, float] = {"flops_per_step": float(self.flops)}
        if self.xla_flops:
            out["xla_flops_per_step"] = float(self.xla_flops)
        if self.latency:
            out["step_latency_s"] = float(self.latency)
            out["achieved_flops_per_sec"] = float(self.flops) / self.latency
        return out

    def print_model_profile(self, params: Optional[Any] = None,
                            module_depth: int = -1, top_modules: int = 1,
                            detailed: bool = True,
                            output_file: Optional[str] = None):
        """Render the profile (reference print_model_profile :282)."""
        lines = ["", "-------------------------- DeepSpeed-TPU Flops Profiler "
                     "--------------------------"]
        if params is not None:
            n_params = sum(int(np.prod(l.shape))
                           for l in jax.tree_util.tree_leaves(params))
            lines.append(f"params per device:      {_num(n_params)}")
        lines.append(f"flops per step (jaxpr): {_num(self.flops, 'FLOPs')}")
        if getattr(self, "has_while", False):
            lines.append(
                "NOTE: the step contains lax.while_loop(s); their bodies are "
                "counted ONCE (trip count is data-dependent) — the jaxpr "
                "figure UNDERCOUNTS loops such as decode generation")
        if self.xla_flops:
            lines.append(f"flops per step (XLA):   "
                         f"{_num(self.xla_flops, 'FLOPs')}")
        if self.latency:
            lines.append(f"latency per step:       {self.latency*1e3:.2f} ms")
            lines.append(f"achieved throughput:    "
                         f"{_num(self.flops/self.latency, 'FLOPS')}")
        if detailed and self.by_module:
            lines.append("")
            lines.append("per-module matmul/conv flops "
                         "(flax scope, scan bodies x trip count):")
            depth = module_depth if module_depth and module_depth > 0 else 3
            rows = _tree_rollup(self.by_module, depth)
            total = max(self.flops, 1)
            for path, fl in rows[:max(top_modules * 8, 16)]:
                lines.append(f"  {fl/total*100:5.1f}%  {_num(fl, 'FLOPs'):>14}"
                             f"  {path}")
        lines.append("-" * 84)
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "a") as f:
                f.write(text + "\n")
        log_dist(text, ranks=[0])
        return text
