from deepspeed_tpu.profiling.flops_profiler import (FlopsProfiler,
                                                    jaxpr_flops_by_module)

__all__ = ["FlopsProfiler", "jaxpr_flops_by_module"]
