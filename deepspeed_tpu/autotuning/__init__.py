from deepspeed_tpu.autotuning.autotuner import (Autotuner,  # noqa: F401
                                                ProbeResult)
