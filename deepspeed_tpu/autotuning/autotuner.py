"""Autotuner — stage/mesh/micro-batch search with real compile+step probes.

Reference parity: ``autotuning/autotuner.py`` — the micro-batch tuner
(``get_min_max_micro_batch_size`` :741, ``run_tuning_micro_batch_size`` :960),
the ZeRO-stage memory model that prunes candidates before any experiment runs
(``autotuner.py:278`` ``_get_instantiation_memory_required_per_gpu``), the
experiment generator (:304 over stages × configs), and the model-based tuner
(tuner/model_based.py).  The reference launches whole training jobs per
experiment through the launcher and scrapes metrics files; here a probe is
in-process — build the engine, compile the train step, time a few real steps
— because one JAX process drives every local chip.

Round-3 search (``tune()``): candidates = {ZeRO stage} × {fsdp·tp mesh
split}; the MEMORY MODEL estimates each candidate's fixed per-chip bytes
(params + grads + optimizer state under that stage's sharding) and prunes
those over the HBM budget WITHOUT probing (the reference's "fast" path);
survivors get the doubling+bisect micro-batch search; everything lands in a
ranked experiment report (the reference's experiment-summary role).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.logging import log_dist


@dataclasses.dataclass
class ProbeResult:
    micro_batch: int
    ok: bool
    step_time_s: float = float("inf")
    tokens_per_s: float = 0.0
    error: str = ""


def _is_oom(err: Exception) -> bool:
    s = str(err)
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s.lower())


def estimate_fixed_bytes(n_params: int, *, stage: int, fsdp: int, tp: int = 1,
                         compute_bytes: int = 2, master_weights: bool = True,
                         optimizer_moments: int = 2) -> Dict[str, float]:
    """Per-chip FIXED memory (params + grads + optimizer state) under a ZeRO
    stage and mesh split — the reference's
    ``_get_instantiation_memory_required_per_gpu`` (autotuner.py:278).

    Sharding rules mirror parallel/partition.py: tp divides every tensor-
    parallel weight (≈ all of them for transformers); fsdp divides params at
    stage 3 and grads/optimizer state at stages ≥2/≥1.  Activations are NOT
    modeled — they scale with micro-batch, which the probe search explores.
    """
    p_local = n_params / tp
    params = p_local * compute_bytes / (fsdp if stage >= 3 else 1)
    grads = p_local * 4 / (fsdp if stage >= 2 else 1)
    opt_shard = fsdp if stage >= 1 else 1
    opt = p_local * 4 * optimizer_moments / opt_shard
    masters = (p_local * 4 / opt_shard) if master_weights else 0.0
    return {"params": params, "grads": grads, "optimizer": opt,
            "masters": masters,
            "total": params + grads + opt + masters}


class Autotuner:
    """model + base config + a batch factory → best micro-batch.

    batch_factory(micro_batch) must return a host batch pytree with
    ``micro_batch`` leading rows (per chip).
    """

    def __init__(self, model, base_config: Dict[str, Any],
                 batch_factory: Callable[[int], Any],
                 probe_steps: int = 3):
        self.model = model
        self.base_config = dict(base_config)
        self.batch_factory = batch_factory
        self.probe_steps = probe_steps
        self.results: List[ProbeResult] = []

    # ---------------------------------------------------------------- probes
    def _probe(self, mbs: int) -> ProbeResult:
        import jax
        import numpy as np
        import deepspeed_tpu

        cfg = dict(self.base_config)
        cfg["train_micro_batch_size_per_gpu"] = mbs
        cfg["gradient_accumulation_steps"] = 1
        cfg.pop("train_batch_size", None)
        cfg["steps_per_print"] = 0
        batch = self.batch_factory(mbs)
        try:
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model, config=cfg, example_batch=batch)
            # per-chip rows → [gas=1, micro_global, ...]
            dpw = engine.mesh.shape["dp"] * engine.mesh.shape["fsdp"]

            def expand(x):
                x = np.asarray(x)
                reps = -(-mbs * dpw // x.shape[0])
                return np.tile(x, (reps,) + (1,) * (x.ndim - 1)
                               )[None, :mbs * dpw]
            full = jax.tree_util.tree_map(expand, batch)
            m = engine.train_batch(full)          # compile + step 0
            jax.device_get(m.loss)
            t0 = time.perf_counter()
            for _ in range(self.probe_steps):
                m = engine.train_batch(full)
            jax.device_get(m.loss)
            dt = (time.perf_counter() - t0) / self.probe_steps
            leaves = jax.tree_util.tree_leaves(full)
            # [gas, rows, T, ...] → real tokens/step (matches engine
            # train_batch accounting, shape[:3])
            tokens = int(np.prod(leaves[0].shape[:3]))
            res = ProbeResult(mbs, True, dt, tokens / dt)
        except Exception as e:  # noqa: BLE001 — OOM/compile failures end probes
            if not _is_oom(e):
                raise
            res = ProbeResult(mbs, False, error=str(e)[:200])
        self.results.append(res)
        log_dist(f"autotune probe mbs={mbs}: "
                 + (f"{res.tokens_per_s:,.0f} tok/s" if res.ok
                    else "OOM"), ranks=[0])
        return res

    # ---------------------------------------------------------------- search
    def tune_micro_batch_size(self, start: int = 1,
                              max_mbs: Optional[int] = None) -> int:
        """Doubling until OOM/max, bisect the boundary, return the fastest
        micro-batch (reference get_min_max_micro_batch_size :741)."""
        ok: List[ProbeResult] = []
        mbs = start
        last_ok, first_bad = 0, None
        while True:
            if max_mbs is not None and mbs > max_mbs:
                break
            r = self._probe(mbs)
            if not r.ok:
                first_bad = mbs
                break
            ok.append(r)
            last_ok = mbs
            mbs *= 2
        if first_bad is not None:
            lo, hi = last_ok, first_bad
            while hi - lo > max(1, lo // 4):     # coarse bisect (reference
                mid = (lo + hi) // 2             # uses similar tolerance)
                if mid in (lo, hi) or mid == 0:
                    break
                r = self._probe(mid)
                if r.ok:
                    ok.append(r)
                    lo = mid
                else:
                    hi = mid
        if not ok:
            raise RuntimeError(
                f"no micro batch ≥ {start} fits on this chip "
                f"(first OOM at {first_bad})")
        best = max(ok, key=lambda r: r.tokens_per_s)
        log_dist(f"autotune: best micro_batch={best.micro_batch} "
                 f"({best.tokens_per_s:,.0f} tok/s over "
                 f"{len(self.results)} probes)", ranks=[0])
        return best.micro_batch

    # ------------------------------------------------- stage/mesh search
    def tune(self, *, n_params: Optional[int] = None,
             stages: Sequence[int] = (0, 2, 3),
             mesh_splits: Optional[Sequence[Tuple[int, int]]] = None,
             hbm_budget_bytes: Optional[float] = None,
             start: int = 1, max_mbs: Optional[int] = None,
             report_path: Optional[str] = None) -> Dict[str, Any]:
        """Full search: {ZeRO stage} × {(fsdp, tp) split} × micro-batch.

        The memory model prunes candidates whose fixed state cannot fit
        ``hbm_budget_bytes`` per chip BEFORE any probe runs (reference
        model-based tuner); survivors are probed for real and ranked by
        tokens/s.  Returns the best config dict; the full experiment record
        goes to ``report_path`` (JSON) and ``self.experiments``.
        """
        import jax
        n_dev = len(jax.devices())
        if mesh_splits is None:
            # the advertised fsdp×tp product space (tp capped at 2 by
            # default — wider tp belongs to explicit mesh_splits)
            mesh_splits = [(f, t) for t in (1, 2)
                           for f in (1, 2, 4, 8, 16, 32)
                           if f * t <= n_dev and n_dev % (f * t) == 0]
        if n_params is None:
            n_params = self._count_params()
        compute_bytes = 2 if (self.base_config.get("bf16", {}).get("enabled")
                              or self.base_config.get("fp16", {}).get(
                                  "enabled")) else 4
        master = compute_bytes == 2
        self.experiments: List[Dict[str, Any]] = []
        for stage in stages:
            for fsdp, tp in mesh_splits:
                exp: Dict[str, Any] = {"stage": stage, "fsdp": fsdp,
                                       "tp": tp}
                est = estimate_fixed_bytes(
                    n_params, stage=stage, fsdp=fsdp, tp=tp,
                    compute_bytes=compute_bytes, master_weights=master)
                exp["est_fixed_bytes"] = est["total"]
                if (hbm_budget_bytes is not None
                        and est["total"] > hbm_budget_bytes):
                    exp["status"] = "pruned"
                    exp["reason"] = (f"fixed state {est['total']/2**30:.2f}"
                                     f"GiB > budget "
                                     f"{hbm_budget_bytes/2**30:.2f}GiB")
                    self.experiments.append(exp)
                    log_dist(f"autotune: PRUNE stage={stage} fsdp={fsdp} "
                             f"tp={tp}: {exp['reason']}", ranks=[0])
                    continue
                saved = dict(self.base_config)
                self.base_config["zero_optimization"] = dict(
                    self.base_config.get("zero_optimization", {}),
                    stage=stage)
                self.base_config["mesh"] = {"dp": -1, "fsdp": fsdp, "tp": tp}
                self.results = []
                try:
                    best_mbs = self.tune_micro_batch_size(start=start,
                                                          max_mbs=max_mbs)
                    best_r = max((r for r in self.results if r.ok),
                                 key=lambda r: r.tokens_per_s)
                    exp.update(status="ok", micro_batch=best_mbs,
                               tokens_per_s=best_r.tokens_per_s,
                               step_time_s=best_r.step_time_s,
                               probes=len(self.results))
                except Exception as e:  # noqa: BLE001 — a candidate failing
                    exp.update(status="failed", reason=str(e)[:200])
                finally:
                    self.base_config = saved
                self.experiments.append(exp)
        ranked = sorted(
            (e for e in self.experiments if e.get("status") == "ok"),
            key=lambda e: -e["tokens_per_s"])
        report = {"model_params": n_params, "n_devices": n_dev,
                  "hbm_budget_bytes": hbm_budget_bytes,
                  "experiments": self.experiments,
                  "ranking": ranked}
        if report_path:
            with open(report_path, "w") as f:
                json.dump(report, f, indent=1)
        if not ranked:
            raise RuntimeError(
                "autotune: every stage/mesh candidate was pruned or failed; "
                f"see the experiment record ({len(self.experiments)} entries)")
        best = ranked[0]
        log_dist(f"autotune: BEST stage={best['stage']} fsdp={best['fsdp']} "
                 f"tp={best['tp']} micro_batch={best['micro_batch']} "
                 f"({best['tokens_per_s']:,.0f} tok/s; "
                 f"{len(self.experiments)} experiments)", ranks=[0])
        return best

    def _count_params(self) -> int:
        import jax
        import numpy as np
        batch = self.batch_factory(1)
        model = self.model
        if hasattr(model, "init"):
            boxed = jax.eval_shape(
                lambda r: model.init(r, batch), jax.random.PRNGKey(0))
            from deepspeed_tpu.parallel.metadata import unbox
            return sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(unbox(boxed)))
        raise ValueError("pass n_params= for non-flax models")
