"""Autotuner — find the fastest micro-batch size with real compile+step probes.

Reference parity: ``autotuning/autotuner.py`` — the micro-batch tuner
(``get_min_max_micro_batch_size`` :741, ``run_tuning_micro_batch_size`` :960)
and its fast/model-based tuners (tuner/*.py).  The reference launches whole
training jobs per experiment through the launcher and scrapes metrics files;
here a probe is in-process — build the engine, compile the train step, time a
few real steps — because one JAX process drives every local chip, so no
process orchestration is needed.

Search shape mirrors the reference: geometric doubling from ``start`` until a
probe fails (OOM) or ``max_mbs`` is hit, then the failure boundary is refined
by bisection, and the fastest measured micro-batch (tokens/s) wins.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist


@dataclasses.dataclass
class ProbeResult:
    micro_batch: int
    ok: bool
    step_time_s: float = float("inf")
    tokens_per_s: float = 0.0
    error: str = ""


def _is_oom(err: Exception) -> bool:
    s = str(err)
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s.lower())


class Autotuner:
    """model + base config + a batch factory → best micro-batch.

    batch_factory(micro_batch) must return a host batch pytree with
    ``micro_batch`` leading rows (per chip).
    """

    def __init__(self, model, base_config: Dict[str, Any],
                 batch_factory: Callable[[int], Any],
                 probe_steps: int = 3):
        self.model = model
        self.base_config = dict(base_config)
        self.batch_factory = batch_factory
        self.probe_steps = probe_steps
        self.results: List[ProbeResult] = []

    # ---------------------------------------------------------------- probes
    def _probe(self, mbs: int) -> ProbeResult:
        import jax
        import numpy as np
        import deepspeed_tpu

        cfg = dict(self.base_config)
        cfg["train_micro_batch_size_per_gpu"] = mbs
        cfg["gradient_accumulation_steps"] = 1
        cfg.pop("train_batch_size", None)
        cfg["steps_per_print"] = 0
        batch = self.batch_factory(mbs)
        try:
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model, config=cfg, example_batch=batch)
            # per-chip rows → [gas=1, micro_global, ...]
            dpw = engine.mesh.shape["dp"] * engine.mesh.shape["fsdp"]

            def expand(x):
                x = np.asarray(x)
                reps = -(-mbs * dpw // x.shape[0])
                return np.tile(x, (reps,) + (1,) * (x.ndim - 1)
                               )[None, :mbs * dpw]
            full = jax.tree_util.tree_map(expand, batch)
            m = engine.train_batch(full)          # compile + step 0
            jax.device_get(m.loss)
            t0 = time.perf_counter()
            for _ in range(self.probe_steps):
                m = engine.train_batch(full)
            jax.device_get(m.loss)
            dt = (time.perf_counter() - t0) / self.probe_steps
            leaves = jax.tree_util.tree_leaves(full)
            # [gas, rows, T, ...] → real tokens/step (matches engine
            # train_batch accounting, shape[:3])
            tokens = int(np.prod(leaves[0].shape[:3]))
            res = ProbeResult(mbs, True, dt, tokens / dt)
        except Exception as e:  # noqa: BLE001 — OOM/compile failures end probes
            if not _is_oom(e):
                raise
            res = ProbeResult(mbs, False, error=str(e)[:200])
        self.results.append(res)
        log_dist(f"autotune probe mbs={mbs}: "
                 + (f"{res.tokens_per_s:,.0f} tok/s" if res.ok
                    else "OOM"), ranks=[0])
        return res

    # ---------------------------------------------------------------- search
    def tune_micro_batch_size(self, start: int = 1,
                              max_mbs: Optional[int] = None) -> int:
        """Doubling until OOM/max, bisect the boundary, return the fastest
        micro-batch (reference get_min_max_micro_batch_size :741)."""
        ok: List[ProbeResult] = []
        mbs = start
        last_ok, first_bad = 0, None
        while True:
            if max_mbs is not None and mbs > max_mbs:
                break
            r = self._probe(mbs)
            if not r.ok:
                first_bad = mbs
                break
            ok.append(r)
            last_ok = mbs
            mbs *= 2
        if first_bad is not None:
            lo, hi = last_ok, first_bad
            while hi - lo > max(1, lo // 4):     # coarse bisect (reference
                mid = (lo + hi) // 2             # uses similar tolerance)
                if mid in (lo, hi) or mid == 0:
                    break
                r = self._probe(mid)
                if r.ok:
                    ok.append(r)
                    lo = mid
                else:
                    hi = mid
        if not ok:
            raise RuntimeError(
                f"no micro batch ≥ {start} fits on this chip "
                f"(first OOM at {first_bad})")
        best = max(ok, key=lambda r: r.tokens_per_s)
        log_dist(f"autotune: best micro_batch={best.micro_batch} "
                 f"({best.tokens_per_s:,.0f} tok/s over "
                 f"{len(self.results)} probes)", ranks=[0])
        return best.micro_batch
