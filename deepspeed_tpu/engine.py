"""Training engine.

TPU-native analog of ``DeepSpeedEngine`` (reference runtime/engine.py:180, 3630 LoC).
The reference wraps a torch module and intercepts forward/backward/step with
hook-and-mutate machinery; here the engine *builds a jitted SPMD train step* from
(model, config) and owns the sharded train state.  Correspondences:

- ``engine.forward/backward/step``   → compatibility trio driving the same jitted
  grad/apply functions (reference engine.py:1785,1924,2123)
- ``engine.train_batch``             → one fused jitted step: scan over
  gradient-accumulation microbatches, ZeRO-sharded state update, loss-scale state
  machine (reference: the full fwd/bwd/step loop + stage_1_and_2/stage3 machinery)
- ZeRO stages                        → sharding choices (parallel/partition.py)
- fp16 dynamic loss scale            → runtime/precision.py inside the jitted step
- bf16 + fp32 master                 → runtime/zero.py with_master_weights
- gradient clipping                  → optax clip_by_global_norm in the chain
  (reference runtime/utils.py clip_grad_norm_)
- checkpoint save/load              → orbax (reference engine.py:2710-3554)
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.config import (DeepSpeedTPUConfig, parse_config,
                                  warn_inert_config)
from deepspeed_tpu.monitor import MonitorMaster
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.parallel import partition
from deepspeed_tpu.parallel.metadata import annotate_abstract, unbox
from deepspeed_tpu.runtime import faults, lr_schedules, optimizers, zero
from deepspeed_tpu.runtime.precision import (LossScaleState, grads_finite,
                                             init_loss_scale, update_loss_scale)
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (DATA_TIMER, TRAIN_BATCH_TIMER,
                                       SynchronizedWallClockTimer,
                                       ThroughputTimer)


class TrainState(NamedTuple):
    """Functional train state — the analog of the reference engine's mutable
    (module, optimizer, loss_scaler) aggregate."""

    step: jnp.ndarray
    params: Any
    opt_state: Any
    loss_scale: LossScaleState
    rng: jax.Array


class StepMetrics(NamedTuple):
    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    loss_scale: jnp.ndarray
    skipped_steps: jnp.ndarray


# grad_norm reported for an overflow-skipped step: a FINITE sentinel instead
# of the raw NaN/Inf, on both the device and the offload path — downstream
# consumers (monitors, schedulers keying on get_global_grad_norm) must never
# see a non-finite norm for a step whose update was skipped; the per-group
# attribution of the overflow lives in the health stats.  Matches the
# reference's overflow contract (skipped_steps counts it, the norm stays
# usable).
OVERFLOW_GNORM = -1.0


def _cast_params(params, dtype):
    return jax.tree_util.tree_map(
        lambda p: p.astype(dtype) if jnp.issubdtype(p.dtype, jnp.floating) else p,
        params)


def _moe_stats_to_python(moe_host):
    """Host-side MoE stats → plain python: the [E] expert-tokens vector
    becomes a list, scalars become floats (flight-recorder/JSON-safe)."""
    return {k: (v.tolist() if getattr(v, "ndim", 0) else float(v))
            for k, v in moe_host.items()}


def _reduce_moe_micros(moes):
    """Reduce [gas]-stacked per-micro MoE stats (moe/layer.py sows,
    aggregated per micro by ``aggregate_moe_stats``) to one step-level
    dict: token counts sum over microbatches, aux/entropy average."""
    if not moes:
        return {}
    return {k: (moes[k].mean(axis=0) if k in ("aux_loss", "gate_entropy")
                else moes[k].sum(axis=0)) for k in moes}


def _poison_first_float_leaf(params):
    """Engine-site payload of the ``nan`` fault kind at ``step.grads``:
    multiply the first floating-point parameter leaf by NaN (shape, dtype
    and sharding preserved).  The poisoned leaf drives this step's loss and
    gradients non-finite, and — whether the update is skipped by the
    overflow machinery or applied — the corruption PERSISTS in the live
    state, exactly the NaN-burst failure the guardian's rollback must heal
    (a replayed step without the fault cannot; only restoring a
    health-verified checkpoint can)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    for i, leaf in enumerate(leaves):
        if (hasattr(leaf, "dtype")
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            leaves[i] = leaf * jnp.array(jnp.nan, leaf.dtype)
            break
    return jax.tree_util.tree_unflatten(treedef, leaves)


class DeepSpeedTPUEngine:
    """Config-driven training engine over a device mesh.

    model contract: a flax linen Module whose ``__call__(batch)`` (after ``init``)
    returns a scalar loss, or a pair ``(init_fn, apply_fn)`` of pure functions with
    ``init_fn(rng, batch) -> params`` and ``apply_fn(params, batch, rng) -> loss``.
    """

    def __init__(self, model, config: DeepSpeedTPUConfig, example_batch,
                 mesh: Optional[Mesh] = None,
                 lr_scheduler: Optional[Callable[[int], float]] = None,
                 client_optimizer: Optional[optax.GradientTransformation] = None):
        self.config = config
        # overlap regime FIRST — XLA_FLAGS are parsed once at backend init,
        # so the latency-hiding/async-collective flags must be exported
        # before any jax backend touch (runtime/overlap.py warns when the
        # backend beat us to it)
        from deepspeed_tpu.runtime.overlap import apply_overlap_flags
        apply_overlap_flags(config.overlap)
        comm.init_distributed()
        if config.resilience.compilation_cache_dir:
            # persistent XLA compilation cache: a replacement host rebuilds
            # its step programs from disk instead of recompiling for
            # minutes (runtime/resilience.py; the config is read at first
            # COMPILE, so after distributed init is early enough — and the
            # CPU-unsafe gate needs the resolved backend)
            from deepspeed_tpu.runtime.resilience import \
                enable_compilation_cache
            enable_compilation_cache(config.resilience.compilation_cache_dir)
        comm.comms_logger.configure(config.comms_logger.enabled,
                                    config.comms_logger.verbose)
        warn_inert_config(config)

        # ---- mesh (replaces reference groups.initialize / mpu) ----
        if mesh is None:
            m = config.mesh
            dp, fsdp = m.dp, m.fsdp
            mics = config.zero_optimization.mics_shard_size
            if mics and mics > 0:
                # MiCS (reference runtime/zero/mics.py MiCS_Init:88): params
                # shard within SUBGROUPS of mics_shard_size chips and
                # replicate across groups — exactly fsdp=shard_size ×
                # dp=world/shard_size on this mesh, so the param all-gather
                # stays inside the (ICI-adjacent) subgroup and only the grad
                # reduce crosses groups (hierarchical_allgather analog)
                if config.zero_optimization.stage < 3:
                    raise ValueError("mics_shard_size requires zero stage 3")
                fsdp, dp = mics, -1
            elif not isinstance(fsdp, int):  # "auto": ZeRO shards over the
                # whole DP world (reference semantics), so data parallelism
                # rides the fsdp axis when any ZeRO stage is on
                if config.zero_optimization.stage >= 1:
                    fsdp = -1
                    dp = 1 if dp == -1 else dp
                else:
                    fsdp = 1
            spec = mesh_lib.MeshSpec(pp=m.pp, dp=dp, fsdp=fsdp, ep=m.ep,
                                     sp=m.sp, tp=m.tp)
            mesh = mesh_lib.build_mesh(spec)
        self.mesh = mesh
        self.dp_world_size = mesh.shape["dp"] * mesh.shape["fsdp"]
        if config.elasticity.enabled:
            # the SOLVER controls the batch triad (reference
            # runtime/config.py:733: elastic config overrides / rejects
            # user-set batch params)
            self._apply_elasticity_config(config)
        config.resolve_batch_size(self.dp_world_size)

        self.zero_stage = config.zero_optimization.stage
        self.compute_dtype = config.compute_dtype
        # ZeRO-Offload: optimizer state + fp32 masters live on the HOST
        # (runtime/offload.py); the device holds only compute-dtype params and
        # runs a grads-only program each step
        off = config.zero_optimization.offload_optimizer
        self.offloading = off.device != "none"
        if config.zero_optimization.offload_param.device != "none":
            raise ValueError(
                "offload_param is served by the Infinity engine — build via "
                "deepspeed_tpu.initialize() (which dispatches to "
                "runtime.infinity.InfinityEngine), not DeepSpeedTPUEngine "
                "directly")
        # master-weight mode iff low-precision params (reference: BF16_Optimizer /
        # fp16 fused optimizer wrap client optimizer the same way); under
        # offload the fp32 master lives host-side instead of in the opt state
        self.use_master_weights = ((config.bf16.enabled or config.fp16.enabled)
                                   and not self.offloading)
        self.gas = int(config.gradient_accumulation_steps)

        # ---- qgZ: quantized gradient reduce (reference ZeRO++ qgZ,
        # runtime/zero/stage3.py:1497 quantized gradient reduction; config
        # runtime/zero/config.py zero_quantized_gradients).  Grads are
        # computed per-device inside a collective-free shard_map over the
        # data axis, stacked, and reduced by the quantized pipeline
        # (runtime/zero.pipeline_grad_reduce: int-wire all-to-all
        # reduce-scatter / EQuARX-style quantized allreduce) instead of the
        # partitioner's implicit fp32 reduce.  ``zeropp.quantized_allreduce``
        # opens the same path at stage 0/1, where the dp grad exchange is a
        # plain allreduce (no scatter target needed — arXiv:2506.17615).
        self._qgz_axis = None
        zpp = config.zero_optimization.zeropp
        if (config.zero_optimization.zero_quantized_gradients
                or zpp.quantized_allreduce):
            nested_axes = {a: mesh.shape[a] for a in ("sp", "ep", "pp")
                           if mesh.shape[a] > 1}
            data_axes = [a for a in ("dp", "fsdp") if mesh.shape[a] > 1]
            if (self.zero_stage < 2
                    and config.zero_optimization.zero_quantized_gradients
                    and not zpp.quantized_allreduce):
                raise ValueError(
                    "zero_quantized_gradients requires zero stage >= 2 "
                    "(gradients must be partitioned for the quantized "
                    "reduce-scatter to have a scatter target); at stage "
                    "0/1 set zero_optimization.zeropp.quantized_allreduce "
                    "for the block-quantized allreduce instead")
            if nested_axes:
                # sp/ep/pp express their collectives with their OWN
                # shard_map (ring/Ulysses/MoE route/pipeline) — shardy
                # cannot nest a manual_computation inside the manual-dp
                # grad region ('operates on axis already bound by a
                # parent'), so these compose only via the auto path
                raise NotImplementedError(
                    f"zero_quantized_gradients with mesh axes {nested_axes}"
                    f": sequence/expert/pipeline parallelism run their own "
                    f"shard_map collectives, which cannot nest inside the "
                    f"manual data-axis gradient shard_map; tp composes "
                    f"(pure GSPMD), sp/ep/pp do not yet")
            # qgZ quantizes the CROSS-REPLICA dp reduce; everything else
            # (fsdp param-gather-fused reduce-scatter, tp activation
            # collectives) stays under GSPMD inside the partial-manual
            # body.  At stage >= 3 (and stage 2 with dp x fsdp) the fsdp
            # reduce rides intra-group ICI — the reference qgZ's
            # hierarchical design targets exactly the cross-group hop.
            if mesh.shape["dp"] > 1:
                self._qgz_axis = "dp"
            elif mesh.shape["fsdp"] > 1 and self.zero_stage < 3:
                self._qgz_axis = "fsdp"
            elif not data_axes:
                logger.warning(
                    "zero_quantized_gradients set but the data-parallel "
                    "world is 1 — there is no gradient reduce to quantize; "
                    "flag is inert on this mesh")
            elif config.zero_optimization.zero_quantized_gradients:
                # stage 3 with dp=1: no cross-replica reduce — the ONLY
                # gradient exchange is the fsdp reduce-scatter riding the
                # param-gather transpose, which the composable pipeline
                # quantizes (runtime/zero._qwire_exchange bwd); no manual
                # data-axis region needed
                log_dist(
                    "qgZ at stage 3 with dp=1: gradient quantization rides "
                    "the chunked gather's transpose (quantized "
                    "reduce-scatter over 'fsdp')", ranks=[0])
            else:
                logger.warning(
                    "zeropp.quantized_allreduce at stage 3 with dp=1: the "
                    "only gradient reduce is the fsdp reduce-scatter fused "
                    "with the param gather — set zero_quantized_gradients "
                    "to quantize it; the allreduce knob is inert here")
            if self._qgz_axis:
                auto = [a for a in ("fsdp", "tp")
                        if mesh.shape[a] > 1 and a != self._qgz_axis]
                if len(auto) > 1:
                    # two auto axes under one manual axis trips a fatal
                    # CHECK in XLA's SPMD partitioner
                    # (spmd_partitioner_util.cc replica-group mismatch) —
                    # refuse rather than crash the process; one auto axis
                    # (dp x fsdp, dp x tp) composes fine
                    raise NotImplementedError(
                        f"zero_quantized_gradients over '{self._qgz_axis}' "
                        f"with BOTH {auto[0]} > 1 and {auto[1]} > 1: XLA's "
                        f"partitioner cannot yet mix two auto axes under "
                        f"the manual gradient region (fatal partitioner "
                        f"check); drop one axis or disable qgZ")
                log_dist(f"qgZ: int8 gradient reduce over mesh axis "
                         f"'{self._qgz_axis}' "
                         f"({mesh.shape[self._qgz_axis]} ways"
                         + (f", {'/'.join(auto)} under GSPMD" if auto
                            else "") + ")", ranks=[0])

        # low-precision mode casts PARAMS, but flax models own their COMPUTE
        # dtype — fp32 activations silently demote every matmul off the bf16
        # MXU path (measured ~12 MFU points on GPT-2-small).  Warn when the
        # model's config disagrees with the precision block.
        mcfg = getattr(model, "cfg", None)
        if (mcfg is not None
                and getattr(mcfg, "dtype", None) == jnp.float32):
            want = ("bf16" if config.bf16.enabled
                    else "fp16" if config.fp16.enabled else None)
            if want:
                log_dist(
                    f"WARNING: {want} is enabled but the model computes in "
                    f"float32 (model cfg.dtype) — matmuls will not hit the "
                    f"low-precision MXU path.  Set dtype=jnp.{'bfloat16' if want == 'bf16' else 'float16'} "
                    f"in the model config for full throughput.", ranks=[0])

        # ---- model functions ----
        # bind the engine's mesh into mesh-aware models (MoE ep route,
        # Ulysses).  The model stays BOUND under qgZ too (round-4 verdict:
        # unbinding left the embedding path to GSPMD's layout whims inside
        # the manual grad shard_map): constraints naming auto axes
        # (fsdp/tp) apply inside the partial-manual body, and constraints
        # naming the manual data axis are dropped by the partitioner.
        if (hasattr(model, "clone") and hasattr(model, "mesh")
                and model.mesh is None):
            model = model.clone(mesh=self.mesh)
        # random-LTD: push the configured layer ids into the model config so
        # ds_config is the single source of truth (reference: the data_routing
        # block rewires layers at initialize() time)
        rl_cfg = config.data_efficiency.data_routing.random_ltd
        if (config.data_efficiency.enabled and rl_cfg.enabled
                and hasattr(model, "clone") and hasattr(model, "cfg")
                and hasattr(model.cfg, "random_ltd_layer_ids")):
            cfg_ids = tuple(rl_cfg.random_ltd_layer_ids)
            model_ids = tuple(model.cfg.random_ltd_layer_ids)
            if not model_ids:
                import dataclasses as _dc
                model = model.clone(cfg=_dc.replace(
                    model.cfg, random_ltd_layer_ids=cfg_ids))
            elif model_ids != cfg_ids:
                raise ValueError(
                    f"random_ltd_layer_ids mismatch: model cfg has "
                    f"{model_ids}, ds_config says {cfg_ids} — set them in "
                    f"ONE place")
        # activation quantization (reference compression QuantAct): the model
        # config carries the bits so the fake-quant happens inside the layers
        from deepspeed_tpu.compression.pruning import \
            parse_activation_quant_config
        act_bits = parse_activation_quant_config(
            config.compression_training or {})
        if act_bits:
            if not (hasattr(model, "clone") and hasattr(model, "cfg")
                    and hasattr(model.cfg, "act_quant_bits")):
                raise ValueError(
                    "compression_training.activation_quantization needs a "
                    "model whose config takes act_quant_bits (models/gpt.py "
                    "GPT); this model would silently ignore it")
            import dataclasses as _dc
            model = model.clone(cfg=_dc.replace(model.cfg,
                                                act_quant_bits=act_bits))
        # overlap.collective_matmul: route the model's TP row-parallel
        # matmuls through the explicit ppermute-ring fusions
        # (ops/collective_matmul.py) — ds_config is the single source of
        # truth, like the random-LTD / activation-quant knobs above
        if config.overlap.enabled and config.overlap.collective_matmul:
            if (hasattr(model, "clone") and hasattr(model, "cfg")
                    and hasattr(model.cfg, "tp_collective_matmul")):
                if not getattr(model.cfg, "tp_collective_matmul"):
                    import dataclasses as _dc
                    model = model.clone(cfg=_dc.replace(
                        model.cfg, tp_collective_matmul=True))
            else:
                logger.warning(
                    "overlap.collective_matmul set but the model config has "
                    "no tp_collective_matmul knob (models/gpt.py GPT) — the "
                    "ring collective-matmul fusions are inert for this model")
        # moe: push the ep a2a wire/overlap knobs into the model config so
        # ds_config is the single source of truth (moe/comm.py fast path),
        # like the random-LTD / activation-quant knobs above
        moe_cfg = config.moe
        if moe_cfg.wire_bits or moe_cfg.num_chunks > 1 or moe_cfg.hierarchical:
            if (hasattr(model, "clone") and hasattr(model, "cfg")
                    and hasattr(model.cfg, "moe_wire_bits")):
                import dataclasses as _dc
                model = model.clone(cfg=_dc.replace(
                    model.cfg, moe_wire_bits=moe_cfg.wire_bits,
                    moe_wire_block=moe_cfg.block_size,
                    moe_hierarchical=moe_cfg.hierarchical,
                    moe_num_chunks=moe_cfg.num_chunks))
            else:
                logger.warning(
                    "moe.* wire/overlap knobs set but the model config has "
                    "no moe_wire_bits knob (models/gpt.py GPT) — the MoE a2a "
                    "fast path is inert for this model")
        # progressive layer drop (reference engine.progressive_layer_drop
        # built at initialize() when the config block is enabled)
        pld_cfg = config.progressive_layer_drop
        if pld_cfg.enabled:
            if getattr(model, "is_pipeline", False) or isinstance(model,
                                                                  tuple):
                raise ValueError(
                    "progressive_layer_drop requires a flax LM that reads "
                    "batch['pld_theta'] (models/gpt.py GPT); pipeline and "
                    "duck-typed models would silently ignore it")
            from deepspeed_tpu.runtime.progressive_layer_drop import \
                ProgressiveLayerDrop
            self.pld = ProgressiveLayerDrop(theta=pld_cfg.theta,
                                            gamma=pld_cfg.gamma)
        else:
            self.pld = None
        # pipeline models consume all gas microbatches in one pipelined scan
        # (reference: PipelineEngine.train_batch owns the microbatch loop)
        self.gas_in_model = bool(getattr(model, "is_pipeline", False))
        self._apply_fn_stats = None     # flax models only (moe_stats sow)
        if isinstance(model, tuple):
            self._init_fn, self._apply_fn = model
            # rng=None signals "deterministic" by convention (PipeGPT does
            # the same); an apply_fn that ignores rng is unaffected
            self._apply_fn_det = (
                lambda params, batch, rng: self._apply_fn(params, batch,
                                                          None))
        else:
            import flax.linen as fnn
            self._init_fn = lambda rng, batch: model.init(rng, batch)
            if isinstance(model, fnn.Module):
                self._apply_fn = lambda params, batch, rng: model.apply(
                    params, batch, rngs={"dropout": rng})
                # expert-telemetry leg: same forward with the moe_stats sow
                # collection mutable — returns (out, {"moe_stats": ...})
                self._apply_fn_stats = \
                    lambda params, batch, rng: model.apply(
                        params, batch, rngs={"dropout": rng},
                        mutable=["moe_stats"])
                # deterministic leg for eval_batch (reference module.eval()):
                # only if the module's __call__ actually takes the optional
                # `deterministic` flag — the base contract (__call__(batch))
                # doesn't require it
                import inspect
                try:
                    takes_det = "deterministic" in inspect.signature(
                        type(model).__call__).parameters
                except (TypeError, ValueError):
                    takes_det = False
                if takes_det:
                    self._apply_fn_det = \
                        lambda params, batch, rng: model.apply(
                            params, batch, deterministic=True,
                            rngs={"dropout": rng})
                else:
                    self._apply_fn_det = self._apply_fn
            else:  # duck-typed (init/apply) object, e.g. PipeGPT
                self._apply_fn = lambda params, batch, rng: model.apply(
                    params, batch, rng)
                # PipeGPT contract: rng=None disables dropout
                self._apply_fn_det = lambda params, batch, rng: model.apply(
                    params, batch, None)
        self.model = model

        # ---- optimizer + schedule (reference engine._configure_optimizer
        #      engine.py:1219 + _configure_lr_scheduler :905) ----
        self.lr_schedule = lr_scheduler
        if self.lr_schedule is None and config.scheduler is not None:
            self.lr_schedule = lr_schedules.build_schedule(
                config.scheduler.type, config.scheduler.params)
        if self.offloading:
            from deepspeed_tpu.runtime.offload import OffloadAdam
            if client_optimizer is not None:
                raise ValueError(
                    "ZeRO-Offload builds its own host Adam (the reference "
                    "likewise swaps client optimizers for DeepSpeedCPUAdam); "
                    "drop the client optimizer or offload")
            self.offload_opt = OffloadAdam(
                config.optimizer.type, config.optimizer.params,
                device=off.device, nvme_path=off.nvme_path,
                aio_threads=max(1, int(config.aio.thread_count)))
            # API contract: initialize() returns the swapped-in host optimizer
            # (reference returns DeepSpeedCPUAdam on the offload path)
            self.optimizer = self.offload_opt
            self._opt_params = dict(config.optimizer.params)
        else:
            self.offload_opt = None
        # guardian clamp-down state: effective LR = configured LR x
        # _lr_scale (engine.clamp_lr); kept OUTSIDE the optimizer so the
        # offload host step reads it sync-free and the device paths rebuild
        # their chain from it on a clamp
        self._lr_scale = 1.0
        self._client_optimizer = client_optimizer
        if not self.offloading:
            self.optimizer, self._opt_params = self._build_tx(client_optimizer)
        # overlapped host step (offload_optimizer.overlap_step): the CPU Adam
        # of step N runs on a worker thread while the device computes step
        # N+1's grads against one-update-stale params (reference ZeRO-Offload
        # delayed parameter update); runtime/offload.py HostStepWorker
        self._overlap_step = bool(self.offloading and off.overlap_step)
        self._host_worker = None
        if self._overlap_step:
            from deepspeed_tpu.runtime.offload import HostStepWorker
            self._host_worker = HostStepWorker()

        # normalize the example batch's leading dim to the global microbatch so
        # init tracing and the jitted step see shardable shapes; only leaves
        # sharing the example's batch dim are tiled (non-batch leaves pass through)
        micro_global = (int(config.train_micro_batch_size_per_gpu)
                        * self.dp_world_size)
        leaves = jax.tree_util.tree_leaves(example_batch)
        example_bs = np.asarray(leaves[0]).shape[0] if leaves else 0

        def _tile(x):
            x = np.asarray(x)
            if (x.ndim == 0 or x.shape[0] != example_bs
                    or x.shape[0] == micro_global):
                return x
            reps = -(-micro_global // x.shape[0])
            return np.tile(x, (reps,) + (1,) * (x.ndim - 1))[:micro_global]
        example_batch = jax.tree_util.tree_map(_tile, example_batch)

        # ---- abstract shapes + shardings (zero.Init analog: params are created
        #      already sharded; reference partition_parameters.py:808) ----
        rng = jax.random.PRNGKey(config.seed)
        boxed = jax.eval_shape(self._init_fn, rng, example_batch)
        annotated = annotate_abstract(boxed)

        # hpZ (reference zero_hpz_partition_size,
        # partition_parameters.py:1653): PARAMS shard only within the
        # fsdp subgroup (fwd/bwd gathers ride intra-group ICI) while
        # optimizer state + grads shard over the FULL (fsdp, dp) world
        hpz = config.zero_optimization.zero_hpz_partition_size
        self._state_fsdp_axes = ("fsdp",)
        if hpz and hpz > 1:
            if self.zero_stage < 3:
                raise ValueError("zero_hpz_partition_size requires stage 3")
            if mesh.shape["fsdp"] != hpz:
                raise ValueError(
                    f"zero_hpz_partition_size={hpz} must equal the fsdp mesh "
                    f"axis ({mesh.shape['fsdp']}); set mesh "
                    f"{{'fsdp': {hpz}, 'dp': -1}} so dp carries the "
                    f"cross-group replicas")
            self._state_fsdp_axes = ("fsdp", "dp")
        self.param_shardings = partition.param_shardings(
            annotated, mesh, self.zero_stage)
        abstract_params = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), annotated)
        if self.use_master_weights:
            abstract_params = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, self.compute_dtype)
                if jnp.issubdtype(l.dtype, jnp.floating) else l, abstract_params)
        if self.offloading:
            # optimizer state lives host-side; nothing on device
            abstract_opt = ()
            self.opt_shardings = ()
        else:
            abstract_opt = jax.eval_shape(self.optimizer.init, abstract_params)
            self.opt_shardings = partition.opt_state_shardings(
                abstract_opt, annotated, mesh, self.zero_stage,
                fsdp_axes=self._state_fsdp_axes)

        self.state_shardings = TrainState(
            step=NamedSharding(mesh, P()),
            params=self.param_shardings,
            opt_state=self.opt_shardings,
            loss_scale=jax.tree_util.tree_map(
                lambda _: NamedSharding(mesh, P()), init_loss_scale(config.fp16)),
            rng=NamedSharding(mesh, P()),
        )
        # grad accumulation buffers: sharded like optimizer state at stage ≥ 2
        # (ZeRO-2 gradient partitioning, reference stage_1_and_2.py:1361)
        self.grad_shardings = partition.state_leaf_shardings(
            annotated, mesh, self.zero_stage if self.zero_stage >= 2 else 0,
            fsdp_axes=self._state_fsdp_axes)

        # staged QAT groups (compression/basic.py); empty = off
        from deepspeed_tpu.compression import parse_compression_config
        from deepspeed_tpu.compression.pruning import parse_pruning_config
        self._compression_specs = parse_compression_config(
            config.compression_training)
        if self._compression_specs:
            log_dist(f"compression: {len(self._compression_specs)} weight-"
                     f"quantization group(s) active", ranks=[0])
        # pruning family (compression/pruning.py; reference basic_layer.py
        # sparse/row/head pruning) — masks applied in-loss past each group's
        # schedule_offset
        nh = int(getattr(getattr(self.model, "cfg", None), "num_heads", 0)
                 or 0)
        self._pruning_specs = parse_pruning_config(
            config.compression_training or {}, num_heads=nh)
        if self._pruning_specs:
            log_dist(f"compression: {len(self._pruning_specs)} pruning "
                     f"group(s) active "
                     f"({sorted(set(s.kind for s in self._pruning_specs))})",
                     ranks=[0])

        # ZeRO++ qwZ: per-leaf fsdp-sharded dims (None = flag off / inert
        # mesh).  The pipeline recomputes its own dims (partition.
        # sharded_dim inside pipeline_param_gather); this tree survives as
        # the qwZ-active gate for the wire plan below and as the
        # introspection surface (tests/serving probes read it)
        self._qwz_dims = None
        if (config.zero_optimization.zero_quantized_weights
                and self.zero_stage >= 3 and mesh.shape["fsdp"] > 1):
            # -1 sentinel = leaf not fsdp-sharded; dims co-sharded with
            # another axis (tuple specs) keep the partitioner's implicit
            # gather (parallel/partition.py sharded_dim)
            self._qwz_dims = partition.fsdp_shard_dims(self.param_shardings)
        elif (config.zero_optimization.zero_quantized_weights
              and self.zero_stage >= 3):
            logger.warning("zero_quantized_weights set but the fsdp mesh axis "
                           "is 1 — there is no weight all-gather to quantize; "
                           "flag is inert on this mesh")

        # ---- composable collective pipeline (runtime/zero.py, ISSUE 14):
        # chunking (overlap.num_chunks), block quantization (qwZ fwd / qgZ
        # bwd wire bits from the zeropp block), and hierarchy
        # (zeropp.hierarchical per-axis wire policy) compose on ONE
        # stage-3 gather/reduce path.  The former either/or conflict gates
        # (chunks × qwZ, chunks × qgZ) are gone: quantization runs INSIDE
        # the chunk bodies, and the qgZ data-axis reduce consumes stacked
        # per-replica grads in its own full-manual region, so nothing
        # nests inside the manual grad shard_map anymore.
        ov = config.overlap
        # qgZ proper (zero_quantized_gradients) quantizes BOTH gradient
        # exchanges: the gather-transpose reduce-scatter (grad_bits in the
        # wire plan) and the data-axis reduce.  zeropp.quantized_allreduce
        # is scoped to the DATA-AXIS reduce only (its stage-0/1 reason for
        # existing) — it must never flip the fsdp reduce-scatter to lossy
        # wire on a config that didn't ask for qgZ, so it feeds
        # _dp_reduce_plan below but not this plan's grad_bits.
        qgz_on = bool(config.zero_optimization.zero_quantized_gradients)
        self._wire_plan = zero.WirePlan(
            num_chunks=max(1, int(ov.num_chunks) if ov.enabled else 1),
            weight_bits=(int(zpp.weight_bits)
                         if self._qwz_dims is not None else 0),
            grad_bits=int(zpp.grad_bits) if qgz_on else 0,
            block_size=int(zpp.block_size),
            hierarchical=bool(zpp.hierarchical),
        )
        self._dp_reduce_plan = self._wire_plan._replace(
            grad_bits=(int(zpp.grad_bits)
                       if (qgz_on or zpp.quantized_allreduce) else 0))
        # the explicit gather engages when ANY pipeline layer asks for it;
        # otherwise the partitioner's implicit per-consumer gathers stand
        # (the seed behavior)
        self._gather_chunks = 0
        self._pipeline_active = False
        want_pipeline = (self._wire_plan.num_chunks > 1
                         or self._wire_plan.weight_bits > 0
                         or (qgz_on and self.zero_stage >= 3))
        if want_pipeline:
            if self.zero_stage < 3 or mesh.shape["fsdp"] <= 1:
                if ov.enabled and ov.num_chunks > 1:
                    logger.warning(
                        "overlap.num_chunks=%d set but there is no stage-3 "
                        "param all-gather to chunk (stage %d, fsdp=%d) — "
                        "chunking is inert on this config; the XLA "
                        "scheduler flags still apply", ov.num_chunks,
                        self.zero_stage, mesh.shape["fsdp"])
            else:
                self._pipeline_active = True
                self._gather_chunks = self._wire_plan.num_chunks
                wb, gb = zero.resolve_wire_bits(self._wire_plan, mesh,
                                                "fsdp")
                log_dist(
                    f"pipeline: stage-3 param gather in "
                    f"{self._wire_plan.num_chunks} per-layer-group "
                    f"chunk(s) over 'fsdp' ({mesh.shape['fsdp']} ways), "
                    f"wire={'q%d' % wb if wb else 'full'} gather / "
                    f"{'q%d' % gb if gb else 'full'} reduce-scatter"
                    + (" [hierarchical]"
                       if self._wire_plan.hierarchical else ""),
                    ranks=[0])

        # numerics health monitor (telemetry.health): per-group stats are
        # traced INTO the step programs, so the flags must exist before
        # _build_step_functions
        self._health_enabled = bool(config.telemetry.health.enabled)
        self._health_depth = int(config.telemetry.health.group_depth)

        # expert-load telemetry (moe/layer.py _sow_stats): traced INTO the
        # step as one extra output (the health pattern — no steady-state
        # recompile); flax MoE models only, and not under the qgZ
        # partial-manual wrapper, whose shard_map can't carry the extra
        # mutable-collection output
        self._moe_stats_on = bool(
            config.moe.expert_telemetry
            and self._apply_fn_stats is not None
            and getattr(getattr(model, "cfg", None), "num_experts", 0) > 0
            and self._qgz_axis is None)
        self._last_moe_host = None

        # ---- build + jit the step functions ----
        self._jit_init = jax.jit(
            self._make_init(), out_shardings=self._as_shardings_tuple())
        self._build_step_functions()

        # On legacy jax, ``with mesh:`` defines the thread-resources mesh
        # that makes flax's scope.param unboxing apply LOGICAL partition
        # names as sharding constraints mid-init — logical names are not
        # mesh axes, so that is always an error (out_shardings are explicit
        # NamedShardings and don't need the context).  On current jax the
        # context is harmless and user init_fns may rely on it to resolve
        # bare PartitionSpec constraints, so it stays.
        from deepspeed_tpu.utils.compat import is_legacy_jax
        if is_legacy_jax():
            self.state = self._jit_init(rng, example_batch)
        else:
            with self.mesh:
                self.state = self._jit_init(rng, example_batch)
        if self.offloading:
            # stream the initial params to host: fp32 masters + moments are
            # built there (zero.Init-at-construction analog for the host tier)
            self.offload_opt.initialize(jax.device_get(self.state.params))

        # forward/backward/step compatibility buffers
        self._accum_grads = None
        self._micro_losses = []
        self._micro_steps = 0
        self.global_steps = 0
        self._last_metrics: Optional[StepMetrics] = None
        # host mirror of the latest StepMetrics (+ health stats), filled by
        # the ONE sanctioned device fetch in _fetch_metrics —
        # get_global_grad_norm()/skipped_steps read this instead of syncing
        # per scalar
        self._last_metrics_host: Optional[StepMetrics] = None
        self._last_health = None          # device pytree (or host dict)
        self._last_health_host: dict = {}
        self._host_metrics_step = -1
        self._step_times = []

        # ---- observability (reference: MonitorMaster engine.py:1000,
        #      EngineTimers :145, flops profiler hook :1797) ----
        self.monitor = MonitorMaster(config)
        self.timers = SynchronizedWallClockTimer()
        # rate logging rides the engine's print cadence (reference
        # ThroughputTimer prints its own line at steps_per_output)
        self.tput_timer = ThroughputTimer(
            steps_per_output=int(config.steps_per_print or 0),
            warmup_steps=1)
        self.wall_clock_breakdown = bool(config.wall_clock_breakdown)
        # unified step telemetry (telemetry/): span tracer + recompile
        # watchdog + counter/gauge registries + snapshot exporter
        from deepspeed_tpu.telemetry import StepTelemetry
        self.telemetry = StepTelemetry(config, monitor=self.monitor)

        # ---- data-efficiency pipeline (reference runtime/data_pipeline/) ----
        self.curriculum_scheduler = None
        self.random_ltd_scheduler = None
        de = config.data_efficiency
        if de.enabled and de.data_sampling.curriculum_learning.enabled:
            from deepspeed_tpu.data_pipeline import CurriculumScheduler
            cl = de.data_sampling.curriculum_learning
            if cl.curriculum_type != "seqlen":
                raise NotImplementedError(
                    "engine-integrated curriculum supports the seqlen metric; "
                    "other metrics go through data_pipeline."
                    "CurriculumDataSampler on the dataloader side")
            self.curriculum_scheduler = CurriculumScheduler(
                cl.model_dump(exclude={"enabled"}))
        if de.enabled and de.data_routing.random_ltd.enabled:
            from deepspeed_tpu.data_pipeline import RandomLTDScheduler
            rl = de.data_routing.random_ltd
            if self.gas_in_model:
                raise NotImplementedError(
                    "random-LTD inside the pipeline engine is unsupported")
            if not rl.random_ltd_layer_ids:
                raise ValueError("random_ltd.random_ltd_layer_ids is empty")
            if self.mesh.shape["sp"] > 1:
                raise NotImplementedError("random-LTD with Ulysses sequence "
                                          "parallelism is unsupported")
            self.random_ltd_scheduler = RandomLTDScheduler(rl.model_dump())
            self._ltd_layer_ids = tuple(rl.random_ltd_layer_ids)
            self._de_seed = de.seed
        self._flops_profiled = False
        self._last_batch = None
        if config.dump_state:
            log_dist("config state:\n" + config.model_dump_json(indent=2),
                     ranks=[0])

        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(annotated))
        self.num_parameters = n_params
        log_dist(
            f"engine ready: params={n_params/1e6:.1f}M zero_stage={self.zero_stage} "
            f"mesh={dict(self.mesh.shape)} dtype={self.compute_dtype.__name__} "
            f"micro_bs/gpu={config.train_micro_batch_size_per_gpu} gas={self.gas} "
            f"global_bs={config.train_batch_size}", ranks=[0])

    # ------------------------------------------------------------------ builders

    def _apply_elasticity_config(self, config):
        """ds_config "elasticity" block (reference runtime/config.py:733):
        solve the batch geometry for the CURRENT world size and take control
        of the batch triad; explicitly-set batch params are an error unless
        ignore_non_elastic_batch_info."""
        from deepspeed_tpu.constants import AUTO
        from deepspeed_tpu.elasticity import (ElasticityConfig,
                                              compute_elastic_config)
        e = config.elasticity
        triad_set = any(v != AUTO for v in (
            config.train_batch_size, config.train_micro_batch_size_per_gpu,
            config.gradient_accumulation_steps))
        if triad_set and not e.ignore_non_elastic_batch_info:
            raise ValueError(
                "batch-related parameters found in the ds_config while "
                "elasticity is enabled — elastic training controls "
                "train_batch_size/train_micro_batch_size_per_gpu/"
                "gradient_accumulation_steps; remove them or set "
                "elasticity.ignore_non_elastic_batch_info (reference "
                "ElasticityConfigError semantics)")
        if float(e.version) not in (0.1, 0.2):
            raise ValueError(
                f"elasticity.version {e.version} is not supported "
                f"(reference semantics: 0.1 chip-granular, 0.2 "
                f"host-granular)")
        chips = self.dp_world_size * e.model_parallel_size
        ec = ElasticityConfig(
            enabled=True,
            max_train_batch_size=e.max_train_batch_size,
            micro_batch_sizes=list(e.micro_batch_sizes),
            min_chips=e.min_gpus, max_chips=e.max_gpus,
            # v0.1 solves at CHIP granularity (reference elasticity.py
            # version gate); v0.2 adds the host-granular constraint.  The
            # chip-granular unit is one model replica (mp chips).
            chips_per_host=(e.num_gpus_per_node
                            if float(e.version) >= 0.2
                            else e.model_parallel_size),
            model_parallel_size=e.model_parallel_size,
            prefer_larger_batch=e.prefer_larger_batch,
            version=e.version)
        batch, valid_dp, micro = compute_elastic_config(ec, chips)
        if micro is None:
            raise ValueError(
                f"elasticity: no micro batch in {e.micro_batch_sizes} "
                f"divides batch {batch} at dp world {self.dp_world_size}")
        gas = batch // (micro * self.dp_world_size)
        config.train_batch_size = batch
        config.train_micro_batch_size_per_gpu = micro
        config.gradient_accumulation_steps = gas
        log_dist(f"[Elasticity] batch={batch} micro={micro} gas={gas} "
                 f"valid dp counts={valid_dp}", ranks=[0])

    def _build_tx(self, client_optimizer):
        cfg = self.config
        if client_optimizer is not None:
            inner = client_optimizer
            opt_params = {}
        else:
            params = dict(cfg.optimizer.params)
            if self.lr_schedule is not None:
                params["lr"] = self.lr_schedule
            scale = self._lr_scale
            base = params.get("lr", 1e-3)
            if scale != 1.0:
                # guardian clamp-down: scale whatever LR the chain would
                # have seen (schedule or constant) — the clamp survives a
                # re-jit because _build_tx is the single LR authority
                if callable(base):
                    params["lr"] = lambda s, _b=base, _k=scale: _b(s) * _k
                else:
                    params["lr"] = float(base) * scale
            inner, opt_params = optimizers.build_optimizer(
                cfg.optimizer.type, params)
            if scale != 1.0 and not callable(base):
                # readers (get_lr) apply _lr_scale themselves: keep the
                # resolved params UNSCALED so the clamp is applied once
                opt_params = dict(opt_params, lr=float(base))
        chain = []
        # error-feedback compressed grads (runtime/compression.py) — BEFORE
        # clipping so the clip sees the signal the optimizer will consume.
        # Requested either via the gradient_compression block or by a 1-bit
        # optimizer NAME (reference fp16/onebit/); one stage either way, with
        # the block's dtype as the single knob
        wants_onebit = (client_optimizer is None
                        and optimizers.is_onebit(cfg.optimizer.type))
        if cfg.gradient_compression.enabled or wants_onebit:
            from deepspeed_tpu.runtime.compression import compress_gradients
            dtype = (cfg.gradient_compression.dtype
                     if cfg.gradient_compression.enabled else "int8")
            chain.append(compress_gradients(dtype))
        if cfg.gradient_clipping and cfg.gradient_clipping > 0:
            chain.append(optax.clip_by_global_norm(cfg.gradient_clipping))
        chain.append(inner)
        tx = optax.chain(*chain) if len(chain) > 1 else inner
        if self.use_master_weights:
            tx = zero.with_master_weights(tx)
        return tx, opt_params

    def _as_shardings_tuple(self):
        return self.state_shardings

    def _build_step_functions(self):
        """(Re)jit the train/grad step programs.  Called at init and again by
        configure_moq — the compiled programs close over the compression
        specs at trace time, so a schedule change needs a re-trace."""
        tel = getattr(self, "telemetry", None)   # absent on the init call
        if tel is not None and tel.enabled:
            # fresh jit objects have empty caches: the next dispatch IS a
            # compile, and the old compiled-HLO figures are stale
            tel.invalidate()
        self._jit_eval = None              # rebuilt lazily by eval_batch
        self._jit_grad = jax.jit(self._make_grad_fn())
        if self.offloading:
            # device runs grads-only; optimizer step is host-side
            self._grads_batch_fn = self._make_grads_batch()
            self._train_batch_fn = self._grads_batch_fn  # flops profiler trace
            self._jit_grads_batch = jax.jit(
                self._grads_batch_fn,
                out_shardings=(self.grad_shardings, None, None, None))
            self._jit_train_batch = None
            self._jit_apply = None
            self._jit_gnorm = jax.jit(optax.global_norm)
            # trio (forward/backward/step) offload path: the accumulated
            # grads never pass through _jit_grads_batch, so health stats
            # need their own jitted program
            self._jit_health = None
            if self._health_enabled:
                from deepspeed_tpu.telemetry.health import (
                    compute_group_health)
                self._jit_health = jax.jit(
                    lambda params, grads: compute_group_health(
                        params, grads, depth=self._health_depth))
        else:
            self._train_batch_fn = self._make_train_batch()
            self._jit_train_batch = jax.jit(
                self._train_batch_fn,
                donate_argnums=(0,),
                out_shardings=(self._as_shardings_tuple(), None, None))
            self._jit_apply = jax.jit(
                self._make_apply_fn(), donate_argnums=(0,),
                out_shardings=(self._as_shardings_tuple(), None, None))

    def configure_moq(self, sample_batch, layer_paths=None, *,
                      multiplier: int = 4, max_iter: int = 20,
                      tol: float = 1e-2) -> dict:
        """Mixture-of-Quantization (reference runtime/quantize.py +
        engine.py:334 _configure_eigenvalue): measure per-layer Hessian
        eigenvalues on ``sample_batch``, stretch each layer's staged-QDQ
        quantization period by 1 + floor(λ_norm·multiplier), and re-jit.

        Call once after ``initialize`` (and optionally again at curriculum
        boundaries).  Returns {layer_path: λ}.
        """
        if not self._compression_specs:
            raise ValueError(
                "configure_moq needs a compression_training block with "
                "weight_quantization groups (none configured)")
        from deepspeed_tpu.compression.moq import moq_adjusted_specs
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

        if layer_paths is None:
            # key listing needs only tree structure — no host transfer
            root = self.state.params
            prefix = ""
            for key in ("params", "backbone"):   # flax collection + GPT tree
                if isinstance(root, dict) and key in root:
                    prefix += key + "/"
                    root = root[key]
            layer_paths = sorted(
                f"{prefix}{k}" for k in root
                if isinstance(root[k], dict) and k.startswith("block_"))
            if not layer_paths:
                raise ValueError("no block_* layers found; pass layer_paths")

        rng = jax.random.PRNGKey(self.config.seed)

        def loss_fn(p):
            return self._apply_fn(p, sample_batch, rng)

        ev = Eigenvalue(max_iter=max_iter, tol=tol)
        with self.mesh:
            eigenvalues = ev.compute(loss_fn, self.state.params, layer_paths)
        self._compression_specs = moq_adjusted_specs(
            self._compression_specs, eigenvalues, multiplier=multiplier)
        self._build_step_functions()
        log_dist(f"MoQ: adjusted quantization periods for "
                 f"{len(eigenvalues)} layers "
                 f"(λ_norm={Eigenvalue.quantization_ratios(eigenvalues)})",
                 ranks=[0])
        return eigenvalues

    def _make_init(self):
        compute_dtype = self.compute_dtype
        cast_at_init = self.use_master_weights or self.offloading
        fp16_cfg = self.config.fp16
        init_fn = self._init_fn
        tx = None if self.offloading else self.optimizer

        def init(rng, batch):
            params = unbox(init_fn(rng, batch))
            if cast_at_init:
                params = _cast_params(params, compute_dtype)
            opt_state = tx.init(params) if tx is not None else ()
            return TrainState(
                step=jnp.int32(0),
                params=params,
                opt_state=opt_state,
                loss_scale=init_loss_scale(fp16_cfg),
                rng=jax.random.fold_in(rng, 1),
            )
        return init

    def _prepare_params(self, params, step):
        """Differentiable param-side half of the loss: compute-dtype cast,
        staged QDQ/pruning, then the composable pipeline gather
        (runtime/zero.pipeline_param_gather — chunked, optionally
        quantized, hierarchy-aware).  Split out of ``_loss`` so the qgZ
        path can run it (and, via ``jax.vjp``, its transposed chunked/
        quantized reduce-scatter) OUTSIDE the manual data-axis region —
        shard_maps cannot nest, and this split is what lets chunking ×
        quantization × the manual qgZ reduce compose."""
        if not self.use_master_weights:
            params = _cast_params(params, self.compute_dtype)
        if self._compression_specs and step is not None:
            # staged QAT (compression/basic.py; reference compression/
            # compress.py): matching weights see their scheduled quant grid
            from deepspeed_tpu.compression import scheduled_weight_qdq
            params = scheduled_weight_qdq(params, self._compression_specs,
                                          step)
        if self._pruning_specs and step is not None:
            from deepspeed_tpu.compression.pruning import scheduled_pruning
            params = scheduled_pruning(params, self._pruning_specs, step)
        if self._pipeline_active:
            # explicit per-layer-group gather replaces the partitioner's
            # per-consumer all-gathers; the autodiff transpose is the
            # chunked (and, under qgZ, quantized) grad reduce-scatter
            params = zero.pipeline_param_gather(
                params, self.param_shardings, self.mesh, self._wire_plan)
        return params

    def _loss(self, params, batch, rng, scale, step=None,
              deterministic=False, prepared=False):
        if not prepared:
            params = self._prepare_params(params, step)
        if self.pld is not None and step is not None:
            # theta is a pure function of the step — computed in-graph, so
            # PLD adds zero host↔device traffic (reference updates it on the
            # host each step, progressive_layer_drop.py update_state)
            batch = dict(batch, pld_theta=self.pld.theta_at(step))
        apply = self._apply_fn_det if deterministic else self._apply_fn
        loss = apply(params, batch, rng)
        return (loss * scale).astype(jnp.float32), loss

    def _loss_stats(self, params, batch, rng, scale, step=None):
        """``_loss`` with the ``moe_stats`` sow collection mutable — aux is
        ``(loss, stats)`` where stats aggregates the per-layer expert-load
        sows (moe/layer.py ``_sow_stats``) into one small dict that rides
        the step program as an extra output (the health pattern)."""
        from deepspeed_tpu.moe.layer import aggregate_moe_stats
        params = self._prepare_params(params, step)
        if self.pld is not None and step is not None:
            batch = dict(batch, pld_theta=self.pld.theta_at(step))
        loss, var = self._apply_fn_stats(params, batch, rng)
        stats = aggregate_moe_stats(var.get("moe_stats", {}))
        return (loss * scale).astype(jnp.float32), (loss, stats)

    def _grads_one_micro(self, state: TrainState, batch, idx):
        """One microbatch's (grads, loss, moe_stats) — moe_stats is {} off
        the expert-telemetry path (empty pytree, free under scan/jit)."""
        rng = jax.random.fold_in(state.rng, state.step * self.gas + idx)
        if self._qgz_axis is not None:
            grads, loss = self._qgz_grads(state, batch, rng)
            return grads, loss, {}
        if self._moe_stats_on:
            (_, (loss, moe)), grads = jax.value_and_grad(
                self._loss_stats, has_aux=True)(
                    state.params, batch, rng, state.loss_scale.scale,
                    state.step)
        else:
            (_, loss), grads = jax.value_and_grad(self._loss, has_aux=True)(
                state.params, batch, rng, state.loss_scale.scale, state.step)
            moe = {}
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        grads = jax.lax.with_sharding_constraint(
            grads, self.grad_shardings)
        return grads, loss, moe

    def _qgz_grads(self, state: TrainState, batch, rng):
        """qgZ grad computation, restructured as three composable stages
        (reference runtime/zero/stage3.py:1497 quantized gradient
        reduction; EQuARX, arXiv:2506.17615, for the allreduce form):

        1. **param pipeline** (outside any manual region): ``jax.vjp`` over
           ``_prepare_params`` — cast/QDQ/pruning plus, at stage 3, the
           chunked/quantized pipeline gather.  Its pullback, applied in
           stage 3b, is the chunked (and under qgZ quantized)
           reduce-scatter over fsdp.
        2. **per-replica grads** (partial-manual shard_map over the data
           axis, fsdp/tp auto): each replica computes grads on its own
           batch shard and emits them STACKED on a new leading axis — the
           region contains no manual-axis collectives beyond the loss
           pmean, which is what keeps it lowerable on every jax this
           package supports (utils/compat.shard_map legacy caveat).
        3. **quantized data-axis reduce** (full-manual
           runtime/zero.pipeline_grad_reduce): int codes + fp32 block
           scales on the wire — all-to-all reduce-scatter into partitioned
           layouts, EQuARX-style quantized allreduce for replicated
           leaves, plain psum for scalars — then (3b) the pipeline
           pullback maps the reduced cotangent to sharded-param space.
        """
        from deepspeed_tpu.utils.compat import shard_map
        from deepspeed_tpu.parallel.mesh import auto_axes_spec
        mesh, axis = self.mesh, self._qgz_axis
        size = mesh.shape[axis]

        # -- stage 1: param-side pipeline + its pullback, outside the
        #    manual region (shard_maps cannot nest)
        prepared, prep_vjp = jax.vjp(
            lambda p: self._prepare_params(p, state.step), state.params)

        def bspec(x):
            if getattr(x, "ndim", 0) < 1:
                return P()                       # scalars replicate
            if x.shape[0] % size:
                raise ValueError(
                    f"qgZ: batch leaf with shape {x.shape} has leading dim "
                    f"not divisible by mesh axis {axis}={size} — silently "
                    f"replicating it while other leaves split would pair "
                    f"mismatched rows across leaves; pad the batch so every "
                    f"leaf's leading dim divides the data-parallel size")
            return P(axis)
        bspecs = jax.tree_util.tree_map(bspec, batch)
        pspecs = jax.tree_util.tree_map(lambda _: P(), prepared)
        # stacked out_specs name ONLY the manual axis (legal on both
        # shard_map APIs); fsdp/tp layout rides the in-body anchor below +
        # the exit constraint
        stack_specs = jax.tree_util.tree_map(
            lambda g: P(axis, *([None] * getattr(g, "ndim", 0))), prepared)

        # in-body anchor (round-4 verdict item 4): each replica's cotangent
        # re-anchors to the AUTO part of its target layout inside the
        # region, so GSPMD emits the intra-replica reduce as a
        # reduce-scatter into that layout rather than an allreduce.  For
        # gathered (pipeline) leaves the anchor is the raw param sharding's
        # auto part (fsdp dims re-sharded for storage); otherwise the grad
        # sharding's.
        anchor_tree = (self.param_shardings if self._pipeline_active
                       else self.grad_shardings)
        auto_shardings = jax.tree_util.tree_map(
            lambda sh: NamedSharding(mesh, auto_axes_spec(sh.spec,
                                                          manual={axis})),
            anchor_tree)

        # -- stage 2: per-replica grads, stacked over the data axis
        def local(params, mb, rng, scale, step):
            # decorrelate dropout masks across data shards (the global-batch
            # path gets this for free from position-dependent masking)
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
            (_, loss), grads = jax.value_and_grad(
                self._loss, has_aux=True)(params, mb, rng, scale, step,
                                          prepared=True)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            grads = jax.lax.with_sharding_constraint(grads, auto_shardings)
            return (jax.tree_util.tree_map(lambda g: g[None], grads),
                    jax.lax.pmean(loss, axis))

        stacked, loss = shard_map(
            local, mesh=mesh, in_specs=(pspecs, bspecs, P(), P(), P()),
            out_specs=(stack_specs, P()), check_vma=False,
            axis_names={axis})(
                prepared, batch, rng, state.loss_scale.scale, state.step)

        # -- stage 3: quantized data-axis reduce of the stacks, then the
        #    pipeline pullback (chunked/quantized fsdp reduce-scatter).
        #    Reduce target: with the pipeline active the cotangents live in
        #    GATHERED space (fsdp dims dropped by the gather — the dp
        #    reduce is an allreduce there and the pullback re-scatters);
        #    without it they live in raw-param space and scatter straight
        #    into the ZeRO grad partitioning (the qgZ-axis dims of
        #    grad_shardings).
        from deepspeed_tpu.parallel.partition import spec_without_axis
        if self._pipeline_active:
            target = jax.tree_util.tree_map(
                lambda sh: NamedSharding(
                    mesh, spec_without_axis(sh.spec, "fsdp")),
                self.param_shardings)
        else:
            target = self.grad_shardings
        stacked = jax.lax.with_sharding_constraint(
            stacked, jax.tree_util.tree_map(
                lambda sh: NamedSharding(
                    mesh, P(axis, *spec_without_axis(sh.spec, axis))),
                target))
        reduced = zero.pipeline_grad_reduce(
            stacked, target, mesh, axis, self._dp_reduce_plan, mean=True)
        (grads,) = prep_vjp(jax.tree_util.tree_map(
            lambda g, p: g.astype(p.dtype), reduced, prepared))
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        grads = jax.lax.with_sharding_constraint(grads, self.grad_shardings)
        return grads, loss

    def _unscale(self, grads, scale, n_micro):
        # Note: gradient_predivide_factor is accepted for config parity but is a
        # no-op here — in the reference it pre-divides before allreduce and
        # post-multiplies after, netting out to the world-size average, which we
        # already get because loss is a global-batch mean computed on the global
        # jax.Array view (reduction order is XLA's concern, not ours).
        denom = scale * n_micro
        return jax.tree_util.tree_map(lambda g: g / denom, grads)

    def _apply_update(self, state: TrainState, grads
                      ) -> Tuple[TrainState, StepMetrics, dict]:
        finite = grads_finite(grads)
        new_ls = update_loss_scale(state.loss_scale, finite, self.config.fp16)
        # overflow steps surface the finite OVERFLOW_GNORM sentinel, not the
        # raw NaN/Inf norm; skipped_steps records the overflow and the health
        # stats (below) carry the per-group attribution
        grad_norm = jnp.where(finite, optax.global_norm(grads),
                              jnp.float32(OVERFLOW_GNORM))

        def do_step(operand):
            params, opt_state, grads = operand
            updates, new_opt = self.optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt

        def skip_step(operand):
            params, opt_state, _ = operand
            return params, opt_state

        new_params, new_opt = jax.lax.cond(
            finite, do_step, skip_step, (state.params, state.opt_state, grads))
        new_state = TrainState(
            # overflow-skipped steps do not advance the schedule clock (reference:
            # _take_model_step skips lr_scheduler.step() on overflow)
            step=state.step + jnp.where(finite, 1, 0).astype(jnp.int32),
            params=new_params,
            opt_state=new_opt,
            loss_scale=new_ls,
            rng=state.rng,
        )
        metrics = StepMetrics(
            loss=jnp.float32(0.0),  # filled by caller
            grad_norm=grad_norm,
            loss_scale=new_ls.scale,
            skipped_steps=new_ls.skipped,
        )
        # per-module-group numerics stats ride the step program as one extra
        # (tiny) output — same trace, no extra compile; {} when disabled
        health = {}
        if self._health_enabled:
            from deepspeed_tpu.telemetry.health import compute_group_health
            health = compute_group_health(state.params, grads,
                                          new_params=new_params,
                                          depth=self._health_depth)
        return new_state, metrics, health

    def _accumulate_grads(self, state: TrainState, batch):
        """Scan over gas microbatches accumulating fp32 grads — the ONE
        accumulation loop, shared by the fused train step and the offload
        grads program.  Returns (acc_grads, per-micro losses, per-micro
        moe stats — {} when expert telemetry is off).

        gas=1 bypasses the scan entirely: lax.scan lowers to a while loop
        whose carry is a SEPARATE fp32 accumulation buffer (4 bytes/param of
        peak HBM) that XLA cannot fold away — at billion-param scale that
        buffer is the difference between fitting and OOM."""
        if self.gas == 1:
            mb = jax.tree_util.tree_map(lambda x: x[0], batch)
            grads, loss, moe = self._grads_one_micro(state, mb, jnp.int32(0))
            return grads, loss[None], jax.tree_util.tree_map(
                lambda a: a[None], moe)

        def micro(carry, xs):
            idx, mb = xs
            grads, loss, moe = self._grads_one_micro(state, mb, idx)
            acc = jax.tree_util.tree_map(jnp.add, carry, grads)
            acc = jax.lax.with_sharding_constraint(acc, self.grad_shardings)
            return acc, (loss, moe)

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
        zeros = jax.lax.with_sharding_constraint(zeros, self.grad_shardings)
        acc, (losses, moes) = jax.lax.scan(
            micro, zeros, (jnp.arange(self.gas), batch))
        return acc, losses, moes

    def _make_train_batch(self):
        if self.gas_in_model:
            # pipeline path: the model's pipelined scan IS the microbatch loop;
            # one grad computation over the whole [gas, micro, ...] batch
            def train_batch_pipe(state: TrainState, batch):
                grads, loss, _ = self._grads_one_micro(state, batch, 0)
                grads = self._unscale(grads, state.loss_scale.scale, 1)
                new_state, metrics, health = self._apply_update(state, grads)
                return new_state, metrics._replace(
                    loss=loss.astype(jnp.float32)), health
            return train_batch_pipe

        def train_batch(state: TrainState, batch):
            # batch leaves: [gas, micro_global, ...]
            acc, losses, moes = self._accumulate_grads(state, batch)
            grads = self._unscale(acc, state.loss_scale.scale, self.gas)
            new_state, metrics, health = self._apply_update(state, grads)
            metrics = metrics._replace(loss=jnp.mean(losses).astype(jnp.float32))
            if moes:
                # expert-load stats ride the health dict under a reserved
                # key; popped host-side before health post-processing
                health = dict(health, __moe__=_reduce_moe_micros(moes))
            return new_state, metrics, health
        return train_batch

    def _make_grads_batch(self):
        """Offload-mode device program: accumulated scaled fp32 grads + mean
        loss + grad norm (of the scaled sum) + health stats.  No optimizer
        state touched — that's the host's job (runtime/offload.py)."""
        def health_of(state, grads):
            # grads here are still loss-scaled sums; the host step rescales
            # the norms (NaN/Inf counts are scale-invariant).  No
            # update_ratio on this path — the update happens host-side.
            if not self._health_enabled:
                return {}
            from deepspeed_tpu.telemetry.health import compute_group_health
            return compute_group_health(state.params, grads,
                                        depth=self._health_depth)

        if self.gas_in_model:
            def grads_pipe(state: TrainState, batch):
                grads, loss, _ = self._grads_one_micro(state, batch, 0)
                return (grads, loss.astype(jnp.float32),
                        optax.global_norm(grads), health_of(state, grads))
            return grads_pipe

        def grads_batch(state: TrainState, batch):
            acc, losses, moes = self._accumulate_grads(state, batch)
            health = health_of(state, acc)
            if moes:
                health = dict(health, __moe__=_reduce_moe_micros(moes))
            return (acc, jnp.mean(losses).astype(jnp.float32),
                    optax.global_norm(acc), health)
        return grads_batch

    def _train_batch_offload(self, batch):
        # dispatch FIRST: the device starts this step's grads against the
        # params currently on device — under overlap_step those are ONE
        # update stale (the previous host Adam may still be in flight) —
        # and only then join the previous overlapped host step, so the CPU
        # Adam of step N-1 hides behind step N's device grad computation
        # (reference ZeRO-Offload delayed parameter update)
        grads, loss, gnorm, health = self._jit_grads_batch(self.state, batch)
        if self._overlap_step:
            self._join_host_step()
        n_micro = 1 if self.gas_in_model else self.gas
        return self._host_step(grads, loss, gnorm, n_micro, health_dev=health,
                               overlap=self._overlap_step)

    def _join_host_step(self) -> None:
        """Install the params produced by the overlapped ZeRO-Offload host
        step (``offload_optimizer.overlap_step``); no-op when nothing is in
        flight.  A worker failure re-raises HERE — one train_batch late, but
        a lost optimizer update never looks like a completed one.  Every API
        that reads committed params (eval/checkpoint/export/trio) fences
        through this first."""
        w = self._host_worker
        if w is None or not w.busy:
            return
        t0 = time.perf_counter()
        new_params = w.join()
        blocked = time.perf_counter() - t0
        if new_params is not None:
            self.state = self.state._replace(params=new_params)
        work = w.last_work_s
        if self.telemetry.enabled and work > 0.0:
            # 1.0 = the whole host step hid behind device compute; 0.0 = the
            # join blocked for the full host-step duration (no overlap won)
            self.telemetry.registry.gauge(
                "host_step_overlap_ratio",
                "fraction of the overlapped ZeRO-Offload host optimizer "
                "step hidden behind device compute (1.0 = fully overlapped)"
            ).set(max(0.0, 1.0 - blocked / work))

    def _host_step(self, grads_dev, loss_dev, gnorm_dev, n_micro,
                   health_dev=None, overlap=False) -> StepMetrics:
        """The offloaded optimizer step: fetch grads, host Adam on the fp32
        masters (cpu/nvme tier), stream compute-dtype params back.  Loss-scale
        bookkeeping runs in plain Python (reference: _take_model_step +
        DeepSpeedCPUAdam.step on the offload path).

        ``overlap=True`` (train_batch under ``overlap_step``) runs the
        grads fetch + Adam + params device_put on the HostStepWorker instead
        of inline — identical math on identical inputs, so the off-path is
        bitwise-reproduced; only WHEN the new params land differs (at the
        next step's ``_join_host_step``).  The scalar bookkeeping (loss
        scale, clip coefficient, schedule clock) stays on this thread either
        way: it needs only gnorm, which the single fetch below already
        blocks on."""
        from deepspeed_tpu.runtime.precision import update_loss_scale_host
        state = self.state
        # one host fetch for every scalar this step reads (gnorm, loss, the
        # loss-scale state machine, the schedule clock, health stats) — the
        # per-scalar float(...) pattern cost a device round trip each
        gnorm_scaled, loss_host, ls_host, step_host, health_host = \
            jax.device_get((gnorm_dev, loss_dev, state.loss_scale,
                            state.step, health_dev))
        gnorm_scaled = float(gnorm_scaled)  # sync-ok: host value from the fetch above
        scale = float(ls_host.scale)        # sync-ok: host value from the fetch above
        denom = scale * n_micro
        finite = bool(np.isfinite(gnorm_scaled))
        # overflow: finite sentinel + skipped_steps, matching the device
        # path's _apply_update contract (was: raw NaN/Inf leaked into the
        # reported norm)
        raw_norm = gnorm_scaled / denom if finite else OVERFLOW_GNORM
        if finite:
            clip = float(self.config.gradient_clipping or 0.0)  # sync-ok: config scalar
            coef = 1.0
            if clip > 0.0 and raw_norm > clip:
                coef = clip / (raw_norm + 1e-6)
            # optax schedules see the update count (0-based), matching the
            # device path's optax scheduling.  No worker is in flight here
            # (callers join before _host_step), so reading step_count — which
            # only the worker mutates — is race-free.
            lr = (float(self.lr_schedule(self.offload_opt.step_count))  # sync-ok: host schedule math
                  if self.lr_schedule is not None
                  else float(self._opt_params.get("lr", 1e-3)))  # sync-ok: config scalar
            lr *= self._lr_scale          # guardian clamp-down (1.0 normally)

            def host_update(grad_scale=coef / denom, lr=lr):
                # the heavy half: grads fetch + host Adam over the fp32
                # masters + compute-dtype params upload.  Under overlap this
                # body runs on the HostStepWorker while the caller dispatches
                # the next device step — same math on the same inputs as the
                # inline path, so off/on differ only in WHEN params land.
                grads_np = jax.device_get(grads_dev)
                new_params_np = self.offload_opt.update(
                    grads_np, lr=lr, grad_scale=grad_scale)
                with self.mesh:
                    return jax.device_put(new_params_np,
                                          self.param_shardings)

            if overlap:
                self._host_worker.submit(host_update)
                # stale on purpose (ZeRO-Offload delayed parameter update):
                # the next step's grads run against these params; the fresh
                # ones install at that step's _join_host_step
                new_params = state.params
            else:
                new_params = host_update()
            new_step = jnp.int32(int(step_host) + 1)
        else:
            # overflow: nothing to overlap — the step is skipped entirely
            # (no Adam, no staleness), only the loss-scale machine advances
            new_params, new_step = state.params, state.step
        new_ls = update_loss_scale_host(ls_host, finite, self.config.fp16)
        self.state = TrainState(step=new_step, params=new_params,
                                opt_state=(), loss_scale=new_ls,
                                rng=state.rng)
        if health_host:
            # device program measured the loss-scaled grad sums — rescale
            # the norms to match the reported raw_norm (counts and param
            # norms are scale-free)
            from deepspeed_tpu.telemetry.health import to_python
            if "__moe__" in health_host:   # [E] vector: not per-group stats
                self._last_moe_host = _moe_stats_to_python(
                    health_host.pop("__moe__"))
            health_host = to_python(health_host)
            for stats in health_host.values():
                gn = stats.get("grad_norm")
                if gn is not None and np.isfinite(gn):
                    stats["grad_norm"] = gn / denom
        self._last_health = health_host or {}
        return StepMetrics(
            loss=jnp.float32(float(loss_host)),  # sync-ok: host value from the fetch above
            grad_norm=jnp.float32(raw_norm),
            loss_scale=new_ls.scale,
            skipped_steps=new_ls.skipped)

    def _make_grad_fn(self):
        def grad_fn(state: TrainState, batch, idx):
            grads, loss, _ = self._grads_one_micro(state, batch, idx)
            return grads, loss
        return grad_fn

    def _make_apply_fn(self):
        def apply_fn(state: TrainState, grads, n_micro):
            grads = self._unscale(grads, state.loss_scale.scale, n_micro)
            return self._apply_update(state, grads)
        return apply_fn

    # ------------------------------------------------------------------ data

    def _apply_data_efficiency(self, batch):
        """Host-side curriculum seqlen truncation + random-LTD keep-index
        injection on the FLAT batch (reference: data_pipeline hooks in
        deepspeed.initialize / DataEfficiency tutorial).  Shape changes re-key
        jit per difficulty/keep bucket — difficulty_step / seq_per_step bound
        the program count."""
        if self.curriculum_scheduler is None \
                and self.random_ltd_scheduler is None:
            return batch
        if not isinstance(batch, dict):
            return batch
        batch = dict(batch)
        # normalize the pre-shaped [gas, micro_local, ...] form to flat rows —
        # ltd index shapes and truncation work on [rows, T]; train_batch's
        # shape check reshapes back afterwards
        ids0 = np.asarray(batch["input_ids"])
        local_bs = self.config.train_batch_size // jax.process_count()
        if (ids0.ndim >= 3 and ids0.shape[0] == self.gas
                and ids0.shape[1] == local_bs // self.gas):
            batch = {k: np.asarray(v).reshape(
                (-1,) + np.asarray(v).shape[2:]) for k, v in batch.items()}
        step = self.global_steps
        if self.curriculum_scheduler is not None:
            from deepspeed_tpu.data_pipeline import truncate_to_difficulty
            diff = self.curriculum_scheduler.update_difficulty(step)
            dstep = self.curriculum_scheduler.schedule_config.get(
                "difficulty_step", 1)
            batch = truncate_to_difficulty(batch, diff, dstep)
        if self.random_ltd_scheduler is not None:
            from deepspeed_tpu.data_pipeline import random_ltd_block_indices
            ids = np.asarray(batch["input_ids"])
            rows, T = ids.shape[0], ids.shape[-1]
            keep = self.random_ltd_scheduler.get_value(step)
            # decorrelate drop patterns across hosts: each process samples
            # for its own local rows
            idx = random_ltd_block_indices(
                step, keep, rows, T, len(self._ltd_layer_ids),
                seed=self._de_seed + 31337 * jax.process_index())
            batch["random_ltd_idx"] = np.moveaxis(idx, 0, 1)
        return batch

    def _shard_batch(self, batch, leading_gas: bool = False):
        """Place a host batch onto the mesh: batch dim over (dp, fsdp); the
        sequence dim (dim 1 of each microbatch) over sp when Ulysses sequence
        parallelism is active.

        Multi-process: each host passes its PROCESS-LOCAL rows and the global
        batch is assembled via jax.make_array_from_process_local_data —
        no host ever holds (or ships) the whole global batch (reference: each
        rank's dataloader feeds its own local microbatches)."""
        sp = "sp" if self.mesh.shape["sp"] > 1 else None
        multiproc = jax.process_count() > 1

        def put(x):
            x = np.asarray(x)
            extra = x.ndim - 1 - (1 if leading_gas else 0)
            dims = [("dp", "fsdp")] + [None] * extra
            if sp and extra >= 1:
                dims[1] = sp
            if leading_gas:
                dims = [None] + dims
            sharding = NamedSharding(self.mesh, P(*dims))
            if multiproc:
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)
        return jax.tree_util.tree_map(put, batch)

    def _reshape_gas(self, batch):
        """[gas*micro_global, ...] → [gas, micro_global, ...]."""
        def r(x):
            x = np.asarray(x)
            return x.reshape((self.gas, x.shape[0] // self.gas) + x.shape[1:])
        return jax.tree_util.tree_map(r, batch)

    def _form_batch(self, batch):
        """Host-side half of batch preparation (no device traffic):
        data-efficiency transforms + normalization to the
        [gas, micro_local, ...] form; returns (batch, global tokens per
        optimizer step).  train_batch's ``batch_input`` phase, shared with
        ``prepare_batch`` so the prefetch worker forms batches identically."""
        batch = self._apply_data_efficiency(batch)
        first_shape = tuple(jax.tree_util.tree_leaves(batch)[0].shape)
        # multi-process: each host feeds its process-local slice of the
        # global batch (train_batch_size / process_count rows)
        local_bs = self.config.train_batch_size // jax.process_count()
        micro_local = local_bs // self.gas
        # disambiguate [gas, micro_local, ...] (pre-shaped) from the flat
        # [local_bs, ...] form by the SECOND dim too — when gas ==
        # local_bs the leading dim alone cannot tell them apart
        if (first_shape[0] == self.gas and len(first_shape) > 1
                and first_shape[1] == micro_local):
            pass                            # already [gas, micro_local, ...]
        elif first_shape[0] == local_bs:
            batch = self._reshape_gas(batch)
        else:
            raise ValueError(
                f"train_batch leading dims {first_shape[:2]} match "
                f"neither [gas={self.gas}, micro_local={micro_local}, "
                f"...] nor the flat process-local batch [{local_bs}, "
                f"...] (train_batch_size={self.config.train_batch_size} "
                f"/ {jax.process_count()} processes)")
        lead_shape = tuple(jax.tree_util.tree_leaves(batch)[0].shape)
        # [gas, micro_local, T, ...] → tokens per optimizer step (global)
        tokens = (int(np.prod(lead_shape[:3])) * jax.process_count()
                  if len(lead_shape) >= 3 else 0)
        return batch, tokens

    def prepare_batch(self, batch):
        """Form, shard, and ``device_put`` ONE host batch ahead of its step —
        the work of train_batch's ``batch_input`` + ``host_to_device``
        phases — returning a :class:`PreparedBatch` that ``train_batch``
        accepts directly.  This is the ``prepare_fn`` the prefetch worker
        runs (``prefetch_loader``); calling it inline is equivalent.

        Note: curriculum/random-LTD schedules read ``global_steps`` at
        PREPARE time, so under prefetch a difficulty change lands up to
        ``prefetch_depth`` steps late (bounded by the queue depth)."""
        from deepspeed_tpu.runtime.prefetch import PreparedBatch
        step = self.global_steps
        batch, tokens = self._form_batch(batch)
        batch = self._shard_batch(batch, leading_gas=True)
        return PreparedBatch(batch=batch, tokens=tokens, step_enqueued=step)

    def prefetch_loader(self, source, depth: Optional[int] = None):
        """Wrap an iterable of host batches in the background device-prefetch
        pipeline (runtime/prefetch.py): a worker thread keeps up to ``depth``
        batches formed/sharded/``device_put`` ahead of the step, so
        ``train_batch``'s ``host_to_device`` span collapses to a queue pop.
        ``depth`` defaults to ``data_pipeline.prefetch_depth``; 0 prepares
        each batch synchronously behind the same iterator surface.  Use as a
        context manager (or call ``.close()``) for clean worker shutdown."""
        from deepspeed_tpu.runtime.prefetch import (PrefetchIterator,
                                                    _InlinePrefetch)
        if depth is None:
            depth = int(self.config.data_pipeline.prefetch_depth)
        if depth <= 0:
            return _InlinePrefetch(source, self.prepare_batch)
        return PrefetchIterator(
            source, self.prepare_batch, depth=depth,
            registry=self.telemetry.registry if self.telemetry.enabled
            else None)

    # ------------------------------------------------------------------ API

    def train_batch(self, batch) -> StepMetrics:
        """One full optimizer step over ``gas`` microbatches.

        ``batch`` leaves are host arrays of global shape
        [gas × micro × dp_world, ...] (or already [gas, micro_global, ...]).
        Mirrors PipelineEngine.train_batch (runtime/pipe/engine.py:326) semantics
        for the non-pipelined engine.
        """
        from deepspeed_tpu.runtime.prefetch import PreparedBatch
        t0 = time.perf_counter()
        tel = self.telemetry
        step_id = self.global_steps + 1
        self.tput_timer.start()
        if isinstance(batch, PreparedBatch):
            # the prefetch worker already formed/sharded/device_put this
            # batch while the previous step ran (runtime/prefetch.py) — both
            # input phases collapse to an unwrap
            self.timers(DATA_TIMER).start()
            with tel.span("host_to_device", step=step_id, prefetched=True):
                batch, tokens = batch.batch, batch.tokens
            self.timers(DATA_TIMER).stop()
        else:
            with tel.span("batch_input", step=step_id):
                batch, tokens = self._form_batch(batch)
            self.timers(DATA_TIMER).start()
            with tel.span("host_to_device", step=step_id):
                batch = self._shard_batch(batch, leading_gas=True)
            self.timers(DATA_TIMER).stop()
        fp = self.config.flops_profiler
        profile_pending = (fp.enabled and not self._flops_profiled
                           and self.global_steps + 1 >= fp.profile_step)
        if profile_pending:
            self._last_batch = batch  # traced by the flops profiler, then freed
        # chaos: ``nan@step.grads`` forces this step's gradient computation
        # non-finite (see _poison_first_float_leaf) — the signal the
        # guardian's rollback remediation is chaos-verified against
        if faults.fire("step.grads", step=step_id) == "nan":
            self.state = self.state._replace(
                params=_poison_first_float_leaf(self.state.params))
        self.timers(TRAIN_BATCH_TIMER).start()
        with self.mesh:
            if tel.enabled:
                # recompile watchdog + (on a signature miss) compiled-HLO
                # collective bytes / cost / memory figures
                jfn = (self._jit_grads_batch if self.offloading
                       else self._jit_train_batch)
                tel.before_dispatch(
                    "train_batch", batch, step_id,
                    lower=lambda: jfn.lower(self.state, batch))
            with tel.span("dispatch", step=step_id):
                # chaos: ``sleep@step.dispatch`` models a hung collective /
                # straggler stall — the guardian watchdog's deadline target
                faults.fire("step.dispatch", step=step_id)
                if self.offloading:
                    # sets _last_health (host dict) itself
                    metrics = self._train_batch_offload(batch)
                else:
                    self.state, metrics, health = self._jit_train_batch(
                        self.state, batch)
                    self._last_health = health
        with tel.span("device_complete", step=step_id):
            if (tel.tracer.enabled or self.wall_clock_breakdown
                    or profile_pending):
                # synchronize so the timer covers device execution, not just
                # dispatch (axon: fetching a value is the only reliable sync)
                jax.device_get(metrics.loss)
        self.timers(TRAIN_BATCH_TIMER).stop()
        self.global_steps += 1
        self._last_metrics = metrics
        self._step_times.append(time.perf_counter() - t0)
        self.tput_timer.stop(int(self.config.train_batch_size), tokens)
        with tel.span("step_bookkeeping", step=step_id):
            self._post_step_reporting(metrics)
        tel.end_step(self.global_steps,
                     samples=self.global_steps
                     * int(self.config.train_batch_size),
                     tokens=tokens)
        return metrics

    def eval_batch(self, batch):
        """Deterministic evaluation loss on one global batch — no grads, no
        state mutation (reference PipelineEngine.eval_batch
        pipe/engine.py:415; plain-engine eval = module.eval() + forward).

        Weight-side semantics match training exactly (master-weight cast,
        staged QDQ at the CURRENT step, qwZ gather).  Dropout/PLD/random-LTD
        are off for models exposing a deterministic leg (a flax module with a
        ``deterministic`` flag, or an apply_fn treating ``rng=None`` as
        eval); other models run their training-mode forward with the current
        state rng.  Returns the scalar loss as a float32 jax array.
        """
        self._join_host_step()     # eval on committed params, never stale
        # no leading gas dim: pipeline models treat a flat [B, T] batch as a
        # single microbatch (pipe/module.py _3d)
        batch = self._shard_batch(batch)
        if self._jit_eval is None:
            def eval_fn(state, batch):
                _, loss = self._loss(state.params, batch, state.rng,
                                     jnp.float32(1.0), state.step,
                                     deterministic=True)
                return loss.astype(jnp.float32)
            self._jit_eval = jax.jit(eval_fn)
        if self.telemetry.enabled:
            # watchdog only (no HLO analysis: eval is off the hot path and
            # an AOT compile per eval shape isn't worth the figures)
            self.telemetry.before_dispatch("eval_batch", batch,
                                           self.global_steps)
        with self.mesh:
            return self._jit_eval(self.state, batch)

    def forward(self, batch):
        """Compatibility trio part 1 (reference engine.forward engine.py:1785):
        computes loss *and* grads for one microbatch, accumulating grads."""
        if self.gas_in_model:
            # parity: the reference PipelineEngine also only supports
            # train_batch/eval_batch (pipe/engine.py:56 "only via train_batch")
            raise RuntimeError(
                "pipeline models only support train_batch(), not the "
                "forward/backward/step trio")
        self._join_host_step()     # mixing trio + train_batch: fence first
        batch = self._apply_data_efficiency(batch)
        batch = self._shard_batch(batch)
        with self.mesh:
            grads, loss = self._jit_grad(self.state, batch,
                                         jnp.int32(self._micro_steps))
        if self._accum_grads is None:
            self._accum_grads = grads
        else:
            self._accum_grads = jax.tree_util.tree_map(
                jnp.add, self._accum_grads, grads)
        self._micro_losses.append(loss)
        self._micro_steps += 1
        return loss

    def backward(self, loss=None):
        """Grads were produced in forward() (JAX has no separate backward pass
        to intercept); kept for API parity (reference engine.py:1924)."""
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        return self._micro_steps % self.gas == 0

    def step(self):
        """Apply the accumulated update at the gradient-accumulation boundary
        (reference engine.step engine.py:2123)."""
        if not self.is_gradient_accumulation_boundary():
            return None
        assert self._accum_grads is not None, "call forward() before step()"
        # the trio's host step runs inline (overlap is a train_batch-loop
        # optimization); a stray overlapped step must land before the
        # masters are touched again
        self._join_host_step()
        # one fetch for all micro losses (was a float() sync per microbatch)
        mean_loss = jnp.float32(np.mean(jax.device_get(self._micro_losses)))
        if self.offloading:
            with self.mesh:
                gnorm = self._jit_gnorm(self._accum_grads)
                health = (self._jit_health(self.state.params,
                                           self._accum_grads)
                          if self._jit_health is not None else None)
            metrics = self._host_step(self._accum_grads, mean_loss, gnorm,
                                      self.gas, health_dev=health)
        else:
            with self.mesh:
                self.state, metrics, health = self._jit_apply(
                    self.state, self._accum_grads, jnp.float32(self.gas))
            self._last_health = health
            metrics = metrics._replace(loss=mean_loss)
        self._accum_grads = None
        self._micro_losses = []
        self._micro_steps = 0
        self.global_steps += 1
        self._last_metrics = metrics
        self._post_step_reporting(metrics)
        return metrics

    def hybrid_engine(self, inference_config=None):
        """Train↔generate bridge for RLHF (runtime/hybrid_engine.py;
        reference DeepSpeedHybridEngine).  Built lazily, cached — enable via
        the ``hybrid_engine`` config block or call directly."""
        if getattr(self, "_hybrid", None) is None:
            from deepspeed_tpu.runtime.hybrid_engine import HybridEngine
            self._hybrid = HybridEngine(self, inference_config)
            self._hybrid_cfg = inference_config
        elif (inference_config is not None
              and inference_config != self._hybrid_cfg):
            raise ValueError(
                "hybrid_engine() was already built with a different "
                "inference_config; build a HybridEngine directly for a "
                "second configuration")
        return self._hybrid

    # ------------------------------------------------------------------ info

    @property
    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    @property
    def train_batch_size(self):
        return self.config.train_batch_size

    def get_lr(self):
        if self.lr_schedule is None:
            return [float(self._opt_params.get("lr", 0.0)) * self._lr_scale]
        host = self._last_metrics_host
        if host is not None and self._host_metrics_step == self.global_steps:
            # state.step mirror without a device sync: overflow-skipped
            # steps do not advance the schedule clock
            step = self.global_steps - host.skipped_steps
        else:
            step = int(self.state.step)  # sync-ok: cold path, no cached copy
        return [float(self.lr_schedule(step)) * self._lr_scale]

    def _fetch_metrics(self, metrics: StepMetrics,
                       health=None) -> StepMetrics:
        """THE sanctioned device→host fetch point for step scalars: ONE
        ``jax.device_get`` moves the whole StepMetrics (+ the small health
        pytree) and the host copy is cached for every later reader —
        ``get_global_grad_norm()``, ``skipped_steps``, prints, monitors,
        the flight recorder.  scripts/check_no_sync.py enforces that the
        step path performs host syncs only here (or via an explicit
        ``device_get`` / ``# sync-ok`` disclosure)."""
        from deepspeed_tpu.telemetry.health import to_python
        vals, health_host = jax.device_get((tuple(metrics), health))
        host = StepMetrics(loss=float(vals[0]), grad_norm=float(vals[1]),
                           loss_scale=float(vals[2]),
                           skipped_steps=int(vals[3]))
        self._last_metrics_host = host
        # expert-load stats ride the health pytree under a reserved key but
        # are NOT per-group numerics (expert_tokens is an [E] vector, which
        # to_python's float() would reject) — split them off first
        if isinstance(health_host, dict) and "__moe__" in health_host:
            self._last_moe_host = _moe_stats_to_python(
                health_host.pop("__moe__"))
        self._last_health_host = to_python(health_host)
        self._host_metrics_step = self.global_steps
        return host

    def _reset_host_metrics_cache(self) -> None:
        """Drop the cached host metrics — checkpoint loads rewind
        global_steps, which could otherwise alias a stale cache entry."""
        self._last_metrics = None
        self._last_metrics_host = None
        self._last_health = None
        self._last_health_host = {}
        self._host_metrics_step = -1
        self.telemetry.reset_numerics_baseline()

    def _host_metrics(self) -> Optional[StepMetrics]:
        """Cached host StepMetrics for the latest step (fetching once if a
        reader arrives before the reporting path did)."""
        if self._last_metrics is None:
            return None
        if (self._last_metrics_host is None
                or self._host_metrics_step != self.global_steps):
            self._fetch_metrics(self._last_metrics, self._last_health)
        return self._last_metrics_host

    def get_global_grad_norm(self):
        host = self._host_metrics()
        return None if host is None else host.grad_norm

    @property
    def skipped_steps(self):
        host = self._host_metrics()
        return 0 if host is None else host.skipped_steps

    def dump_postmortem(self, note: Optional[str] = None):
        """Explicitly dump the flight-recorder buffer as a postmortem bundle
        (requires ``telemetry.health.enabled``); returns the bundle dir."""
        return self.telemetry.dump_postmortem(note=note)

    def _maybe_print(self, host: StepMetrics):
        spp = self.config.steps_per_print
        if spp and self.global_steps % spp == 0:
            log_dist(
                f"step={self.global_steps} loss={host.loss:.4f} "
                f"lr={self.get_lr()[0]:.3e} "
                f"grad_norm={host.grad_norm:.3f} "
                f"loss_scale={host.loss_scale:.0f}", ranks=[0])

    def _post_step_reporting(self, metrics: StepMetrics):
        """Console print + monitor fan-out + flight recorder + timer log +
        flops profile, at their configured cadences (reference
        engine.py:2264 _write_monitor, :1797 flops profiler hook, :145
        EngineTimers).  All host reads go through the single
        ``_fetch_metrics`` fetch; steps where nothing reports skip the
        device sync entirely."""
        if self.pld is not None:
            # keep the host mirror in sync with the in-graph schedule so
            # get_theta()/get_state() report the effective value; the theta
            # applied THIS step was computed from the pre-increment state.step
            self.pld.update_state(self.global_steps - 1)
        spp = self.config.steps_per_print
        at_cadence = spp and self.global_steps % spp == 0
        # monitors write even when console printing is off (steps_per_print=0
        # means every step, matching the reference's monitor-independent
        # cadence; costs one device sync per write)
        monitor_cadence = at_cadence or (not spp and self.monitor.enabled)
        need_host = bool(at_cadence or (self.monitor.enabled
                                        and monitor_cadence)
                         or self._health_enabled or self._moe_stats_on)
        host = (self._fetch_metrics(metrics, self._last_health)
                if need_host else None)
        if host is not None and at_cadence:
            self._maybe_print(host)
        samples = self.global_steps * int(self.config.train_batch_size)
        if self.monitor.enabled and monitor_cadence and host is not None:
            # x-axis is samples seen, matching the reference's
            # Train/Samples/* convention (engine.py:2272)
            events = [
                ("Train/Samples/train_loss", host.loss, samples),
                ("Train/Samples/lr", self.get_lr()[0], samples),
                ("Train/Samples/grad_norm", host.grad_norm, samples),
                ("Train/Samples/loss_scale", host.loss_scale, samples),
            ]
            if self.tput_timer.avg_samples_per_sec:
                events.append(("Train/Samples/throughput_samples_per_sec",
                               self.tput_timer.avg_samples_per_sec, samples))
            if self.tput_timer.avg_tokens_per_sec:
                events.append(("Train/Samples/throughput_tokens_per_sec",
                               self.tput_timer.avg_tokens_per_sec, samples))
            self.monitor.write_events(events)
        if self._health_enabled and host is not None:
            # anomaly rules + ring buffer + dump triggers (nonfinite loss,
            # overflow streak) — telemetry/health.py, flight_recorder.py
            self.telemetry.health_step(
                self.global_steps, host, self._last_health_host,
                lr=self.get_lr()[0], samples=samples)
        if self._moe_stats_on and host is not None \
                and self._last_moe_host is not None:
            # per-expert load gauges + drop counters (telemetry registry) —
            # reads only the host copy fetched above, no device sync
            self.telemetry.moe_step(self._last_moe_host)
        if self.wall_clock_breakdown and at_cadence:
            self.timers.log([DATA_TIMER, TRAIN_BATCH_TIMER], normalizer=spp)
        fp = self.config.flops_profiler
        if (fp.enabled and not self._flops_profiled
                and self.global_steps >= fp.profile_step):
            self._flops_profiled = True
            self._print_flops_profile()
        if self.config.memory_breakdown and self.global_steps == 1:
            self._print_memory_breakdown()

    def _print_flops_profile(self):
        from deepspeed_tpu.profiling import FlopsProfiler
        if self._last_batch is None:
            logger.warning(
                "flops profiler: no traced batch available — the profiler "
                "supports the train_batch() API only, not the "
                "forward/backward/step trio")
            return
        fp = self.config.flops_profiler
        prof = FlopsProfiler(fp)
        try:
            prof.count(self._train_batch_fn, self.state, self._last_batch)
        except Exception as e:  # profiling must never kill training
            logger.warning(f"flops profiler failed to trace the step: {e!r}")
            return
        finally:
            self._last_batch = None  # free the pinned device batch
        # _step_times[-1] was synchronized (profile_pending forced a fetch)
        prof.latency = self._step_times[-1] if self._step_times else 0.0
        self.telemetry.record_flops(prof.as_metrics())
        prof.print_model_profile(params=self.state.params,
                                 module_depth=fp.module_depth,
                                 top_modules=fp.top_modules,
                                 detailed=fp.detailed,
                                 output_file=fp.output_file)

    def profile_comms(self, batch, iters: int = 2):
        """Measure the jitted train step's per-collective bytes + latency
        (comm.profile_jitted) and record them into the comms logger —
        ``comm.comms_logger.log_summary()`` then shows algo-BW for the
        jitted collectives (reference calc_bw_log role under XLA).

        Functional state is NOT mutated (the step runs on a copy of the
        inputs through an undonated jit)."""
        from deepspeed_tpu.comm.comm import profile_jitted
        self._join_host_step()
        batch = self._apply_data_efficiency(batch)
        first = tuple(jax.tree_util.tree_leaves(batch)[0].shape)
        local_bs = self.config.train_batch_size // jax.process_count()
        micro_local = local_bs // self.gas
        # same batch-form disambiguation as train_batch (incl. the
        # gas == local_bs ambiguity resolved by the SECOND dim)
        if (first[0] == self.gas and len(first) > 1
                and first[1] == micro_local):
            pass                            # already [gas, micro_local, ...]
        elif first[0] == local_bs:
            batch = self._reshape_gas(batch)
        else:
            raise ValueError(
                f"profile_comms batch leading dims {first[:2]} match "
                f"neither [gas={self.gas}, micro_local={micro_local}, ...] "
                f"nor the flat [{local_bs}, ...] form")
        batch = self._shard_batch(batch, leading_gas=True)
        with self.mesh:
            return profile_jitted(jax.jit(self._train_batch_fn),
                                  self.state, batch, iters=iters)

    def _print_memory_breakdown(self):
        """reference: see_memory_usage / memory_breakdown config."""
        from deepspeed_tpu.utils.memory import collect_memory_stats
        lines = []
        for d, stats in zip(jax.local_devices(),
                            collect_memory_stats()["devices"]):
            if stats:
                used = stats.get("bytes_in_use", 0) / 2**30
                limit = stats.get("bytes_limit", 0) / 2**30
                peak = stats.get("peak_bytes_in_use", 0) / 2**30
                lines.append(f"  {d}: in_use={used:.2f}GiB "
                             f"peak={peak:.2f}GiB limit={limit:.2f}GiB")
        if lines:
            log_dist("device memory breakdown:\n" + "\n".join(lines),
                     ranks=[0])

    # ------------------------------------------------------------------ ckpt

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[dict] = None,
                        async_save: bool = False):
        """reference engine.save_checkpoint (engine.py:3056): sharded save via
        orbax; every process participates (global-view jax.Arrays).
        ``async_save=True`` returns once device arrays are snapshotted
        (``checkpoint_snapshot`` span, blocking and short) and streams the
        serialize/write in the background (``checkpoint_write`` span,
        recorded at commit); an in-progress marker + commit-ordered 'latest'
        keep a crash mid-write from ever orphaning the previous checkpoint.
        Call ``engine.wait_for_checkpoint()`` before exiting (the checkpoint
        module also fences atexit)."""
        import os

        from deepspeed_tpu.checkpoint import save_train_state
        self._join_host_step()   # only committed params reach the snapshot
        self.wait_for_checkpoint()   # previous save commits (and zeroes the
        #                              backlog gauge) before this one starts
        tag = tag or f"global_step{self.global_steps}"
        tel = self.telemetry
        step = self.global_steps
        pre_commit = None
        if self.offloading and jax.process_index() == 0:
            # host-resident masters/moments ride alongside the orbax tree
            # (reference: _save_zero_checkpoint per-rank optimizer shards),
            # streamed as npz on the waiter thread, pre-commit: it lands
            # inside the in-progress window, before 'latest' can move,
            # without blocking the dispatch thread.  Only an async save
            # snapshots a COPY (state_dict returns live views the next host
            # step mutates; a blocking save writes before anything can, and
            # the copy would transiently double the optimizer-state
            # footprint on exactly the host-RAM-bound runs that offload)
            sd = self.offload_opt.state_dict()
            if async_save:
                sd = {k: (np.copy(v) if isinstance(v, np.ndarray) else v)
                      for k, v in sd.items()}
            npz_path = os.path.join(save_dir, tag, "offload_state.npz")

            def pre_commit(_sd=sd, _path=npz_path):
                np.savez(_path, **_sd)
        backlog = (tel.registry.gauge(
            "checkpoint_write_backlog",
            "async checkpoint writes still streaming in the background")
            if tel.enabled else None)

        def on_commit(write_s, _tag=tag, _step=step):
            # runs on the waiter thread for async saves, inline for blocking
            # ones — tracer.record/gauge.set are thread-safe appends
            if backlog is not None:
                backlog.set(0)
            if tel.tracer.enabled:
                end = tel.tracer.now_us()
                tel.tracer.record("checkpoint_write", end - write_s * 1e6,
                                  write_s * 1e6, step=_step, tag=_tag,
                                  op="save")

        if backlog is not None and async_save:
            backlog.set(1)
        from deepspeed_tpu.checkpoint import reshard
        with tel.span("checkpoint_snapshot", step=step, tag=tag, op="save"):
            save_train_state(save_dir, tag, self.state,
                             client_state=dict(
                                 client_state or {},
                                 global_steps=self.global_steps,
                                 # physical layout descriptor: a different
                                 # topology restoring this tag keys its
                                 # resharding transform on it
                                 layout=reshard.engine_layout(self)),
                             block=not async_save, on_commit=on_commit,
                             pre_commit=pre_commit)
        if self.telemetry.enabled and self.telemetry.snapshot_interval:
            # flush so the checkpoint_io span reaches the trace file even
            # when no further step follows (end-of-run checkpoints); same
            # samples x-axis as end_step so monitor series stay monotonic
            self.telemetry.export(
                step=self.global_steps,
                samples=self.global_steps * int(self.config.train_batch_size))
        return tag

    def drain(self, run_dir: str, *, reason: str = "preemption",
              out_dir: Optional[str] = None) -> Optional[str]:
        """Graceful drain on a preemption notice (runtime/resilience.py):
        fence the overlapped host step and any in-flight async checkpoint,
        commit a final universal export (+ executable fingerprints) under
        ``run_dir``, and record ``preemptions_total{reason}`` + the
        ``drain`` span.  Call from the step loop after
        ``PreemptionHandler.requested`` turns true; then exit with
        ``resilience.EXIT_DRAINED``."""
        from deepspeed_tpu.runtime import resilience
        return resilience.drain(self, run_dir, reason=reason,
                                out_dir=out_dir)

    def clamp_lr(self, factor: float) -> float:
        """Multiply the effective learning rate by ``factor`` from now on —
        the guardian's escalated-retry clamp-down.  On the device paths the
        LR is traced into the compiled update, so this rebuilds the
        optimizer chain and re-jits the step programs (one recompile; the
        recompile watchdog is invalidated so it doesn't warn).  The offload
        host step reads the scale directly — no recompile.  Returns the
        cumulative scale.  Refuses under a client optimizer: the engine
        cannot rebuild a chain it did not build."""
        if not 0 < factor <= 1:
            raise ValueError(f"clamp_lr factor must be in (0, 1], "
                             f"got {factor}")
        if self._client_optimizer is not None:
            raise ValueError(
                "clamp_lr cannot rebuild a client optimizer chain; clamp "
                "the LR inside your own optimizer/schedule instead")
        self._lr_scale *= float(factor)
        if not self.offloading:
            self.optimizer, self._opt_params = self._build_tx(None)
            self._build_step_functions()
        logger.warning(f"guardian: learning rate clamped x{factor:g} "
                       f"(cumulative scale {self._lr_scale:g})")
        return self._lr_scale

    def clamp_loss_scale(self, factor: float) -> None:
        """Scale the dynamic loss scale DOWN by ``factor`` (floored at
        ``fp16.min_loss_scale``) — a data-only state edit, no recompile.
        No-op outside dynamic fp16 scaling (bf16/fp32 run at the frozen
        unit scale)."""
        if not 0 < factor <= 1:
            raise ValueError(f"clamp_loss_scale factor must be in (0, 1], "
                             f"got {factor}")
        cfg = self.config.fp16
        if not cfg.enabled or cfg.loss_scale > 0:
            return
        ls = self.state.loss_scale
        new_scale = jnp.maximum(ls.scale * jnp.float32(factor),
                                jnp.float32(cfg.min_loss_scale))
        self.state = self.state._replace(
            loss_scale=ls._replace(scale=new_scale))

    def guardian(self, run_dir: str, *, batch_fn=None, cursor=None,
                 handler=None, config=None, **kwargs):
        """Build the self-healing control loop over this engine
        (runtime/guardian.py Guardian): guarded checkpoint ring, anomaly →
        rollback/skip/clamp remediation, hang watchdog.  ``batch_fn(i)``
        must be a pure (seed-stable) host-batch factory; alternatively pass
        a prepared ``DataCursor``.  Reads the ``guardian`` config block
        unless ``config`` overrides it."""
        from deepspeed_tpu.runtime.guardian import Guardian
        return Guardian(self, run_dir, batch_fn=batch_fn, cursor=cursor,
                        handler=handler, config=config, **kwargs)

    def resume_from_latest(self, run_dir: str,
                           warmup: Optional[bool] = None) -> Optional[str]:
        """Resume from the newest COMPLETE universal export under
        ``run_dir`` (``checkpoint.latest_universal``), AOT-warming the step
        programs from the drained host's fingerprints when
        ``resilience.aot_warmup`` is on.  Returns the export path, or None
        on a cold start.  Records ``restarts_total``, the
        ``time_to_resume_ms`` histogram, and the ``resume`` span."""
        from deepspeed_tpu.runtime import resilience
        return resilience.resume(self, run_dir, warmup=warmup)

    def wait_for_checkpoint(self) -> None:
        """Fence for the async checkpoint pipeline: block until any
        in-flight background write fully commits ('latest' moved, the
        in-progress marker removed), re-raising a failed write — a lost
        checkpoint must not look like a successful one.  Also registered
        atexit by the checkpoint module, so a forgotten fence degrades to a
        slow exit, not a torn checkpoint."""
        from deepspeed_tpu.checkpoint import wait_pending
        wait_pending()

    def save_16bit_model(self, save_dir: str,
                         filename: str = "model_states.safetensors") -> str:
        """Consolidated low-precision weight export (reference
        engine.save_16bit_model / _zero3_consolidated_16bit_state_dict
        engine.py:3485,3554): the FULL (unsharded) param tree in the compute
        dtype, one safetensors file with dotted names — loadable without this
        framework.  For HF-architecture models prefer
        checkpoint.hf.save_hf_checkpoint (adds config.json)."""
        import os as _os

        from deepspeed_tpu.checkpoint.universal import _flatten_params
        self._join_host_step()
        _os.makedirs(save_dir, exist_ok=True)
        params = jax.device_get(self.state.params)   # gathers sharded leaves
        flat = {k: np.asarray(v).astype(self.compute_dtype)
                if np.asarray(v).dtype.kind == "f"
                or np.asarray(v).dtype == jnp.bfloat16 else np.asarray(v)
                for k, v in _flatten_params(params).items()}
        path = _os.path.join(save_dir, filename)
        if jax.process_index() == 0:
            import safetensors.numpy
            safetensors.numpy.save_file(flat, path)
        return path

    def export_universal_checkpoint(self, out_dir: str, *,
                                    run_dir: Optional[str] = None) -> str:
        """reference checkpoint/ds_to_universal.py: dump per-parameter fp32
        fragments (+ Adam moments) in a framework-neutral LOGICAL layout any
        topology or toolchain can ingest (pipeline-stacked leaves are
        unstacked to per-layer fragments — checkpoint/reshard.py).  Written
        under the crash-safe commit protocol; ``run_dir`` additionally moves
        the ``latest_universal`` pointer post-commit so elastic workers find
        this export via ``checkpoint.latest_universal(run_dir)``."""
        from deepspeed_tpu.checkpoint import reshard
        from deepspeed_tpu.checkpoint import universal as _u
        self._join_host_step()
        layout = reshard.engine_layout(self)
        if self.offloading:
            return _u.export_universal_offload(
                jax.device_get(self.state.params), self.offload_opt, out_dir,
                step=self.global_steps, layout=layout, run_dir=run_dir)
        # step = global_steps (train_batch count), not state.step: an
        # overflow-skipped update leaves state.step behind, and the resume
        # contract (loss logs, TOTAL_STEPS loops) counts batches
        return _u.export_universal(jax.device_get(self.state), out_dir,
                                   step=self.global_steps, layout=layout,
                                   run_dir=run_dir)

    def _install_fragments(self, frags, step: int, *,
                           strict: bool = True) -> None:
        """Install TARGET-layout fragments into this engine's params /
        masters / Adam moments and re-place them onto the mesh (the
        device_put against ``state_shardings`` IS the resharding: any
        dp/fsdp/pp/tp placement follows from the specs alone)."""
        from deepspeed_tpu.checkpoint.universal import (
            apply_universal, offload_state_dict_from_fragments)
        host = jax.device_get(self.state)
        new = apply_universal(host, frags, strict=strict, step=step)
        new = new._replace(step=jnp.asarray(step, np.asarray(host.step).dtype))
        self.state = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), new, self.state_shardings)
        self.global_steps = step
        self._reset_host_metrics_cache()
        if self.offloading:
            sd = offload_state_dict_from_fragments(host.params, frags, step)
            if len(sd) > 1:
                self.offload_opt.load_state_dict(sd)

    def load_universal_checkpoint(self, universal_dir: str, *,
                                  strict: bool = True) -> dict:
        """reference checkpoint/universal_checkpoint.py
        load_hp_checkpoint_state: install fp32 fragments into this engine's
        params / masters / Adam moments regardless of the mesh, ZeRO stage,
        physical layout (pipeline stage-stacking included), or framework
        that produced them (torch ``fp32.pt`` fragments load too)."""
        from deepspeed_tpu.checkpoint import reshard
        from deepspeed_tpu.checkpoint.universal import load_universal
        self._join_host_step()   # an in-flight update must not overwrite
        frags, meta = load_universal(universal_dir)
        frags = reshard.relayout(frags, meta.get("layout"),
                                 reshard.engine_layout(self))
        step = meta.get("step")
        if step is None:
            step = int(np.asarray(jax.device_get(self.state.step)))
        self._install_fragments(frags, int(step), strict=strict)
        return meta

    def _load_cross_topology(self, load_dir: str, tag: str, cause) -> dict:
        """Resharding-restore fallback for load_checkpoint: when the saved
        pytree STRUCTURE does not match this engine (different physical
        layout — e.g. a plain dp/fsdp checkpoint restoring into a
        pipeline-stacked engine — or a different optimizer-state shape
        across ZeRO stages), reduce the tag to LOGICAL universal fragments
        and re-lay them out for this engine (checkpoint/reshard.py; per
        arXiv:2004.13336 this is a sharding-spec transform, not a
        checkpoint-format special case)."""
        import json as _json
        import os

        from deepspeed_tpu.checkpoint import reshard
        cs_path = os.path.join(load_dir, tag, "client_state.json")
        client_state = {}
        if os.path.exists(cs_path):
            with open(cs_path) as f:
                client_state = _json.load(f)
        log_dist(f"load_checkpoint: structured restore of '{tag}' does not "
                 f"match this engine ({cause}); falling back to the "
                 f"cross-topology resharding restore", ranks=[0])
        frags = reshard.fragments_from_orbax(load_dir, tag)
        frags = reshard.relayout(frags, client_state.get("layout"),
                                 reshard.engine_layout(self))
        self._install_fragments(frags, int(client_state.get(
            "global_steps", 0)))
        return client_state

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None):
        """reference engine.load_checkpoint (engine.py:2710).  Mesh
        resharding on load comes free from named shardings; a STRUCTURAL
        mismatch (pipeline stacking, cross-stage optimizer shape) falls
        back to the logical-fragment resharding transform
        (_load_cross_topology).  Raises ``checkpoint.CheckpointNotFound`` /
        ``checkpoint.CheckpointCorrupt`` instead of backend-dependent
        errors."""
        from deepspeed_tpu.checkpoint import (latest_tag,
                                              restore_train_state,
                                              wait_pending)
        self._join_host_step()   # an in-flight update must not overwrite
        # surface a failed async write NOW: a lost checkpoint must never be
        # misread as a layout mismatch by the fallback below
        wait_pending()
        tag = tag or latest_tag(load_dir)
        if tag is None:
            return None, {}
        structured = True
        with self.telemetry.span("checkpoint_io", step=self.global_steps,
                                 tag=tag, op="load"):
            try:
                self.state, client_state = restore_train_state(
                    load_dir, tag, self.state_shardings, self.state)
            except (ValueError, TypeError, KeyError) as e:
                # orbax reports a saved-vs-target pytree STRUCTURE mismatch
                # with these; missing/torn tags raise the typed
                # CheckpointNotFound/CheckpointCorrupt and propagate —
                # resharding cannot help those
                client_state = self._load_cross_topology(load_dir, tag, e)
                structured = False
        self.global_steps = int(client_state.get("global_steps", 0))
        self._reset_host_metrics_cache()
        if self.offloading and structured:
            # same-layout restore: host optimizer state rides the npz
            # sidecar.  The cross-topology fallback already installed
            # masters/moments from the LOGICAL fragments (relayouted for
            # this engine) — the source-physical npz must not clobber them,
            # and a non-offload source has no npz at all.
            import os
            p = os.path.join(load_dir, tag, "offload_state.npz")
            if not os.path.exists(p):
                from deepspeed_tpu.checkpoint import CheckpointCorrupt
                raise CheckpointCorrupt(
                    f"offload checkpoint missing {p}; this checkpoint was "
                    f"saved without offload_optimizer")
            with np.load(p) as sd:
                self.offload_opt.load_state_dict(dict(sd))
        return tag, client_state
