"""Elastic agent — liveness monitoring, membership change, relaunch.

Reference parity: ``deepspeed/elasticity/elastic_agent.py:32 DSElasticAgent``
(the torch-elastic agent that restarts the worker group at a new world size)
+ ``launcher/runner.py:391 --elastic_training``.  The batch-geometry solver it
consults is ``deepspeed_tpu.elasticity.compute_elastic_config`` (v0.1/v0.2).

TPU-native shape: one worker process per host (SPMD owns the devices), so the
agent is a HOST-level supervisor:

1. solve the batch geometry for the current host count,
2. launch one worker per host with the fleet-identity env + the solved
   ``DSTPU_ELASTIC_*`` batch overrides,
3. poll liveness; on an ABRUPT worker death (host loss) SIGKILL the
   survivors (they are blocked in collectives — reference: the agent tears
   the whole group down the same way); a worker exiting
   ``resilience.EXIT_DRAINED`` drained gracefully on a preemption notice
   and leaves the membership without taking the group down abruptly,
4. drop the lost/preempted hosts, re-solve the batch geometry under the
   ``elasticity.py`` valid-count constraints, back off (bounded, growing
   per consecutive restart), and relaunch; workers resume from the newest
   COMPLETE universal export (``checkpoint.latest_universal(run_dir)`` —
   the crash-safe commit protocol guarantees a torn export is never picked)
   so training continues at the new world size with loss continuity.

Worker contract (what the training script must do to be elastic):
- read ``DSTPU_ELASTIC_BATCH`` / ``DSTPU_ELASTIC_MICRO`` for the batch triad,
- on start, ``engine.resume_from_latest(DSTPU_RUN_DIR)``,
- export a universal checkpoint periodically (host 0,
  ``export_universal_checkpoint(dir, run_dir=...)``),
- install a ``resilience.PreemptionHandler``; on a notice, drain
  (``engine.drain(run_dir)``) and exit ``resilience.EXIT_DRAINED``,
- exit 0 when done.

``--sim_hosts`` mode launches local single-process CPU workers (the test
path — the CPU backend has no cross-process collectives, so each simulated
host computes independently and reads its fleet identity from
``DSTPU_SIM_*``); a real DCN fleet swaps the Popen for the launcher's ssh
commands and the JAX rendezvous env.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from deepspeed_tpu.elasticity import ElasticityConfig, compute_elastic_config
from deepspeed_tpu.runtime.resilience import EXIT_DRAINED
from deepspeed_tpu.utils.logging import logger


class ElasticAgent:
    def __init__(self, script: str, script_args: Optional[List[str]] = None,
                 *, n_hosts: int, elastic_config: ElasticityConfig,
                 run_dir: str, devices_per_host: int = 2,
                 base_port: int = 29821, min_hosts: int = 1,
                 max_restarts: int = 3, poll_interval: float = 0.25,
                 gen_timeout: Optional[float] = None,
                 restart_backoff: float = 0.2,
                 max_backoff: float = 5.0,
                 extra_env: Optional[Dict[str, str]] = None):
        self.script = script
        self.script_args = list(script_args or [])
        self.n_hosts = n_hosts
        self.cfg = elastic_config
        self.run_dir = run_dir
        self.devices_per_host = devices_per_host
        self.base_port = base_port           # legacy knob (rendezvous-era)
        self.min_hosts = min_hosts
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.gen_timeout = gen_timeout
        self.restart_backoff = restart_backoff
        self.max_backoff = max_backoff
        self.extra_env = dict(extra_env or {})
        os.makedirs(run_dir, exist_ok=True)
        self.history: List[dict] = []
        self.preemptions = 0
        self.host_losses = 0

    # ---------------------------------------------------------------- spawn
    def _spawn(self, world: int, restarts: int,
               batch: int, micro: Optional[int]) -> List[subprocess.Popen]:
        procs = []
        for rank in range(world):
            env = dict(os.environ)
            env.pop("JAX_COORDINATOR_ADDRESS", None)
            env.update(self.extra_env)
            env.update({
                "JAX_PLATFORMS": "cpu",
                # single-process-per-host simulation: fleet identity via
                # DSTPU_SIM_* (comm.host_rank) — the CPU backend cannot run
                # cross-process collectives, so no jax.distributed here
                "DSTPU_SIM_FLEET": "1",
                "DSTPU_SIM_RANK": str(rank),
                "DSTPU_SIM_WORLD": str(world),
                "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                              + f" --xla_force_host_platform_device_count="
                              f"{self.devices_per_host}").strip(),
                "DSTPU_ELASTIC_BATCH": str(batch),
                "DSTPU_ELASTIC_MICRO": str(micro or 1),
                "DSTPU_RESTART_COUNT": str(restarts),
                "DSTPU_RUN_DIR": self.run_dir,
            })
            procs.append(subprocess.Popen(
                [sys.executable, self.script] + self.script_args, env=env))
        return procs

    def _write_status(self, **kw) -> None:
        state = dict(kw)
        state["history"] = self.history
        state["preemptions"] = self.preemptions
        state["host_losses"] = self.host_losses
        tmp = os.path.join(self.run_dir, "agent_status.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(self.run_dir, "agent_status.json"))

    @staticmethod
    def _kill_all(procs: List[subprocess.Popen]) -> None:
        # survivors sit in collectives waiting for the dead peer — SIGKILL,
        # not SIGTERM (reference: the agent tears the worker group down)
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGKILL)
                except OSError:
                    pass
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass

    def preempt(self, procs: List[subprocess.Popen], rank: int) -> None:
        """Deliver a preemption notice (SIGTERM) to one worker — the fault
        path chaos tests drive; the worker's PreemptionHandler drains and
        exits EXIT_DRAINED."""
        if procs[rank].poll() is None:
            procs[rank].send_signal(signal.SIGTERM)

    # ------------------------------------------------------------------ run
    def run(self) -> int:
        world = self.n_hosts
        restarts = 0
        while True:
            chips = world * self.devices_per_host
            batch, valid_dp, micro = compute_elastic_config(self.cfg, chips)
            gen = {"world": world, "batch": batch, "micro": micro,
                   "restarts": restarts}
            logger.info(f"elastic agent: generation {restarts}: "
                        f"world={world} batch={batch} micro={micro}")
            procs = self._spawn(world, restarts, batch, micro)
            gen["pids"] = [p.pid for p in procs]
            self.history.append(gen)
            self._write_status(phase="running", **gen)

            t0 = time.time()
            crashed: Optional[List[int]] = None
            drained: List[int] = []
            while True:
                codes = [p.poll() for p in procs]
                crashed = [i for i, c in enumerate(codes)
                           if c not in (None, 0, EXIT_DRAINED)]
                drained = [i for i, c in enumerate(codes)
                           if c == EXIT_DRAINED]
                if crashed or drained:
                    break
                if all(c == 0 for c in codes):
                    self._write_status(phase="done", **gen)
                    return 0
                if (self.gen_timeout is not None
                        and time.time() - t0 > self.gen_timeout):
                    logger.warning("elastic agent: generation timed out — "
                                   "restarting at the same world size")
                    break
                time.sleep(self.poll_interval)

            if drained and not crashed:
                # graceful preemption(s): give OTHER notified workers a
                # beat to finish their drains (they are writing final
                # exports).  Survivors that got no notice keep training and
                # never exit — so stop as soon as the exit set stabilizes
                # (no new exit for a few polls), not after a fixed stall.
                deadline = time.time() + 60
                settle = max(1.0, 4 * self.poll_interval)
                last_change = time.time()
                exited = sum(c is not None
                             for c in (p.poll() for p in procs))
                while time.time() < deadline:
                    codes = [p.poll() for p in procs]
                    now_exited = sum(c is not None for c in codes)
                    if now_exited == len(procs):
                        break
                    if now_exited != exited:
                        exited, last_change = now_exited, time.time()
                    elif time.time() - last_change > settle:
                        break
                    time.sleep(self.poll_interval)
                # re-book from the FINAL exit codes: a worker that crashed
                # during the drain window is a host loss, not a graceful
                # departure
                codes = [p.poll() for p in procs]
                crashed = [i for i, c in enumerate(codes)
                           if c not in (None, 0, EXIT_DRAINED)]
                drained = [i for i, c in enumerate(codes)
                           if c == EXIT_DRAINED]
            self._kill_all(procs)
            lost = len(set((crashed or []) + drained))
            if crashed:
                self.host_losses += len(crashed)
                logger.warning(
                    f"elastic agent: worker(s) {crashed} died — membership "
                    f"change to world={world - lost}")
            if drained:
                self.preemptions += len(drained)
                logger.info(
                    f"elastic agent: worker(s) {drained} drained on "
                    f"preemption — membership change to world={world - lost}")
            world -= lost
            gen["crashed"] = crashed or []
            gen["drained"] = drained
            restarts += 1
            if world < self.min_hosts:
                self._write_status(phase="failed", reason="below min_hosts",
                                   **gen)
                return 1
            if restarts > self.max_restarts:
                self._write_status(phase="failed", reason="max_restarts",
                                   **gen)
                return 1
            # bounded exponential backoff between generations: a
            # crash-looping worker must not spin the fleet
            backoff = min(self.restart_backoff * (2 ** (restarts - 1)),
                          self.max_backoff)
            time.sleep(backoff)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="dstpu-elastic",
        description="elastic training agent (reference DSElasticAgent)")
    ap.add_argument("--sim_hosts", type=int, required=True)
    ap.add_argument("--devices_per_host", type=int, default=2)
    ap.add_argument("--run_dir", required=True)
    ap.add_argument("--min_hosts", type=int, default=1)
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("--micro_batch_sizes", type=int, nargs="+",
                    default=[1, 2, 4])
    ap.add_argument("--max_train_batch_size", type=int, default=64)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs="*")
    args = ap.parse_args(argv)
    cfg = ElasticityConfig(
        micro_batch_sizes=tuple(args.micro_batch_sizes),
        max_train_batch_size=args.max_train_batch_size,
        min_chips=args.min_hosts * args.devices_per_host,
        max_chips=args.sim_hosts * args.devices_per_host,
        chips_per_host=args.devices_per_host)
    agent = ElasticAgent(args.script, args.script_args,
                         n_hosts=args.sim_hosts, elastic_config=cfg,
                         run_dir=args.run_dir,
                         devices_per_host=args.devices_per_host,
                         min_hosts=args.min_hosts,
                         max_restarts=args.max_restarts)
    return agent.run()


if __name__ == "__main__":
    raise SystemExit(main())
