"""dstpu launcher — start one training process per host and wire up the JAX
distributed runtime.

Reference parity: ``launcher/runner.py:388 main`` (hostfile parsing :120,
resource pools, pdsh/ssh multinode runners) + ``launcher/launch.py:133`` (the
per-node process spawner that exports RANK/LOCAL_RANK/WORLD_SIZE).

TPU-native redesign: there is no per-GPU process tree — JAX runs ONE process
per host and SPMD handles every device from it.  What remains of the
reference's launcher stack is:

- **rendezvous env** (reference launch.py env exports → here
  JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID consumed by
  ``comm.init_distributed``);
- **hostfile** parsing (same ``hostname slots=N`` format) and ssh command
  construction for DCN fleets (reference PDSHRunner.get_cmd);
- **--sim_hosts**: spawn K local processes with a virtual CPU mesh each —
  the test path for multi-process semantics without a pod (reference's
  ``--force_multi`` local pool, runner.py:344).

Cloud TPU pods need none of the rendezvous flags: ``jax.distributed``
autodiscovers via the metadata server, so ``dstpu script.py`` on every host
is enough (the reference needs NCCL_… + static ranks; JAX does discovery).
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
from typing import Dict, List, Optional, Tuple


def parse_hostfile(text: str) -> Dict[str, int]:
    """'hostname slots=N' per line (reference launcher/runner.py:120
    _parse_hostfile; comments + blank lines ignored)."""
    pool: Dict[str, int] = {}
    for ln in text.splitlines():
        ln = ln.split("#", 1)[0].strip()
        if not ln:
            continue
        parts = ln.split()
        host = parts[0]
        slots = 1
        for p in parts[1:]:
            if p.startswith("slots="):
                slots = int(p.split("=", 1)[1])
        if host in pool:
            raise ValueError(f"duplicate host {host!r} in hostfile")
        pool[host] = slots
    if not pool:
        raise ValueError("hostfile is empty")
    return pool


def ssh_commands(pool: Dict[str, int], coordinator: str, script: str,
                 script_args: List[str],
                 export_env: Optional[Dict[str, str]] = None,
                 ) -> List[Tuple[str, str]]:
    """Build one ssh command per host (reference PDSHRunner.get_cmd analog —
    pdsh fan-out replaced by plain per-host ssh; the caller decides how to
    run them)."""
    cmds = []
    n = len(pool)
    for rank, host in enumerate(pool):
        env = {
            "JAX_COORDINATOR_ADDRESS": coordinator,
            "JAX_NUM_PROCESSES": str(n),
            "JAX_PROCESS_ID": str(rank),
            **(export_env or {}),
        }
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        inner = f"{exports} {sys.executable} {shlex.quote(script)} " + \
            " ".join(shlex.quote(a) for a in script_args)
        cmds.append((host, f"ssh {shlex.quote(host)} {shlex.quote(inner)}"))
    return cmds


def _run_sim(args, script_args: List[str]) -> int:
    """K local "host" processes, each a SINGLE-process JAX runtime with its
    own virtual CPU mesh (reference --force_multi local resource pool).

    The CPU backend cannot execute cross-process computations
    ("Multiprocess computations aren't implemented on the CPU backend"),
    so the sim does NOT wire the jax.distributed rendezvous: each host gets
    its fleet identity via the ``DSTPU_SIM_*`` env
    (``comm.host_rank``/``host_world_size``) and computes independently on
    its local devices.  Real DCN fleets go through ``ssh_commands`` with
    the JAX rendezvous env instead."""
    n = args.sim_hosts
    procs: List[subprocess.Popen] = []
    for rank in range(n):
        env = dict(os.environ)
        env.pop("JAX_COORDINATOR_ADDRESS", None)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "DSTPU_SIM_FLEET": "1",
            "DSTPU_SIM_RANK": str(rank),
            "DSTPU_SIM_WORLD": str(n),
            "XLA_FLAGS": (env.get("XLA_FLAGS", "") +
                          f" --xla_force_host_platform_device_count="
                          f"{args.devices_per_host}").strip(),
        })
        procs.append(subprocess.Popen(
            [sys.executable, args.script] + script_args, env=env))
    rc = 0
    for rank, p in enumerate(procs):
        code = p.wait()
        if code != 0:
            print(f"[dstpu] rank {rank} exited with {code}", file=sys.stderr)
            rc = rc or code
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu", description="deepspeed_tpu multi-host launcher "
        "(reference: deepspeed CLI, launcher/runner.py:388)")
    ap.add_argument("--hostfile", help="'host slots=N' lines; prints/executes "
                    "one ssh command per host")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (multi-host DCN fleets; "
                    "Cloud TPU pods autodiscover)")
    ap.add_argument("--num_nodes", type=int, default=None)
    ap.add_argument("--node_rank", type=int, default=None)
    ap.add_argument("--sim_hosts", type=int, default=0,
                    help="spawn K local CPU-mesh processes (test path)")
    ap.add_argument("--devices_per_host", type=int, default=4,
                    help="virtual devices per sim host")
    ap.add_argument("--sim_port", type=int, default=29731)
    # elastic training (reference launcher/runner.py:391 --elastic_training →
    # elasticity/elastic_agent.py DSElasticAgent)
    ap.add_argument("--elastic_training", action="store_true",
                    help="supervise workers with the elastic agent: on a "
                    "host loss, re-solve the batch geometry and relaunch "
                    "from the latest universal checkpoint")
    ap.add_argument("--elastic_run_dir", default="./elastic_run")
    ap.add_argument("--min_hosts", type=int, default=1)
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("--elastic_micro_batches", type=int, nargs="+",
                    default=[1, 2, 4])
    ap.add_argument("--max_train_batch_size", type=int, default=64)
    ap.add_argument("--ssh", action="store_true",
                    help="with --hostfile: actually execute the ssh commands "
                    "(default: print them)")
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    if args.elastic_training:
        if not args.sim_hosts:
            ap.error("--elastic_training currently supervises --sim_hosts "
                     "fleets (a DCN fleet swaps Popen for ssh)")
        from deepspeed_tpu.elasticity import ElasticityConfig
        from deepspeed_tpu.launcher.elastic_agent import ElasticAgent
        cfg = ElasticityConfig(
            micro_batch_sizes=list(args.elastic_micro_batches),
            max_train_batch_size=args.max_train_batch_size,
            min_chips=args.min_hosts * args.devices_per_host,
            max_chips=args.sim_hosts * args.devices_per_host,
            chips_per_host=args.devices_per_host)
        agent = ElasticAgent(args.script, args.script_args,
                             n_hosts=args.sim_hosts, elastic_config=cfg,
                             run_dir=args.elastic_run_dir,
                             devices_per_host=args.devices_per_host,
                             min_hosts=args.min_hosts,
                             max_restarts=args.max_restarts,
                             base_port=args.sim_port)
        return agent.run()

    if args.sim_hosts:
        return _run_sim(args, args.script_args)

    if args.hostfile:
        with open(args.hostfile) as f:
            pool = parse_hostfile(f.read())
        coordinator = args.coordinator or f"{next(iter(pool))}:29500"
        cmds = ssh_commands(pool, coordinator, args.script, args.script_args)
        if not args.ssh:
            for host, cmd in cmds:
                print(cmd)
            return 0
        procs = [subprocess.Popen(cmd, shell=True) for _, cmd in cmds]
        rc = 0
        for (host, _), p in zip(cmds, procs):
            code = p.wait()
            if code != 0:    # signals give negative codes — max() would mask
                print(f"[dstpu] {host} exited with {code}", file=sys.stderr)
                rc = rc or code
        return rc

    # single-host / this-host-of-a-fleet: export rendezvous env when given,
    # then run the script in-process (reference launch.py exec path)
    if args.coordinator is not None:
        os.environ["JAX_COORDINATOR_ADDRESS"] = args.coordinator
    if args.num_nodes is not None:
        os.environ["JAX_NUM_PROCESSES"] = str(args.num_nodes)
    if args.node_rank is not None:
        os.environ["JAX_PROCESS_ID"] = str(args.node_rank)
    sys.argv = [args.script] + args.script_args
    import runpy
    runpy.run_path(args.script, run_name="__main__")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
