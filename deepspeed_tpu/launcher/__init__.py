"""Launcher — multi-host process orchestration (``python -m
deepspeed_tpu.launcher``, the ``deepspeed``/``dstpu`` CLI analog)."""

from deepspeed_tpu.launcher.runner import main, parse_hostfile  # noqa: F401
