from deepspeed_tpu.launcher.runner import main

if __name__ == "__main__":
    raise SystemExit(main())
