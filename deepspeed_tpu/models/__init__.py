from deepspeed_tpu.models.gpt import (GPT, GPTBackbone, GPTChunkedLoss,
                                      GPTConfig, GPTLogits)

__all__ = ["GPT", "GPTBackbone", "GPTChunkedLoss", "GPTConfig", "GPTLogits"]
