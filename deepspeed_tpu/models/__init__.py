from deepspeed_tpu.models.gpt import (GPT, GPTBackbone, GPTChunkedLoss,
                                      GPTConfig)

__all__ = ["GPT", "GPTBackbone", "GPTChunkedLoss", "GPTConfig"]
