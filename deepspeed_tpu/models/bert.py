"""BERT-family encoder (bidirectional transformer + MLM head).

Reference: module_inject/containers/{bert,distil_bert}.py (HFBertLayerPolicy —
the injection zoo's encoder rows) and the fused training transformer kernel
(csrc/transformer/ds_transformer_cuda.cpp) whose flagship workload was BERT
pre-training.

TPU-first shape: same logical-axis annotations as models/gpt.py (TP/FSDP fall
out of parallel/partition.py), one fused einsum attention path on the MXU, and
HF's POST-LayerNorm residual order reproduced exactly so checkpoints load
bit-compatibly.  No causal mask — padding is the only mask.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    mlp_dim: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    dtype: object = jnp.float32
    param_dtype: object = jnp.float32
    activation: str = "gelu_exact"      # HF bert uses exact erf gelu
    pooler_act: str = "tanh"            # bert pooler tanh; distilbert
    #                                     pre_classifier relu
    pos_pad_token: Optional[int] = None  # roberta: positions count only
    #                                      non-pad tokens (HF create_position_
    #                                      ids_from_input_ids); None = arange

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 128)
        kw.setdefault("max_seq_len", 64)
        return cls(num_layers=2, num_heads=4, hidden_size=64, mlp_dim=128,
                   **kw)


def _part(init, names):
    return nn.with_partitioning(init, names)


def _kinit():
    return nn.initializers.normal(stddev=0.02)


class _Norm(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x):
        from deepspeed_tpu.ops import layer_norm
        c = self.cfg
        scale = self.param("scale", _part(nn.initializers.ones, ("embed",)),
                           (c.hidden_size,), c.param_dtype)
        bias = self.param("bias", _part(nn.initializers.zeros, ("embed",)),
                          (c.hidden_size,), c.param_dtype)
        return layer_norm(x, scale, bias, eps=c.norm_eps)


class _SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, pad_mask):
        c = self.cfg
        H, nh, hd = c.hidden_size, c.num_heads, c.head_dim

        def lin(name, shape, axes):
            return (self.param(f"w{name}", _part(_kinit(), axes), shape,
                               c.param_dtype),
                    self.param(f"b{name}",
                               _part(nn.initializers.zeros, axes[1:]),
                               shape[1:], c.param_dtype))

        wq, bq = lin("q", (H, nh, hd), ("embed", "heads", "kv"))
        wk, bk = lin("k", (H, nh, hd), ("embed", "heads", "kv"))
        wv, bv = lin("v", (H, nh, hd), ("embed", "heads", "kv"))
        wo = self.param("wo", _part(_kinit(), ("heads", "kv", "embed")),
                        (nh, hd, H), c.param_dtype)
        bo = self.param("bo", _part(nn.initializers.zeros, ("embed",)),
                        (H,), c.param_dtype)

        q = jnp.einsum("bth,hnd->btnd", x, wq.astype(x.dtype)) + bq.astype(
            x.dtype)
        k = jnp.einsum("bth,hnd->btnd", x, wk.astype(x.dtype)) + bk.astype(
            x.dtype)
        v = jnp.einsum("bth,hnd->btnd", x, wv.astype(x.dtype)) + bv.astype(
            x.dtype)
        # bidirectional: every query row sees all non-pad keys
        mask = jnp.broadcast_to(pad_mask[:, None, :].astype(bool),
                                (x.shape[0], x.shape[1], x.shape[1]))
        from deepspeed_tpu import ops
        out = ops.causal_attention(q, k, v, causal=False, mask=mask)
        return jnp.einsum("btnd,ndh->bth", out, wo.astype(x.dtype)) \
            + bo.astype(x.dtype)


class _Mlp(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x):
        from deepspeed_tpu.models.gpt import mlp_activation
        c = self.cfg
        wi = self.param("wi", _part(_kinit(), ("embed", "mlp")),
                        (c.hidden_size, c.mlp_dim), c.param_dtype)
        bi = self.param("bi", _part(nn.initializers.zeros, ("mlp",)),
                        (c.mlp_dim,), c.param_dtype)
        wo = self.param("wo", _part(_kinit(), ("mlp", "embed")),
                        (c.mlp_dim, c.hidden_size), c.param_dtype)
        bo = self.param("bo", _part(nn.initializers.zeros, ("embed",)),
                        (c.hidden_size,), c.param_dtype)
        h = mlp_activation(c.activation)(x @ wi.astype(x.dtype)
                                         + bi.astype(x.dtype))
        return h @ wo.astype(x.dtype) + bo.astype(x.dtype)


class _Block(nn.Module):
    """HF Bert layer: POST-norm — x = LN(x + attn(x)); x = LN(x + mlp(x))."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, pad_mask):
        c = self.cfg
        x = _Norm(c, name="attn_norm")(x + _SelfAttention(c, name="attn")(
            x, pad_mask))
        x = _Norm(c, name="mlp_norm")(x + _Mlp(c, name="mlp")(x))
        return x


class BertEncoder(nn.Module):
    """ids (+ token types, padding mask) → (hidden states [B, T, H], wte) —
    the embedding table rides along for the tied MLM decoder (same contract
    as gpt.py GPTBackbone)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        c = self.cfg
        B, T = input_ids.shape
        wte = self.param("wte", _part(_kinit(), ("vocab", "embed")),
                         (c.vocab_size, c.hidden_size), c.param_dtype)
        # roberta keeps its padding_idx-offset position table whole: real
        # token #k sits at row k+padding_idx, pad tokens at row padding_idx
        pos_rows = c.max_seq_len + (c.pos_pad_token + 1
                                    if c.pos_pad_token is not None else 0)
        wpe = self.param("wpe", _part(_kinit(), (None, "embed")),
                         (pos_rows, c.hidden_size), c.param_dtype)
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        if c.pos_pad_token is not None:
            # HF create_position_ids_from_input_ids exactly: an id equal to
            # the pad token never advances the counter and takes row
            # padding_idx itself
            real = (input_ids != c.pos_pad_token).astype(jnp.int32)
            pos = jnp.cumsum(real, axis=1) * real + c.pos_pad_token
            pos_emb = wpe.astype(c.dtype)[pos]
        else:
            pos_emb = wpe.astype(c.dtype)[jnp.arange(T)][None]
        x = wte.astype(c.dtype)[input_ids] + pos_emb
        if c.type_vocab_size:          # distilbert has no segment embeddings
            wtt = self.param("wtt", _part(_kinit(), (None, "embed")),
                             (c.type_vocab_size, c.hidden_size),
                             c.param_dtype)
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + wtt.astype(c.dtype)[token_type_ids]
        x = _Norm(c, name="embed_norm")(x)
        for i in range(c.num_layers):
            x = _Block(c, name=f"block_{i}")(x, attention_mask)
        return x, wte


class BertForSequenceClassification(nn.Module):
    """Encoder + pooler (dense-tanh on [CLS]) + classifier — HF's
    BertForSequenceClassification layout."""

    cfg: BertConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        c = self.cfg
        x, _ = BertEncoder(c, name="encoder")(input_ids, token_type_ids,
                                              attention_mask)
        wp = self.param("pooler_w", _part(_kinit(), ("embed", "embed2")),
                        (c.hidden_size, c.hidden_size), c.param_dtype)
        bp = self.param("pooler_b", _part(nn.initializers.zeros, ("embed2",)),
                        (c.hidden_size,), c.param_dtype)
        act = jnp.tanh if c.pooler_act == "tanh" else jax.nn.relu
        pooled = act(x[:, 0] @ wp.astype(x.dtype) + bp.astype(x.dtype))
        wc = self.param("cls_w", _part(_kinit(), ("embed", None)),
                        (c.hidden_size, self.num_labels), c.param_dtype)
        bc = self.param("cls_b", _part(nn.initializers.zeros, (None,)),
                        (self.num_labels,), c.param_dtype)
        return (pooled @ wc.astype(x.dtype)
                + bc.astype(x.dtype)).astype(jnp.float32)


class BertForMaskedLM(nn.Module):
    """Encoder + MLM transform head (dense→gelu→LN→tied decoder + bias) —
    exactly HF's BertOnlyMLMHead so checkpoints reproduce logits."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        c = self.cfg
        x, wte = BertEncoder(c, name="encoder")(input_ids, token_type_ids,
                                                attention_mask)
        wt = self.param("transform_w", _part(_kinit(), ("embed", "embed2")),
                        (c.hidden_size, c.hidden_size), c.param_dtype)
        bt = self.param("transform_b", _part(nn.initializers.zeros,
                                             ("embed2",)),
                        (c.hidden_size,), c.param_dtype)
        from deepspeed_tpu.models.gpt import mlp_activation
        x = mlp_activation(c.activation)(x @ wt.astype(x.dtype)
                                         + bt.astype(x.dtype))
        x = _Norm(c, name="transform_norm")(x)
        logits = x @ wte.astype(x.dtype).T           # tied decoder
        bias = self.param("decoder_bias", _part(nn.initializers.zeros,
                                                ("vocab",)),
                          (c.vocab_size,), c.param_dtype)
        return (logits + bias.astype(x.dtype)).astype(jnp.float32)
