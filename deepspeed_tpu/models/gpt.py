"""Decoder-only transformer LM (GPT-2 / Llama family) — the flagship model.

This plays the role of the reference's model zoo entries (GPT-2/Llama policies in
module_inject/containers/{gpt2,llama}.py and inference/v2/model_implementations/
llama_v2) but as a TPU-first flax module:

- every parameter carries logical sharding axes via ``nn.with_partitioning``
  (mapped to mesh axes by parallel/partition.py — TP/FSDP/SP fall out of the
  annotations instead of graph surgery)
- pre-norm blocks, optional RoPE + RMSNorm (llama style) or learned positions +
  LayerNorm (gpt2 style), gated (SwiGLU) or GELU MLP
- causal attention via a single fused einsum path XLA maps onto the MXU;
  flash-attention Pallas kernel is swapped in by ops/ when enabled
- ``remat`` applies jax.checkpoint per block (reference:
  runtime/activation_checkpointing/checkpointing.py)

call contract: ``model.apply(params, batch, rngs={"dropout": k}) -> scalar loss``
where batch = {"input_ids": [B, T] int32, optional "labels": [B, T],
optional "loss_mask": [B, T]}.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = object


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    num_layers: int = 12
    num_heads: int = 12
    head_dim: int = 64
    hidden_size: int = 768
    mlp_ratio: int = 4
    max_seq_len: int = 1024
    dropout: float = 0.0
    dtype: Dtype = jnp.float32          # compute dtype (engine casts params)
    param_dtype: Dtype = jnp.float32
    use_rope: bool = False              # llama-style when True
    use_rmsnorm: bool = False
    gated_mlp: bool = False             # SwiGLU
    num_kv_heads: Optional[int] = None  # GQA; defaults to num_heads
    remat: bool = False
    tie_embeddings: bool = True
    # MoE (reference deepspeed.moe; Mixtral-style when num_experts > 0)
    num_experts: int = 0
    moe_k: int = 1
    moe_every: int = 2                  # MoE replaces MLP every Nth block
    moe_aux_coef: float = 0.01
    moe_capacity_factor: float = 1.25
    moe_dropless: bool = False          # ragged grouped-GEMM routing
    #                                     (ep>1: padded-bucket a2a, no drops)
    # ep a2a fast path (moe/comm.py; pushed from the ds_config `moe` block):
    # wire width of dispatch/combine a2as (0=full, 8/4=blockwise int codes),
    # quantization block, all-ICI full-width policy, and the chunk count
    # interleaving expert GEMMs with in-flight a2a chunks
    moe_wire_bits: int = 0
    moe_wire_block: int = 256
    moe_hierarchical: bool = False
    moe_num_chunks: int = 1
    # parallelism (mesh passed separately to the GPT module attribute)
    sequence_parallel: bool = False     # attention over the sp axis
    sp_impl: str = "ulysses"            # "ulysses" (a2a head swap) | "ring"
    # ring layout: "drop_in" permutes in/out of zig-zag placement inside every
    # attention call (~4 tensor volumes of sp wire per call, contiguous
    # activations everywhere); "native" permutes token ids + positions +
    # labels ONCE per step at the loss wrapper and keeps activations in
    # zig-zag layout through the whole stack — the ring hops become the only
    # sp-axis traffic (sequence/ring.py layout= docstring)
    sp_ring_layout: str = "drop_in"     # "drop_in" | "native"
    # ring inner attend: "einsum" materializes [c, c] logits per sub-attend;
    # "flash" runs the Pallas flash kernel with logsumexp merging and a
    # ring-level custom_vjp — O(inputs) attention memory for long context
    # (sequence/ring.py inner= docstring; needs T/(2·sp) >= 8, d % 8 == 0)
    sp_ring_inner: str = "einsum"       # "einsum" | "flash"
    # kernel selection (reference: replace_with_kernel_inject / DS_BUILD flags);
    # None = registry auto (pallas flash on TPU, XLA elsewhere)
    attn_impl: Optional[str] = None
    # route the TP row-parallel matmuls (MLP down-projection, attention
    # output projection) through the explicit ppermute-ring
    # collective-matmul fusions (ops/collective_matmul.py) so the TP
    # all-reduce overlaps the chunk matmuls; set by the engine from
    # ``overlap.collective_matmul``.  Inert at tp=1; loud error on unwired
    # combinations (sequence parallelism, non-dividing shapes).
    tp_collective_matmul: bool = False
    # chunked unembed+CE (ops/cross_entropy.py); 0 = one-shot logits
    loss_chunk: int = 0
    # HF-architecture knobs (checkpoint/hf.py maps real configs onto these):
    # explicit FFN width (llama intermediate_size is not a hidden multiple),
    # rope base (llama3 5e5, qwen2 1e6), norm eps, and bias placement
    # (qwen2: qkv only; gpt2: everywhere)
    mlp_dim_override: Optional[int] = None
    rope_theta: float = 10000.0
    # rope scaling (llama-3.1+ long-context checkpoints; HF rope_scaling):
    # ("llama3", factor, low_freq_factor, high_freq_factor, original_max) or
    # ("linear", factor); None = unscaled
    rope_scaling: Optional[tuple] = None
    norm_eps: Optional[float] = None    # None = ops/norms.py defaults
    qkv_bias: bool = False
    attn_out_bias: bool = False
    mlp_bias: bool = False
    # architecture variants for the wider HF zoo (reference zoo:
    # module_inject/containers/opt.py, inference/v2/model_implementations/
    # {phi,falcon}):
    activation: str = "gelu"            # non-gated MLP: gelu|gelu_exact|relu
    parallel_block: bool = False        # x + attn(n(x)) + mlp(n(x)) (falcon/phi)
    parallel_norms: int = 1             # 1 = shared input norm; 2 = ln_attn+ln_mlp
    rope_pct: float = 1.0               # partial rotary (phi partial_rotary_factor)
    unembed_bias: bool = False          # lm_head bias (phi)
    use_alibi: bool = False             # alibi attention bias, no positional
    #                                     table (bloom/falcon-rw)
    gate_act: str = "silu"              # gated-MLP gate: silu (SwiGLU) or
    #                                     gelu (gemma GeGLU)
    embed_scale: Optional[float] = None  # gemma: x·√H after the embedding
    #                                      gather (unembed stays unscaled)
    sliding_window: Optional[int] = None  # each token sees the last W keys
    #                                       (mistral; gpt-neo local layers)
    local_attn_layers: tuple = ()       # layers the window applies to; empty
    #                                     + sliding_window set = all layers
    attn_scale: Optional[float] = None  # logit scale; None = 1/sqrt(head_dim)
    #                                     (gpt-neo uses 1.0)
    alibi_prescale: bool = False        # falcon-rw: (scores+alibi)·scale with
    #                                     bf16-rounded slopes; bloom adds the
    #                                     bias AFTER scaling
    embed_norm: bool = False            # LayerNorm right after the embedding
    #                                     (bloom word_embeddings_layernorm)
    # random-LTD (data_pipeline/random_ltd.py): layers that run on a kept
    # token subset when the batch carries "random_ltd_idx"
    random_ltd_layer_ids: tuple = ()
    # activation fake-quant bits (compression/pruning.py quant_act —
    # reference basic_layer.py QuantAct); None/0 = off
    act_quant_bits: Optional[int] = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    def window_for_layer(self, i: int):
        """Per-layer sliding window — THE gating rule shared by the training
        model, ragged prefill, and paged decode paths."""
        if self.sliding_window and (not self.local_attn_layers
                                    or i in self.local_attn_layers):
            return self.sliding_window
        return None

    @property
    def mlp_dim(self) -> int:
        return self.mlp_dim_override or self.hidden_size * self.mlp_ratio

    @classmethod
    def gpt2_small(cls, **kw):
        return cls(num_layers=12, num_heads=12, head_dim=64, hidden_size=768, **kw)

    @classmethod
    def llama(cls, num_layers=8, hidden=512, heads=8, **kw):
        return cls(num_layers=num_layers, hidden_size=hidden, num_heads=heads,
                   head_dim=hidden // heads, use_rope=True, use_rmsnorm=True,
                   gated_mlp=True, tie_embeddings=False, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq_len", 128)
        return cls(num_layers=2, num_heads=4, head_dim=8, hidden_size=32,
                   mlp_ratio=2, **kw)


def _gather_table(table, mesh, vocab_axis="tp"):
    """Constrain a [rows, embed] lookup table's embed dim to replicated right
    before a gather.

    Under ZeRO-3 the table is fsdp-sharded on the embed dim; a direct gather
    would produce embed-sharded activations that SPMD can only reshard to the
    batch-sharded layout by replicate-then-repartition ("Involuntary full
    rematerialization").  Un-sharding just the embed dim makes XLA emit one
    clean all-gather (ZeRO-3's gather-then-use).  The vocab dim KEEPS its tp
    sharding (Megatron-style vocab-parallel embedding: masked local gather +
    activation all-reduce), so tp>1 serving never materializes the full table."""
    if mesh is None:
        return table
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.parallel.mesh import auto_axes_spec
    spec0 = None
    if (vocab_axis and mesh.shape.get(vocab_axis, 1) > 1
            and table.shape[0] % mesh.shape[vocab_axis] == 0):
        spec0 = vocab_axis
    return jax.lax.with_sharding_constraint(
        table, NamedSharding(mesh, auto_axes_spec(P(spec0, None))))


def _pin_activations(x, mesh, seq_parallel: bool):
    """Constrain [B, T, ...] activations to (dp/fsdp-batch, sp-seq) sharding.

    Applied right after the embedding gather: without it XLA's SPMD partitioner
    may resolve the gather of an fsdp-sharded table by replicating the result
    and repartitioning ("Involuntary full rematerialization") — a full
    allgather of the activations on exactly the fsdp/sp meshes this framework
    targets.  Axes that don't divide the dim are skipped (e.g. T=1 decode)."""
    if mesh is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.parallel.mesh import manual_axes_now
    # axes already applied by an enclosing manual shard_map (qgZ grad
    # region) drop out: in-body shapes are LOCAL over them, and naming
    # them in a constraint is illegal — size and pin over the rest
    manual = manual_axes_now()
    baxes = tuple(a for a in ("dp", "fsdp")
                  if mesh.shape.get(a, 1) > 1 and a not in manual)
    bsize = 1
    for a in baxes:
        bsize *= mesh.shape[a]
    spec = [None] * x.ndim
    if baxes and x.shape[0] % bsize == 0:
        spec[0] = baxes if len(baxes) > 1 else baxes[0]
    sp = mesh.shape.get("sp", 1)
    if (seq_parallel and sp > 1 and "sp" not in manual and x.ndim > 1
            and x.shape[1] % sp == 0):
        spec[1] = "sp"
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def _collective_matmul_active(cfg, mesh, t: int, k: int,
                              use_cache: bool = False) -> bool:
    """Gate for routing a row-parallel matmul through the ring
    collective-matmul fusion (ops/collective_matmul.py).  False when there
    is nothing to fuse (flag off, no mesh, tp=1, or a decode/cache call
    whose T=1 has no sequence to chunk); RAISES on combinations the fusion
    is not wired for — an opt-in perf flag must not silently degrade."""
    if not cfg.tp_collective_matmul or mesh is None or use_cache:
        return False
    tp = mesh.shape.get("tp", 1)
    if tp <= 1:
        return False
    if cfg.sequence_parallel:
        raise ValueError(
            "tp_collective_matmul + sequence parallelism is not wired (the "
            "sp attention paths own the sequence dim the ring would chunk)")
    if t % tp or k % tp:
        raise ValueError(
            f"tp_collective_matmul: seq len {t} and contraction dim {k} "
            f"must both divide tp={tp} (the ring chunks the sequence and "
            f"shards the contraction)")
    return True


def _kernel_init():
    return nn.initializers.normal(stddev=0.02)


def _part(init, names):
    return nn.with_partitioning(init, names)


def alibi_slopes(n_heads: int, head_dim: int = 0, prescale: bool = False):
    """Per-head alibi slopes (HF build_alibi_tensor formula: geometric
    sequence from the closest power of two, odd-power infill for non-pow2
    head counts).  Reference: bloom/falcon-rw attention bias.

    ``prescale`` applies the falcon-rw convention in ONE place for all three
    attention paths: slopes bf16-rounded (HF casts them before the product)
    and folded into the 1/√head_dim scale, because falcon computes
    ``(scores + alibi)·scale`` while bloom adds the bias post-scale."""
    import math
    cp2 = 2 ** math.floor(math.log2(n_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(cp2) - 3)))
    slopes = [base ** i for i in range(1, cp2 + 1)]
    if cp2 != n_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * cp2) - 3)))
        slopes += [extra_base ** i
                   for i in range(1, 2 * (n_heads - cp2), 2)]
    import numpy as np
    s = np.asarray(slopes, np.float32)
    if prescale:
        import ml_dtypes
        s = s.astype(ml_dtypes.bfloat16).astype(np.float32) * (
            head_dim ** -0.5)
    return s


def rotary_dim(head_dim: int, rope_pct: float) -> int:
    """Rotated prefix width for partial rotary (phi partial_rotary_factor),
    rounded down to even so the half-split convention holds."""
    rot = head_dim if rope_pct >= 1.0 else int(head_dim * rope_pct)
    return rot - (rot % 2)


def _scale_rope_freq(freq, scaling):
    """Frequency transform for long-context rope scaling (HF
    modeling_rope_utils):
    - ("linear", factor): inv_freq / factor (position interpolation)
    - ("llama3", factor, low_freq_factor, high_freq_factor, original_max):
      the llama-3.1 piecewise scheme — low frequencies divide by factor,
      high frequencies pass through, the medium band interpolates smoothly
      (matches _compute_llama3_parameters bit-for-bit in fp32)."""
    import math as _math
    kind = scaling[0]
    if kind == "linear":
        return freq / float(scaling[1])
    if kind == "llama3":
        _, factor, lo_f, hi_f, orig = scaling
        factor, lo_f, hi_f, orig = (float(factor), float(lo_f),
                                    float(hi_f), float(orig))
        wavelen = 2.0 * _math.pi / freq
        low_wl = orig / lo_f
        high_wl = orig / hi_f
        scaled = jnp.where(wavelen > low_wl, freq / factor, freq)
        smooth = (orig / wavelen - lo_f) / (hi_f - lo_f)
        smoothed = (1.0 - smooth) * scaled / factor + smooth * scaled
        is_medium = (wavelen >= high_wl) & (wavelen <= low_wl)
        return jnp.where(is_medium, smoothed, scaled)
    raise ValueError(f"unknown rope scaling kind {kind!r}")


def rope(q, k, positions, head_dim, base=10000.0, rope_pct=1.0,
         scaling=None, seq_lens=None):
    """Rotary position embedding (reference CUDA kernel:
    csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu — on TPU a few
    elementwise ops XLA fuses into the attention matmuls).  rope_pct < 1
    rotates only the first ``rotary_dim`` channels (phi-style partial rotary);
    the remainder passes through.  ``scaling`` = GPTConfig.rope_scaling.

    longrope (phi-3 long-context; ("longrope", attention_factor,
    short_factors, long_factors, original_max)): the short/long per-channel
    factor table is selected IN-GRAPH from each SEQUENCE's current length vs
    the pretrained context (HF selects per forward the same way), and
    cos/sin scale by the attention factor.  ``seq_lens``: per-element
    sequence lengths shaped like ``positions`` (ragged serving passes each
    token's slot kv length so co-batched sequences select independently);
    default = per-ROW max position + 1."""
    att_factor = None
    rot = rotary_dim(head_dim, rope_pct)
    half = rot // 2
    freq = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if scaling is not None and scaling[0] == "longrope":
        _, att_factor, short_f, long_f, orig = scaling
        if seq_lens is None:
            # per-row: a padded/multi-row batch must not let one long row
            # flip the others' factor table
            seq_lens = jnp.max(positions, axis=-1, keepdims=True) + 1
        is_long = (seq_lens > orig)[..., None]           # [..., 1]
        ext = jnp.where(is_long,
                        jnp.asarray(long_f, jnp.float32),
                        jnp.asarray(short_f, jnp.float32))
        angles = (positions[..., None].astype(jnp.float32)
                  * (freq / ext))                        # [B,T,half]
    else:
        if scaling is not None:
            freq = _scale_rope_freq(freq, tuple(scaling))
        angles = positions[..., None].astype(jnp.float32) * freq
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    if att_factor is not None:
        sin = sin * jnp.float32(att_factor)
        cos = cos * jnp.float32(att_factor)

    def rotfn(x):
        x1, x2 = x[..., :half], x[..., half:rot]
        s = sin[:, :, None, :].astype(x.dtype)
        c = cos[:, :, None, :].astype(x.dtype)
        parts = [x1 * c - x2 * s, x2 * c + x1 * s]
        if rot < head_dim:
            parts.append(x[..., rot:])
        return jnp.concatenate(parts, axis=-1)

    return rotfn(q), rotfn(k)


def mlp_activation(name: str):
    """Non-gated MLP activation by HF ``activation_function``/``hidden_act``
    name: gpt2/phi use tanh-approx gelu ("gelu_new"), falcon exact-erf gelu,
    OPT relu (reference containers set these per policy)."""
    try:
        return {"gelu": nn.gelu,
                "gelu_exact": lambda x: nn.gelu(x, approximate=False),
                "relu": nn.relu,
                "silu": nn.silu,
                # clip text encoder: x·sigmoid(1.702x)
                "quick_gelu": lambda x: x * jax.nn.sigmoid(1.702 * x)}[name]
    except KeyError:
        raise ValueError(f"unknown MLP activation {name!r}; expected "
                         "gelu|gelu_exact|relu|silu|quick_gelu") from None


class Norm(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x):
        from deepspeed_tpu.ops import layer_norm, rms_norm
        from deepspeed_tpu.ops.norms import LN_EPS, RMS_EPS
        c = self.cfg
        scale = self.param("scale", _part(nn.initializers.ones, ("embed",)),
                           (c.hidden_size,), c.param_dtype)
        if c.use_rmsnorm:
            return rms_norm(x, scale, eps=c.norm_eps or RMS_EPS)
        bias = self.param("bias", _part(nn.initializers.zeros, ("embed",)),
                          (c.hidden_size,), c.param_dtype)
        return layer_norm(x, scale, bias, eps=c.norm_eps or LN_EPS)


def attend_with_mask(q, k, v, mask, bias=None, scale=None):
    """Attention with an explicit boolean mask [B, Tq, S] — the KV-cache /
    padded-prefill path (reference: masked softmax in
    csrc/transformer/inference/csrc/softmax.cu).  Delegates to the ops layer."""
    from deepspeed_tpu import ops
    return ops.causal_attention(q, k, v, causal=False, mask=mask, bias=bias,
                                scale=scale)


def causal_attend(q, k, v, probs_dropout=None):
    """Plain causal softmax attention on [B, T, N, D] (the "local attention" in
    reference sequence/layer.py terms) — the XLA reference body lives in the ops
    registry; this thin alias keeps the Ulysses local-attention signature."""
    from deepspeed_tpu import ops
    return ops.causal_attention(q, k, v, dropout_fn=probs_dropout, impl="xla")


class Attention(nn.Module):
    cfg: GPTConfig
    mesh: Optional[object] = None

    @nn.compact
    def __call__(self, x, positions, deterministic: bool,
                 use_cache: bool = False, kv_mask=None, start_index=0,
                 kv_positions=None, window=None, fused_ok: bool = False):
        c = self.cfg
        B, T, H = x.shape
        nh, nkv, hd = c.num_heads, c.kv_heads, c.head_dim
        if c.act_quant_bits:
            from deepspeed_tpu.compression.pruning import quant_act
            x = quant_act(x, c.act_quant_bits)

        wq = self.param("wq", _part(_kernel_init(), ("embed", "heads", "kv")),
                        (H, nh, hd), c.param_dtype)
        wk = self.param("wk", _part(_kernel_init(), ("embed", "heads", "kv")),
                        (H, nkv, hd), c.param_dtype)
        wv = self.param("wv", _part(_kernel_init(), ("embed", "heads", "kv")),
                        (H, nkv, hd), c.param_dtype)
        wo = self.param("wo", _part(_kernel_init(), ("heads", "kv", "embed")),
                        (nh, hd, H), c.param_dtype)
        bo = (self.param("bo", _part(nn.initializers.zeros, ("embed",)),
                         (H,), c.param_dtype)
              if c.attn_out_bias else None)

        cm_fused = _collective_matmul_active(c, self.mesh, T, nh * hd,
                                             use_cache=use_cache)

        def out_proj(o):
            if cm_fused:
                # row-parallel over tp-sharded heads: the output all-reduce
                # decomposed into ring chunk matmuls + neighbor hops
                # (ops/collective_matmul.py row_parallel_matmul)
                from deepspeed_tpu.ops import collective_matmul as cm_ops
                Bo, To = o.shape[0], o.shape[1]
                y = cm_ops.row_parallel_matmul(
                    o.reshape(Bo, To, nh * hd),
                    wo.astype(x.dtype).reshape(nh * hd, H), self.mesh)
            else:
                y = jnp.einsum("btnd,ndh->bth", o, wo.astype(x.dtype))
            return y if bo is None else y + bo.astype(x.dtype)

        q = jnp.einsum("bth,hnd->btnd", x, wq.astype(x.dtype))
        k = jnp.einsum("bth,hnd->btnd", x, wk.astype(x.dtype))
        v = jnp.einsum("bth,hnd->btnd", x, wv.astype(x.dtype))
        if c.qkv_bias:
            q = q + self.param("bq", _part(nn.initializers.zeros,
                                           ("heads", "kv")),
                               (nh, hd), c.param_dtype).astype(x.dtype)
            k = k + self.param("bk", _part(nn.initializers.zeros,
                                           ("heads", "kv")),
                               (nkv, hd), c.param_dtype).astype(x.dtype)
            v = v + self.param("bv", _part(nn.initializers.zeros,
                                           ("heads", "kv")),
                               (nkv, hd), c.param_dtype).astype(x.dtype)

        if c.use_rope:
            q, k = rope(q, k, positions, hd, base=c.rope_theta,
                        rope_pct=c.rope_pct, scaling=c.rope_scaling)

        def alibi_bias(key_pos):
            """[.., S] key positions → [.., nh, 1, S] logit bias.  Key-
            position-only form: softmax is invariant to the per-row
            -slope·qpos constant, so slope·kpos ≡ slope·(kpos−qpos)
            (reference bloom build_alibi_tensor)."""
            if not c.use_alibi:
                return None
            s = jnp.asarray(alibi_slopes(nh, hd, c.alibi_prescale))
            return (s[:, None, None]
                    * key_pos[..., None, None, :].astype(jnp.float32))

        if use_cache:
            # static KV cache in a flax "cache" collection (reference:
            # inference_context.h KV workspace; flax decode-cache idiom).
            S = c.max_seq_len
            ck = self.variable("cache", "cached_key",
                               jnp.zeros, (B, S, nkv, hd), x.dtype)
            cv = self.variable("cache", "cached_value",
                               jnp.zeros, (B, S, nkv, hd), x.dtype)
            start = jnp.asarray(start_index, jnp.int32)
            ck.value = jax.lax.dynamic_update_slice(ck.value, k,
                                                    (0, start, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(cv.value, v,
                                                    (0, start, 0, 0))
            # causal over LOGICAL positions: with left-padded prompts the cache
            # slot index differs from the token's position, so the engine passes
            # per-slot kv_positions; default (no padding) slot == position.
            if kv_positions is None:
                kp2 = jnp.arange(S)[None, :]                 # [1, S]
            else:
                kp2 = kv_positions                           # [B, S]
            kvpos = kp2[:, None, :]                          # [B|1, 1, S]
            mask = kvpos <= positions[:, :, None]            # causal, absolute
            if window is not None:
                # sliding window over LOGICAL positions (mistral/gpt-neo
                # local attention): key within the last `window` positions
                mask = mask & (kvpos > positions[:, :, None] - window)
            if kv_mask is not None:
                mask = mask & kv_mask[:, None, :].astype(bool)
            out = attend_with_mask(q, ck.value, cv.value, mask,
                                   bias=alibi_bias(kp2), scale=c.attn_scale)
            return out_proj(out)

        sp_active = (c.sequence_parallel and self.mesh is not None
                     and self.mesh.shape["sp"] > 1)
        if c.use_alibi and sp_active:
            raise ValueError("alibi + sequence parallelism is not wired "
                             "(the a2a/ring paths carry no logit bias)")
        if window is not None and sp_active:
            raise ValueError("sliding-window attention + sequence "
                             "parallelism is not wired")
        if c.attn_scale is not None and sp_active:
            raise ValueError("custom attn_scale + sequence parallelism is "
                             "not wired (the a2a/ring paths use the default "
                             "1/sqrt(head_dim) scale)")
        if sp_active:
            # sequence parallelism: Ulysses (seq→head all-to-all swap around
            # local attention) or ring (KV blocks rotate over neighbor links;
            # no head-divisibility constraint — sequence/ring.py).  Dropout
            # falls on the attention *output* here (rng plumbing inside
            # shard_map isn't worth it); local path keeps standard
            # prob-dropout.
            from deepspeed_tpu import ops
            if c.sp_impl == "ring":
                from deepspeed_tpu.sequence import ring_attention
                out = ring_attention(
                    self.mesh, q, k, v,
                    layout=("zigzag" if c.sp_ring_layout == "native"
                            else "contiguous"),
                    inner=c.sp_ring_inner)
            elif c.sp_impl != "ulysses":
                raise ValueError(f"unknown sp_impl {c.sp_impl!r}; expected "
                                 f"'ulysses' or 'ring'")
            else:
                from deepspeed_tpu.sequence import ulysses_attention
                local_attn = lambda q_, k_, v_: ops.causal_attention(  # noqa: E731,E501
                    q_, k_, v_, impl=c.attn_impl)
                out = ulysses_attention(local_attn, self.mesh, q, k, v)
            if c.dropout > 0 and not deterministic:
                out = nn.Dropout(rate=c.dropout)(out, deterministic=False)
        else:
            from deepspeed_tpu import ops
            pdrop = None
            if c.dropout > 0 and not deterministic:
                pdrop = lambda p: nn.Dropout(rate=c.dropout)(  # noqa: E731
                    p, deterministic=False)
            if fused_ok and (window is not None or c.use_alibi):
                # canonical positions (query t at position t): window/alibi go
                # in FIRST-CLASS so the Pallas kernel handles them in-kernel
                # (VERDICT r2 item 3 — no more masked-dense fallback for
                # bloom/falcon-rw/mistral/qwen2/gpt-neo training)
                slopes = (jnp.asarray(alibi_slopes(nh, hd, c.alibi_prescale))
                          if c.use_alibi else None)
                out = ops.causal_attention(q, k, v, causal=True,
                                           window=window,
                                           alibi_slopes=slopes,
                                           dropout_fn=pdrop,
                                           scale=c.attn_scale,
                                           impl=c.attn_impl)
            elif window is not None:
                # causal ∧ within-window, over absolute positions
                rel = positions[:, :, None] - positions[:, None, :]
                wmask = (rel >= 0) & (rel < window)
                out = ops.causal_attention(q, k, v, causal=False, mask=wmask,
                                           dropout_fn=pdrop,
                                           bias=alibi_bias(positions),
                                           scale=c.attn_scale,
                                           impl=c.attn_impl)
            else:
                out = ops.causal_attention(q, k, v, dropout_fn=pdrop,
                                           bias=alibi_bias(positions),
                                           scale=c.attn_scale,
                                           impl=c.attn_impl)
        return out_proj(out)


class MLP(nn.Module):
    cfg: GPTConfig
    mesh: Optional[object] = None

    @nn.compact
    def __call__(self, x, deterministic: bool, use_cache: bool = False):
        c = self.cfg
        if c.act_quant_bits:
            from deepspeed_tpu.compression.pruning import quant_act
            x = quant_act(x, c.act_quant_bits)
        H, M = c.hidden_size, c.mlp_dim
        wi = self.param("wi", _part(_kernel_init(), ("embed", "mlp")),
                        (H, M), c.param_dtype)
        wo = self.param("wo", _part(_kernel_init(), ("mlp", "embed")),
                        (M, H), c.param_dtype)
        h = x @ wi.astype(x.dtype)
        if c.mlp_bias:
            h = h + self.param("bi", _part(nn.initializers.zeros, ("mlp",)),
                               (M,), c.param_dtype).astype(x.dtype)
        if c.gated_mlp:
            wg = self.param("wg", _part(_kernel_init(), ("embed", "mlp")),
                            (H, M), c.param_dtype)
            h = mlp_activation(c.gate_act)(x @ wg.astype(x.dtype)) * h
        else:
            h = mlp_activation(c.activation)(h)
        if c.dropout > 0 and not deterministic:
            h = nn.Dropout(rate=c.dropout)(h, deterministic=False)
        if _collective_matmul_active(c, self.mesh, x.shape[1], M,
                                     use_cache=use_cache):
            # row-parallel down-projection: the tp all-reduce decomposed
            # into a ring of chunk matmuls + neighbor hops
            from deepspeed_tpu.ops import collective_matmul as cm_ops
            y = cm_ops.row_parallel_matmul(h, wo.astype(x.dtype), self.mesh)
        else:
            y = h @ wo.astype(x.dtype)
        if c.mlp_bias:
            y = y + self.param("bo", _part(nn.initializers.zeros, ("embed",)),
                               (H,), c.param_dtype).astype(x.dtype)
        return y


class Block(nn.Module):
    cfg: GPTConfig
    is_moe: bool = False
    mesh: Optional[object] = None

    @nn.compact
    def __call__(self, x, positions, deterministic: bool,
                 use_cache: bool = False, kv_mask=None, start_index=0,
                 kv_positions=None, pld_keep=None, window=None,
                 fused_ok: bool = False):
        c = self.cfg

        def pld_mask():
            # progressive layer drop (runtime/progressive_layer_drop.py):
            # one Bernoulli per sublayer per step, shared across the batch;
            # None = gate inactive (eval / cache / disabled)
            if pld_keep is None or deterministic or use_cache:
                return None
            return jax.random.bernoulli(self.make_rng("dropout"), pld_keep)

        def pld_gate(delta):
            m = pld_mask()
            if m is None:
                return delta
            # inverted scaling (PLD paper Alg. 1): kept branches divide by p
            # so train-time expectation matches the full-depth eval forward
            return delta * (m.astype(delta.dtype)
                            / jnp.asarray(pld_keep, delta.dtype))

        if c.parallel_block:
            # falcon/phi-style parallel residual: attention and MLP both read
            # the SAME residual input (one shared input norm, or falcon-40b's
            # ln_attn + ln_mlp pair) and their outputs sum into one residual
            # add (reference inference/v2/model_implementations/falcon,
            # module_inject/containers/ — parallel_attn semantics).
            if self.is_moe:
                raise ValueError("parallel_block + MoE is not a supported "
                                 "architecture combination")
            h_attn = Norm(c)(x)                       # Norm_0
            h_mlp = Norm(c)(x) if c.parallel_norms == 2 else h_attn  # Norm_1
            a = Attention(c, mesh=self.mesh)(h_attn, positions, deterministic,
                                             use_cache, kv_mask, start_index,
                                             kv_positions, window=window,
                                             fused_ok=fused_ok)
            return (x + pld_gate(a)
                    + pld_gate(MLP(c, mesh=self.mesh)(h_mlp, deterministic,
                                                      use_cache=use_cache)),
                    jnp.float32(0.0))
        x = x + pld_gate(
            Attention(c, mesh=self.mesh)(Norm(c)(x), positions,
                                         deterministic, use_cache,
                                         kv_mask, start_index,
                                         kv_positions, window=window,
                                         fused_ok=fused_ok))
        if self.is_moe:
            from deepspeed_tpu.moe import MoE
            rng = (self.make_rng("dropout")
                   if self.has_rng("dropout") else None)
            moe_out, aux = MoE(hidden_size=c.hidden_size,
                               num_experts=c.num_experts, k=c.moe_k,
                               capacity_factor=c.moe_capacity_factor,
                               mlp_ratio=c.mlp_ratio, mlp_dim=c.mlp_dim,
                               mesh=self.mesh,
                               param_dtype=c.param_dtype,
                               dropless=c.moe_dropless,
                               gated=c.gated_mlp,
                               wire_bits=c.moe_wire_bits,
                               wire_block=c.moe_wire_block,
                               hierarchical=c.moe_hierarchical,
                               num_chunks=c.moe_num_chunks,
                               name="moe")(Norm(c)(x), rng, deterministic)
            m = pld_mask()
            if m is not None:     # one keep gates BOTH the output and the
                scale = m.astype(moe_out.dtype) / jnp.asarray(
                    pld_keep, moe_out.dtype)
                moe_out = moe_out * scale
                aux = aux * scale.astype(aux.dtype)  # dropped ffn: no LB loss
            x = x + moe_out
        else:
            aux = jnp.float32(0.0)
            x = x + pld_gate(MLP(c, mesh=self.mesh)(Norm(c)(x),
                                                    deterministic,
                                                    use_cache=use_cache))
        return x, aux


class GPTBackbone(nn.Module):
    """Token ids → final hidden states (used by both the LM loss wrapper and,
    later, the inference engine)."""

    cfg: GPTConfig
    mesh: Optional[object] = None

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True,
                 positions=None, use_cache: bool = False, kv_mask=None,
                 start_index=0, kv_positions=None, ltd_idx=None,
                 pld_theta=None):
        """positions: [B, T] absolute positions (default arange — the training
        path); the inference engine passes per-row positions for left-padded
        prompts and incremental decode.  kv_mask: [B, max_seq_len] validity of
        cache slots.  start_index: scalar cache write offset.  ltd_idx:
        [n_ltd_layers, B, keep] sorted random-LTD keep indices (data_pipeline/
        random_ltd.py) — layers in cfg.random_ltd_layer_ids run on the kept
        subset only, dropped tokens skip them (reference data_routing/
        basic_layer.py)."""
        c = self.cfg
        B, T = input_ids.shape
        emb = self.param("wte", _part(_kernel_init(), ("vocab", "embed")),
                         (c.vocab_size, c.hidden_size), c.param_dtype)
        x = _gather_table(emb.astype(c.dtype), self.mesh)[input_ids]
        if c.embed_scale:    # gemma √H normalizer (unembed stays unscaled)
            x = x * jnp.asarray(c.embed_scale, c.dtype)
        x = _pin_activations(x, self.mesh, c.sequence_parallel)
        if c.embed_norm:     # bloom word_embeddings_layernorm
            x = Norm(c, name="embed_norm")(x)
        canonical_pos = positions is None   # query t sits at position t: the
        # training fast path where window/alibi can fuse into the flash kernel
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        if not c.use_rope and not c.use_alibi:
            pos_emb = self.param("wpe", _part(_kernel_init(), (None, "embed")),
                                 (c.max_seq_len, c.hidden_size), c.param_dtype)
            x = x + _gather_table(pos_emb.astype(c.dtype), self.mesh,
                                  vocab_axis=None)[positions]
            x = _pin_activations(x, self.mesh, c.sequence_parallel)
        if c.dropout > 0 and not deterministic:
            x = nn.Dropout(rate=c.dropout)(x, deterministic=False)

        block_cls = Block
        if c.remat and not use_cache:
            # static: deterministic, use_cache, window, fused_ok (the last two
            # select the fused attention path at trace time)
            block_cls = nn.remat(Block, static_argnums=(3, 4, 9, 10),
                                 policy=jax.checkpoint_policies.nothing_saveable)
        ltd_layers = tuple(c.random_ltd_layer_ids or ())
        aux_total = jnp.float32(0.0)
        for i in range(c.num_layers):
            # reference examples put MoE on every other layer
            is_moe = (c.num_experts > 0 and i % c.moe_every == c.moe_every - 1)
            block = block_cls(c, is_moe, self.mesh, name=f"block_{i}")
            keep = None
            if pld_theta is not None:
                from deepspeed_tpu.runtime.progressive_layer_drop import \
                    layer_keep_prob
                keep = layer_keep_prob(i, c.num_layers, pld_theta)
            win = c.window_for_layer(i)
            if (ltd_idx is not None and i in ltd_layers and not use_cache):
                from deepspeed_tpu.data_pipeline.random_ltd import \
                    apply_random_ltd
                idx = ltd_idx[ltd_layers.index(i)]
                x, aux = apply_random_ltd(
                    # args positional: remat's static_argnums (9=window,
                    # 10=fused_ok) must be within the positional arg list;
                    # gathered positions are non-canonical → fused_ok False
                    lambda xk, pk: block(xk, pk, deterministic, False,
                                         None, 0, None, keep, win, False),
                    x, positions, idx)
            else:
                x, aux = block(x, positions, deterministic,
                               use_cache, kv_mask, start_index, kv_positions,
                               keep, win, canonical_pos and not use_cache)
            aux_total = aux_total + aux
        x = Norm(c, name="final_norm")(x)
        return x, emb, aux_total


def shift_labels(batch, input_ids):
    """(labels, mask) for next-token LM, honoring explicit labels/loss_mask and
    the -100-style ignore convention (labels < 0)."""
    labels = batch.get("labels")
    if labels is None:  # next-token LM
        labels = jnp.pad(input_ids[:, 1:], ((0, 0), (0, 1)))
        mask = jnp.ones_like(labels, dtype=jnp.float32).at[:, -1].set(0.0)
    else:
        mask = batch.get("loss_mask", jnp.ones_like(labels, dtype=jnp.float32))
        mask = mask.astype(jnp.float32) * (labels >= 0)
        labels = jnp.maximum(labels, 0)
    return labels, mask


class GPT(nn.Module):
    """LM-loss wrapper satisfying the engine's model contract.

    ``cfg.loss_chunk > 0`` computes the unembed+CE in rematerialized chunks
    (ops/cross_entropy.py) so the fp32 [B, T, V] logits never hit HBM; 0 keeps
    the one-shot logits path.
    """

    cfg: GPTConfig
    mesh: Optional[object] = None

    # subclass hook: chunk size actually used (0 = one-shot)
    def _loss_chunk(self) -> int:
        return self.cfg.loss_chunk

    @nn.compact
    def __call__(self, batch, deterministic: bool = False):
        c = self.cfg
        input_ids = batch["input_ids"]
        ltd = batch.get("random_ltd_idx")       # [B, n_ltd, keep] host layout
        if ltd is not None:
            ltd = jnp.moveaxis(jnp.asarray(ltd), 1, 0)   # → [n_ltd, B, keep]
        positions = labels = mask = None
        if c.sp_ring_layout not in ("drop_in", "native"):
            raise ValueError(f"sp_ring_layout must be drop_in|native, got "
                             f"{c.sp_ring_layout!r}")
        sp = (self.mesh.shape["sp"]
              if c.sequence_parallel and self.mesh is not None else 1)
        if c.sequence_parallel and c.sp_ring_layout == "native" and sp > 1:
            # layout-native zig-zag ring (sequence/ring.py layout=): shift
            # labels in contiguous order, then permute ids + labels + mask +
            # positions ONCE — token ids are ~H·dtype_bytes/4 cheaper to
            # reshuffle than activations, every position-wise op is layout-
            # blind, the masked-mean LM loss is permutation-invariant, and
            # the ring hops become the only per-layer sp traffic
            if c.sp_impl != "ring":
                raise ValueError("sp_ring_layout='native' requires "
                                 "sp_impl='ring' (ulysses is layout-free)")
            if ltd is not None:
                raise ValueError("random-LTD + sp_ring_layout='native' is "
                                 "not wired (the gathered subsequence breaks "
                                 "the zig-zag placement)")
            from deepspeed_tpu.sequence import zigzag_order
            idx, _ = zigzag_order(input_ids.shape[1], sp)  # raises on T%2sp
            labels, mask = shift_labels(batch, input_ids)
            input_ids = jnp.take(input_ids, idx, axis=1)
            labels = jnp.take(labels, idx, axis=1)
            mask = jnp.take(mask, idx, axis=1)
            positions = jnp.broadcast_to(idx, input_ids.shape)
        x, emb, moe_aux = GPTBackbone(c, self.mesh,
                                      name="backbone")(input_ids,
                                                       deterministic,
                                                       positions=positions,
                                                       ltd_idx=ltd,
                                                       pld_theta=batch.get(
                                                           "pld_theta"))
        if c.tie_embeddings:
            unembed = emb.astype(x.dtype).T                # [H, V]
        else:
            unembed = self.param("lm_head",
                                 _part(_kernel_init(), ("embed", "vocab")),
                                 (c.hidden_size, c.vocab_size),
                                 c.param_dtype).astype(x.dtype)
        if labels is None:
            labels, mask = shift_labels(batch, input_ids)
        lm_bias = (self.param("lm_head_bias",
                              _part(nn.initializers.zeros, ("vocab",)),
                              (c.vocab_size,), c.param_dtype)
                   if c.unembed_bias else None)
        from deepspeed_tpu.ops import lm_cross_entropy
        loss = lm_cross_entropy(x, unembed, labels, mask,
                                chunk_size=self._loss_chunk() or None,
                                bias=lm_bias)
        if c.num_experts > 0:
            loss = loss + c.moe_aux_coef * moe_aux
        return loss


class GPTLogits(nn.Module):
    """Token ids → logits, with optional KV cache — the inference-engine view of
    the same parameter tree as ``GPT`` (backbone + tied/untied unembed), so a
    training checkpoint loads directly (reference: the injected inference module
    reusing the HF layer weights, module_inject/replace_module.py:183)."""

    cfg: GPTConfig
    mesh: Optional[object] = None

    @nn.compact
    def __call__(self, input_ids, positions=None, kv_mask=None,
                 use_cache: bool = False, start_index=0, kv_positions=None,
                 deterministic: bool = True):
        c = self.cfg
        if (c.sequence_parallel and c.sp_ring_layout == "native"
                and self.mesh is not None and self.mesh.shape["sp"] > 1):
            raise ValueError(
                "sp_ring_layout='native' is a training-layout config (the "
                "loss wrapper permutes the batch into zig-zag placement); "
                "the logits view expects contiguous rows — use 'drop_in'")
        x, emb, _ = GPTBackbone(c, self.mesh, name="backbone")(
            input_ids, deterministic, positions=positions,
            use_cache=use_cache, kv_mask=kv_mask, start_index=start_index,
            kv_positions=kv_positions)
        if c.tie_embeddings:
            unembed = emb.astype(x.dtype).T
        else:
            unembed = self.param("lm_head",
                                 _part(_kernel_init(), ("embed", "vocab")),
                                 (c.hidden_size, c.vocab_size),
                                 c.param_dtype).astype(x.dtype)
        logits = (x @ unembed).astype(jnp.float32)
        if c.unembed_bias:
            logits = logits + self.param(
                "lm_head_bias", _part(nn.initializers.zeros, ("vocab",)),
                (c.vocab_size,), c.param_dtype).astype(jnp.float32)
        return logits


class GPTChunkedLoss(GPT):
    """GPT that always chunks the unembed+CE (defaults to 512-token chunks when
    ``cfg.loss_chunk`` is unset) — batch scales past the logits OOM wall."""

    def _loss_chunk(self) -> int:
        return self.cfg.loss_chunk or 512


def count_params(cfg: GPTConfig) -> int:
    H, M, V = cfg.hidden_size, cfg.mlp_dim, cfg.vocab_size
    norms = 1 if (cfg.parallel_block and cfg.parallel_norms == 1) else 2
    per_layer = (cfg.num_heads * cfg.head_dim * H * 2          # wq, wo
                 + cfg.kv_heads * cfg.head_dim * H * 2         # wk, wv
                 + H * M * (3 if cfg.gated_mlp else 2)         # mlp
                 + H * norms * (1 if cfg.use_rmsnorm else 2))
    total = per_layer * cfg.num_layers + V * H + H
    if not cfg.use_rope and not cfg.use_alibi:
        total += cfg.max_seq_len * H
    if cfg.embed_norm:
        total += H * (1 if cfg.use_rmsnorm else 2)
    if not cfg.tie_embeddings:
        total += V * H
    if cfg.unembed_bias:
        total += V
    return total
