"""Stable-diffusion UNet + VAE — the image leg of the SD serving stack.

Reference parity: ``module_inject/containers/unet.py`` and ``vae.py`` wrap
diffusers' ``UNet2DConditionModel`` / ``AutoencoderKL`` with optimized
attention.  ``diffusers`` is not in this image, so the modules themselves are
re-implemented here TPU-first and their weights import directly from
diffusers checkpoints (``checkpoint/diffusion.py``).

TPU-native design:
- **NHWC layout end to end** (channels-last is the TPU conv layout; the
  NCHW↔NHWC transposes happen once at the engine boundary), convs in HWIO.
- params are a PLAIN NESTED TREE mirroring the diffusers state-dict paths
  (``down_blocks.0.resnets.1.conv1 → {kernel, bias}``) and the forward is a
  pure function over it — the same serving-model idiom as
  ``inference/v2/model.py``, so checkpoint import is a name walk, not module
  surgery.
- attention (self, cross, and the VAE's single-head spatial attention) runs
  through ``ops.causal_attention(causal=False)`` — the one attention body in
  the codebase, which the registry maps onto the Pallas flash kernel when
  shapes allow (this is the reference containers' "replace attention with
  the optimized kernel" role).

Supported architecture family: the SD 1.x/2.x UNet (CrossAttnDownBlock2D /
DownBlock2D towers, one mid block, mirrored up path) and the SD
AutoencoderKL.  ``num_attention_heads`` inherits diffusers' legacy quirk
(``attention_head_dim`` IS the head count for this family).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ------------------------------------------------------------------ configs

@dataclasses.dataclass(frozen=True)
class UNetConfig:
    """Mirrors the consumed subset of diffusers UNet2DConditionModel
    config.json (SD 1.x/2.x family)."""

    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    attention_head_dim: Any = 8          # int or per-block list
    down_block_types: Tuple[str, ...] = (
        "CrossAttnDownBlock2D", "CrossAttnDownBlock2D",
        "CrossAttnDownBlock2D", "DownBlock2D")
    up_block_types: Tuple[str, ...] = (
        "UpBlock2D", "CrossAttnUpBlock2D", "CrossAttnUpBlock2D",
        "CrossAttnUpBlock2D")
    norm_num_groups: int = 32
    norm_eps: float = 1e-5
    use_linear_projection: bool = False   # SD2.x: True
    flip_sin_to_cos: bool = True
    freq_shift: int = 0
    dtype: Any = jnp.float32

    def heads_for_block(self, i: int) -> int:
        ahd = self.attention_head_dim
        return int(ahd[i]) if isinstance(ahd, (list, tuple)) else int(ahd)

    @classmethod
    def from_hf(cls, hf: Dict[str, Any], dtype=jnp.float32) -> "UNetConfig":
        # semantic keys this forward does NOT implement: accepting a config
        # that sets them (SDXL's addition embeddings, class conditioning,
        # deeper transformer stacks, ...) would silently serve wrong images
        unsupported = {
            "addition_embed_type": None, "class_embed_type": None,
            "encoder_hid_dim": None, "time_embedding_type": "positional",
            "class_embeddings_concat": False, "time_cond_proj_dim": None,
            "conv_in_kernel": 3, "conv_out_kernel": 3,
            "resnet_time_scale_shift": "default",
            "dual_cross_attention": False, "mid_block_only_cross_attention":
            None, "only_cross_attention": False}
        for key, default in unsupported.items():
            if key in hf and hf[key] not in (default, None) \
                    and not (default is False and hf[key] is False):
                raise NotImplementedError(
                    f"UNet config sets {key}={hf[key]!r} — not implemented "
                    f"(SD 1.x/2.x family only); serving it would silently "
                    f"produce wrong images")
        tlpb = hf.get("transformer_layers_per_block", 1)
        if tlpb not in (1, [1] * 16) and set(np.atleast_1d(tlpb).tolist()) \
                != {1}:
            raise NotImplementedError(
                f"transformer_layers_per_block={tlpb} — only depth-1 "
                f"transformer stacks (SD 1.x/2.x) are implemented")
        if hf.get("num_attention_heads") is not None:
            raise NotImplementedError(
                "num_attention_heads set explicitly — this family derives "
                "heads from attention_head_dim (the diffusers legacy "
                "convention); explicit values are SD3/SDXL-era configs")
        known = {
            "in_channels", "out_channels", "block_out_channels",
            "layers_per_block", "cross_attention_dim", "attention_head_dim",
            "down_block_types", "up_block_types", "norm_num_groups",
            "norm_eps", "use_linear_projection", "flip_sin_to_cos",
            "freq_shift"}
        kw = {k: (tuple(v) if isinstance(v, list) and k != "attention_head_dim"
                  else v)
              for k, v in hf.items() if k in known}
        for t in kw.get("down_block_types", ()) + kw.get("up_block_types", ()):
            if t not in ("CrossAttnDownBlock2D", "DownBlock2D",
                         "CrossAttnUpBlock2D", "UpBlock2D"):
                raise NotImplementedError(
                    f"unsupported UNet block type {t!r} (SD 1.x/2.x family "
                    f"only — serving a checkpoint with {t} would silently "
                    f"produce wrong images)")
        return cls(dtype=dtype, **kw)

    @classmethod
    def tiny(cls, **kw):
        """2-level config for tests."""
        kw.setdefault("block_out_channels", (32, 64))
        kw.setdefault("down_block_types",
                      ("CrossAttnDownBlock2D", "DownBlock2D"))
        kw.setdefault("up_block_types",
                      ("UpBlock2D", "CrossAttnUpBlock2D"))
        kw.setdefault("layers_per_block", 1)
        kw.setdefault("cross_attention_dim", 32)
        kw.setdefault("attention_head_dim", 4)
        kw.setdefault("norm_num_groups", 8)
        return cls(**kw)


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    """Mirrors diffusers AutoencoderKL config.json."""

    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    scaling_factor: float = 0.18215
    dtype: Any = jnp.float32

    @classmethod
    def from_hf(cls, hf: Dict[str, Any], dtype=jnp.float32) -> "VAEConfig":
        known = {"in_channels", "out_channels", "latent_channels",
                 "block_out_channels", "layers_per_block", "norm_num_groups",
                 "scaling_factor"}
        kw = {k: (tuple(v) if isinstance(v, list) else v)
              for k, v in hf.items() if k in known}
        return cls(dtype=dtype, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("block_out_channels", (16, 32))
        kw.setdefault("layers_per_block", 1)
        kw.setdefault("norm_num_groups", 4)
        kw.setdefault("latent_channels", 4)
        return cls(**kw)


# --------------------------------------------------------------- primitives

def conv2d(p, x, *, stride: int = 1, padding: int = 1):
    """NHWC conv with HWIO kernel + bias."""
    y = jax.lax.conv_general_dilated(
        x, p["kernel"].astype(x.dtype),
        window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y + p["bias"].astype(x.dtype)


def linear(p, x):
    y = x @ p["kernel"].astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def group_norm(p, x, groups: int, eps: float):
    """GroupNorm over NHWC (stats per group of channels, fp32)."""
    B, H, W, C = x.shape
    xg = x.astype(jnp.float32).reshape(B, H, W, groups, C // groups)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = xg.reshape(B, H, W, C)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def timestep_embedding(timesteps, dim: int, *, flip_sin_to_cos: bool,
                       freq_shift: float, max_period: float = 10000.0):
    """Sinusoidal timestep embedding (diffusers embeddings.py
    get_timestep_embedding)."""
    half = dim // 2
    exponent = -math.log(max_period) * jnp.arange(half, dtype=jnp.float32)
    exponent = exponent / (half - freq_shift)
    emb = jnp.exp(exponent)[None, :] * timesteps.astype(jnp.float32)[:, None]
    sin, cos = jnp.sin(emb), jnp.cos(emb)
    out = (jnp.concatenate([cos, sin], -1) if flip_sin_to_cos
           else jnp.concatenate([sin, cos], -1))
    if dim % 2:
        out = jnp.pad(out, ((0, 0), (0, 1)))
    return out


def _attention(q, k, v, heads: int):
    """Multi-head attention over token sequences via the ops registry body
    (the reference containers' optimized-attention swap)."""
    from deepspeed_tpu import ops
    B, Tq, C = q.shape
    S = k.shape[1]
    hd = C // heads
    q = q.reshape(B, Tq, heads, hd)
    k = k.reshape(B, S, heads, hd)
    v = v.reshape(B, S, heads, hd)
    o = ops.causal_attention(q, k, v, causal=False)
    return o.reshape(B, Tq, C)


def cross_attention(p, x, context, heads: int):
    """diffusers Attention (to_q/to_k/to_v/to_out.0) on [B, T, C] tokens."""
    q = linear(p["to_q"], x)
    k = linear(p["to_k"], context)
    v = linear(p["to_v"], context)
    return linear(p["to_out"], _attention(q, k, v, heads))


def resnet_block(p, x, temb, cfg_groups: int, eps: float):
    """diffusers ResnetBlock2D: GN→silu→conv1 (+temb proj) →GN→silu→conv2 +
    shortcut."""
    h = jax.nn.silu(group_norm(p["norm1"], x, cfg_groups, eps))
    h = conv2d(p["conv1"], h)
    if temb is not None and "time_emb_proj" in p:
        t = linear(p["time_emb_proj"], jax.nn.silu(temb))
        h = h + t[:, None, None, :].astype(h.dtype)
    h = jax.nn.silu(group_norm(p["norm2"], h, cfg_groups, eps))
    h = conv2d(p["conv2"], h)
    if "conv_shortcut" in p:
        x = conv2d(p["conv_shortcut"], x, padding=0)
    return x + h


def transformer_block(p, x, context, heads: int):
    """diffusers BasicTransformerBlock: LN→self-attn, LN→cross-attn,
    LN→GEGLU ff — all residual."""
    def ln(q, y):
        m = y.astype(jnp.float32)
        m = (m - m.mean(-1, keepdims=True)) * jax.lax.rsqrt(
            m.var(-1, keepdims=True) + 1e-5)
        return (m * q["scale"].astype(jnp.float32)
                + q["bias"].astype(jnp.float32)).astype(y.dtype)

    x = x + cross_attention(p["attn1"], ln(p["norm1"], x), ln(p["norm1"], x),
                            heads)
    x = x + cross_attention(p["attn2"], ln(p["norm2"], x), context, heads)
    h = linear(p["ff_proj"], ln(p["norm3"], x))
    h, gate = jnp.split(h, 2, axis=-1)
    h = h * jax.nn.gelu(gate)
    return x + linear(p["ff_out"], h)


def spatial_transformer(p, x, context, heads: int, groups: int, eps: float,
                        use_linear: bool):
    """diffusers Transformer2DModel: GN → proj_in → transformer blocks over
    HW tokens → proj_out, residual."""
    B, H, W, C = x.shape
    res = x
    h = group_norm(p["norm"], x, groups, eps)
    if use_linear:
        h = linear(p["proj_in"], h.reshape(B, H * W, C))
    else:
        h = conv2d(p["proj_in"], h, padding=0).reshape(B, H * W, C)
    for blk in p["transformer_blocks"]:
        h = transformer_block(blk, h, context, heads)
    if use_linear:
        h = linear(p["proj_out"], h).reshape(B, H, W, C)
    else:
        h = conv2d(p["proj_out"], h.reshape(B, H, W, C), padding=0)
    return h + res


def downsample(p, x):
    return conv2d(p, x, stride=2)


def upsample(p, x):
    B, H, W, C = x.shape
    x = jax.image.resize(x, (B, 2 * H, 2 * W, C), method="nearest")
    return conv2d(p, x)


# ------------------------------------------------------------------- UNet

def unet_forward(params, sample, timesteps, encoder_hidden_states,
                 cfg: UNetConfig):
    """One denoising step: NHWC latents [B, H, W, Cin], timesteps [B],
    text context [B, T, cross_attention_dim] → noise prediction
    [B, H, W, Cout]."""
    dtype = cfg.dtype
    x = sample.astype(dtype)
    ctx = encoder_hidden_states.astype(dtype)
    groups, eps = cfg.norm_num_groups, cfg.norm_eps

    # time embedding: sinusoid(c0) → linear → silu → linear
    temb = timestep_embedding(jnp.atleast_1d(timesteps),
                              cfg.block_out_channels[0],
                              flip_sin_to_cos=cfg.flip_sin_to_cos,
                              freq_shift=cfg.freq_shift)
    temb = jnp.broadcast_to(temb, (x.shape[0], temb.shape[-1])).astype(dtype)
    temb = linear(params["time_embedding"]["linear_2"],
                  jax.nn.silu(linear(params["time_embedding"]["linear_1"],
                                     temb)))

    x = conv2d(params["conv_in"], x)
    skips = [x]

    for i, btype in enumerate(cfg.down_block_types):
        bp = params["down_blocks"][i]
        heads = cfg.heads_for_block(i)
        for j in range(cfg.layers_per_block):
            x = resnet_block(bp["resnets"][j], x, temb, groups, eps)
            if btype == "CrossAttnDownBlock2D":
                x = spatial_transformer(bp["attentions"][j], x, ctx, heads,
                                        groups, eps,
                                        cfg.use_linear_projection)
            skips.append(x)
        if "downsampler" in bp:            # every block but the last
            x = downsample(bp["downsampler"], x)
            skips.append(x)

    mp = params["mid_block"]
    heads_mid = cfg.heads_for_block(len(cfg.block_out_channels) - 1)
    x = resnet_block(mp["resnets"][0], x, temb, groups, eps)
    x = spatial_transformer(mp["attentions"][0], x, ctx, heads_mid, groups,
                            eps, cfg.use_linear_projection)
    x = resnet_block(mp["resnets"][1], x, temb, groups, eps)

    for i, btype in enumerate(cfg.up_block_types):
        bp = params["up_blocks"][i]
        heads = cfg.heads_for_block(len(cfg.block_out_channels) - 1 - i)
        for j in range(cfg.layers_per_block + 1):
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = resnet_block(bp["resnets"][j], x, temb, groups, eps)
            if btype == "CrossAttnUpBlock2D":
                x = spatial_transformer(bp["attentions"][j], x, ctx, heads,
                                        groups, eps,
                                        cfg.use_linear_projection)
        if "upsampler" in bp:
            x = upsample(bp["upsampler"], x)

    x = jax.nn.silu(group_norm(params["conv_norm_out"], x, groups, eps))
    return conv2d(params["conv_out"], x)


# -------------------------------------------------------------------- VAE

def _vae_attention(p, x, groups: int, eps: float):
    """diffusers Attention inside the VAE mid block (single head over HW
    tokens)."""
    B, H, W, C = x.shape
    h = group_norm(p["group_norm"], x, groups, eps).reshape(B, H * W, C)
    q = linear(p["to_q"], h)
    k = linear(p["to_k"], h)
    v = linear(p["to_v"], h)
    o = linear(p["to_out"], _attention(q, k, v, heads=1))
    return x + o.reshape(B, H, W, C)


def vae_encode(params, image, cfg: VAEConfig, *, sample_rng=None):
    """NHWC image [B, H, W, 3] → latent [B, H/8, W/8, latent] (mode of the
    posterior unless ``sample_rng`` is given), scaled by scaling_factor."""
    p = params["encoder"]
    groups, eps = cfg.norm_num_groups, 1e-6
    x = conv2d(p["conv_in"], image.astype(cfg.dtype))
    n = len(cfg.block_out_channels)
    for i in range(n):
        bp = p["down_blocks"][i]
        for j in range(cfg.layers_per_block):
            x = resnet_block(bp["resnets"][j], x, None, groups, eps)
        if "downsampler" in bp:
            # diffusers VAE downsampler pads asymmetrically (0,1) each side
            x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
            y = jax.lax.conv_general_dilated(
                x, bp["downsampler"]["kernel"].astype(x.dtype), (2, 2),
                padding=((0, 0), (0, 0)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = y + bp["downsampler"]["bias"].astype(x.dtype)
    mp = p["mid_block"]
    x = resnet_block(mp["resnets"][0], x, None, groups, eps)
    x = _vae_attention(mp["attentions"][0], x, groups, eps)
    x = resnet_block(mp["resnets"][1], x, None, groups, eps)
    x = jax.nn.silu(group_norm(p["conv_norm_out"], x, groups, eps))
    x = conv2d(p["conv_out"], x)
    moments = conv2d(params["quant_conv"], x, padding=0)
    mean, logvar = jnp.split(moments, 2, axis=-1)
    if sample_rng is not None:
        std = jnp.exp(0.5 * jnp.clip(logvar, -30.0, 20.0))
        mean = mean + std * jax.random.normal(sample_rng, mean.shape,
                                              mean.dtype)
    return mean * cfg.scaling_factor


def vae_decode(params, latent, cfg: VAEConfig):
    """Latent [B, h, w, latent] → NHWC image [B, 8h, 8w, 3] in [-1, 1]."""
    p = params["decoder"]
    groups, eps = cfg.norm_num_groups, 1e-6
    z = latent.astype(cfg.dtype) / cfg.scaling_factor
    z = conv2d(params["post_quant_conv"], z, padding=0)
    x = conv2d(p["conv_in"], z)
    mp = p["mid_block"]
    x = resnet_block(mp["resnets"][0], x, None, groups, eps)
    x = _vae_attention(mp["attentions"][0], x, groups, eps)
    x = resnet_block(mp["resnets"][1], x, None, groups, eps)
    for i in range(len(cfg.block_out_channels)):
        bp = p["up_blocks"][i]
        for j in range(cfg.layers_per_block + 1):
            x = resnet_block(bp["resnets"][j], x, None, groups, eps)
        if "upsampler" in bp:
            x = upsample(bp["upsampler"], x)
    x = jax.nn.silu(group_norm(p["conv_norm_out"], x, groups, eps))
    return conv2d(p["conv_out"], x)


# --------------------------------------------------- random init (tests)

def _rand_conv(rng, kh, kw, cin, cout, dtype):
    k1, k2 = jax.random.split(rng)
    scale = 1.0 / math.sqrt(kh * kw * cin)
    return {"kernel": jax.random.uniform(k1, (kh, kw, cin, cout), dtype,
                                         -scale, scale),
            "bias": jax.random.uniform(k2, (cout,), dtype, -scale, scale)}


def _rand_linear(rng, cin, cout, dtype, bias=True):
    k1, k2 = jax.random.split(rng)
    scale = 1.0 / math.sqrt(cin)
    p = {"kernel": jax.random.uniform(k1, (cin, cout), dtype, -scale, scale)}
    if bias:
        p["bias"] = jax.random.uniform(k2, (cout,), dtype, -scale, scale)
    return p


def _rand_norm(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _rand_resnet(rng, cin, cout, temb_dim, dtype):
    ks = jax.random.split(rng, 4)
    p = {"norm1": _rand_norm(cin, dtype),
         "conv1": _rand_conv(ks[0], 3, 3, cin, cout, dtype),
         "norm2": _rand_norm(cout, dtype),
         "conv2": _rand_conv(ks[1], 3, 3, cout, cout, dtype)}
    if temb_dim:
        p["time_emb_proj"] = _rand_linear(ks[2], temb_dim, cout, dtype)
    if cin != cout:
        p["conv_shortcut"] = _rand_conv(ks[3], 1, 1, cin, cout, dtype)
    return p


def _rand_xf_block(rng, c, ctx_dim, dtype):
    ks = jax.random.split(rng, 8)
    attn = lambda k, kv: {"to_q": _rand_linear(k[0], c, c, dtype, False),
                          "to_k": _rand_linear(k[1], kv, c, dtype, False),
                          "to_v": _rand_linear(k[2], kv, c, dtype, False),
                          "to_out": _rand_linear(k[3], c, c, dtype)}
    return {"norm1": _rand_norm(c, dtype),
            "attn1": attn(ks[0:4], c),
            "norm2": _rand_norm(c, dtype),
            "attn2": attn(ks[4:8], ctx_dim),
            "norm3": _rand_norm(c, dtype),
            "ff_proj": _rand_linear(ks[4], c, 8 * c, dtype),
            "ff_out": _rand_linear(ks[5], 4 * c, c, dtype)}


def _rand_spatial_xf(rng, c, ctx_dim, use_linear, dtype):
    ks = jax.random.split(rng, 3)
    proj = (_rand_linear(ks[0], c, c, dtype) if use_linear
            else _rand_conv(ks[0], 1, 1, c, c, dtype))
    proj_o = (_rand_linear(ks[1], c, c, dtype) if use_linear
              else _rand_conv(ks[1], 1, 1, c, c, dtype))
    return {"norm": _rand_norm(c, dtype), "proj_in": proj,
            "transformer_blocks": [_rand_xf_block(ks[2], c, ctx_dim, dtype)],
            "proj_out": proj_o}


def init_unet_params(rng, cfg: UNetConfig):
    """Random UNet tree in the import layout (tests + from-scratch use)."""
    dtype = cfg.dtype
    ks = iter(jax.random.split(rng, 256))
    c0 = cfg.block_out_channels[0]
    temb = 4 * c0
    p: Dict[str, Any] = {
        "conv_in": _rand_conv(next(ks), 3, 3, cfg.in_channels, c0, dtype),
        "time_embedding": {"linear_1": _rand_linear(next(ks), c0, temb, dtype),
                           "linear_2": _rand_linear(next(ks), temb, temb,
                                                    dtype)},
        "down_blocks": [], "up_blocks": [],
    }
    chans = [c0]
    cin = c0
    for i, btype in enumerate(cfg.down_block_types):
        cout = cfg.block_out_channels[i]
        bp: Dict[str, Any] = {"resnets": [], "attentions": []}
        for j in range(cfg.layers_per_block):
            bp["resnets"].append(_rand_resnet(next(ks), cin, cout, temb,
                                              dtype))
            if btype == "CrossAttnDownBlock2D":
                bp["attentions"].append(_rand_spatial_xf(
                    next(ks), cout, cfg.cross_attention_dim,
                    cfg.use_linear_projection, dtype))
            cin = cout
            chans.append(cout)
        if i < len(cfg.down_block_types) - 1:
            bp["downsampler"] = _rand_conv(next(ks), 3, 3, cout, cout, dtype)
            chans.append(cout)
        if not bp["attentions"]:
            del bp["attentions"]
        p["down_blocks"].append(bp)
    cmid = cfg.block_out_channels[-1]
    p["mid_block"] = {
        "resnets": [_rand_resnet(next(ks), cmid, cmid, temb, dtype),
                    _rand_resnet(next(ks), cmid, cmid, temb, dtype)],
        "attentions": [_rand_spatial_xf(next(ks), cmid,
                                        cfg.cross_attention_dim,
                                        cfg.use_linear_projection, dtype)]}
    rev = list(reversed(cfg.block_out_channels))
    cin = cmid
    for i, btype in enumerate(cfg.up_block_types):
        cout = rev[i]
        bp = {"resnets": [], "attentions": []}
        for j in range(cfg.layers_per_block + 1):
            skip = chans.pop()
            bp["resnets"].append(_rand_resnet(next(ks), cin + skip, cout,
                                              temb, dtype))
            if btype == "CrossAttnUpBlock2D":
                bp["attentions"].append(_rand_spatial_xf(
                    next(ks), cout, cfg.cross_attention_dim,
                    cfg.use_linear_projection, dtype))
            cin = cout
        if i < len(cfg.up_block_types) - 1:
            bp["upsampler"] = _rand_conv(next(ks), 3, 3, cout, cout, dtype)
        if not bp["attentions"]:
            del bp["attentions"]
        p["up_blocks"].append(bp)
    p["conv_norm_out"] = _rand_norm(cfg.block_out_channels[0], dtype)
    p["conv_out"] = _rand_conv(next(ks), 3, 3, cfg.block_out_channels[0],
                               cfg.out_channels, dtype)
    return p


def init_vae_params(rng, cfg: VAEConfig):
    dtype = cfg.dtype
    ks = iter(jax.random.split(rng, 256))
    ch = cfg.block_out_channels

    def vae_attn(c):
        return {"group_norm": _rand_norm(c, dtype),
                "to_q": _rand_linear(next(ks), c, c, dtype),
                "to_k": _rand_linear(next(ks), c, c, dtype),
                "to_v": _rand_linear(next(ks), c, c, dtype),
                "to_out": _rand_linear(next(ks), c, c, dtype)}

    enc: Dict[str, Any] = {
        "conv_in": _rand_conv(next(ks), 3, 3, cfg.in_channels, ch[0], dtype),
        "down_blocks": []}
    cin = ch[0]
    for i, cout in enumerate(ch):
        bp = {"resnets": [_rand_resnet(next(ks),
                                       cin if j == 0 else cout, cout, 0,
                                       dtype)
                          for j in range(cfg.layers_per_block)]}
        if i < len(ch) - 1:
            bp["downsampler"] = _rand_conv(next(ks), 3, 3, cout, cout, dtype)
        enc["down_blocks"].append(bp)
        cin = cout
    enc["mid_block"] = {
        "resnets": [_rand_resnet(next(ks), ch[-1], ch[-1], 0, dtype),
                    _rand_resnet(next(ks), ch[-1], ch[-1], 0, dtype)],
        "attentions": [vae_attn(ch[-1])]}
    enc["conv_norm_out"] = _rand_norm(ch[-1], dtype)
    enc["conv_out"] = _rand_conv(next(ks), 3, 3, ch[-1],
                                 2 * cfg.latent_channels, dtype)

    dec: Dict[str, Any] = {
        "conv_in": _rand_conv(next(ks), 3, 3, cfg.latent_channels, ch[-1],
                              dtype),
        "mid_block": {
            "resnets": [_rand_resnet(next(ks), ch[-1], ch[-1], 0, dtype),
                        _rand_resnet(next(ks), ch[-1], ch[-1], 0, dtype)],
            "attentions": [vae_attn(ch[-1])]},
        "up_blocks": []}
    rev = list(reversed(ch))
    cin = ch[-1]
    for i, cout in enumerate(rev):
        bp = {"resnets": [_rand_resnet(next(ks),
                                       cin if j == 0 else cout, cout, 0,
                                       dtype)
                          for j in range(cfg.layers_per_block + 1)]}
        if i < len(rev) - 1:
            bp["upsampler"] = _rand_conv(next(ks), 3, 3, cout, cout, dtype)
        dec["up_blocks"].append(bp)
        cin = cout
    dec["conv_norm_out"] = _rand_norm(ch[0], dtype)
    dec["conv_out"] = _rand_conv(next(ks), 3, 3, ch[0], cfg.out_channels,
                                 dtype)
    return {"encoder": enc, "decoder": dec,
            "quant_conv": _rand_conv(next(ks), 1, 1, 2 * cfg.latent_channels,
                                     2 * cfg.latent_channels, dtype),
            "post_quant_conv": _rand_conv(next(ks), 1, 1,
                                          cfg.latent_channels,
                                          cfg.latent_channels, dtype)}
