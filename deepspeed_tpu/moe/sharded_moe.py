"""Top-k gating for MoE.

Reference parity: ``deepspeed/moe/sharded_moe.py`` — ``TopKGate`` (:372),
``top1gating`` (:181), ``top2gating`` (:288): softmax router with capacity
limits, optional jitter noise, load-balancing aux loss, GShard-style einsum
dispatch/combine tensors.

The einsum-dispatch formulation is *already* the TPU-native paradigm (it comes
from GShard, which targeted TPU): everything is dense one-hot algebra that XLA
maps onto the MXU — no scatter/gather kernels needed.

Shapes: S tokens (per dispatch group), E experts, C capacity.
Returns (aux_loss, combine [S,E,C] float, dispatch [S,E,C] bool).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int, k: int = 1) -> int:
    """reference sharded_moe.py:_capacity — tokens-per-expert budget."""
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor * k))
    return max(cap, min_capacity)


def _one_hot(idx, n, dtype=jnp.float32):
    return jax.nn.one_hot(idx, n, dtype=dtype)


def topk_gating(logits: jax.Array, k: int, capacity_factor: float = 1.0,
                min_capacity: int = 4, rng: Optional[jax.Array] = None,
                noise_std: float = 0.0,
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Generic top-k gating (k=1 ≡ reference top1gating, k=2 ≡ top2gating).

    Load-balancing aux loss = E * Σ_e mean(gate_e) * mean(assigned_e)
    (reference sharded_moe.py:249) computed on the top-1 assignment.

    k == 1 routes through ``_top1_gating_indexed`` — same outputs bitwise
    (test-pinned) without materializing the intermediate fp32 one-hot
    ``[S, E]``/``[S, E, C]`` algebra, the layer's biggest HBM term at
    large S·E·C.
    """
    if k == 1:
        return _top1_gating_indexed(logits, capacity_factor, min_capacity,
                                    rng, noise_std)
    return _topk_gating_dense(logits, k, capacity_factor, min_capacity,
                              rng, noise_std)


def _top1_gating_indexed(logits, capacity_factor=1.0, min_capacity=4,
                         rng=None, noise_std=0.0):
    """Index-based top-1 gating: argmax index + scatter instead of the dense
    one-hot cumsum algebra.  Bitwise-equal to ``_topk_gating_dense`` at
    k == 1: picking ``gates[s, idx]`` equals summing ``gates * one_hot``
    (adding exact zeros), integer ranks equal the fp32 cumsum-of-one-hot
    positions (counts < 2^24), and the dropped-token scatter adds +0.0 —
    bitwise-neutral on the zero-initialized combine tensor."""
    S, E = logits.shape
    C = _capacity(S, E, capacity_factor, min_capacity, 1)
    if rng is not None and noise_std > 0.0:
        logits = logits + jax.random.normal(rng, logits.shape,
                                            logits.dtype) * noise_std
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [S, E]
    idx = jnp.argmax(gates, axis=-1)                             # [S]
    gval = jnp.take_along_axis(gates, idx[:, None], axis=-1)[:, 0]

    counts = jnp.bincount(idx, length=E)                         # [E]
    me = jnp.mean(gates, axis=0)
    ce = counts.astype(jnp.float32) / S
    aux_loss = jnp.sum(me * ce) * E

    gval = gval / jnp.clip(gval, 1e-9, None)

    # rank within the expert queue: stable sort by expert, offset by the
    # expert's segment start (== the dense path's cumsum-of-one-hot)
    order = jnp.argsort(idx)
    start = (jnp.cumsum(counts) - counts).astype(jnp.int32)      # [E]
    pos = jnp.zeros((S,), jnp.int32).at[order].set(
        jnp.arange(S, dtype=jnp.int32) - start[idx[order]])
    keep = pos < C
    combine = jnp.zeros((S, E, C), jnp.float32).at[
        jnp.arange(S), idx, jnp.minimum(pos, C - 1)].add(gval * keep)
    dispatch = combine > 0.0
    return aux_loss, combine, dispatch


def _topk_gating_dense(logits: jax.Array, k: int, capacity_factor: float = 1.0,
                       min_capacity: int = 4, rng: Optional[jax.Array] = None,
                       noise_std: float = 0.0,
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The dense GShard one-hot algebra, any k — the k == 1 reference for
    the indexed fast path's bitwise pin."""
    S, E = logits.shape
    C = _capacity(S, E, capacity_factor, min_capacity, k)
    if rng is not None and noise_std > 0.0:
        # reference: 'Jitter'/'RSample' noisy gate policy (sharded_moe.py:426)
        logits = logits + jax.random.normal(rng, logits.shape,
                                            logits.dtype) * noise_std
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [S, E]

    remaining = gates
    masks, gate_vals = [], []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)            # [S]
        mask = _one_hot(idx, E)                         # [S, E]
        masks.append(mask)
        gate_vals.append(jnp.sum(gates * mask, axis=-1))  # [S]
        remaining = remaining * (1.0 - mask)

    # aux loss on the primary assignment
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(masks[0], axis=0)
    aux_loss = jnp.sum(me * ce) * E

    # normalize the k gate values (reference top2gating denominator)
    denom = jnp.clip(sum(gate_vals), 1e-9, None)
    gate_vals = [g / denom for g in gate_vals]

    # positions within each expert queue, later choices stacked after earlier
    combine = jnp.zeros((S, E, C), jnp.float32)
    prior_counts = jnp.zeros((E,), jnp.float32)
    for mask, gval in zip(masks, gate_vals):
        loc = jnp.cumsum(mask, axis=0) - mask + prior_counts[None, :]  # [S, E]
        pos = jnp.sum(loc * mask, axis=-1).astype(jnp.int32)           # [S]
        keep = pos < C
        gval = gval * keep
        sc = _one_hot(pos, C)                                          # [S, C]
        combine = combine + (gval[:, None] * mask)[..., None] * sc[:, None, :]
        prior_counts = prior_counts + jnp.sum(mask, axis=0)

    dispatch = combine > 0.0
    return aux_loss, combine, dispatch


def top1_gating(logits, capacity_factor=1.0, min_capacity=4, rng=None,
                noise_std=0.0):
    """reference sharded_moe.py:181 top1gating."""
    return topk_gating(logits, 1, capacity_factor, min_capacity, rng, noise_std)


def top2_gating(logits, capacity_factor=1.0, min_capacity=4, rng=None,
                noise_std=0.0):
    """reference sharded_moe.py:288 top2gating."""
    return topk_gating(logits, 2, capacity_factor, min_capacity, rng, noise_std)


def dropless_topk(logits: jax.Array, k: int,
                  rng: Optional[jax.Array] = None, noise_std: float = 0.0,
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Dropless top-k routing: (aux_loss, expert_idx [S,k], weights [S,k]).

    The capacity-free side of the gating algebra (reference sharded_moe.py
    uses fixed capacity; MegaBlocks-style dropless needs only the assignment
    and normalized weights — the grouped GEMM handles raggedness).  Expert
    choice and weight normalization match ``topk_gating`` exactly, so at
    large capacity the two paths agree numerically."""
    S, E = logits.shape
    if rng is not None and noise_std > 0.0:
        logits = logits + jax.random.normal(rng, logits.shape,
                                            logits.dtype) * noise_std
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    remaining = gates
    idxs, gate_vals, masks = [], [], []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        mask = _one_hot(idx, E)
        idxs.append(idx)
        masks.append(mask)
        gate_vals.append(jnp.sum(gates * mask, axis=-1))
        remaining = remaining * (1.0 - mask)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(masks[0], axis=0)
    aux_loss = jnp.sum(me * ce) * E
    denom = jnp.clip(sum(gate_vals), 1e-9, None)
    weights = jnp.stack([g / denom for g in gate_vals], axis=1)
    return aux_loss, jnp.stack(idxs, axis=1).astype(jnp.int32), weights
