"""Quantized, differentiable all-to-all for the expert-parallel route.

The MoE dispatch/combine all-to-alls are the dominant wire cost of an
expert-parallel step; this module puts them on the same composable comm
stack as the ZeRO collectives (runtime/zero.py, ZeRO++ arXiv:2306.10209):

- ``qwire_a2a`` builds a ``custom_vjp`` exchange for use INSIDE the MoE
  route's ``shard_map``: the forward moves int8/int4 codes + fp32 block
  scales through ``ops/quantization.q_all_to_all`` (the shared wire core,
  so the format and its ``all_to_all_q{bits}`` byte accounting live once);
  the backward is the transposed a2a (split/concat swapped) at the SAME
  wire width — the quantized-transpose pattern of
  ``runtime/zero._qwire_exchange``, which keeps the wire differentiable
  without differentiating through the quantizer's round/clip.
- ``resolve_a2a_bits`` is the per-axis hierarchy policy
  (``runtime/zero.resolve_wire_bits`` applied to the ep axis): all-ICI ep
  rings keep full-width values — intra-host bandwidth is cheap and the
  quantizer costs accuracy for nothing — while host-crossing rings
  quantize.  Resolved OUTSIDE the shard_map, at trace time, from the mesh
  device placement.
"""

from __future__ import annotations

import jax

from deepspeed_tpu.comm import collectives
from deepspeed_tpu.ops.quantization import q_all_to_all


def resolve_a2a_bits(bits: int, *, hierarchical: bool, mesh=None,
                     axis="ep") -> int:
    """Effective wire width for the ep all-to-all pair: 0 (full width)
    when quantization is off, or when the ``hierarchical`` policy finds
    the axis's ring entirely inside one host (``axis_dcn_fraction == 0``).
    Call OUTSIDE the shard_map — the decision is static per mesh."""
    if not bits:
        return 0
    if hierarchical and collectives.axis_dcn_fraction(axis, mesh=mesh) == 0.0:
        return 0
    return bits


def qwire_a2a(axis, size: int, split_axis: int, concat_axis: int, *,
              bits: int = 0, block_size: int = 256):
    """Build an all-to-all exchange function for use INSIDE ``shard_map``
    over ``axis``: semantically ``lax.all_to_all(x, axis, split_axis,
    concat_axis, tiled=True)`` in both directions, with ``bits``-wide
    codes + scales on the wire when ``bits`` is 4 or 8 (0 = full width,
    the plain logged wrapper).  The VJP is the transposed exchange —
    ``(concat_axis, split_axis)`` — at the same wire width, so combine
    gradients ride the quantized wire too."""

    def _go(x, s, c):
        if bits:
            return q_all_to_all(x, axis, size, s, c,
                                bits=bits, block_size=block_size)
        return collectives.all_to_all(x, axis, split_dim=s, concat_dim=c)

    @jax.custom_vjp
    def exchange(x):
        return _go(x, split_axis, concat_axis)

    def _fwd(x):
        return _go(x, split_axis, concat_axis), None

    def _bwd(_, g):
        return (_go(g, concat_axis, split_axis),)

    exchange.defvjp(_fwd, _bwd)
    return exchange
