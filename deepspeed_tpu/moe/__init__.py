from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.moe.sharded_moe import top1_gating, top2_gating, topk_gating

__all__ = ["MoE", "top1_gating", "top2_gating", "topk_gating"]
