"""MoE layer with expert parallelism.

Reference parity: ``deepspeed/moe/layer.py:17`` (MoE module), ``sharded_moe.py:455``
(MOELayer: einsum dispatch → all-to-all → local experts → all-to-all → combine),
``moe/experts.py`` (Experts container).

TPU-native: expert weights are stacked [E, ...] arrays annotated with the
``expert`` logical axis (sharded over the ``ep`` mesh axis); the token route is
the same GShard einsum algebra — which was *born* on TPU — with the two
all-to-alls expressed in ``shard_map`` over ``ep`` when ep > 1.  EP composes
with dp/fsdp exactly like the reference's expert+data parallel groups
(utils/groups.py:114 _create_expert_and_data_parallel).

call: ``MoE(...)(x, rng)`` → ``(y, aux_loss)`` with x [B, T, H].
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map

from deepspeed_tpu.moe.sharded_moe import topk_gating


def _part(init, names):
    return nn.with_partitioning(init, names)


def _expert_ffn(d, wi, wo, wg=None):
    """Grouped expert FFN: one big [E,...] einsum (MXU grouped matmul) instead of
    the reference's per-expert module list (moe/experts.py).  wg (per-expert
    gate, [E, H, M]) switches GELU → SwiGLU (Mixtral experts)."""
    h = jnp.einsum("ech,ehm->ecm", d, wi.astype(d.dtype))
    if wg is not None:
        h = nn.silu(jnp.einsum("ech,ehm->ecm", d, wg.astype(d.dtype))) * h
    else:
        h = nn.gelu(h)
    return jnp.einsum("ecm,emh->ech", h, wo.astype(d.dtype))


def _expert_ffn_ragged(tokens, expert_idx, weights, wi, wo, wg=None):
    """Dropless grouped GEMM via ``lax.ragged_dot`` (megablox semantics —
    reference analog: inference/v2 MoE gather/scatter + cutlass grouped GEMM,
    and the MegaBlocks paper): tokens sort by expert, each expert multiplies
    exactly its rows — no capacity padding, no dropped tokens.

    tokens [S, H]; expert_idx [S, k]; weights [S, k] → [S, H]."""
    S, H = tokens.shape
    k = expert_idx.shape[1]
    E = wi.shape[0]
    flat_e = expert_idx.reshape(-1)                       # [S*k]
    order = jnp.argsort(flat_e)                           # group by expert
    tok_rows = jnp.repeat(jnp.arange(S), k)[order]        # source token/row
    sorted_tok = tokens[tok_rows]
    group_sizes = jnp.bincount(flat_e, length=E).astype(jnp.int32)
    h = jax.lax.ragged_dot(sorted_tok, wi.astype(tokens.dtype), group_sizes)
    if wg is not None:
        h = nn.silu(jax.lax.ragged_dot(sorted_tok, wg.astype(tokens.dtype),
                                       group_sizes)) * h
    else:
        h = nn.gelu(h)
    o = jax.lax.ragged_dot(h, wo.astype(tokens.dtype), group_sizes)
    w = weights.reshape(-1)[order].astype(o.dtype)
    return jnp.zeros_like(tokens).at[tok_rows].add(o * w[:, None])


class MoE(nn.Module):
    """Mixture-of-experts layer (reference deepspeed.moe.layer.MoE).

    Experts are distributed over the ``ep`` mesh axis; each ep rank holds
    num_experts/ep_size experts.  use_residual=True gives Residual MoE
    (reference layer.py:27).
    """

    hidden_size: int
    num_experts: int = 8
    k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    use_residual: bool = False
    mlp_ratio: int = 4
    mlp_dim: Optional[int] = None       # explicit FFN width (Mixtral 14336)
    mesh: Optional[Mesh] = None
    param_dtype: object = jnp.float32
    # dropless routing (ragged grouped GEMM, no capacity/no token drops);
    # ep>1 keeps the capacity path (the A2A needs static per-rank shapes)
    dropless: bool = False
    # SwiGLU experts (per-expert gate matrix — Mixtral style)
    gated: bool = False

    @nn.compact
    def __call__(self, x, rng: Optional[jax.Array] = None,
                 deterministic: bool = False):
        B, T, H = x.shape
        E = self.num_experts
        M = self.mlp_dim or self.hidden_size * self.mlp_ratio
        cf = self.eval_capacity_factor if deterministic else self.capacity_factor
        k_init = nn.initializers.normal(stddev=0.02)

        wg = self.param("gate", _part(k_init, ("embed", None)),
                        (H, E), self.param_dtype)
        wi = self.param("wi", _part(k_init, ("expert", "embed", "mlp")),
                        (E, H, M), self.param_dtype)
        wo = self.param("wo", _part(k_init, ("expert", "mlp", "embed")),
                        (E, M, H), self.param_dtype)
        weg = (self.param("wge", _part(k_init, ("expert", "embed", "mlp")),
                          (E, H, M), self.param_dtype)
               if self.gated else None)    # per-expert SwiGLU gate (Mixtral)

        tokens = x.reshape(B * T, H)
        logits = tokens @ wg.astype(x.dtype)
        noise_std = 1.0 / E if (self.noisy_gate_policy and not deterministic
                                and rng is not None) else 0.0

        ep = self.mesh.shape["ep"] if self.mesh is not None else 1
        if self.dropless:
            from deepspeed_tpu.moe.sharded_moe import dropless_topk
            aux, expert_idx, weights = dropless_topk(logits, self.k, rng,
                                                     noise_std)
            if ep > 1:
                if E % ep:
                    raise ValueError(f"num_experts {E} not divisible by "
                                     f"ep {ep}")
                out = _ep_route_dropless(self.mesh, tokens, expert_idx,
                                         weights, wi, wo, weg)
            else:
                out = _expert_ffn_ragged(tokens, expert_idx, weights, wi, wo,
                                         weg)
            return self._finish(x, out.reshape(B, T, H), aux, k_init)

        aux, combine, dispatch = topk_gating(
            logits, self.k, cf, self.min_capacity, rng, noise_std)

        if ep > 1:
            out = _ep_route(self.mesh, tokens, combine, dispatch, wi, wo, weg)
        else:
            dispatched = jnp.einsum("sec,sh->ech",
                                    dispatch.astype(x.dtype), tokens)
            expert_out = _expert_ffn(dispatched, wi, wo, weg)
            out = jnp.einsum("sec,ech->sh", combine.astype(x.dtype), expert_out)

        return self._finish(x, out.reshape(B, T, H), aux, k_init)

    def _finish(self, x, out, aux, k_init):
        if self.use_residual:
            # Residual MoE (reference layer.py use_residual): dense MLP branch
            # mixed with the MoE branch by a learned per-token coefficient
            H, M = self.hidden_size, self.hidden_size * self.mlp_ratio
            mi = self.param("residual_wi", _part(k_init, ("embed", "mlp")),
                            (H, M), self.param_dtype)
            mo = self.param("residual_wo", _part(k_init, ("mlp", "embed")),
                            (M, H), self.param_dtype)
            mlp_out = nn.gelu(x @ mi.astype(x.dtype)) @ mo.astype(x.dtype)
            coef_w = self.param("coefficient", _part(nn.initializers.zeros,
                                                     ("embed", None)),
                                (H, 2), self.param_dtype)
            coef = jax.nn.softmax(x @ coef_w.astype(x.dtype), axis=-1)
            out = out * coef[..., 0:1] + mlp_out * coef[..., 1:2]
        return out, aux


def _ep_route(mesh: Mesh, tokens, combine, dispatch, wi, wo, weg=None):
    """all-to-all route (reference sharded_moe.py MOELayer.forward): dispatch
    einsum → A2A (tokens meet their expert owners) → local experts → A2A back →
    combine einsum, inside shard_map over the ep axis.

    Token batch is replicated over ep within each dp shard here (ep composes
    with dp/fsdp at the mesh level; each ep rank routes its 1/ep slice of the
    local tokens — reference: EP group is orthogonal to DP group).
    """

    # tokens/combine/dispatch split over the joint (dp, fsdp, ep) group so dp
    # replicas don't redo each other's expert work (reference: expert+data
    # parallel groups, utils/groups.py:114); expert weights live on ep only.
    tok_spec = P(("dp", "fsdp", "ep"), None)
    sec_spec = P(("dp", "fsdp", "ep"), None, None)
    w_spec = P("ep", None, None)
    gated = weg is not None
    in_specs = (tok_spec, sec_spec, sec_spec, w_spec, w_spec) + \
        ((w_spec,) if gated else ())

    @partial(shard_map, mesh=mesh, in_specs=in_specs,
             out_specs=tok_spec, check_vma=False)
    def route(tokens, combine, dispatch, wi, wo, *maybe_weg):
        # local shapes: tokens [S/(dp·fsdp·ep), H]; combine/dispatch [S', E, C];
        # wi [E/ep, H, M]; wo [E/ep, M, H]
        dispatched = jnp.einsum("sec,sh->ech",
                                dispatch.astype(tokens.dtype), tokens)
        # [E, C, H] → [E/ep, C*ep, H]
        dispatched = lax.all_to_all(dispatched, "ep", split_axis=0,
                                    concat_axis=1, tiled=True)
        expert_out = _expert_ffn(dispatched, wi, wo,
                                 maybe_weg[0] if maybe_weg else None)
        expert_out = lax.all_to_all(expert_out, "ep", split_axis=1,
                                    concat_axis=0, tiled=True)
        return jnp.einsum("sec,ech->sh", combine.astype(tokens.dtype),
                          expert_out)

    args = (tokens, combine, dispatch, wi, wo) + ((weg,) if gated else ())
    return route(*args)


def _ep_route_dropless(mesh: Mesh, tokens, expert_idx, weights, wi, wo,
                       weg=None):
    """Capacity-FREE expert-parallel route (round-3 VERDICT item 7 —
    reference analog: inference/v2 cutlass grouped GEMM consumed under EP;
    MegaBlocks): no token is ever dropped.

    Static-shape scheme (XLA needs fixed a2a sizes): each rank sorts its
    A = S_local·k assignments by destination rank, packs them into a
    per-destination bucket PADDED to A rows (worst case: every assignment
    goes to one peer), all-to-alls the [ep, A, H] buffer + a parallel
    local-expert id buffer (sentinel id = dead row), runs ``ragged_dot``
    over its received rows grouped by local expert (sentinel rows hit a
    zero-weight dummy expert), and all-to-alls results back to be combined
    at the source.  Bandwidth is worst-case padded — the price of static
    shapes; the capacity path stays available when a bounded a2a matters
    more than zero drops."""
    ep = mesh.shape["ep"]
    E, H, M = wi.shape
    E_local = E // ep
    k = expert_idx.shape[1]
    gated = weg is not None

    tok_spec = P(("dp", "fsdp", "ep"), None)
    idx_spec = P(("dp", "fsdp", "ep"), None)
    w_spec = P("ep", None, None)
    in_specs = (tok_spec, idx_spec, idx_spec, w_spec, w_spec) + \
        ((w_spec,) if gated else ())

    @partial(shard_map, mesh=mesh, in_specs=in_specs,
             out_specs=tok_spec, check_vma=False)
    def route(tokens, expert_idx, weights, wi, wo, *maybe_weg):
        S = tokens.shape[0]                      # local rows
        A = S * k
        flat_e = expert_idx.reshape(A)           # global expert ids
        order = jnp.argsort(flat_e)              # by (dest rank, local expert)
        e_sorted = flat_e[order]
        tok_rows = jnp.repeat(jnp.arange(S), k)[order]
        d_sorted = e_sorted // E_local           # nondecreasing dest rank
        cnt = jnp.bincount(d_sorted, length=ep)
        start = jnp.concatenate([jnp.zeros((1,), cnt.dtype),
                                 jnp.cumsum(cnt)])[:-1]
        pos = jnp.arange(A) - start[d_sorted]    # slot within dest bucket

        send = jnp.zeros((ep * A, H), tokens.dtype).at[
            d_sorted * A + pos].set(tokens[tok_rows])
        ids = jnp.full((ep * A,), E_local, jnp.int32).at[
            d_sorted * A + pos].set((e_sorted % E_local).astype(jnp.int32))
        recv = lax.all_to_all(send.reshape(ep, A, H), "ep", 0, 0, tiled=True)
        rids = lax.all_to_all(ids.reshape(ep, A), "ep", 0, 0, tiled=True)

        flat = recv.reshape(ep * A, H)
        fids = rids.reshape(ep * A)
        ord2 = jnp.argsort(fids)                 # group by local expert;
        rows = flat[ord2]                        # sentinel rows sort last
        gs = jnp.bincount(fids, length=E_local + 1).astype(jnp.int32)
        pad = jnp.zeros((1, H, M), wi.dtype)
        h = jax.lax.ragged_dot(rows, jnp.concatenate(
            [wi, pad]).astype(rows.dtype), gs)
        if maybe_weg:
            h = nn.silu(jax.lax.ragged_dot(
                rows, jnp.concatenate([maybe_weg[0], pad]).astype(rows.dtype),
                gs)) * h
        else:
            h = nn.gelu(h)
        o = jax.lax.ragged_dot(h, jnp.concatenate(
            [wo, jnp.zeros((1, M, H), wo.dtype)]).astype(rows.dtype), gs)
        o = o[jnp.argsort(ord2)].reshape(ep, A, H)

        back = lax.all_to_all(o, "ep", 0, 0, tiled=True)
        res_sorted = back[d_sorted, pos]         # [A, H] expert outputs
        w_sorted = weights.reshape(A)[order].astype(res_sorted.dtype)
        return jnp.zeros_like(tokens).at[tok_rows].add(
            res_sorted * w_sorted[:, None])

    args = (tokens, expert_idx, weights, wi, wo) + ((weg,) if gated else ())
    return route(*args)
