"""MoE layer with expert parallelism.

Reference parity: ``deepspeed/moe/layer.py:17`` (MoE module), ``sharded_moe.py:455``
(MOELayer: einsum dispatch → all-to-all → local experts → all-to-all → combine),
``moe/experts.py`` (Experts container).

TPU-native: expert weights are stacked [E, ...] arrays annotated with the
``expert`` logical axis (sharded over the ``ep`` mesh axis); the token route is
the same GShard einsum algebra — which was *born* on TPU — with the two
all-to-alls expressed in ``shard_map`` over ``ep`` when ep > 1.  EP composes
with dp/fsdp exactly like the reference's expert+data parallel groups
(utils/groups.py:114 _create_expert_and_data_parallel).

call: ``MoE(...)(x, rng)`` → ``(y, aux_loss)`` with x [B, T, H].
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map

from deepspeed_tpu.moe.comm import qwire_a2a, resolve_a2a_bits
from deepspeed_tpu.moe.sharded_moe import topk_gating


def _part(init, names):
    return nn.with_partitioning(init, names)


def aggregate_moe_stats(collection):
    """Fold the per-layer ``moe_stats`` sows (engine's
    ``mutable=["moe_stats"]`` apply) into ONE small dict: token counts sum
    across MoE layers, aux-loss/gate-entropy average.  {} when the model
    sowed nothing (dense model, or telemetry off)."""
    dicts = jax.tree_util.tree_leaves(
        collection,
        is_leaf=lambda x: isinstance(x, dict) and "expert_tokens" in x)
    dicts = [d for d in dicts if isinstance(d, dict)]
    if not dicts:
        return {}
    n = len(dicts)      # static python int — divides arrays exactly
    return {
        "expert_tokens": sum(d["expert_tokens"] for d in dicts),
        "dropped_tokens": sum(d["dropped_tokens"] for d in dicts),
        "assigned_tokens": sum(d["assigned_tokens"] for d in dicts),
        "aux_loss": sum(d["aux_loss"] for d in dicts) / n,
        "gate_entropy": sum(d["gate_entropy"] for d in dicts) / n,
    }


def _resolve_chunks(n_units: int, num_chunks: int) -> int:
    """Largest divisor of ``n_units`` that is <= ``num_chunks`` — the chunk
    count must tile the expert (or assignment) dim exactly, and asking for
    more chunks than units degrades gracefully to one unit per chunk."""
    nc = max(1, min(num_chunks, n_units))
    while n_units % nc:
        nc -= 1
    return nc


def _expert_ffn(d, wi, wo, wg=None):
    """Grouped expert FFN: one big [E,...] einsum (MXU grouped matmul) instead of
    the reference's per-expert module list (moe/experts.py).  wg (per-expert
    gate, [E, H, M]) switches GELU → SwiGLU (Mixtral experts)."""
    h = jnp.einsum("ech,ehm->ecm", d, wi.astype(d.dtype))
    if wg is not None:
        h = nn.silu(jnp.einsum("ech,ehm->ecm", d, wg.astype(d.dtype))) * h
    else:
        h = nn.gelu(h)
    return jnp.einsum("ecm,emh->ech", h, wo.astype(d.dtype))


def _expert_ffn_ragged(tokens, expert_idx, weights, wi, wo, wg=None):
    """Dropless grouped GEMM via ``lax.ragged_dot`` (megablox semantics —
    reference analog: inference/v2 MoE gather/scatter + cutlass grouped GEMM,
    and the MegaBlocks paper): tokens sort by expert, each expert multiplies
    exactly its rows — no capacity padding, no dropped tokens.

    tokens [S, H]; expert_idx [S, k]; weights [S, k] → [S, H]."""
    S, H = tokens.shape
    k = expert_idx.shape[1]
    E = wi.shape[0]
    flat_e = expert_idx.reshape(-1)                       # [S*k]
    order = jnp.argsort(flat_e)                           # group by expert
    tok_rows = jnp.repeat(jnp.arange(S), k)[order]        # source token/row
    sorted_tok = tokens[tok_rows]
    group_sizes = jnp.bincount(flat_e, length=E).astype(jnp.int32)
    h = jax.lax.ragged_dot(sorted_tok, wi.astype(tokens.dtype), group_sizes)
    if wg is not None:
        h = nn.silu(jax.lax.ragged_dot(sorted_tok, wg.astype(tokens.dtype),
                                       group_sizes)) * h
    else:
        h = nn.gelu(h)
    o = jax.lax.ragged_dot(h, wo.astype(tokens.dtype), group_sizes)
    w = weights.reshape(-1)[order].astype(o.dtype)
    return jnp.zeros_like(tokens).at[tok_rows].add(o * w[:, None])


class MoE(nn.Module):
    """Mixture-of-experts layer (reference deepspeed.moe.layer.MoE).

    Experts are distributed over the ``ep`` mesh axis; each ep rank holds
    num_experts/ep_size experts.  use_residual=True gives Residual MoE
    (reference layer.py:27).
    """

    hidden_size: int
    num_experts: int = 8
    k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    use_residual: bool = False
    mlp_ratio: int = 4
    mlp_dim: Optional[int] = None       # explicit FFN width (Mixtral 14336)
    mesh: Optional[Mesh] = None
    param_dtype: object = jnp.float32
    # dropless routing (ragged grouped GEMM, no capacity/no token drops);
    # ep>1 keeps the capacity path (the A2A needs static per-rank shapes)
    dropless: bool = False
    # SwiGLU experts (per-expert gate matrix — Mixtral style)
    gated: bool = False
    # wire format of the ep dispatch/combine all-to-alls (moe/comm.py):
    # 0 = full width; 8/4 = blockwise int codes + fp32 scales
    wire_bits: int = 0
    wire_block: int = 256
    # hierarchical wire policy: all-ICI ep axes stay full width
    hierarchical: bool = False
    # chunk the dispatch-a2a -> expert FFN -> combine-a2a chain over this
    # many expert sub-groups so GEMMs interleave with in-flight a2a chunks
    num_chunks: int = 1

    @nn.compact
    def __call__(self, x, rng: Optional[jax.Array] = None,
                 deterministic: bool = False):
        B, T, H = x.shape
        E = self.num_experts
        M = self.mlp_dim or self.hidden_size * self.mlp_ratio
        cf = self.eval_capacity_factor if deterministic else self.capacity_factor
        k_init = nn.initializers.normal(stddev=0.02)

        wg = self.param("gate", _part(k_init, ("embed", None)),
                        (H, E), self.param_dtype)
        wi = self.param("wi", _part(k_init, ("expert", "embed", "mlp")),
                        (E, H, M), self.param_dtype)
        wo = self.param("wo", _part(k_init, ("expert", "mlp", "embed")),
                        (E, M, H), self.param_dtype)
        weg = (self.param("wge", _part(k_init, ("expert", "embed", "mlp")),
                          (E, H, M), self.param_dtype)
               if self.gated else None)    # per-expert SwiGLU gate (Mixtral)

        tokens = x.reshape(B * T, H)
        logits = tokens @ wg.astype(x.dtype)
        noise_std = 1.0 / E if (self.noisy_gate_policy and not deterministic
                                and rng is not None) else 0.0

        ep = self.mesh.shape["ep"] if self.mesh is not None else 1
        # per-axis hierarchy policy resolves OUTSIDE the shard_map (static
        # per mesh); ep == 1 has no wire at all
        bits = resolve_a2a_bits(self.wire_bits, hierarchical=self.hierarchical,
                                mesh=self.mesh) if ep > 1 else 0
        if self.dropless:
            from deepspeed_tpu.moe.sharded_moe import dropless_topk
            aux, expert_idx, weights = dropless_topk(logits, self.k, rng,
                                                     noise_std)
            if ep > 1:
                if E % ep:
                    raise ValueError(f"num_experts {E} not divisible by "
                                     f"ep {ep}")
                out = _ep_route_dropless(self.mesh, tokens, expert_idx,
                                         weights, wi, wo, weg,
                                         wire_bits=bits,
                                         wire_block=self.wire_block,
                                         num_chunks=self.num_chunks)
            else:
                out = _expert_ffn_ragged(tokens, expert_idx, weights, wi, wo,
                                         weg)
            exp_tokens = jnp.bincount(expert_idx.reshape(-1), length=E)
            self._sow_stats(logits, aux, exp_tokens, jnp.float32(0.0))
            return self._finish(x, out.reshape(B, T, H), aux, k_init)

        aux, combine, dispatch = topk_gating(
            logits, self.k, cf, self.min_capacity, rng, noise_std)

        if ep > 1:
            out = _ep_route(self.mesh, tokens, combine, dispatch, wi, wo, weg,
                            wire_bits=bits, wire_block=self.wire_block,
                            num_chunks=self.num_chunks)
        else:
            dispatched = jnp.einsum("sec,sh->ech",
                                    dispatch.astype(x.dtype), tokens)
            expert_out = _expert_ffn(dispatched, wi, wo, weg)
            out = jnp.einsum("sec,ech->sh", combine.astype(x.dtype), expert_out)

        kept = dispatch.astype(jnp.float32)
        self._sow_stats(logits, aux, kept.sum(axis=(0, 2)),
                        logits.shape[0] * self.k - kept.sum())
        return self._finish(x, out.reshape(B, T, H), aux, k_init)

    def _sow_stats(self, logits, aux, expert_tokens, dropped):
        """Expert-load observability: sow per-layer routing stats into the
        ``moe_stats`` collection (lax.stop_gradient — pure telemetry).  A
        no-op unless the caller passes ``mutable=["moe_stats"]`` (the
        engine's stats apply fn); guarded against ``init``, where every
        collection is mutable and the sow would pollute the params tree."""
        if self.is_initializing():
            return
        p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        ent = jnp.mean(-jnp.sum(p * jnp.log(p + 1e-9), axis=-1))
        self.sow("moe_stats", "stats", jax.lax.stop_gradient({
            "expert_tokens": expert_tokens.astype(jnp.float32),
            "dropped_tokens": jnp.asarray(dropped, jnp.float32),
            "assigned_tokens": jnp.float32(logits.shape[0] * self.k),
            "aux_loss": jnp.asarray(aux, jnp.float32),
            "gate_entropy": ent,
        }))

    def _finish(self, x, out, aux, k_init):
        if self.use_residual:
            # Residual MoE (reference layer.py use_residual): dense MLP branch
            # mixed with the MoE branch by a learned per-token coefficient
            H, M = self.hidden_size, self.hidden_size * self.mlp_ratio
            mi = self.param("residual_wi", _part(k_init, ("embed", "mlp")),
                            (H, M), self.param_dtype)
            mo = self.param("residual_wo", _part(k_init, ("mlp", "embed")),
                            (M, H), self.param_dtype)
            mlp_out = nn.gelu(x @ mi.astype(x.dtype)) @ mo.astype(x.dtype)
            coef_w = self.param("coefficient", _part(nn.initializers.zeros,
                                                     ("embed", None)),
                                (H, 2), self.param_dtype)
            coef = jax.nn.softmax(x @ coef_w.astype(x.dtype), axis=-1)
            out = out * coef[..., 0:1] + mlp_out * coef[..., 1:2]
        return out, aux


def _ep_route(mesh: Mesh, tokens, combine, dispatch, wi, wo, weg=None, *,
              wire_bits: int = 0, wire_block: int = 256, num_chunks: int = 1):
    """all-to-all route (reference sharded_moe.py MOELayer.forward): dispatch
    einsum → A2A (tokens meet their expert owners) → local experts → A2A back →
    combine einsum, inside shard_map over the ep axis.

    Token batch is replicated over ep within each dp shard here (ep composes
    with dp/fsdp at the mesh level; each ep rank routes its 1/ep slice of the
    local tokens — reference: EP group is orthogonal to DP group).

    The a2a pair goes through ``moe/comm.qwire_a2a`` — int codes + scales on
    the wire when ``wire_bits`` is 4/8 — and the dispatch-a2a → FFN →
    combine-a2a chain tiles over ``num_chunks`` local-expert sub-groups so
    XLA's latency-hiding scheduler can interleave chunk c's expert GEMM with
    chunk c+1's in-flight a2a (the T3 pattern; PR 4 chunk semantics).
    """

    # tokens/combine/dispatch split over the joint (dp, fsdp, ep) group so dp
    # replicas don't redo each other's expert work (reference: expert+data
    # parallel groups, utils/groups.py:114); expert weights live on ep only.
    tok_spec = P(("dp", "fsdp", "ep"), None)
    sec_spec = P(("dp", "fsdp", "ep"), None, None)
    w_spec = P("ep", None, None)
    gated = weg is not None
    in_specs = (tok_spec, sec_spec, sec_spec, w_spec, w_spec) + \
        ((w_spec,) if gated else ())

    ep = mesh.shape["ep"]
    E_local = wi.shape[0] // ep
    nc = _resolve_chunks(E_local, num_chunks)
    g = E_local // nc                       # local experts per chunk
    ex_d = qwire_a2a("ep", ep, 0, 1, bits=wire_bits, block_size=wire_block)
    ex_c = qwire_a2a("ep", ep, 1, 0, bits=wire_bits, block_size=wire_block)

    @partial(shard_map, mesh=mesh, in_specs=in_specs,
             out_specs=tok_spec, check_vma=False)
    def route(tokens, combine, dispatch, wi, wo, *maybe_weg):
        # local shapes: tokens [S/(dp·fsdp·ep), H]; combine/dispatch [S', E, C];
        # wi [E/ep, H, M]
        weg_l = maybe_weg[0] if maybe_weg else None
        dispatched = jnp.einsum("sec,sh->ech",
                                dispatch.astype(tokens.dtype), tokens)
        E, C, H = dispatched.shape
        # global expert e = p*E_local + l (dest rank p, local expert l):
        # chunk c covers local experts [c*g, (c+1)*g) on EVERY rank
        disp4 = dispatched.reshape(ep, E_local, C, H)
        outs = []
        for c in range(nc):
            lo, hi = c * g, (c + 1) * g
            part = disp4[:, lo:hi].reshape(ep * g, C, H)
            ex = ex_d(part)                 # [g, C*ep, H]: this rank's chunk
            eo = _expert_ffn(ex, wi[lo:hi], wo[lo:hi],
                             weg_l[lo:hi] if weg_l is not None else None)
            back = ex_c(eo)                 # [g*ep, C, H], peer-major
            outs.append(back.reshape(ep, g, C, H))
        # [ep, nc, g, C, H] → [E, C, H]: global id p*E_local + c*g + j
        expert_out = jnp.stack(outs, axis=1).reshape(E, C, H)
        return jnp.einsum("sec,ech->sh", combine.astype(tokens.dtype),
                          expert_out)

    args = (tokens, combine, dispatch, wi, wo) + ((weg,) if gated else ())
    return route(*args)


def _ep_route_dropless(mesh: Mesh, tokens, expert_idx, weights, wi, wo,
                       weg=None, *, wire_bits: int = 0, wire_block: int = 256,
                       num_chunks: int = 1):
    """Capacity-FREE expert-parallel route (round-3 VERDICT item 7 —
    reference analog: inference/v2 cutlass grouped GEMM consumed under EP;
    MegaBlocks): no token is ever dropped.

    Static-shape scheme (XLA needs fixed a2a sizes): each rank sorts its
    A = S_local·k assignments by destination rank, packs them into a
    per-destination bucket PADDED to A rows (worst case: every assignment
    goes to one peer), all-to-alls the [ep, A, H] buffer + a parallel
    local-expert id buffer (sentinel id = dead row), runs ``ragged_dot``
    over its received rows grouped by local expert (sentinel rows hit a
    zero-weight dummy expert), and all-to-alls results back to be combined
    at the source.  Bandwidth is worst-case padded — the price of static
    shapes; the capacity path stays available when a bounded a2a matters
    more than zero drops.

    The three value a2as ride ``moe/comm.qwire_a2a`` (int wire when
    ``wire_bits``); the int32 id buffer always moves FULL width — routing
    indices must survive the wire exactly.  ``num_chunks`` tiles the
    assignment dim so per-chunk expert GEMMs interleave with in-flight a2a
    chunks; the grouping only changes GEMM batching, outputs are identical
    row-wise."""
    ep = mesh.shape["ep"]
    E, H, M = wi.shape
    E_local = E // ep
    k = expert_idx.shape[1]
    gated = weg is not None

    tok_spec = P(("dp", "fsdp", "ep"), None)
    idx_spec = P(("dp", "fsdp", "ep"), None)
    w_spec = P("ep", None, None)
    in_specs = (tok_spec, idx_spec, idx_spec, w_spec, w_spec) + \
        ((w_spec,) if gated else ())

    # (0,0) a2a is its own transpose — one exchange serves both directions
    ex_v = qwire_a2a("ep", ep, 0, 0, bits=wire_bits, block_size=wire_block)

    @partial(shard_map, mesh=mesh, in_specs=in_specs,
             out_specs=tok_spec, check_vma=False)
    def route(tokens, expert_idx, weights, wi, wo, *maybe_weg):
        S = tokens.shape[0]                      # local rows
        A = S * k
        nc = _resolve_chunks(A, num_chunks)
        ac = A // nc                             # assignments per chunk
        flat_e = expert_idx.reshape(A)           # global expert ids
        order = jnp.argsort(flat_e)              # by (dest rank, local expert)
        e_sorted = flat_e[order]
        tok_rows = jnp.repeat(jnp.arange(S), k)[order]
        d_sorted = e_sorted // E_local           # nondecreasing dest rank
        cnt = jnp.bincount(d_sorted, length=ep)
        start = jnp.concatenate([jnp.zeros((1,), cnt.dtype),
                                 jnp.cumsum(cnt)])[:-1]
        pos = jnp.arange(A) - start[d_sorted]    # slot within dest bucket

        send = jnp.zeros((ep * A, H), tokens.dtype).at[
            d_sorted * A + pos].set(tokens[tok_rows]).reshape(ep, A, H)
        ids = jnp.full((ep * A,), E_local, jnp.int32).at[
            d_sorted * A + pos].set((e_sorted % E_local).astype(
                jnp.int32)).reshape(ep, A)

        pad_i = jnp.concatenate([wi, jnp.zeros((1, H, M), wi.dtype)])
        pad_o = jnp.concatenate([wo, jnp.zeros((1, M, H), wo.dtype)])
        pad_g = (jnp.concatenate([maybe_weg[0],
                                  jnp.zeros((1, H, M), wo.dtype)])
                 if maybe_weg else None)

        back_chunks = []
        for c in range(nc):
            lo, hi = c * ac, (c + 1) * ac
            recv = ex_v(send[:, lo:hi])          # [ep, ac, H] values
            rids = lax.all_to_all(ids[:, lo:hi], "ep", 0, 0, tiled=True)

            flat = recv.reshape(ep * ac, H)
            fids = rids.reshape(ep * ac)
            ord2 = jnp.argsort(fids)             # group by local expert;
            rows = flat[ord2]                    # sentinel rows sort last
            gs = jnp.bincount(fids, length=E_local + 1).astype(jnp.int32)
            h = jax.lax.ragged_dot(rows, pad_i.astype(rows.dtype), gs)
            if pad_g is not None:
                h = nn.silu(jax.lax.ragged_dot(
                    rows, pad_g.astype(rows.dtype), gs)) * h
            else:
                h = nn.gelu(h)
            o = jax.lax.ragged_dot(h, pad_o.astype(rows.dtype), gs)
            o = o[jnp.argsort(ord2)].reshape(ep, ac, H)
            back_chunks.append(ex_v(o))          # [ep, ac, H] results
        back = jnp.concatenate(back_chunks, axis=1)   # == unchunked [ep, A, H]

        res_sorted = back[d_sorted, pos]         # [A, H] expert outputs
        w_sorted = weights.reshape(A)[order].astype(res_sorted.dtype)
        return jnp.zeros_like(tokens).at[tok_rows].add(
            res_sorted * w_sorted[:, None])

    args = (tokens, expert_idx, weights, wi, wo) + ((weg,) if gated else ())
    return route(*args)
