"""Multi-window SLO burn-rate monitoring for the serving fleet.

An SLO here is "``objective`` of requests see ``metric`` at or under
``threshold_ms``" (e.g. 99% of requests get TTFT <= 500 ms).  The
monitor samples the fleet's latency histograms through a bounded
:class:`~deepspeed_tpu.telemetry.timeseries.TimeSeriesStore` and derives
the standard SRE burn rate per window::

    bad_fraction(W) = 1 - good(W) / total(W)          (from the window's
                                                       attainment delta)
    burn(W)         = bad_fraction(W) / (1 - objective)

burn == 1 means the error budget is being spent exactly at the rate the
objective allows; burn == 10 exhausts a 30-day budget in 3 days.
Multi-window alerting (the Google SRE workbook shape) fires ``page``
only when EVERY configured window burns past the threshold — the long
window proves the problem is real, the short window proves it is still
happening — and ``warn`` when only the shortest window does.  Alerts
are edge-triggered into ``slo_alerts_total{slo,severity}`` and the live
per-window burn sits in ``slo_burn_rate{slo,window}``; both fan through
MonitorMaster when one is attached (``attach_monitor``).

The fleet ticks the monitor from its dispatcher loop (sampling must
never block a scheduler round — scripts/check_no_sync.py scans
``tick``), and the current paging-condition burn (``max_burn()``) is
offered opt-in to admission shedding and the pool autoscaler, closing
observability into the control loop.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from pydantic import Field

from deepspeed_tpu.config import DeepSpeedConfigModel
from deepspeed_tpu.telemetry.timeseries import TimeSeriesStore

__all__ = ["SLOSpec", "SLOConfig", "SLOMonitor", "burn_rate"]


class SLOSpec(DeepSpeedConfigModel):
    """One latency objective over an existing histogram family."""

    name: str                        # label value in slo_burn_rate{slo=}
    metric: str = "serving_ttft_ms"  # histogram to read
    threshold_ms: float = 500.0      # "good" boundary (put it on a bucket
    #                                  boundary for exact attainment)
    objective: float = 0.99          # target good fraction in [0, 1)


class SLOConfig(DeepSpeedConfigModel):
    """``slo`` block of the fleet config.  Defaults OFF: burn-rate
    monitoring is an opt-in layer and an empty ``slos`` list would only
    burn sampling cost."""

    enabled: bool = False
    sample_interval_s: float = 0.25
    capacity: int = 4096             # ring samples kept per series
    # multi-window alert shape, shortest first; ``page`` needs every
    # window past ``alert_burn_threshold``, ``warn`` just the shortest
    windows_s: List[float] = Field(default_factory=lambda: [5.0, 60.0])
    alert_burn_threshold: float = 1.0
    slos: List[SLOSpec] = Field(default_factory=list)


def burn_rate(good: float, total: float, objective: float) -> float:
    """Pure burn-rate math (unit-tested against hand-computed values):
    the window's bad fraction over the SLO's allowed bad fraction."""
    if total <= 0:
        return 0.0
    bad = max(0.0, 1.0 - good / total)
    budget = 1.0 - objective
    if budget <= 0:
        return float("inf") if bad > 0 else 0.0
    return bad / budget


class SLOMonitor:
    """Continuous burn-rate evaluation over the fleet registry."""

    def __init__(self, config: Optional[SLOConfig] = None, *,
                 registry, clock: Optional[Callable[[], float]] = None,
                 monitor=None):
        self.config = SLOConfig.parse(config)
        self.clock = clock or time.monotonic
        self.registry = registry
        self._monitor = monitor          # optional MonitorMaster fan-out
        self.store = TimeSeriesStore(
            interval_s=self.config.sample_interval_s,
            capacity=self.config.capacity, clock=self.clock)
        self.windows = sorted(float(w) for w in self.config.windows_s)
        self.g_burn = registry.gauge(
            "slo_burn_rate", "SLO error-budget burn rate per objective "
            "per window: the window's bad-request fraction over the "
            "objective's allowed bad fraction (1.0 = spending budget "
            "exactly at the sustainable rate)")
        self.c_alerts = registry.counter(
            "slo_alerts_total", "burn-rate alert firings, edge-triggered "
            "per SLO per severity (page = every window past the "
            "threshold, warn = shortest window only)")
        self._tracked: Dict[str, SLOSpec] = {}
        for spec in self.config.slos:
            self._track(spec)
        # alerting state per (slo, severity): edge-triggered counters
        self._alerting: Dict[tuple, bool] = {}
        # burn per slo per window from the most recent evaluation; the
        # bench and the control-loop hooks read these without resampling
        self.last_burn: Dict[str, Dict[float, float]] = {}

    def _track(self, spec: SLOSpec) -> None:
        hist = self.registry._metrics.get(spec.metric)
        if hist is None:
            # the serving telemetry registers its families eagerly, but a
            # fleet of fake engines (tests) may not: register on demand so
            # the tracker binds to whatever later observes into it
            # binds to an EXISTING documented family named by the SLO
            # config (default serving_ttft_ms); registers no new name in
            # production, only under test fakes that skipped eager
            # registration
            hist = self.registry.histogram(spec.metric)  # metric-name-ok
        if getattr(hist, "kind", None) != "histogram":
            raise ValueError(f"SLO {spec.name!r}: metric {spec.metric!r} "
                             f"is {getattr(hist, 'kind', None)}, need a "
                             f"histogram")
        self._tracked[spec.name] = spec
        self.store.track_attainment(hist, spec.threshold_ms,
                                    key=f"slo.{spec.name}")

    def attach_monitor(self, monitor) -> None:
        """Fan burn gauges/alerts through a MonitorMaster as well."""
        self._monitor = monitor

    # ------------------------------------------------------------- ticking
    def tick(self, now: Optional[float] = None) -> float:
        """Sample (cadence-gated) and re-evaluate burn.  Returns the
        current paging-condition burn (``max_burn``).  Bounded host
        work only — called inside the dispatcher round."""
        now = self.clock() if now is None else now
        if not self.store.maybe_sample(now):
            return self.max_burn()
        events = []
        for name, spec in self._tracked.items():
            burns = self.last_burn.setdefault(name, {})
            for w in self.windows:
                good = self.store.window_delta(f"slo.{name}.good", w, now)
                total = self.store.window_delta(f"slo.{name}.total", w, now)
                b = burn_rate(good, total, spec.objective)
                burns[w] = b
                self.g_burn.set(b, slo=name, window=f"{w:g}s")
                events.append((f"slo_burn_rate/{name}/{w:g}s", b,
                               self.store.samples_taken))
            self._evaluate_alerts(name, burns, events)
        if self._monitor is not None and events:
            try:
                self._monitor.write_events(events)
            except Exception:  # noqa: BLE001 — monitoring fan-out must
                pass           # never take the dispatcher down
        return self.max_burn()

    def _evaluate_alerts(self, name: str, burns: Dict[float, float],
                         events: list) -> None:
        thr = self.config.alert_burn_threshold
        page = bool(burns) and all(b >= thr for b in burns.values())
        warn = (not page and bool(burns)
                and burns[self.windows[0]] >= thr)
        for severity, active in (("page", page), ("warn", warn)):
            key = (name, severity)
            was = self._alerting.get(key, False)
            if active and not was:
                self.c_alerts.inc(1, slo=name, severity=severity)
                events.append(
                    (f"slo_alerts_total/{name}/{severity}",
                     self.c_alerts.value(slo=name, severity=severity),
                     self.store.samples_taken))
            self._alerting[key] = active

    # --------------------------------------------------------------- reads
    def max_burn(self) -> float:
        """The control-loop signal: per SLO the PAGE-condition burn (the
        minimum across windows — every window must agree, so one noisy
        short window cannot trip the autoscaler), maximum across SLOs."""
        worst = 0.0
        for burns in self.last_burn.values():
            if burns:
                worst = max(worst, min(burns.values()))
        return worst

    def alerts_total(self) -> float:
        total = 0.0
        for (name, severity) in self._alerting:
            total += self.c_alerts.value(slo=name, severity=severity)
        return total
