"""deepspeed_tpu.serving — multi-replica serving fleet with failure
tolerance end to end: supervised ``InferenceEngineV2`` replicas
(fleet.py), a failure-tolerant router with bounded retry and request
migration (router.py), hysteresis admission control (admission.py), and
signal-driven prefill/decode pool autoscaling for the disaggregated mode
(autoscale.py).  Chaos sites (``runtime/faults.py``):
``router.dispatch``, ``replica.heartbeat``, ``replica.mid_decode``,
``admission.decide``, ``handoff.mid_transfer``.
"""

from deepspeed_tpu.serving.admission import (AdmissionConfig,
                                             AdmissionController)
from deepspeed_tpu.serving.autoscale import AutoscaleConfig, PoolAutoscaler
from deepspeed_tpu.serving.fleet import (FleetConfig, FleetDrained, Replica,
                                         REPLICA_STATES, ServingFleet)
from deepspeed_tpu.serving.router import (POLICIES, FleetRequest,
                                          NoHealthyReplicas, RequestFailed,
                                          Router, RouterConfig)
from deepspeed_tpu.serving.slo import (SLOConfig, SLOMonitor, SLOSpec,
                                       burn_rate)

__all__ = ["ServingFleet", "FleetConfig", "FleetDrained", "Replica",
           "REPLICA_STATES", "Router", "RouterConfig", "FleetRequest",
           "RequestFailed", "NoHealthyReplicas", "POLICIES",
           "AdmissionController", "AdmissionConfig",
           "PoolAutoscaler", "AutoscaleConfig",
           "SLOMonitor", "SLOConfig", "SLOSpec", "burn_rate"]
