"""Failure-tolerant request routing for the multi-replica serving fleet.

The router owns the request-level half of the fleet contract
(serving/fleet.py owns the replica-lifecycle half): every request moves
through

    pending --(policy pick + ``router.dispatch`` fault site)--> inflight
      --> done | failed

with three ways back from ``inflight`` to ``pending``:

- **retry** — a dispatch error or a per-attempt timeout re-enters the
  queue after an exponential-backoff-with-jitter delay; each retry burns
  one unit of the bounded budget (``max_retries`` re-dispatches after the
  first attempt) and exhaustion surfaces a typed :class:`RequestFailed`,
  never a hang;
- **migration on death** — a replica dying mid-decode re-enters its queued
  and in-flight requests immediately (no backoff: the survivors are
  healthy) with their ORIGINAL arrival timestamps and any host-known
  generated prefix folded into the prompt (engine
  ``export_pending_requests``), so open-loop greedy output stays
  token-exact vs. a no-failure run; death migrations still count against
  the retry budget so a crash-looping fleet fails requests instead of
  cycling them forever;
- **migration on drain** — a graceful drain migrates the same way but
  burns NO budget (the operator asked for it; punishing the request would
  make drains lossy).

Determinism for tests: the clock is injected (``clock=...``), and the
backoff jitter comes from a seeded ``numpy`` Generator, so the full retry
schedule is pinned by ``RouterConfig.seed``.

Routing policies are pluggable (``POLICIES``): ``least_outstanding_tokens``
(default) balances by the live token footprint per replica;
``round_robin`` is the trivial baseline; ``prefix_affinity`` routes on
ACTUAL radix prefix-cache residency — each healthy replica's engine is
probed for the request's longest cached prefix
(``engine.prefix_cached_tokens``, a read-only host trie walk that is
cross-thread safe) and the request goes to the replica holding the most
of its prompt, least-outstanding-tokens breaking ties.  Replicas without
a probe (cache off, fake engines) report 0, so a cache-less fleet
degrades to exactly least-outstanding routing; under replica death the
migrated request re-probes the survivors and re-prefills only its
uncached suffix there (token-exact either way).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.config import DeepSpeedConfigModel
from deepspeed_tpu.runtime import faults
from deepspeed_tpu.telemetry import tracecontext
from deepspeed_tpu.utils.logging import logger


class RequestFailed(RuntimeError):
    """A request exhausted its bounded retry budget (or the fleet has no
    replica left to serve it).  Typed so callers can distinguish "the
    request failed" from "the fleet is broken" — carries the request
    index, the final failure reason, and the attempt count."""

    def __init__(self, index: int, reason: str, attempts: int,
                 detail: str = ""):
        super().__init__(
            f"request {index} failed after {attempts} attempt(s): {reason}"
            + (f" ({detail})" if detail else ""))
        self.index = index
        self.reason = reason
        self.attempts = attempts


class NoHealthyReplicas(RuntimeError):
    """No replica is in the healthy state to dispatch to (transient while a
    respawn is in flight; terminal when the fleet is out of respawns)."""


class RouterConfig(DeepSpeedConfigModel):
    """``router`` block of the fleet config.

    ``max_retries`` bounds RE-dispatches after the first attempt (so a
    request is tried at most ``max_retries + 1`` times).  The k-th failed
    attempt waits ``min(backoff_max_s, backoff_base_s * backoff_factor**
    (k-1)) * (1 + backoff_jitter * u)`` with ``u ~ U[0, 1)`` from the
    seeded generator.  ``request_timeout_s`` is the per-ATTEMPT completion
    deadline (0 disables): a replica sitting on a request past it gets the
    request retried elsewhere (the stale attempt's late result is
    deduplicated by the assignment epoch)."""

    policy: str = "least_outstanding_tokens"
    max_retries: int = 3
    request_timeout_s: float = 0.0
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 2.0
    backoff_jitter: float = 0.5
    seed: int = 0
    # disaggregated serving (serving/fleet.py FleetConfig.disaggregated
    # mirrors this down): route each PHASE independently — prefill-phase
    # requests to the prefill-role pool by shortest queue, decode-phase
    # requests to the decode-role pool by prefix_affinity against the
    # handoff residency.  An empty role pool falls back to any healthy
    # replica: specialization is an optimization, never a liveness gate
    disaggregated: bool = False


@dataclasses.dataclass
class FleetRequest:
    """One request's routing state.  ``prompt`` is the CURRENT context —
    migrations fold the host-known generated prefix in, exactly like
    recompute-preemption — while ``generated`` accumulates that prefix so
    the final output is ``generated + last_attempt_output`` and
    ``t_arrival`` never changes (original-arrival semantics)."""

    index: int
    prompt: np.ndarray
    max_new_tokens: int                     # ORIGINAL budget
    t_arrival: float = 0.0
    generated: List[int] = dataclasses.field(default_factory=list)
    attempts: int = 0                       # dispatches tried so far
    rejections: int = 0                     # admission 429s taken
    migrations: int = 0
    epoch: int = 0                          # bumped per requeue: stale-result
    #                                         events are ignored against it
    next_eligible: float = 0.0              # arrival / backoff / retry-after
    deadline: float = float("inf")          # per-attempt timeout
    assigned: Optional[str] = None          # replica name while inflight
    # LoRA adapter serving this request (0 = base model).  Routing treats
    # it as a residency signal (prefix_affinity prefers replicas whose
    # pool already holds the adapter's pages); replicas where the adapter
    # cannot EVER fit fail the request typed at dispatch (fleet
    # ``_invalid_reason``), never a replica death
    adapter: int = 0
    # disaggregated lifecycle: "full" (unified fleet — prefill and decode
    # on one replica), "prefill" (serve the prompt + FIRST token only),
    # "decode" (prefill done and folded; serve the remaining budget).
    # ``handoff`` advances prefill -> decode
    phase: str = "full"
    t_first: Optional[float] = None         # fleet-observed first-token time
    #                                         (set at handoff; None unified)
    # distributed-trace context (telemetry/tracecontext.py): trace_id is
    # STABLE for the request's whole lifetime — retries, migrations, and
    # the prefill->decode handoff keep it — while each dispatch attempt
    # mints a child span under it (Router.dispatch)
    trace: Optional[tracecontext.TraceContext] = None

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)


# ------------------------------------------------------------------ policies

def least_outstanding_tokens(req: FleetRequest, healthy: list,
                             router: "Router", rng) -> object:
    """Default: the replica with the smallest live token footprint
    (outstanding prompts + remaining budgets it has been assigned).  Ties
    break by name so the choice is deterministic."""
    return min(healthy,
               key=lambda rep: (router.outstanding_tokens(rep.name),
                                rep.name))


def round_robin(req: FleetRequest, healthy: list, router: "Router",
                rng) -> object:
    router._rr_cursor += 1
    return healthy[router._rr_cursor % len(healthy)]


def prefix_affinity(req: FleetRequest, healthy: list, router: "Router",
                    rng) -> object:
    """Radix-residency routing ([serving_scale], closing the PR 7 stub):
    probe every healthy replica's engine for the request's longest cached
    prefix and send it where the most of its prompt is already resident —
    those tokens skip prefill there entirely.  Ties (including the
    cache-cold 0-everywhere case) break by least outstanding tokens, then
    name, so an unprimed or cache-less fleet load-balances exactly like
    the default policy.  The probe (``engine.prefix_cached_tokens``) is a
    read-only host trie walk, safe to call from the dispatcher thread
    while the replica worker serves; replicas without one report 0.
    Affinity is an optimization, never a correctness gate: a dead
    favorite simply isn't in ``healthy`` and the survivors re-prefill the
    uncached suffix token-exact.

    Probes go through :meth:`Router.residency` — a per-(replica, prompt)
    cache so scheduling stays O(replicas) dict hits per request instead
    of O(replicas) trie walks: at fleet scale the probe itself was the
    routing cost.  The cache invalidates per replica on dispatch
    (residency there is about to grow) and on death/migration
    (``Router.invalidate_residency``), so a stale entry can only
    UNDER-state residency for one pick, never mis-route.

    Multi-tenant LoRA adds a SECOND residency signal: among replicas with
    equal prefix residency, prefer one whose adapter pool already holds
    the request's adapter pages (``Router.adapter_residency``, probing
    ``engine.adapter_resident`` — the same cached host-dict peek shape as
    the prefix probe).  Landing on an adapter-warm replica skips a
    host->device page upload AND spares a cold eviction there; like the
    prefix signal it is an optimization only — an adapter-cold replica
    just hot-loads the pages on admission."""
    return min(healthy,
               key=lambda rep: (-router.residency(rep, req),
                                -router.adapter_residency(rep, req),
                                router.outstanding_tokens(rep.name),
                                rep.name))


POLICIES: Dict[str, Callable] = {
    "least_outstanding_tokens": least_outstanding_tokens,
    "round_robin": round_robin,
    "prefix_affinity": prefix_affinity,
}


class Router:
    """Request queue + retry/migration bookkeeping.  Single-threaded by
    design: only the fleet dispatcher calls in (replica workers talk to the
    dispatcher through the fleet's event queue), so there is no lock and
    the retry schedule stays deterministic under an injected clock."""

    def __init__(self, config: Optional[RouterConfig] = None, *,
                 clock: Callable[[], float], registry):
        self.config = config or RouterConfig()
        if self.config.policy not in POLICIES:
            raise ValueError(
                f"unknown routing policy {self.config.policy!r}; expected "
                f"one of {sorted(POLICIES)}")
        self.clock = clock
        self._policy = POLICIES[self.config.policy]
        self._rng = np.random.default_rng(self.config.seed)
        self._rr_cursor = -1
        self.pending: List[FleetRequest] = []
        self.inflight: Dict[int, FleetRequest] = {}
        self.done: Dict[int, np.ndarray] = {}
        self.failed: Dict[int, RequestFailed] = {}
        self.requests: Dict[int, FleetRequest] = {}
        # per-replica radix-residency probe cache: {replica name ->
        # {prompt bytes -> resident token count}} — see residency()
        self._residency: Dict[str, Dict[bytes, int]] = {}
        self._residency_cap = 4096      # entries per replica before reset
        # per-replica adapter-residency probe cache: {replica name ->
        # {adapter id -> 0/1 resident}} — see adapter_residency(); shares
        # the invalidation sites (and cap) with the prefix cache above
        self._adapter_residency: Dict[str, Dict[int, int]] = {}
        self.c_retries = registry.counter(
            "router_retries_total", "request re-dispatches taken by the "
            "fleet router, per reason (dispatch_error / timeout / "
            "replica_death / heartbeat_timeout)")
        self.c_migrated = registry.counter(
            "requests_migrated_total", "queued or in-flight requests "
            "re-entered into the router after a replica death or drain")
        self.g_depth = registry.gauge(
            "router_queue_depth", "requests arrived and waiting for "
            "dispatch (the admission controller's queue signal)")

    # ----------------------------------------------------------- admission
    def submit(self, req: FleetRequest) -> None:
        self.requests[req.index] = req
        if req.trace is None:
            req.trace = tracecontext.new_trace(phase=req.phase)
        req.next_eligible = max(req.next_eligible, req.t_arrival)
        self.pending.append(req)

    def queue_depth(self, now: float) -> int:
        """Arrived-and-waiting count (not-yet-arrived open-loop requests
        are excluded: they are load that has not happened yet)."""
        depth = sum(1 for r in self.pending if r.t_arrival <= now)
        self.g_depth.set(depth)
        return depth

    def take_dispatchable(self, now: float) -> List[FleetRequest]:
        """Pop every request whose arrival AND backoff/retry-after gates
        have passed, earliest-arrival first (FIFO within a tick)."""
        ready = [r for r in self.pending if r.next_eligible <= now]
        if ready:
            self.pending = [r for r in self.pending
                            if r.next_eligible > now]
            ready.sort(key=lambda r: (r.t_arrival, r.index))
        return ready

    def requeue_wait(self, req: FleetRequest, until: float) -> None:
        """Put a request back untouched (no budget burned) until ``until``
        — the no-healthy-replica / admission-retry-after path."""
        req.next_eligible = until
        self.pending.append(req)

    # ------------------------------------------------------------ dispatch
    def backoff(self, attempt: int) -> float:
        """Delay before re-dispatch number ``attempt`` (1-based count of
        failures so far).  Deterministic given ``RouterConfig.seed``: the
        k-th call consumes the k-th variate of the seeded generator."""
        c = self.config
        base = min(c.backoff_max_s,
                   c.backoff_base_s * c.backoff_factor ** (attempt - 1))
        return base * (1.0 + c.backoff_jitter * float(self._rng.random()))

    def pick(self, req: FleetRequest, healthy: list):
        """Choose a replica for ``req`` under the configured policy.  In
        disaggregated mode each phase routes against its OWN pool:
        prefill-phase requests go to the prefill-role replica with the
        shortest queue (fewest assigned requests — prefill work is one
        prompt-sized burst, so queue length IS the wait), decode-phase
        (and unified "full") requests to the decode pool by
        ``prefix_affinity`` — a handed-off request lands where its folded
        prompt is already radix-resident.  An empty role pool falls back
        to the whole healthy set under the configured policy."""
        if not healthy:
            raise NoHealthyReplicas(
                f"no healthy replica for request {req.index}")
        if self.config.disaggregated:
            role = "prefill" if req.phase == "prefill" else "decode"
            pool = [r for r in healthy
                    if getattr(r, "role", None) == role]
            if pool:
                if role == "prefill":
                    return min(pool, key=lambda rep: (
                        self.assigned_count(rep.name),
                        self.outstanding_tokens(rep.name), rep.name))
                return prefix_affinity(req, pool, self, self._rng)
        return self._policy(req, healthy, self, self._rng)

    def dispatch(self, req: FleetRequest, replica, now: float) -> None:
        """Hand ``req`` to ``replica``.  Fires the ``router.dispatch``
        chaos site BEFORE the hand-off: an injected fault here models a
        dispatch-path failure (connection refused, serialization error)
        and is the retry/backoff path's test vector."""
        req.attempts += 1              # counted even if the dispatch faults
        if req.trace is not None:
            # new attempt span, SAME trace/flow id: a retried or migrated
            # request stays one causal tree with per-attempt children
            req.trace = req.trace.child(phase=req.phase,
                                        attempt=req.attempts)
        faults.fire("router.dispatch", index=req.index,
                    replica=replica.name)
        req.assigned = replica.name
        req.deadline = (now + self.config.request_timeout_s
                        if self.config.request_timeout_s > 0
                        else float("inf"))
        self.inflight[req.index] = req
        # this replica's radix residency is about to change (the dispatch
        # will insert the request's blocks, and its adapter pool may load
        # or evict pages): drop its probe caches so the next pick
        # re-probes it — everyone else's entries stay warm
        self._residency.pop(replica.name, None)
        self._adapter_residency.pop(replica.name, None)
        replica.enqueue(req)

    def fail_attempt(self, req: FleetRequest, now: float, reason: str,
                     detail: str = "") -> None:
        """One attempt failed (dispatch error, timeout, replica death).
        Requeue with backoff, or — past the bounded budget — move the
        request to ``failed`` as a typed :class:`RequestFailed`."""
        self.inflight.pop(req.index, None)
        req.assigned = None
        req.epoch += 1
        if req.attempts > self.config.max_retries:
            self.failed[req.index] = RequestFailed(
                req.index, reason, req.attempts, detail)
            logger.warning(f"router: {self.failed[req.index]}")
            return
        self.c_retries.inc(1, reason=reason)
        req.next_eligible = now + self.backoff(req.attempts)
        self.pending.append(req)

    # ----------------------------------------------------------- migration
    def migrate(self, req: FleetRequest, now: float, *, reason: str,
                record: Optional[dict] = None,
                burn_budget: bool = True) -> None:
        """Re-enter a queued/in-flight request after a replica death or
        drain.  ``record`` is the engine's export for this request (folded
        prompt + generated tail); without one (heartbeat-declared death of
        a hung replica — its state cannot be read safely) the request
        retries from its last known context, recomputing the lost tail.
        The ORIGINAL arrival timestamp is preserved: with greedy decoding
        the re-served request completes token-exact vs. a no-failure run."""
        if req.assigned is not None:
            self._residency.pop(req.assigned, None)
            self._adapter_residency.pop(req.assigned, None)
        self.inflight.pop(req.index, None)
        req.assigned = None
        req.epoch += 1
        req.migrations += 1
        if record is not None:
            req.prompt = record["prompt"]
            req.generated = req.generated + list(record["generated"])
        self.c_migrated.inc(1)
        if burn_budget:
            # the failed dispatch itself already counted in req.attempts
            if req.attempts > self.config.max_retries:
                self.failed[req.index] = RequestFailed(
                    req.index, reason, req.attempts)
                logger.warning(f"router: {self.failed[req.index]}")
                return
            self.c_retries.inc(1, reason=reason)
        # no backoff: the survivors are healthy and the request has waited
        # since its original arrival already
        req.next_eligible = now
        self.pending.append(req)

    # ------------------------------------------------------------- handoff
    def handoff(self, index: int, epoch: int, tokens: np.ndarray,
                now: float) -> Optional[FleetRequest]:
        """Advance a prefill-phase request to its decode phase: fold the
        prefill attempt's output (its first generated token) into the
        prompt — the SAME host-known fold migration uses, so greedy decode
        on any replica continues token-exact — and requeue it immediately
        as phase "decode" for the decode pool to pick up.  Burns no retry
        budget (a handoff is the request's normal lifecycle, not a
        failure).  Strictly epoch-gated, unlike ``complete``: a stale
        prefill attempt must not fold into a request some LIVE attempt
        owns — the live attempt produces its own (token-identical)
        result.  Returns the advanced request, or None when stale/done."""
        if index in self.done or index in self.failed:
            return None
        req = self.inflight.get(index)
        if req is None or req.epoch != epoch:
            return None
        del self.inflight[index]
        req.assigned = None
        req.epoch += 1
        new = [int(t) for t in np.asarray(tokens).reshape(-1)
               [len(req.generated):]]
        if new:
            req.prompt = np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(new, np.int32)])
            req.generated = req.generated + new
        req.phase = "decode"
        req.next_eligible = now     # no backoff: this is progress
        self.pending.append(req)
        return req

    # ---------------------------------------------------------- completion
    def complete(self, index: int, epoch: int, tokens: np.ndarray) -> bool:
        """Record a finished request.  First result wins: a stale attempt
        (its epoch lost a requeue race) that still finished carries
        token-identical output under greedy decoding, so it is accepted
        when it arrives first and dropped when it arrives second — either
        way ``done`` holds exactly one output per request."""
        if index in self.done or index in self.failed:
            return False
        req = self.requests.get(index)
        if req is None:
            return False
        self.done[index] = np.asarray(tokens, np.int32)
        # first result retires the request wherever it currently sits —
        # inflight (fresh or re-dispatched attempt) or pending (queued for
        # a retry the stale result just made unnecessary); ``epoch`` is
        # informational here, it only gates MIGRATION records
        self.inflight.pop(index, None)
        self.pending = [r for r in self.pending if r.index != index]
        return True

    def check_timeouts(self, now: float) -> List[FleetRequest]:
        """Per-attempt deadlines: an in-flight request past its deadline is
        retried elsewhere (reason ``timeout``; the hung attempt's late
        result deduplicates against the epoch bump)."""
        late = [r for r in self.inflight.values() if now > r.deadline]
        for req in late:
            self.fail_attempt(req, now, "timeout",
                              detail=f"replica {req.assigned}")
        return late

    # ----------------------------------------------------------- residency
    def residency(self, rep, req: FleetRequest) -> int:
        """Cached radix-residency probe for ``prefix_affinity``: how many
        of ``req.prompt``'s tokens are radix-resident on ``rep``.  The
        underlying ``engine.prefix_cached_tokens`` walk is O(prompt) per
        replica per request; at fleet scale that walk WAS the routing
        cost, so results cache per (replica, prompt bytes) until the
        replica's residency can have changed — a dispatch to it, a
        migration off it, or its death drops that replica's entries
        (``invalidate_residency``).  A stale entry therefore only ever
        UNDER-states residency, which costs one suboptimal pick, never
        correctness.  Replicas without a probe (fakes, cache off) report
        0 uncached, and a failing probe (dying replica) reports 0 without
        poisoning the cache."""
        probe = getattr(getattr(rep, "engine", None),
                        "prefix_cached_tokens", None)
        if probe is None:
            return 0
        cache = self._residency.setdefault(rep.name, {})
        key = np.asarray(req.prompt, np.int32).tobytes()
        hit = cache.get(key)
        if hit is None:
            try:
                hit = int(probe(req.prompt))
            except Exception:  # noqa: BLE001 — a dying replica's probe
                return 0       # must never take the dispatcher down
            if len(cache) >= self._residency_cap:
                cache.clear()
            cache[key] = hit
        return hit

    def adapter_residency(self, rep, req: FleetRequest) -> int:
        """Cached adapter-residency probe for ``prefix_affinity``: 1 when
        ``rep``'s adapter pool already holds ``req.adapter``'s pages, else
        0.  Base-model requests (adapter 0) and replicas without a probe
        (fakes, adapters off) report 0 — the signal vanishes and routing
        degrades to exactly the prefix/least-outstanding order.  The probe
        (``engine.adapter_resident``) is a read-only host dict peek, safe
        from the dispatcher thread; results cache per (replica, adapter)
        and invalidate wherever the prefix cache does (dispatch,
        migration, death), since a dispatch can load OR evict adapter
        pages.  A stale entry only ever UNDER-states residency — one
        suboptimal pick and a hot-load, never a correctness issue — and a
        failing probe (dying replica) reports 0 without poisoning the
        cache."""
        if not req.adapter:
            return 0
        probe = getattr(getattr(rep, "engine", None),
                        "adapter_resident", None)
        if probe is None:
            return 0
        cache = self._adapter_residency.setdefault(rep.name, {})
        hit = cache.get(req.adapter)
        if hit is None:
            try:
                hit = int(probe([req.adapter]))
            except Exception:  # noqa: BLE001 — a dying replica's probe
                return 0       # must never take the dispatcher down
            if len(cache) >= self._residency_cap:
                cache.clear()
            cache[req.adapter] = hit
        return hit

    def invalidate_residency(self, name: Optional[str] = None) -> None:
        """Drop the residency probe cache for one replica (death, drain,
        role flip) or for the whole fleet (``name=None``)."""
        if name is None:
            self._residency.clear()
            self._adapter_residency.clear()
        else:
            self._residency.pop(name, None)
            self._adapter_residency.pop(name, None)

    # -------------------------------------------------------------- status
    def outstanding_tokens(self, replica_name: str) -> int:
        return sum(len(r.prompt) + r.remaining
                   for r in self.inflight.values()
                   if r.assigned == replica_name)

    def assigned_count(self, replica_name: str) -> int:
        """In-flight requests currently assigned to ``replica_name`` (the
        prefill pool's shortest-queue routing signal)."""
        return sum(1 for r in self.inflight.values()
                   if r.assigned == replica_name)

    def assigned_to(self, replica_name: str) -> List[FleetRequest]:
        return [r for r in self.inflight.values()
                if r.assigned == replica_name]

    def settled(self) -> bool:
        return not self.pending and not self.inflight
