"""PoolAutoscaler — signal-driven prefill/decode pool rebalancing for the
disaggregated serving fleet.

Splitting the fleet into phase-specialist pools (serving/fleet.py,
``FleetConfig.disaggregated``) trades one sizing problem for another: a
fixed prefill/decode split is only right for one workload shape, and real
traffic drifts — a burst of long prompts starves the prefill pool (TTFT
blows up while decode replicas idle), a burst of long generations starves
decode (TPOT blows up while prefill replicas idle).  The autoscaler closes
that loop from signals earlier PRs already landed, no new instrumentation
required:

- **TTFT-vs-TPOT histogram skew** — the ratio of fleet-wide p99
  ``serving_ttft_ms`` to p99 ``serving_tpot_ms``.  TTFT is paid in the
  prefill pool, TPOT in the decode pool, so the ratio points at the
  starved side: above ``skew_to_prefill`` a decode replica flips to
  prefill, below ``skew_to_decode`` a prefill replica flips to decode.
  The fleet's serving histograms are per-``replica``-labeled series over
  one shared registry; the fleet-wide read aggregates across label sets
  (max p99 — the SLO-relevant replica IS the worst one).
- **admission shedding rate** — when the admission controller is actively
  shedding (hysteresis latch + its windowed rejection rate,
  ``AdmissionController.shed_rate``), the fleet is in overload and a
  mis-sized pool is costing goodput NOW: both skew thresholds tighten by
  ``shed_tighten`` so the autoscaler acts earlier.

Decisions are bounded, never a correctness gate: per-pool floors
(``min_prefill``/``min_decode``), an evaluation ``interval_s``, a
``cooldown_s`` between moves, and a ``min_requests`` signal-mass floor
keep one noisy percentile from flapping replicas.  The MOVE itself is the
fleet's job (``ServingFleet._rebalance_pools``): it flips an IDLE
replica's role and respawns it against the shared jitted-step cache, so a
role flip is a warm respawn — the programs both roles run are the same
compiled set, and the recompile watchdog in the tests pins that no new
program is compiled by a flip.

Metrics: ``pool_rebalances_total`` (per direction) counts moves,
``pool_replicas`` (per role) gauges the current split.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

from deepspeed_tpu.config import DeepSpeedConfigModel
from deepspeed_tpu.telemetry.registry import MetricRegistry
from deepspeed_tpu.utils.logging import logger


class AutoscaleConfig(DeepSpeedConfigModel):
    """``autoscale`` block of the fleet config (disaggregated mode only).

    ``skew_to_prefill``/``skew_to_decode`` bound the healthy band of
    p99-TTFT / p99-TPOT: a prefill-starved fleet queues prompts (TTFT
    grows, TPOT flat — ratio rises above the band), a decode-starved one
    queues tokens (ratio falls below it).  The defaults are deliberately
    wide: prefill is prompt-sized work and TTFT p99 legitimately sits
    well above per-token latency; only sustained skew past the band means
    the SPLIT is wrong rather than the workload heavy."""

    enabled: bool = False
    min_prefill: int = 1
    min_decode: int = 1
    interval_s: float = 0.25        # signal evaluation cadence
    cooldown_s: float = 1.0         # minimum time between moves
    skew_to_prefill: float = 50.0   # ratio above: decode replica -> prefill
    skew_to_decode: float = 2.0     # ratio below: prefill replica -> decode
    shed_tighten: float = 2.0       # threshold tightening while shedding
    min_requests: int = 4           # completed-request mass before acting
    # opt-in SLO burn-rate input (serving/slo.py): while the fleet's
    # paging-condition burn is at or past ``high_slo_burn``, the skew
    # thresholds tighten by ``shed_tighten`` exactly as during admission
    # shedding — the error budget is being spent too fast, so a
    # mis-sized split must be corrected earlier
    slo_burn_input: bool = False
    high_slo_burn: float = 1.0


class PoolAutoscaler:
    """Pure decision core + metric bookkeeping; the fleet owns the move.

    Separation of concerns mirrors the admission controller: ``signals()``
    reads the shared registry, ``decide()`` is a pure function of those
    signals (deterministic unit tests feed it directly), ``evaluate()``
    adds the rate limits and pool floors, and ``record_move()`` books a
    move the fleet actually performed."""

    def __init__(self, config: Optional[AutoscaleConfig] = None, *,
                 registry: MetricRegistry,
                 clock: Callable[[], float]):
        self.config = AutoscaleConfig.parse(config)
        self.registry = registry
        self.clock = clock
        self._last_eval = -math.inf
        self._last_move = -math.inf
        self.last_signals: Dict[str, float] = {}
        self.c_rebalances = registry.counter(
            "pool_rebalances_total", "replicas moved between the prefill "
            "and decode pools by the autoscaler, per direction "
            "(to_prefill / to_decode)")
        self.g_pool = registry.gauge(
            "pool_replicas", "healthy replicas per disaggregated pool "
            "role (prefill / decode)")

    # -------------------------------------------------------------- signals
    def _fleet_p99(self, name: str):
        """(max p99 across the metric's per-replica label sets, total
        observation count).  Serving histograms carry a per-``replica``
        label over the shared fleet registry and ``Histogram.quantile`` is
        exact-label-match, so a fleet-wide read must aggregate across the
        label sets; max is the SLO-relevant aggregate (the worst replica
        is the one breaching)."""
        m = self.registry._metrics.get(name)
        if m is None or getattr(m, "kind", "") != "histogram":
            return float("nan"), 0
        worst, count = float("nan"), 0
        for _labels, stats in m.samples():
            count += int(stats.get("count", 0))
            p99 = float(stats.get("p99", float("nan")))
            if not math.isnan(p99) and \
                    (math.isnan(worst) or p99 > worst):
                worst = p99
        return worst, count

    def signals(self, *, shedding: bool = False,
                shed_rate: float = 0.0,
                slo_burn: Optional[float] = None) -> Dict[str, float]:
        """Read the landed signals off the shared registry.  ``shedding``/
        ``shed_rate`` come from the fleet's admission controller and
        ``slo_burn`` from its SLO monitor (they are controller state, not
        registry series with a stable cross-version shape)."""
        ttft, n_ttft = self._fleet_p99("serving_ttft_ms")
        tpot, n_tpot = self._fleet_p99("serving_tpot_ms")
        return {"ttft_p99_ms": ttft, "tpot_p99_ms": tpot,
                "requests": min(n_ttft, n_tpot),
                "shedding": bool(shedding),
                "shed_rate": float(shed_rate),
                "slo_burn": (float(slo_burn)
                             if slo_burn is not None else 0.0)}

    # ------------------------------------------------------------- decision
    def decide(self, signals: Dict[str, float]) -> Optional[str]:
        """Pure skew decision: "to_prefill", "to_decode", or None.  No
        clocks, no floors — ``evaluate`` layers those on."""
        cfg = self.config
        if signals.get("requests", 0) < cfg.min_requests:
            return None
        ttft = signals.get("ttft_p99_ms", float("nan"))
        tpot = signals.get("tpot_p99_ms", float("nan"))
        if math.isnan(ttft) or math.isnan(tpot) or tpot <= 0.0:
            return None
        burning = (cfg.slo_burn_input
                   and signals.get("slo_burn", 0.0) >= cfg.high_slo_burn)
        tighten = (cfg.shed_tighten
                   if (signals.get("shedding") or burning) else 1.0)
        ratio = ttft / tpot
        if ratio > cfg.skew_to_prefill / tighten:
            return "to_prefill"
        if ratio < cfg.skew_to_decode * tighten:
            return "to_decode"
        return None

    def evaluate(self, now: float, pool_sizes: Dict[str, int], *,
                 shedding: bool = False,
                 shed_rate: float = 0.0,
                 slo_burn: Optional[float] = None) -> Optional[str]:
        """Rate-limited decision against the live pool sizes: returns a
        direction the fleet should move ONE replica in, or None.  Keeps
        the ``pool_replicas`` gauge fresh as a side effect (it reads the
        fleet's actual role census, so it is correct even when no move
        happens)."""
        for role in ("prefill", "decode"):
            self.g_pool.set(float(pool_sizes.get(role, 0)), role=role)
        cfg = self.config
        if not cfg.enabled:
            return None
        if now - self._last_eval < cfg.interval_s:
            return None
        self._last_eval = now
        # kept for the bench/tests: proof of what the control loop SAW
        # (e.g. "the burn-rate alert reached the autoscaler hook")
        self.last_signals = self.signals(shedding=shedding,
                                         shed_rate=shed_rate,
                                         slo_burn=slo_burn)
        direction = self.decide(self.last_signals)
        if direction is None:
            return None
        if now - self._last_move < cfg.cooldown_s:
            return None
        donor = "decode" if direction == "to_prefill" else "prefill"
        floor = (cfg.min_decode if donor == "decode"
                 else cfg.min_prefill)
        if pool_sizes.get(donor, 0) <= floor:
            return None
        return direction

    def record_move(self, direction: str, now: float) -> None:
        """Book one completed move (the fleet flipped a replica)."""
        self._last_move = now
        self.c_rebalances.inc(1, direction=direction)
        logger.info(f"autoscaler: moved one replica {direction}")
