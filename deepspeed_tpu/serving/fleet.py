"""ServingFleet — N supervised ``InferenceEngineV2`` replicas behind a
failure-tolerant router.

One v2 engine is not a service: a replica death mid-decode used to lose
every in-flight request, and there was no admission, retry, or
degradation story between "one engine" and real traffic.  This module is
the composition layer over the primitives earlier PRs built — PR 6's
drain semantics and deterministic fault injection (``runtime/faults.py``),
PR 5's serving telemetry (now with a per-replica label over one shared
registry) — treating replica failure as a supported membership event, the
serving-side analogue of the elastic agent's host-loss handling
(arXiv:2004.13336's fault model).

Replica lifecycle (state machine, one worker thread per incarnation)::

    spawn ──> healthy ──────────────> draining ──┐
                │  (request_drain: finish or     │
                │   migrate in-flight, export)   │
                │ death (fault / exception /     │
                │        heartbeat deadline)     │
                ▼                                ▼
              dead ──(respawn: fresh engine, WARM shared compile
                      cache = fast resume)──> healthy

Supervision signals: every replica beats once per engine scheduler round
(``replica.heartbeat`` chaos site) and the dispatcher deadlines busy
replicas on ``heartbeat_deadline_s``; the admission controller reads the
fleet-wide ``kv_alloc_failures_total`` sum and router queue depth.

Request flow: the router (serving/router.py) owns pending/inflight/done
with bounded retry + backoff; replica workers run ``engine.generate`` on
their queued batch and report completions or exported migrations through
one event queue back to the dispatcher (single-threaded control plane —
every state transition happens on the ``serve()`` thread).

Token-exactness invariant: all replicas are built from the SAME params
(shared tree or same init seed), decoding is greedy, and migration folds
only host-known generated tokens into the prompt — so any completion
path (direct, migrated once, migrated twice) yields the byte-identical
output of a single no-failure engine, which is what the chaos tests pin.

Chaos wiring: arm ``runtime/faults.py`` sites ``replica.mid_decode``
(death inside the scheduler loop), ``replica.heartbeat`` (``sleep`` =
stalled replica, ``exc`` = death at the beat), ``router.dispatch``
(dispatch-path failure -> retry/backoff), ``admission.decide`` (controller
failure -> fail open), ``handoff.mid_transfer`` (source replica death
between KV pin and handoff commit -> pins released, request re-enters
via the migration fold).

Disaggregated mode (``disaggregated: true``): replicas split into a
prefill pool (serves prompt + FIRST token only — the TTFT-critical
phase) and a decode pool (the token tail).  The phase boundary reuses
the migration fold: the prefill result folds into the prompt and the
request requeues as a decode-phase dispatch, so the decode replica's
prefill over the folded prompt hits either the radix alias of the
handed-off blocks (single-host shared pool) or recomputes token-exactly
— greedy outputs are byte-identical to a unified fleet either way.  A
signal-driven autoscaler (serving/autoscale.py) rebalances the split at
runtime via warm role flips against the shared compile cache.
"""

from __future__ import annotations

import copy
import dataclasses
import inspect
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
from pydantic import Field

from deepspeed_tpu.config import DeepSpeedConfigModel
from deepspeed_tpu.runtime import faults
from deepspeed_tpu.serving.admission import (AdmissionConfig,
                                             AdmissionController)
from deepspeed_tpu.serving.autoscale import AutoscaleConfig, PoolAutoscaler
from deepspeed_tpu.serving.router import (FleetRequest, NoHealthyReplicas,
                                          RequestFailed, Router,
                                          RouterConfig)
from deepspeed_tpu.serving.slo import SLOConfig, SLOMonitor
from deepspeed_tpu.telemetry.registry import MetricRegistry
from deepspeed_tpu.telemetry.tracer import SpanTracer, TraceEmitter
from deepspeed_tpu.utils.logging import logger

REPLICA_STATES = ("spawning", "healthy", "draining", "dead")


class FleetDrained(RuntimeError):
    """``serve()`` stopped because the whole fleet drained (preemption
    notice / ``drain_all``).  Carries what a successor fleet needs:
    ``completed`` (index -> tokens) and ``pending`` (migration-folded
    :class:`FleetRequest` records, original arrival timestamps intact)."""

    def __init__(self, completed: Dict[int, np.ndarray],
                 pending: List[FleetRequest]):
        super().__init__(
            f"fleet drained: {len(completed)} request(s) completed, "
            f"{len(pending)} exported for a successor")
        self.completed = completed
        self.pending = pending


class FleetConfig(DeepSpeedConfigModel):
    """Top-level fleet config.  ``heartbeat_deadline_s`` only applies to
    BUSY replicas (an idle worker beats from its wait loop without the
    chaos site) that have completed WARM-UP — until an incarnation's first
    ``generate`` completes, the (more generous) ``warmup_deadline_s``
    governs instead: a replica's first call legitimately stalls on the
    on-the-fly XLA compile, and a steady-state deadline would book a cold
    replica dead (the PR 8 review finding; bench_serving used to paper
    over it with a 120 s override).  ``max_respawns`` bounds
    death-respawns per replica; drain-respawns are planned events and
    bypass it (``respawn_after_drain``).  ``share_compile_cache`` hands
    every replica one jitted-step dict, so the fleet compiles each program
    once and a respawned replica fast-resumes warm."""

    num_replicas: int = 2
    heartbeat_deadline_s: float = 10.0
    # deadline for a not-yet-warm incarnation's first busy period (covers
    # the first-call compile); never below heartbeat_deadline_s
    warmup_deadline_s: float = 180.0
    respawn: bool = True
    max_respawns: int = 2
    respawn_after_drain: bool = True
    share_compile_cache: bool = True
    poll_interval_s: float = 0.005
    # disaggregated prefill/decode pools: the first ``prefill_replicas``
    # replicas serve ONLY the prompt+first-token phase, the rest only the
    # decode tail; finished prefill KV hands off to the decode replica
    # through the paged pool (refcounted block pin + radix prefix alias —
    # on single-host pools the alias IS the transfer; the multi-host copy
    # is a stub accounted in kv_handoff_bytes_total).  Both phases are
    # greedy over identical weights, so a disaggregated serve is
    # byte-identical to a unified one.
    disaggregated: bool = False
    prefill_replicas: int = 1
    # router-side distributed tracing: the fleet records dispatch /
    # handoff / request-envelope spans plus the Perfetto flow events
    # (``ph`` s/t/f) that stitch one request across the per-replica
    # trace files (telemetry/tracecontext.py).  Bounded like the replica
    # tracers; off = zero per-request trace work on the dispatcher.
    trace_enabled: bool = True
    max_trace_events: int = 100_000
    router: RouterConfig = Field(default_factory=RouterConfig)
    admission: AdmissionConfig = Field(default_factory=AdmissionConfig)
    autoscale: AutoscaleConfig = Field(default_factory=AutoscaleConfig)
    slo: SLOConfig = Field(default_factory=SLOConfig)


@dataclasses.dataclass(frozen=True)
class _Dispatch:
    """Immutable snapshot of one request at hand-off to a replica worker:
    the worker must never read the live (dispatcher-mutated) FleetRequest.
    ``gen`` is the serve-call generation — events from a zombie worker of
    an earlier serve() are dropped against it."""

    index: int
    epoch: int
    prompt: np.ndarray
    remaining: int
    prefix: Tuple[int, ...]
    gen: int
    # LoRA adapter id serving this request (0 = base model); threaded
    # into engine.generate(adapter_ids=...) when the engine accepts it
    adapter: int = 0
    # TraceContext of the dispatch attempt (already the per-attempt
    # child span — Router.dispatch minted it); threaded into the
    # engine's generate so replica trace files carry the fleet ids
    trace: Any = None


class Replica:
    """One supervised serving replica.  All state transitions happen on
    the dispatcher thread; the worker thread only reads its own
    incarnation's queue and reports through the fleet event queue."""

    def __init__(self, name: str, fleet: "ServingFleet"):
        self.name = name
        self.fleet = fleet
        self.state = "spawning"
        self.engine = None
        # pool membership in disaggregated mode ("prefill"/"decode"; None
        # in unified fleets).  Mutated only by the dispatcher thread — a
        # role flip stale-ifies the worker first (same incarnation fence
        # as a retire), so no worker ever serves across a flip.
        self.role: Optional[str] = None
        self.incarnation = 0
        self.respawns = 0              # death-respawns taken
        self.queue: List[_Dispatch] = []
        self.cond = threading.Condition()
        self.busy = False
        self.last_beat = fleet.clock()
        # warm-up gate: False until this incarnation completes a generate
        # (its first call contains the on-the-fly compile) — the supervisor
        # deadlines it on warmup_deadline_s, not heartbeat_deadline_s
        self.warmed = False
        self.worker: Optional[threading.Thread] = None

    def beat(self) -> None:
        """Engine-loop liveness beat (once per scheduler round, via
        ``engine.heartbeat_fn``).  Fires the ``replica.heartbeat`` chaos
        site FIRST: a ``sleep`` fault stalls the beat (the supervisor
        deadlines the replica out), an ``exc`` fault kills it here."""
        faults.fire("replica.heartbeat", replica=self.name)
        self.last_beat = self.fleet.clock()

    def enqueue(self, req: FleetRequest) -> None:
        # a prefill-phase request serves the prompt plus EXACTLY one token
        # (full prefill + first sample = the TTFT boundary); the decode
        # phase gets the rest of the budget after the handoff fold
        remaining = 1 if req.phase == "prefill" else req.remaining
        d = _Dispatch(index=req.index, epoch=req.epoch,
                      prompt=np.asarray(req.prompt, np.int32),
                      remaining=remaining,
                      prefix=tuple(req.generated),
                      gen=self.fleet._serve_gen,
                      adapter=int(req.adapter),
                      trace=req.trace)
        with self.cond:
            self.queue.append(d)
            self.cond.notify_all()


class ServingFleet:
    """N supervised replicas + router + admission controller.

    ``model``/``engine_config``/``params`` feed the default engine
    factory (every replica gets identical weights — required for
    token-exact migration); pass ``engine_factory(name)`` to construct
    custom (or fake, in tests) engines instead.  The engine protocol the
    fleet needs: ``generate(prompts, max_new_tokens=list)``,
    ``request_drain()``/``clear_drain()``, ``export_pending_requests()``,
    a writable ``heartbeat_fn`` attribute, and ``EngineDrained`` raised
    on drain.

    One shared ``MetricRegistry`` carries every replica's serving series
    (per-``replica`` label) plus the fleet families
    (``fleet_replica_state``, ``router_retries_total``,
    ``requests_migrated_total``, ``admission_rejections_total``, ...).
    """

    def __init__(self, model=None, engine_config: Optional[dict] = None,
                 params=None, config=None,
                 engine_factory: Optional[Callable[[str], Any]] = None,
                 clock: Optional[Callable[[], float]] = None,
                 registry: Optional[MetricRegistry] = None,
                 preemption_handler=None):
        self.config = FleetConfig.parse(config)
        if self.config.disaggregated:
            n, npre = self.config.num_replicas, self.config.prefill_replicas
            if not 1 <= npre < n:
                raise ValueError(
                    f"disaggregated fleet needs 1 <= prefill_replicas < "
                    f"num_replicas, got prefill_replicas={npre} of {n}")
            # the router must see the same mode (phase-aware pick)
            self.config.router.disaggregated = True
        self.clock = clock or time.monotonic
        self.registry = registry if registry is not None else MetricRegistry()
        self._model = model
        self._engine_config = engine_config or {}
        self._params = params
        self._steps_cache: Optional[Dict[Any, Any]] = (
            {} if self.config.share_compile_cache else None)
        if engine_factory is None and model is None:
            raise ValueError("pass a model (+ engine_config/params) or an "
                             "engine_factory")
        self._engine_factory = engine_factory or self._default_factory
        self._events: "queue.Queue" = queue.Queue()
        self._serve_gen = 0
        self._fleet_draining = False
        self._admission_failed_open = False
        self.request_log: List[dict] = []
        self.last_failures: Dict[int, RequestFailed] = {}
        self.router = Router(self.config.router, clock=self.clock,
                             registry=self.registry)
        self.admission = AdmissionController(
            self.config.admission, registry=self.registry, clock=self.clock)
        self.g_state = self.registry.gauge(
            "fleet_replica_state", "one-hot replica state machine: 1 for "
            "the replica's current state (spawning / healthy / draining / "
            "dead), 0 for the rest")
        self.c_deaths = self.registry.counter(
            "fleet_replica_deaths_total", "replica deaths booked by the "
            "supervisor, per reason (replica_death / heartbeat_timeout / "
            "drain / respawn_failed)")
        self.c_respawns = self.registry.counter(
            "fleet_respawns_total", "replica respawns (fresh engine against "
            "the warm shared compile cache) after a death or drain")
        self.h_recovery = self.registry.histogram(
            "fleet_recovery_ms", "replica death/drain detection to the "
            "replacement healthy (in-flight work is already requeued "
            "before the respawn starts)")
        self.c_handoffs = self.registry.counter(
            "fleet_handoffs_total", "prefill->decode phase handoffs, per "
            "outcome: ok (blocks pinned or accounting-free), aborted "
            "(source died mid-transfer; pins released, request re-entered "
            "through the migration fold)")
        self.c_handoff_bytes = self.registry.counter(
            "kv_handoff_bytes_total", "KV bytes the multi-host handoff "
            "copy path WOULD move (pinned blocks x per-block KV bytes); "
            "single-host pools alias the blocks instead of copying, so "
            "the counter sizes the future wire transfer, not work done")
        # index -> (source replica, incarnation at pin time, pinned block
        # ids): handoff pins released at final completion (or dropped when
        # the source incarnation — and with it the allocator — is gone)
        self._handoffs: Dict[int, Tuple[str, int, List[int]]] = {}
        # router-side tracer: dispatch/handoff/request spans + flow
        # events on pid 0 (replica tracers use their own pids), one tid
        # per request.  _trace_clock_t0 anchors the fleet's injected
        # clock onto the tracer's microsecond epoch.
        self.tracer = SpanTracer(enabled=bool(self.config.trace_enabled),
                                 pid=0,
                                 max_events=int(self.config.max_trace_events))
        self.trace_emitter = TraceEmitter(process_name="deepspeed_tpu_router")
        self._trace_clock_t0 = self.clock()
        # per-request start of the current router-hold interval (arrival,
        # or the end of the previous dispatch/handoff) — the "dispatch"
        # slice each attempt records spans it
        self._trace_hold: Dict[int, float] = {}
        # continuous SLO signals: ring-buffer sampling of the shared
        # registry + multi-window burn rate over the TTFT/TPOT histograms
        # (serving/slo.py).  Sampled from the dispatcher tick — the
        # sampler never blocks the scheduler round.
        self.slo_monitor: Optional[SLOMonitor] = None
        if self.config.slo.enabled:
            self.slo_monitor = SLOMonitor(self.config.slo,
                                          registry=self.registry,
                                          clock=self.clock)
        self._autoscaler: Optional[PoolAutoscaler] = None
        if self.config.disaggregated:
            self._autoscaler = PoolAutoscaler(
                self.config.autoscale, registry=self.registry,
                clock=self.clock)
        # fleet-wide LoRA adapter registry: {id -> host weights or None},
        # replayed onto every fresh incarnation in _spawn so a respawned
        # replica can serve a migrated adapter request token-exact
        self._adapter_registry: Dict[int, Any] = {}
        self.replicas: Dict[str, Replica] = {}
        for i in range(int(self.config.num_replicas)):
            rep = Replica(f"r{i}", self)
            if self.config.disaggregated:
                rep.role = ("prefill"
                            if i < int(self.config.prefill_replicas)
                            else "decode")
            self.replicas[rep.name] = rep
            self._spawn(rep, is_respawn=False)
        self._handler = preemption_handler
        if self._handler is not None:
            # latch + poke: the signal frame only sets the flag and drops a
            # marker into the event queue so a sleeping tick wakes promptly
            if hasattr(self._handler, "set_notice_callback"):
                self._handler.set_notice_callback(
                    lambda reason: self._events.put(("wakeup",)))
            self._handler.install()

    # ------------------------------------------------------------ spawning
    def _default_factory(self, name: str):
        from deepspeed_tpu.inference.v2 import InferenceEngineV2
        ecfg = copy.deepcopy(self._engine_config)
        ecfg.setdefault("telemetry", {})["replica"] = name
        if self.config.disaggregated:
            # the handoff pins radix-matched blocks on the source pool, and
            # decode-side prefix_affinity routes on radix residency: both
            # need the prefix cache on every replica
            sm = ecfg.setdefault("state_manager", {})
            if isinstance(sm, dict):
                sm.setdefault("prefix_cache", True)
        return InferenceEngineV2(self._model, ecfg, params=self._params,
                                 steps_cache=self._steps_cache,
                                 telemetry_registry=self.registry)

    def _set_state(self, rep: Replica, state: str) -> None:
        assert state in REPLICA_STATES, state
        rep.state = state
        for s in REPLICA_STATES:
            self.g_state.set(1.0 if s == state else 0.0,
                             replica=rep.name, state=s)

    def _spawn(self, rep: Replica, *, is_respawn: bool) -> bool:
        self._set_state(rep, "spawning")
        try:
            if is_respawn:
                # chaos site: an exc here models the factory itself failing
                # (OOM building the engine, a torn shared cache, ...)
                faults.fire("fleet.respawn_factory", replica=rep.name)
            engine = self._engine_factory(rep.name)
        except Exception as e:  # noqa: BLE001 — a respawn-factory failure
            if not is_respawn:
                raise          # construction-time errors surface to the user
            # books THIS replica dead and keeps the dispatcher alive: one
            # replica that cannot be rebuilt must degrade the fleet to
            # N-1, never unwind the whole control plane (PR 8 finding)
            logger.error(f"fleet: respawn factory for {rep.name} failed "
                         f"({e!r}); booking the replica dead")
            with rep.cond:
                rep.incarnation += 1     # no worker runs this incarnation
                rep.busy = False
                rep.queue.clear()
            rep.engine = None
            self._set_state(rep, "dead")
            self.c_deaths.inc(1, reason="respawn_failed")
            return False
        if hasattr(engine, "clear_drain"):
            engine.clear_drain()
        if self._adapter_registry and hasattr(engine, "register_adapter"):
            # replay the fleet's adapter set onto the fresh pool (host
            # dicts only — pages hot-load on first use); identical
            # weights per id on every replica keeps migration token-exact
            for aid, w in self._adapter_registry.items():
                engine.register_adapter(aid, w)
        rep.engine = engine
        with rep.cond:
            rep.incarnation += 1
            inc = rep.incarnation
            rep.busy = False
            # a respawn against an already-populated shared compile cache
            # performs no first-call compile: it runs under the
            # steady-state deadline immediately — the warm-up budget
            # would let a wedged respawn (and its queued requests) sit
            # undetected for warmup_deadline_s with no compile to excuse.
            # The cache maps engine fingerprint → compiled-program dict,
            # and engines eagerly create their (empty) sub-dict at
            # construction: only a sub-dict with actual programs counts.
            rep.warmed = bool(
                is_respawn and self._steps_cache
                and any(self._steps_cache.values()))
            rep.queue.clear()

        def _beat(rep=rep, inc=inc):
            # incarnation-guarded: a ZOMBIE worker (heartbeat-declared dead,
            # still inside its old engine.generate) must neither refresh the
            # replacement's liveness clock — that would mask a real hang —
            # nor consume chaos faults armed for the live incarnation
            if rep.incarnation == inc:
                rep.beat()
        engine.heartbeat_fn = _beat
        rep.last_beat = self.clock()
        rep.worker = threading.Thread(
            target=self._worker, args=(rep, engine, inc), daemon=True,
            name=f"fleet-{rep.name}-i{inc}")
        rep.worker.start()
        self._set_state(rep, "healthy")
        if is_respawn:
            self.c_respawns.inc(1)
        return True

    # ------------------------------------------------------------- tracing
    def _trace_us(self, t: float) -> float:
        """Map a fleet-clock timestamp onto the router tracer's epoch."""
        return (t - self._trace_clock_t0) * 1e6

    def _trace_dispatch(self, req: FleetRequest, replica_name: str,
                        now: float) -> None:
        """Record one dispatch attempt on the request's router track: a
        slice covering the hold since arrival / the previous hop, plus
        the flow event (``s`` on the first attempt, ``t`` after) that
        chains it to the replica-side spans."""
        if not self.tracer.enabled or req.trace is None:
            return
        tid = req.index + 1
        start = self._trace_hold.get(req.index, req.t_arrival)
        self._trace_hold[req.index] = now
        ts = self._trace_us(start)
        dur = max((now - start) * 1e6, 1.0)
        self.tracer.record(f"dispatch {req.phase}", ts, dur, tid=tid,
                           cat="router", replica=replica_name,
                           **req.trace.args())
        if req.trace.flow_id is not None:
            self.tracer.flow("s" if req.attempts == 1 else "t",
                             req.trace.flow_id, ts + dur / 2, tid=tid)

    def _trace_request(self, req: FleetRequest, now: float,
                       n_tokens: int) -> None:
        """Record the request envelope [arrival, done] — the outer span
        critical_path.py decomposes — and terminate the flow (``f``)."""
        if not self.tracer.enabled or req.trace is None:
            return
        tid = req.index + 1
        self.tracer.set_thread_name(tid, f"req {req.index}")
        ts = self._trace_us(req.t_arrival)
        dur = max((now - req.t_arrival) * 1e6, 1.0)
        self.tracer.record(
            "request", ts, dur, tid=tid, cat="router",
            mode="disagg" if self.config.disaggregated else "unified",
            index=req.index, attempts=req.attempts,
            migrations=req.migrations, generated_tokens=int(n_tokens),
            **req.trace.args())
        if req.trace.flow_id is not None:
            self.tracer.flow("f", req.trace.flow_id, ts + dur / 2, tid=tid)
        self._trace_hold.pop(req.index, None)

    def export_trace(self, path: str) -> Optional[str]:
        """Write the router-side trace (dispatch/handoff/request spans +
        flow events) — merge with the per-replica traces via
        scripts/merge_traces.py for the stitched fleet view."""
        if not self.tracer.enabled or not self.tracer.events:
            return None
        return self.trace_emitter.write(path, self.tracer)

    # ------------------------------------------------------ replica worker
    def _worker(self, rep: Replica, engine, incarnation: int) -> None:
        from deepspeed_tpu.inference.v2.engine_v2 import EngineDrained
        # probed once per incarnation: fake/minimal engines in tests need
        # not accept the trace_ctx / adapter_ids keywords
        try:
            gen_params = inspect.signature(engine.generate).parameters
            accepts_trace = "trace_ctx" in gen_params
            accepts_adapters = "adapter_ids" in gen_params
        except (TypeError, ValueError):
            accepts_trace = False
            accepts_adapters = False
        while True:
            with rep.cond:
                while not rep.queue:
                    if rep.incarnation != incarnation:
                        return
                    rep.cond.wait(timeout=0.05)
                    # idle liveness (no chaos site: only the engine loop's
                    # beat models a SERVING replica's heartbeat)
                    rep.last_beat = self.clock()
                if rep.incarnation != incarnation:
                    return
                batch, rep.queue = rep.queue, []
                rep.busy = True
                # deadline clock starts at pick-up, not at the last idle
                # beat (the queue wait must not count against serving)
                rep.last_beat = self.clock()
            try:
                gen_kwargs = {}
                if accepts_trace:
                    gen_kwargs["trace_ctx"] = [d.trace for d in batch]
                # base-model-only batches skip the keyword entirely so an
                # adapter-less fleet's generate calls stay byte-identical
                if accepts_adapters and any(d.adapter for d in batch):
                    gen_kwargs["adapter_ids"] = [d.adapter for d in batch]
                outs = engine.generate(
                    [d.prompt for d in batch],
                    max_new_tokens=[d.remaining for d in batch],
                    **gen_kwargs)
                items = [(d.index, d.epoch, self._stitch(d.prefix, out))
                         for d, out in zip(batch, outs)]
                self._events.put(("complete", rep.name, incarnation,
                                  batch[0].gen, items))
                with rep.cond:
                    if rep.incarnation == incarnation:
                        rep.busy = False
                        rep.warmed = True    # first generate done: the
                        #                      compile is behind us
            except EngineDrained:
                self._events.put(("drained", rep.name, incarnation,
                                  batch[0].gen,
                                  *self._merge_export(engine, batch), ""))
                self._worker_exit(rep, incarnation)
                return
            except BaseException as e:  # noqa: BLE001 — a replica death is
                #                         whatever escaped the engine
                self._events.put(("death", rep.name, incarnation,
                                  batch[0].gen,
                                  *self._merge_export(engine, batch),
                                  repr(e)))
                self._worker_exit(rep, incarnation)
                return

    def _worker_exit(self, rep: Replica, incarnation: int) -> None:
        with rep.cond:
            if rep.incarnation == incarnation:
                rep.busy = False

    @staticmethod
    def _stitch(prefix: Tuple[int, ...], out: np.ndarray) -> np.ndarray:
        if not prefix:
            return np.asarray(out, np.int32)
        return np.concatenate([np.asarray(prefix, np.int32),
                               np.asarray(out, np.int32)])

    @staticmethod
    def _merge_export(engine, batch: List[_Dispatch]):
        """Map the engine's per-call export (local prompt indices) back to
        fleet indices/epochs.  Safe on a dead engine (host-state only);
        a failed export degrades to record-less migration."""
        try:
            completed, pending = engine.export_pending_requests()
        except Exception:  # noqa: BLE001 — dead replica, best effort
            completed, pending = {}, []
        items = [(batch[i].index, batch[i].epoch,
                  ServingFleet._stitch(batch[i].prefix, toks))
                 for i, toks in completed.items() if i < len(batch)]
        migrations = []
        exported = set()
        for rec in pending:
            if rec["index"] >= len(batch):
                continue                 # defensive: not this batch's export
            d = batch[rec["index"]]
            exported.add(rec["index"])
            migrations.append((d.index, d.epoch,
                               {"prompt": rec["prompt"],
                                "generated": list(rec["generated"])}))
        # engine errors before generate() set a serve context (e.g. a
        # death at the very first scheduler round of a previous context)
        # leave batch members unexported: migrate them record-less
        for i, d in enumerate(batch):
            if i not in exported and all(it[0] != d.index for it in items):
                migrations.append((d.index, d.epoch, None))
        return items, migrations

    # ------------------------------------------------------------- serving
    def serve(self, prompts, max_new_tokens=32, arrival_times=None,
              adapter_ids=None, raise_on_failure: bool = True,
              max_wall_s: Optional[float] = None) -> List[np.ndarray]:
        """Serve ``prompts`` to completion across the fleet and return one
        output array per prompt (order preserved).  ``arrival_times`` are
        open-loop offsets in seconds from call start (requests dispatch
        only once arrived).  ``adapter_ids`` optionally pins each request
        to a LoRA adapter registered on the replicas (0/None = base
        model); the id sticks to the request through retries, migrations,
        and the prefill->decode handoff, and an adapter the target replica
        can never fit fails the REQUEST typed (``invalid_request``), not
        the replica.  Failed requests (retry budget exhausted, admission
        bound, no replicas left) surface as a typed
        :class:`RequestFailed` — raised after everything else settled, or
        returned as ``None`` entries with ``raise_on_failure=False``
        (details in ``self.last_failures``).  ``max_wall_s`` is a hard
        safety deadline for tests ("not a hang")."""
        if isinstance(max_new_tokens, (int, np.integer)):
            max_list = [int(max_new_tokens)] * len(prompts)
        else:
            max_list = [int(m) for m in max_new_tokens]
            if len(max_list) != len(prompts):
                raise ValueError("max_new_tokens list must match prompts")
        if arrival_times is not None and len(arrival_times) != len(prompts):
            raise ValueError("arrival_times must match prompts")
        if adapter_ids is not None and len(adapter_ids) != len(prompts):
            raise ValueError("adapter_ids list must match prompts")
        self._serve_gen += 1
        self.request_log = []
        self.last_failures = {}   # never leak a previous serve's failures
        #                           into a call that exits via an exception
        # purge replica queues of any previous serve's undispatched work
        # (e.g. a timed-out attempt whose replica never woke): a batch is
        # taken atomically, so after this every batch is gen-homogeneous
        # and the event-level gen filter in _handle_event is exact
        for rep in self.replicas.values():
            with rep.cond:
                rep.queue.clear()
        # release any handoff pins a previous serve left behind (e.g. an
        # exception path between handoff and final completion)
        for index in list(self._handoffs):
            self._release_handoff(index)
        self.router = Router(self.config.router, clock=self.clock,
                             registry=self.registry)
        self._trace_hold.clear()
        t0 = self.clock()
        phase = "prefill" if self.config.disaggregated else "full"
        for i, (p, m) in enumerate(zip(prompts, max_list)):
            self.router.submit(FleetRequest(
                index=i, prompt=np.asarray(p, np.int32).reshape(-1),
                max_new_tokens=m, phase=phase,
                adapter=(int(adapter_ids[i])
                         if adapter_ids is not None else 0),
                t_arrival=t0 + (float(arrival_times[i])
                                if arrival_times is not None else 0.0)))
        while not self.router.settled():
            if max_wall_s is not None and self.clock() - t0 > max_wall_s:
                raise RuntimeError(
                    f"fleet serve exceeded max_wall_s={max_wall_s}: "
                    f"{len(self.router.pending)} pending, "
                    f"{len(self.router.inflight)} inflight, states "
                    f"{[(r.name, r.state) for r in self.replicas.values()]}")
            self._tick()
            if self._fleet_draining and not self.router.inflight \
                    and not any(r.busy for r in self.replicas.values()):
                raise FleetDrained(dict(self.router.done),
                                   list(self.router.pending))
        self.last_failures = dict(self.router.failed)
        if self.last_failures and raise_on_failure:
            raise self.last_failures[min(self.last_failures)]
        return [self.router.done.get(i) for i in range(len(prompts))]

    # ------------------------------------------------------ dispatcher tick
    def _tick(self) -> None:
        # 1) block briefly on worker events (this wait paces the loop)
        try:
            self._handle_event(
                self._events.get(timeout=self.config.poll_interval_s))
            while True:
                self._handle_event(self._events.get_nowait())
        except queue.Empty:
            pass
        now = self.clock()
        # 2) preemption notice -> fleet-wide drain (flag polled, never a
        # signal-frame action: same contract as the training-side handler)
        if (self._handler is not None and not self._fleet_draining
                and self._handler.requested):
            self.drain_all()
        # 3) supervision: heartbeat deadlines, per-attempt timeouts,
        # draining replicas that went idle
        self._check_health(now)
        self.router.check_timeouts(now)
        for rep in list(self.replicas.values()):
            if rep.state == "draining":
                with rep.cond:
                    busy = rep.busy
                if busy:
                    rep.engine.request_drain()
                else:
                    self._retire_replica(rep, "drain")
        # 4) continuous SLO signals + admission control tick + dispatch
        slo_burn = None
        if self.slo_monitor is not None:
            # cadence-gated ring-buffer sample + burn re-evaluation:
            # bounded host reads, never blocks the round
            slo_burn = self.slo_monitor.tick(now)
        depth = self.router.queue_depth(now)
        self.admission.update(depth, slo_burn=slo_burn)
        # handoff pins of requests that FAILED (retry budget, admission
        # cap, ...) never reach _complete's release — sweep them here
        if self._handoffs:
            for index in [i for i in self._handoffs
                          if i in self.router.failed]:
                self._release_handoff(index)
        if self._fleet_draining:
            return
        if self._autoscaler is not None:
            self._rebalance_pools(now)
        for req in self.router.take_dispatchable(now):
            try:
                admitted, retry_after = self.admission.decide(req)
            except Exception as e:  # noqa: BLE001 — admission fails OPEN:
                # shedding is an optimization, never a correctness gate
                if not self._admission_failed_open:
                    self._admission_failed_open = True
                    logger.warning(f"admission controller failed open: {e!r}")
                admitted, retry_after = True, 0.0
            if not admitted:
                cap = self.config.admission.max_rejections
                if cap and req.rejections >= cap:
                    self.router.failed[req.index] = RequestFailed(
                        req.index, "admission", req.attempts,
                        f"shed {req.rejections} times")
                else:
                    self.router.requeue_wait(req, now + retry_after)
                continue
            healthy = [r for r in self.replicas.values()
                       if r.state == "healthy"]
            try:
                rep = self.router.pick(req, healthy)
            except NoHealthyReplicas:
                if all(r.state == "dead" for r in self.replicas.values()):
                    self.router.failed[req.index] = RequestFailed(
                        req.index, "no_healthy_replicas", req.attempts)
                else:
                    self.router.requeue_wait(
                        req, now + self.config.poll_interval_s)
                continue
            bad = self._invalid_reason(req, rep)
            if bad is not None:
                # a client input error fails the REQUEST, never the
                # replica: without this gate the engine's validation
                # ValueError would book a replica death and a few poison
                # requests could burn the whole fleet's respawn budget
                self.router.failed[req.index] = RequestFailed(
                    req.index, "invalid_request", req.attempts, bad)
                continue
            try:
                self.router.dispatch(req, rep, now)
                self._trace_dispatch(req, rep.name, now)
            except Exception as e:  # noqa: BLE001 — injected or real
                self.router.fail_attempt(req, now, "dispatch_error",
                                         repr(e))

    def _handle_event(self, ev) -> None:
        kind = ev[0]
        if kind == "wakeup":
            return                       # just a queue poke; tick handles it
        name, incarnation, gen = ev[1], ev[2], ev[3]
        rep = self.replicas.get(name)
        stale_serve = gen != self._serve_gen   # zombie of an earlier serve:
        # its request-level payload addresses a retired Router, but its
        # STATE transition is still real — a dead worker must not leave a
        # "healthy" replica silently black-holing new dispatches
        now = self.clock()
        if kind == "complete":
            if not stale_serve:
                for index, epoch, tokens in ev[4]:
                    self._complete(index, epoch, tokens, now)
            return
        # drained / death
        completions, migrations = ev[4], ev[5]
        reason = "drain" if kind == "drained" else "replica_death"
        if not stale_serve:
            for index, epoch, tokens in completions:
                self._complete(index, epoch, tokens, now)
            for index, epoch, record in migrations:
                self._apply_migration(index, epoch, record, reason, now)
        if rep is not None and rep.incarnation == incarnation:
            if kind == "death":
                logger.warning(
                    f"fleet: replica {name} died mid-serve ({ev[6]}); "
                    f"{len(migrations)} request(s) migrated")
            self._retire_replica(rep, reason)

    def _complete(self, index: int, epoch: int, tokens, now: float) -> None:
        req = self.router.inflight.get(index)
        if (req is not None and req.phase == "prefill"
                and req.epoch == epoch
                and len(tokens) < req.max_new_tokens):
            # prefill phase done (prompt + first token) with budget left:
            # hand the KV off and requeue the decode tail instead of
            # completing.  A one-token budget skips this and completes
            # directly — prefill already produced everything.
            self._advance_phase(req, epoch, tokens, now)
            return
        if not self.router.complete(index, epoch, tokens):
            return
        self._release_handoff(index)
        req = self.router.requests[index]
        self.request_log.append({
            "index": index, "t_arrival": req.t_arrival, "t_done": now,
            "generated_tokens": int(len(tokens)), "attempts": req.attempts,
            "migrations": req.migrations, "rejections": req.rejections,
            "t_first": req.t_first})
        self._trace_request(req, now, len(tokens))

    # ----------------------------------------------------------- KV handoff
    def _advance_phase(self, req: FleetRequest, epoch: int, tokens,
                       now: float) -> None:
        """Prefill -> decode handoff.  The transfer primitive is the PR 15
        radix block-alias path: the source replica's finished prompt
        blocks are PINNED (refcounted ``acquire``) so eviction cannot
        reclaim them while the decode attempt is in flight, and the decode
        replica's prefix probe then aliases them for free on a shared
        single-host pool.  The multi-host path is a stub: the bytes a
        wire copy would move are accounted in ``kv_handoff_bytes_total``.
        ``handoff.mid_transfer`` fires between pin and commit — an
        injected fault there models the source dying mid-transfer: pins
        are released (no refcount leak) and the request re-enters through
        the existing token-exact migration fold."""
        index = req.index
        src = self.replicas.get(req.assigned) if req.assigned else None
        new = [int(t) for t in np.asarray(tokens).reshape(-1)
               [len(req.generated):]]
        folded = np.concatenate(
            [np.asarray(req.prompt, np.int32),
             np.asarray(new, np.int32)]) if new else req.prompt
        blocks: List[int] = []
        pinned = False
        eng = getattr(src, "engine", None) if src is not None else None
        src_inc = src.incarnation if src is not None else -1
        probe = getattr(eng, "prefix_block_handles", None)
        if probe is not None:
            try:
                blocks, _matched = probe(folded)
                if blocks:
                    # pin vs eviction; acquire validates every block
                    # before bumping any, so a lost race with the radix
                    # evictor (dead block) leaves nothing to unwind and
                    # the handoff degrades to accounting-free
                    eng.state.allocator.acquire(blocks)
                    pinned = True
            except Exception:  # noqa: BLE001 — degraded, never corrupt
                blocks, pinned = [], False
        try:
            faults.fire("handoff.mid_transfer", index=index,
                        replica=src.name if src is not None else None)
        except faults.InjectedFault as e:
            if pinned:
                self._release_blocks(eng, blocks)
            self.c_handoffs.inc(1, outcome="aborted")
            logger.warning(
                f"fleet: handoff of request {index} aborted mid-transfer "
                f"({e!r}); re-entering via migration fold")
            # the prefill result is host-known, so the fold keeps it —
            # the request re-enters token-exact as a decode-phase retry
            # (drain-style: an injected infrastructure fault must not
            # burn the client's retry budget)
            req.phase = "decode"
            if req.t_first is None:
                req.t_first = now
            self.router.migrate(
                req, now, reason="handoff_abort",
                record={"prompt": folded, "generated": new},
                burn_budget=False)
            return
        if pinned:
            self._handoffs[index] = (src.name, src_inc, blocks)
            bytes_fn = getattr(eng, "kv_block_bytes", None)
            if bytes_fn is not None:
                self.c_handoff_bytes.inc(len(blocks) * int(bytes_fn()))
        self.c_handoffs.inc(1, outcome="ok")
        if req.t_first is None:
            req.t_first = now
        t_end = self.clock()
        if self.tracer.enabled and req.trace is not None:
            # the handoff slice is critical_path.py's b2->b3 boundary
            # pair: [prefill result observed, decode requeue committed]
            tid = index + 1
            ts = self._trace_us(now)
            dur = max((t_end - now) * 1e6, 1.0)
            self.tracer.record("fleet.handoff", ts, dur, tid=tid,
                               cat="router",
                               src=src.name if src is not None else None,
                               pinned_blocks=len(blocks),
                               **req.trace.args())
            if req.trace.flow_id is not None:
                self.tracer.flow("t", req.trace.flow_id, ts + dur / 2,
                                 tid=tid)
            self._trace_hold[index] = t_end
        self.router.handoff(index, epoch, tokens, now)

    @staticmethod
    def _release_blocks(eng, blocks: List[int]) -> None:
        try:
            eng.state.allocator.release(blocks)
        except Exception as e:  # noqa: BLE001 — bookkeeping must never
            #                     take the dispatcher down
            logger.warning(f"fleet: handoff pin release failed: {e!r}")

    def _release_handoff(self, index: int) -> None:
        """Release a request's pinned handoff blocks on its SOURCE pool.
        Skipped when the source incarnation is gone — its allocator (and
        the pins with it) died with the engine."""
        rec = self._handoffs.pop(index, None)
        if rec is None:
            return
        name, inc, blocks = rec
        rep = self.replicas.get(name)
        if rep is None or rep.incarnation != inc or rep.engine is None:
            return
        self._release_blocks(rep.engine, blocks)

    def _drop_handoffs_for(self, rep: Replica) -> None:
        """Forget pins sourced on a replica whose engine is being torn
        down (retire / role flip): the allocator dies with it, so there
        is nothing to release — keeping the record would release against
        the REPLACEMENT engine's allocator."""
        for index in [i for i, (name, _inc, _b) in self._handoffs.items()
                      if name == rep.name]:
            del self._handoffs[index]

    # ----------------------------------------------------- pool autoscaling
    def _rebalance_pools(self, now: float) -> None:
        """One autoscaler evaluation: ask for a direction, then flip ONE
        idle replica (healthy, nothing queued, nothing assigned) — moving
        a busy replica would migrate its work for a latency optimization,
        which is backwards.  No idle donor means no move this tick; the
        signal persists and a later tick retries."""
        pools = {"prefill": 0, "decode": 0}
        for r in self.replicas.values():
            if r.state == "healthy" and r.role in pools:
                pools[r.role] += 1
        direction = self._autoscaler.evaluate(
            now, pools, shedding=self.admission.shedding,
            shed_rate=self.admission.shed_rate(),
            slo_burn=(self.slo_monitor.max_burn()
                      if self.slo_monitor is not None else None))
        if direction is None:
            return
        donor_role = "decode" if direction == "to_prefill" else "prefill"
        new_role = "prefill" if direction == "to_prefill" else "decode"
        for rep in sorted(self.replicas.values(), key=lambda r: r.name):
            if rep.state != "healthy" or rep.role != donor_role:
                continue
            with rep.cond:
                idle = not rep.busy and not rep.queue
            if not idle or self.router.assigned_to(rep.name):
                continue
            self._flip_role(rep, new_role)
            self._autoscaler.record_move(direction, now)
            return

    def _flip_role(self, rep: Replica, role: str) -> None:
        """Warm role flip: stale-ify the worker (incarnation fence — same
        mechanism as a retire, but no death is booked and no respawn
        budget burns), swap the role, and respawn against the shared
        jitted-step cache.  Both roles run the same compiled program set,
        so the flip is a warm respawn: the recompile watchdog in the
        tests pins that no new program is compiled by one."""
        with rep.cond:
            rep.incarnation += 1
            leftovers, rep.queue = rep.queue, []
            rep.busy = False
            rep.cond.notify_all()
        now = self.clock()
        for d in leftovers:   # donor was idle-checked; belt and braces
            self._apply_migration(d.index, d.epoch, None, "drain", now)
        self._drop_handoffs_for(rep)
        self.router.invalidate_residency(rep.name)
        old = rep.role
        rep.role = role
        logger.info(f"fleet: role flip {rep.name}: {old} -> {role} "
                    f"(warm respawn)")
        self._spawn(rep, is_respawn=True)

    def _apply_migration(self, index: int, epoch: int,
                         record: Optional[dict], reason: str,
                         now: float) -> None:
        req = self.router.inflight.get(index)
        if req is None or req.epoch != epoch:
            return                       # stale: already requeued/finished
        self.router.migrate(req, now, reason=reason, record=record,
                            burn_budget=(reason != "drain"))

    @staticmethod
    def _invalid_reason(req: FleetRequest, rep: Replica) -> Optional[str]:
        """Best-effort mirror of the engine's PER-REQUEST validation (the
        two classes ``generate`` rejects with ValueError before doing any
        work): context overflow and a single request that cannot fit the
        KV pool even empty.  Only runs when the engine exposes the limits
        (fakes without them skip the gate); migration-folded prompts keep
        ``len(prompt) + remaining`` invariant, so a request this gate
        admitted once is never rejected after a migration."""
        eng = rep.engine
        mc = getattr(eng, "model_config", None)
        if mc is not None and len(req.prompt) + req.remaining \
                > mc.max_seq_len:
            return (f"prompt {len(req.prompt)} + {req.remaining} new "
                    f"tokens exceeds max_seq_len {mc.max_seq_len}")
        state = getattr(eng, "state", None)
        need = None
        if state is not None:
            need = -(-(len(req.prompt) + req.remaining)
                     // state.block_size)
            if need > state.allocator.num_blocks:
                return (f"request needs {need} KV blocks but the pool "
                        f"holds {state.allocator.num_blocks}")
        # adapter gate (only when the engine exposes the pool attribute —
        # real engines always do, even disabled; fakes without it also
        # never receive adapter_ids, so there is nothing to mirror): an
        # unknown / never-fits adapter, a base-only replica, or a request
        # whose KV blocks + adapter pages exceed the pool even empty
        # would all ValueError inside generate — on the worker thread
        # that books a replica DEATH, so the gate fails the request here
        if req.adapter and hasattr(eng, "adapters"):
            pool = eng.adapters
            if pool is None:
                return (f"request pins adapter {req.adapter} but the "
                        f"replica serves the base model only "
                        f"(config.adapters disabled)")
            bad = pool.unfittable_reason(req.adapter)
            if bad is not None:
                return bad
            if need is not None and need + pool.blocks_per_adapter \
                    > state.allocator.num_blocks:
                return (f"request needs {need} KV blocks + "
                        f"{pool.blocks_per_adapter} adapter page(s) but "
                        f"the pool holds {state.allocator.num_blocks}")
        return None

    # ---------------------------------------------------------- supervision
    def _check_health(self, now: float) -> None:
        base = self.config.heartbeat_deadline_s
        if base <= 0:
            return
        # a not-yet-warm incarnation's first call contains the on-the-fly
        # compile: deadline it on the warm-up budget, never the steady-state
        # one (a cold replica must not be booked dead — PR 8 finding)
        warmup = max(base, self.config.warmup_deadline_s)
        for rep in list(self.replicas.values()):
            ddl = base if rep.warmed else warmup
            if rep.state in ("healthy", "draining") and rep.busy \
                    and now - rep.last_beat > ddl:
                logger.warning(
                    f"fleet: replica {rep.name} missed its "
                    f"{'steady-state' if rep.warmed else 'warm-up'} "
                    f"heartbeat deadline ({now - rep.last_beat:.2f}s > "
                    f"{ddl}s); declaring dead and migrating its requests")
                self._retire_replica(rep, "heartbeat_timeout")

    def _retire_replica(self, rep: Replica, reason: str) -> None:
        """Book a replica death/drain: stale-ify its worker, migrate every
        request still attributed to it (undispatched queue + router
        inflight), then respawn if policy allows.  Requeue happens BEFORE
        the respawn so migrated work re-dispatches to survivors first."""
        t_detect = self.clock()
        with rep.cond:
            rep.incarnation += 1         # zombie worker exits / goes stale
            leftovers, rep.queue = rep.queue, []
            rep.busy = False
            rep.cond.notify_all()
        self._set_state(rep, "dead")
        self.c_deaths.inc(1, reason=reason)
        self._drop_handoffs_for(rep)
        self.router.invalidate_residency(rep.name)
        now = self.clock()
        for d in leftovers:
            self._apply_migration(d.index, d.epoch, None, reason, now)
        for req in self.router.assigned_to(rep.name):
            self.router.migrate(req, now, reason=reason, record=None,
                                burn_budget=(reason != "drain"))
        if reason == "drain":
            allowed = self.config.respawn_after_drain \
                and not self._fleet_draining
        else:
            # never respawn into a fleet-wide drain either: building an
            # engine inside the preemption window stretches time-to-exit
            # for a replica that could never receive work anyway
            allowed = self.config.respawn \
                and rep.respawns < self.config.max_respawns \
                and not self._fleet_draining
            rep.respawns += 1 if allowed else 0
        if allowed and self._spawn(rep, is_respawn=True):
            self.h_recovery.observe((self.clock() - t_detect) * 1e3)

    # ------------------------------------------------------------- control
    def register_adapter(self, adapter_id: int, weights=None) -> None:
        """Register a LoRA adapter fleet-wide: on every live engine now
        and (via the registry replay in ``_spawn``) on every future
        incarnation.  ``weights=None`` derives deterministic per-id
        weights, identical on every replica — the fleet's token-exactness
        invariant extends to adapter requests, so a migrated or
        handed-off adapter request completes byte-identical wherever it
        lands."""
        self._adapter_registry[int(adapter_id)] = weights
        for rep in self.replicas.values():
            if rep.engine is not None and hasattr(rep.engine,
                                                  "register_adapter"):
                rep.engine.register_adapter(adapter_id, weights)

    def drain_replica(self, name: str) -> None:
        """Graceful drain of one replica: stop admission to it, let it
        finish or migrate in-flight requests (``EngineDrained`` export),
        then retire + respawn it against the warm compile cache."""
        rep = self.replicas[name]
        if rep.state != "healthy":
            return
        self._set_state(rep, "draining")
        with rep.cond:
            busy = rep.busy
        if busy:
            rep.engine.request_drain()
        # idle replicas are finalized by the next tick

    def drain_all(self) -> None:
        """Fleet-wide drain (preemption notice): stop dispatching, drain
        every replica; ``serve()`` surfaces :class:`FleetDrained` with the
        completed + exported request sets."""
        self._fleet_draining = True
        for rep in self.replicas.values():
            if rep.state == "healthy":
                self.drain_replica(rep.name)

    def health(self) -> Dict[str, dict]:
        """Supervisor view: per-replica state, beat age, and the KV-pool
        gauges (per-replica label) the telemetry layer maintains."""
        now = self.clock()
        reg = self.registry._metrics
        out = {}
        for rep in self.replicas.values():
            kv = reg.get("kv_pool_blocks")
            free = kv.value(replica=rep.name, state="free") if kv else 0.0
            used = kv.value(replica=rep.name, state="used") if kv else 0.0
            out[rep.name] = {
                "state": rep.state, "role": rep.role,
                "beat_age_s": now - rep.last_beat,
                "busy": rep.busy, "respawns": rep.respawns,
                "kv_free_blocks": free, "kv_used_blocks": used,
                "outstanding_tokens":
                    self.router.outstanding_tokens(rep.name)}
        return out

    def shutdown(self) -> None:
        """Stop every worker thread (idempotent).  Busy workers are asked
        to drain cooperatively and JOINED: tearing the interpreter down
        with a thread mid-XLA-dispatch aborts the process."""
        for rep in self.replicas.values():
            with rep.cond:
                rep.incarnation += 1
                rep.cond.notify_all()
            if rep.engine is not None and hasattr(rep.engine,
                                                  "request_drain"):
                rep.engine.request_drain()
        for rep in self.replicas.values():
            if rep.worker is not None:
                rep.worker.join(timeout=60.0)

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False
