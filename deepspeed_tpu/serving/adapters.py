"""Paged LoRA adapter pool — multi-tenant adapter weights as block-granular
residents of the SAME refcounted allocator that pages the KV cache.

S-LoRA (arXiv:2311.03285) shape of the idea: a thousand-tenant fleet cannot
give every adapter a dedicated buffer — adapter pages and KV blocks contend
for one HBM pool, so they must share one allocator and one eviction policy.
Here an adapter occupies ``blocks_per_adapter`` blocks of the engine's
``BlockedAllocator`` (inference/v2/ragged.py) for SUPPLY accounting — the
actual bytes live in packed device tables (``[slots, L, H, r]`` per
projection) that the batched-gather kernel (ops/lora_matmul.py) indexes by
slot — and follows the radix cache's exact lifecycle:

- **load** allocates its blocks at refcount 1 (the pool is the holder) and
  ``device_put``s the host pages into its table slot;
- **pin** (one per in-flight request using the adapter) goes through
  ``allocator.acquire`` on the same blocks, so the allocator's refcount is
  the single source of truth for "in use";
- **evictable** exactly when every block is back to refcount 1 — the same
  predicate that makes a radix leaf reclaimable — and eviction takes LRU
  adapters first;
- **supply**: ``DSStateManager.available_blocks`` folds the evictable
  adapter blocks in next to the radix's, so every existing starvation
  check (``kv_alloc_failures_total`` site) stays honest without edits.

Slot 0 is the base-model identity: its pages stay zero and its scale is 0,
so adapter-less rows ride the same fused dispatch with a zero delta — no
per-row branch, no second program.

Thread-safety mirrors the radix cache: mutations (load/evict/pin) run on
the engine's worker thread; the router's cross-thread ``adapter_resident``
probe is a plain dict read under the GIL — a concurrent load/evict can
only make the answer stale, never corrupt it.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

PROJS = ("a_q", "b_q", "a_v", "b_v")


def random_adapter_weights(num_layers: int, hidden: int, rank: int,
                           q_dim: int, v_dim: int, seed: int = 0,
                           init_scale: float = 0.02) -> Dict[str, np.ndarray]:
    """Deterministic per-seed LoRA weights (bench/test tenants).  Both A and
    B are non-zero so distinct adapters produce distinct outputs — the
    classic B=0 init would make every tenant the base model."""
    rng = np.random.default_rng(seed)
    return {
        "a_q": rng.normal(0, init_scale,
                          (num_layers, hidden, rank)).astype(np.float32),
        "b_q": rng.normal(0, init_scale,
                          (num_layers, rank, q_dim)).astype(np.float32),
        "a_v": rng.normal(0, init_scale,
                          (num_layers, hidden, rank)).astype(np.float32),
        "b_v": rng.normal(0, init_scale,
                          (num_layers, rank, v_dim)).astype(np.float32),
    }


class _Resident:
    __slots__ = ("slot", "blocks", "stamp")

    def __init__(self, slot: int, blocks: List[int], stamp: int):
        self.slot = slot
        self.blocks = blocks
        self.stamp = stamp


class AdapterPool:
    """Block-granular LoRA adapter residency over a shared
    ``BlockedAllocator``.

    allocator: the engine's KV pool allocator (shared supply).
    slots: device-table capacity INCLUDING the reserved identity slot 0.
    block_bytes: bytes one allocator block represents (the engine derives
        it from the paged KV layout) — sizes ``blocks_per_adapter``.
    scale: LoRA scaling s = alpha / rank applied to every adapter delta.
    """

    def __init__(self, allocator, *, slots: int, rank: int, hidden: int,
                 num_layers: int, q_dim: int, v_dim: int, block_bytes: int,
                 scale: float, dtype="float32", telemetry=None):
        import jax
        import jax.numpy as jnp
        self.allocator = allocator
        self.slots = int(slots)
        self.rank = int(rank)
        self.hidden = int(hidden)
        self.num_layers = int(num_layers)
        self.q_dim = int(q_dim)
        self.v_dim = int(v_dim)
        self.scale = float(scale)
        self.telemetry = telemetry
        self._dtype = jnp.dtype(dtype)
        per_adapter_bytes = self._dtype.itemsize * num_layers * (
            hidden * rank + rank * q_dim + hidden * rank + rank * v_dim)
        self.blocks_per_adapter = max(
            1, -(-per_adapter_bytes // max(1, int(block_bytes))))
        self._host: Dict[int, Dict[str, np.ndarray]] = {}
        self._resident: Dict[int, _Resident] = {}
        self._free_slots: List[int] = list(range(1, self.slots))
        self._clock = 0
        self._ever_loaded: set = set()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # load/evict serialization: the engine worker loads while the fleet
        # dispatcher may be probing — table SWAPS are atomic refs, but two
        # concurrent loads racing one free slot would double-book it
        self._lock = threading.Lock()
        shapes = {"a_q": (self.slots, num_layers, hidden, rank),
                  "b_q": (self.slots, num_layers, rank, q_dim),
                  "a_v": (self.slots, num_layers, hidden, rank),
                  "b_v": (self.slots, num_layers, rank, v_dim)}
        self._tables = {k: jnp.zeros(shapes[k], self._dtype) for k in PROJS}
        # slot 0 keeps scale 0 — the identity lane's delta is exactly zero
        # even if a stale page were ever read through it
        self._scales = jnp.zeros((self.slots,), jnp.float32)
        self._jax = jax

    # ------------------------------------------------------------ registry
    def register(self, adapter_id: int,
                 weights: Optional[Dict[str, np.ndarray]] = None) -> None:
        """Make ``adapter_id`` loadable.  Host-side only: no pool blocks,
        no device traffic until a request actually selects the adapter.
        ``weights=None`` generates deterministic per-id test weights."""
        aid = int(adapter_id)
        if aid <= 0:
            raise ValueError("adapter id 0 is the reserved base-model "
                             "identity; tenant ids start at 1")
        if weights is None:
            weights = random_adapter_weights(
                self.num_layers, self.hidden, self.rank, self.q_dim,
                self.v_dim, seed=aid)
        for k in PROJS:
            if k not in weights:
                raise ValueError(f"adapter {aid}: missing projection {k!r}")
        self._host[aid] = {k: np.asarray(weights[k]) for k in PROJS}

    def registered(self, adapter_id: int) -> bool:
        return int(adapter_id) == 0 or int(adapter_id) in self._host

    def unfittable_reason(self, adapter_id: int) -> Optional[str]:
        """Why this adapter id can NEVER be served by this pool (a client
        input error → the caller fails the REQUEST, not the replica), or
        None when it is servable."""
        aid = int(adapter_id)
        if aid == 0:
            return None
        if aid not in self._host:
            return f"unknown adapter id {aid} (never registered)"
        if self.blocks_per_adapter > self.allocator.num_blocks:
            return (f"adapter {aid} needs {self.blocks_per_adapter} pool "
                    f"blocks but the pool only has "
                    f"{self.allocator.num_blocks}")
        if self.slots < 2:
            return "adapter pool has no tenant slots (slots < 2)"
        return None

    # ----------------------------------------------------------- residency
    def is_resident(self, adapter_id: int) -> bool:
        """Cross-thread-safe residency peek (router probe) — a dict read,
        no stamps freshened, no side effects."""
        return int(adapter_id) == 0 or int(adapter_id) in self._resident

    def resident_count(self, adapter_ids) -> int:
        return sum(1 for a in set(int(i) for i in adapter_ids)
                   if a != 0 and a in self._resident)

    def slot_of(self, adapter_id: int) -> int:
        aid = int(adapter_id)
        return 0 if aid == 0 else self._resident[aid].slot

    def _evictable_ids(self) -> List[int]:
        """Adapters only the pool holds (every block at refcount 1) —
        the radix-leaf predicate applied to whole adapters."""
        return [aid for aid, res in self._resident.items()
                if all(self.allocator.refcount(b) == 1 for b in res.blocks)]

    def evictable_blocks(self) -> int:
        """Supply reclaimable by evicting cold adapters right now — the
        term ``DSStateManager.available_blocks`` folds in next to the
        radix's."""
        return len(self._evictable_ids()) * self.blocks_per_adapter

    def evict_cold(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` pool blocks by evicting LRU-cold
        adapters (never a pinned one).  Returns blocks actually freed."""
        freed = 0
        with self._lock:
            while freed < n_blocks:
                cold = self._evictable_ids()
                if not cold:
                    break
                aid = min(cold, key=lambda a: self._resident[a].stamp)
                res = self._resident.pop(aid)
                freed += len(self.allocator.release(res.blocks))
                self._free_slots.append(res.slot)
                self.evictions += 1
                if self.telemetry is not None:
                    self.telemetry.adapter_eviction()
        return freed

    # --------------------------------------------------------------- load
    def _load_locked(self, aid: int, spill) -> None:
        if not self._free_slots:
            # all table slots taken: evict ONE cold adapter for its slot
            cold = self._evictable_ids()
            if not cold:
                raise RuntimeError(
                    f"adapter slots exhausted: {self.slots - 1} tenant "
                    f"slots all pinned by in-flight requests")
            victim = min(cold, key=lambda a: self._resident[a].stamp)
            res = self._resident.pop(victim)
            self.allocator.release(res.blocks)
            self._free_slots.append(res.slot)
            self.evictions += 1
            if self.telemetry is not None:
                self.telemetry.adapter_eviction()
        short = self.blocks_per_adapter - self.allocator.free_blocks
        if short > 0:
            # cold adapters first (same-tenancy pressure), then the
            # caller's spill (the state manager hands us radix eviction)
            for aid2 in sorted(self._evictable_ids(),
                               key=lambda a: self._resident[a].stamp):
                if short <= 0:
                    break
                res = self._resident.pop(aid2)
                short -= len(self.allocator.release(res.blocks))
                self._free_slots.append(res.slot)
                self.evictions += 1
                if self.telemetry is not None:
                    self.telemetry.adapter_eviction()
        if short > 0 and spill is not None:
            short -= spill(short)
        # allocate raises "KV cache exhausted" if still short — the caller
        # books the alloc-failure site
        blocks = self.allocator.allocate(self.blocks_per_adapter)
        slot = self._free_slots.pop()
        host = self._host[aid]
        for k in PROJS:
            page = self._jax.device_put(  # sync-ok: host→device adapter
                np.asarray(host[k], self._dtype))  # page upload (load path)
            self._tables[k] = self._tables[k].at[slot].set(page)
        self._scales = self._scales.at[slot].set(self.scale)
        self._clock += 1
        self._resident[aid] = _Resident(slot, blocks, self._clock)

    def ensure(self, adapter_ids, spill=None) -> None:
        """Make every id in ``adapter_ids`` resident, hot-loading misses
        from host.  ``spill(n) -> freed`` reclaims extra blocks beyond
        cold adapters (the state manager passes radix eviction).  Raises
        the allocator's ``RuntimeError`` when the pool genuinely cannot
        fit the load — callers book ``kv_alloc_failures_total``."""
        for aid in sorted(set(int(i) for i in adapter_ids)):
            if aid == 0:
                continue
            if aid not in self._host:
                raise KeyError(f"adapter id {aid} was never registered")
            res = self._resident.get(aid)
            if res is not None:
                self._clock += 1
                res.stamp = self._clock
                self.hits += 1
                if self.telemetry is not None:
                    self.telemetry.adapter_load("hit", self._hit_rate())
                continue
            outcome = "reload" if aid in self._ever_loaded else "miss"
            try:
                with self._lock:
                    self._load_locked(aid, spill)
            except Exception:
                self.misses += 1
                if self.telemetry is not None:
                    self.telemetry.adapter_load("failed", self._hit_rate())
                raise
            self.misses += 1
            self._ever_loaded.add(aid)
            if self.telemetry is not None:
                self.telemetry.adapter_load(outcome, self._hit_rate())

    # ---------------------------------------------------------------- pins
    def acquire(self, adapter_id: int) -> None:
        """One in-flight request starts using the adapter: add a holder to
        its blocks (refcount > 1 ⇒ not evictable)."""
        aid = int(adapter_id)
        if aid:
            self.allocator.acquire(self._resident[aid].blocks)

    def release(self, adapter_id: int) -> None:
        """The request finished: drop its hold.  The pool's own refcount
        keeps the pages resident (warm for the next request) until
        eviction pressure reclaims them."""
        aid = int(adapter_id)
        if aid:
            self.allocator.release(self._resident[aid].blocks)

    # -------------------------------------------------------------- tables
    def tables(self) -> Dict[str, object]:
        """The packed device tables the ragged dispatch threads into the
        model forward: per-projection pages plus the per-slot scales."""
        out = dict(self._tables)
        out["scale"] = self._scales
        return out

    # --------------------------------------------------------------- stats
    def _hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> Dict[str, float]:
        pinned = sum(
            self.blocks_per_adapter for res in self._resident.values()
            if any(self.allocator.refcount(b) > 1 for b in res.blocks))
        resident = len(self._resident) * self.blocks_per_adapter
        return {"resident_adapters": len(self._resident),
                "resident_blocks": resident,
                "pinned_blocks": pinned,
                "evictable_blocks": self.evictable_blocks(),
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": self._hit_rate()}

    def check_invariants(self) -> None:
        """Test hook: every resident adapter's blocks are live, slot
        bookkeeping is exact, and no slot is double-owned."""
        seen = set()
        for aid, res in self._resident.items():
            assert 0 < res.slot < self.slots, (aid, res.slot)
            assert res.slot not in seen, f"slot {res.slot} double-owned"
            seen.add(res.slot)
            for b in res.blocks:
                assert self.allocator.refcount(b) >= 1, (aid, b)
        assert not seen & set(self._free_slots), "free slot still owned"
        assert len(seen) + len(self._free_slots) == self.slots - 1, (
            len(seen), len(self._free_slots), self.slots)
