"""Admission control / graceful degradation for the serving fleet.

At overload, a serving tier has exactly two choices: shed load early with
a cheap, explicit rejection, or accept everything and let every request's
latency fall off a cliff together (the queue grows without bound, TTFT
p99 explodes, and the SLO goodput PR 5 measures collapses to zero even
though tokens/s looks fine).  This controller implements the first choice
as a control loop over the two overload signals the telemetry layer
already emits:

- ``kv_alloc_failures_total`` — every starved allocator decision site in
  the v2 engine counts here (PR 5 put the counter in exactly so "the
  future admission controller" could key off it; with the fleet's shared
  registry the sum spans every replica's series);
- router queue depth — requests arrived and waiting for dispatch.

**Hysteresis**: shedding trips when EITHER signal crosses its high
watermark and releases only when BOTH are back under their low
watermarks, so the controller cannot flap on a load level that hovers at
one threshold (reject → queue drains → admit → queue refills → ...).

A shed request gets a 429-style rejection with a ``retry_after_s`` hint;
the fleet re-enters it after that delay (the in-process stand-in for the
client's retry) without burning the router's retry budget — admission
rejections are back-pressure, not failures.  ``max_rejections`` bounds
how long one request can be shed before it surfaces a typed
``RequestFailed(reason="admission")`` (0 = shed indefinitely: pure
back-pressure).

Chaos site: ``admission.decide`` fires on every decision.  The fleet
treats an injected fault here as *fail open* (admit) — admission is an
optimization layer and must never become a correctness gate.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

from pydantic import model_validator

from deepspeed_tpu.config import DeepSpeedConfigModel
from deepspeed_tpu.runtime import faults


class AdmissionConfig(DeepSpeedConfigModel):
    """``admission`` block of the fleet config.  The ``*_queue_depth``
    band is in requests; the ``*_kv_failures_per_s`` band is the RATE of
    the fleet-wide ``kv_alloc_failures_total`` sum — the counter delta
    normalized by elapsed wall time, measured over spans of at least
    ``rate_window_s``.  The tick period is load-variable (the dispatcher
    tick stretches under exactly the conditions admission exists for), so
    a raw per-tick delta would make the effective threshold drift with
    load (PR 8 finding); per-second is load-invariant, and the minimum
    window keeps back-to-back event-driven ticks from reading one
    isolated failure as an instantaneous thousands/s burst.  The legacy ``*_kv_failures_per_tick`` spellings are
    rejected with a rename hint instead of being silently swallowed by the
    extra="allow" base config."""

    enabled: bool = True
    high_queue_depth: int = 64
    low_queue_depth: int = 16
    high_kv_failures_per_s: float = 128.0
    low_kv_failures_per_s: float = 4.0
    # minimum wall-time span a kv-failure rate is measured over: the
    # dispatcher tick is EVENT-driven (back-to-back ticks can be <1 ms
    # apart), so an instantaneous delta/dt estimate would let one isolated
    # failure between two such ticks read as thousands/s and trip
    # fleet-wide shedding; ticks inside the window reuse the last
    # full-window rate
    rate_window_s: float = 0.25
    retry_after_s: float = 0.25
    max_rejections: int = 0          # 0 = unbounded client retries
    # opt-in third signal (serving/slo.py): SLO burn rate as a shed
    # trigger.  Queue depth and kv starvation are CAUSE signals; burn
    # rate is the EFFECT signal — latency already out of budget — so it
    # catches overloads the queue cannot see (e.g. slow replicas at low
    # depth).  Same hysteresis contract: trips at high, releases only
    # when back under low (and the other signals agree).
    slo_burn_shed: bool = False
    high_slo_burn: float = 2.0
    low_slo_burn: float = 1.0

    @model_validator(mode="after")
    def _reject_legacy_per_tick(self):
        if self.rate_window_s <= 0:
            raise ValueError(
                f"admission.rate_window_s must be > 0, got "
                f"{self.rate_window_s}")
        extras = getattr(self, "__pydantic_extra__", None) or {}
        legacy = [k for k in extras if k.endswith("_kv_failures_per_tick")]
        if legacy:
            raise ValueError(
                f"admission config keys {legacy} were renamed: the "
                f"threshold is now normalized by elapsed time — use "
                f"high_kv_failures_per_s / low_kv_failures_per_s "
                f"(failures per SECOND, not per load-variable tick)")
        return self


class AdmissionController:
    """One instance per fleet; ``update()`` runs once per dispatcher tick,
    ``decide()`` once per dispatch attempt."""

    def __init__(self, config: Optional[AdmissionConfig] = None, *,
                 registry, clock: Callable[[], float]):
        cfg = config or AdmissionConfig()
        if cfg.low_queue_depth > cfg.high_queue_depth:
            raise ValueError(
                f"admission hysteresis band inverted: low_queue_depth="
                f"{cfg.low_queue_depth} > high_queue_depth="
                f"{cfg.high_queue_depth}")
        if cfg.low_kv_failures_per_s > cfg.high_kv_failures_per_s:
            raise ValueError(
                f"admission hysteresis band inverted: "
                f"low_kv_failures_per_s={cfg.low_kv_failures_per_s} "
                f"> high_kv_failures_per_s="
                f"{cfg.high_kv_failures_per_s}")
        self.config = cfg
        self.clock = clock
        self.registry = registry
        self.shedding = False
        # kv-failure rate measured over >= rate_window_s spans (see
        # AdmissionConfig.rate_window_s); ticks inside an open window
        # reuse the last full-window rate
        self._rate = 0.0
        self._win_start_t: Optional[float] = None
        self._win_start_total: Optional[float] = None
        # shed rate (rejections/s) over the same windows: the pool
        # autoscaler's overload signal (serving/autoscale.py) — a
        # mis-sized disaggregated pool split shows up here first
        self._shed_rate = 0.0
        self._win_start_rejections: Optional[float] = None
        self.c_rejections = registry.counter(
            "admission_rejections_total", "requests shed (429-style, with "
            "retry-after) by the fleet admission controller before "
            "dispatch")
        self.g_shedding = registry.gauge(
            "admission_shedding", "1 while the admission controller is in "
            "its shedding state (hysteresis band tripped), else 0")
        self.g_shedding.set(0.0)

    # ------------------------------------------------------------- signals
    def kv_failures_total(self) -> float:
        """Fleet-wide sum of ``kv_alloc_failures_total`` over every label
        set (site x replica) in the shared registry."""
        m = self.registry._metrics.get("kv_alloc_failures_total")
        if m is None:
            return 0.0
        return sum(v for _, v in m.samples())

    def shed_rate(self) -> float:
        """Requests shed per second over the last full rate window (same
        windowing as the kv-failure rate).  Read by the pool autoscaler:
        a nonzero shed rate means overload, where a mis-sized
        prefill/decode split costs goodput immediately."""
        return self._shed_rate

    # -------------------------------------------------------- control loop
    def update(self, queue_depth: int,
               kv_failures_total: Optional[float] = None,
               slo_burn: Optional[float] = None) -> bool:
        """One control tick: fold the current signals through the
        hysteresis band and return the (possibly new) shedding state.
        ``kv_failures_total`` is injectable for tests; by default it is
        read from the shared registry.  The kv signal is the counter delta
        NORMALIZED by wall time (failures/s): the dispatcher tick
        stretches under load, and an un-normalized per-tick delta would
        raise the effective trip threshold exactly when shedding matters
        most.  The rate is measured over at least ``rate_window_s`` of
        wall time (not tick-to-tick): ticks are event-driven and can land
        back-to-back, where an instantaneous delta/dt would let a single
        failure read as thousands/s."""
        cfg = self.config
        if not cfg.enabled:
            return False
        total = (self.kv_failures_total() if kv_failures_total is None
                 else float(kv_failures_total))
        rejections = self.c_rejections.value()
        now = self.clock()
        if self._win_start_t is None:
            self._win_start_t = now
            self._win_start_total = total
            self._win_start_rejections = rejections
        elapsed = now - self._win_start_t
        if elapsed >= float(cfg.rate_window_s):
            self._rate = max(0.0, total - self._win_start_total) / elapsed
            self._shed_rate = max(
                0.0, rejections - (self._win_start_rejections or 0.0)) \
                / elapsed
            self._win_start_t = now
            self._win_start_total = total
            self._win_start_rejections = rejections
        rate = self._rate
        # opt-in SLO burn-rate signal (None when the fleet runs no SLO
        # monitor, 0.0 participation when the feature flag is off)
        burn = (float(slo_burn)
                if cfg.slo_burn_shed and slo_burn is not None else None)
        if not self.shedding:
            if (queue_depth > cfg.high_queue_depth
                    or rate >= cfg.high_kv_failures_per_s
                    or (burn is not None and burn >= cfg.high_slo_burn)):
                self.shedding = True
        else:
            if (queue_depth <= cfg.low_queue_depth
                    and rate <= cfg.low_kv_failures_per_s
                    and (burn is None or burn <= cfg.low_slo_burn)):
                self.shedding = False
        self.g_shedding.set(1.0 if self.shedding else 0.0)
        return self.shedding

    # ------------------------------------------------------------ decision
    def decide(self, req) -> Tuple[bool, float]:
        """Admit or shed one request: ``(admitted, retry_after_s)``.
        Fires the ``admission.decide`` chaos site; the fleet catches any
        injected fault and admits (fail open)."""
        faults.fire("admission.decide", index=getattr(req, "index", None))
        if not self.config.enabled or not self.shedding:
            return True, 0.0
        self.c_rejections.inc(1)
        req.rejections += 1
        return False, self.config.retry_after_s
