"""Admission control / graceful degradation for the serving fleet.

At overload, a serving tier has exactly two choices: shed load early with
a cheap, explicit rejection, or accept everything and let every request's
latency fall off a cliff together (the queue grows without bound, TTFT
p99 explodes, and the SLO goodput PR 5 measures collapses to zero even
though tokens/s looks fine).  This controller implements the first choice
as a control loop over the two overload signals the telemetry layer
already emits:

- ``kv_alloc_failures_total`` — every starved allocator decision site in
  the v2 engine counts here (PR 5 put the counter in exactly so "the
  future admission controller" could key off it; with the fleet's shared
  registry the sum spans every replica's series);
- router queue depth — requests arrived and waiting for dispatch.

**Hysteresis**: shedding trips when EITHER signal crosses its high
watermark and releases only when BOTH are back under their low
watermarks, so the controller cannot flap on a load level that hovers at
one threshold (reject → queue drains → admit → queue refills → ...).

A shed request gets a 429-style rejection with a ``retry_after_s`` hint;
the fleet re-enters it after that delay (the in-process stand-in for the
client's retry) without burning the router's retry budget — admission
rejections are back-pressure, not failures.  ``max_rejections`` bounds
how long one request can be shed before it surfaces a typed
``RequestFailed(reason="admission")`` (0 = shed indefinitely: pure
back-pressure).

Chaos site: ``admission.decide`` fires on every decision.  The fleet
treats an injected fault here as *fail open* (admit) — admission is an
optimization layer and must never become a correctness gate.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

from deepspeed_tpu.config import DeepSpeedConfigModel
from deepspeed_tpu.runtime import faults


class AdmissionConfig(DeepSpeedConfigModel):
    """``admission`` block of the fleet config.  The ``*_queue_depth``
    band is in requests; the ``*_kv_failures_per_tick`` band is the DELTA
    of the fleet-wide ``kv_alloc_failures_total`` sum between control
    ticks (a rate, robust to the counter's monotonic growth)."""

    enabled: bool = True
    high_queue_depth: int = 64
    low_queue_depth: int = 16
    high_kv_failures_per_tick: float = 32.0
    low_kv_failures_per_tick: float = 1.0
    retry_after_s: float = 0.25
    max_rejections: int = 0          # 0 = unbounded client retries


class AdmissionController:
    """One instance per fleet; ``update()`` runs once per dispatcher tick,
    ``decide()`` once per dispatch attempt."""

    def __init__(self, config: Optional[AdmissionConfig] = None, *,
                 registry, clock: Callable[[], float]):
        cfg = config or AdmissionConfig()
        if cfg.low_queue_depth > cfg.high_queue_depth:
            raise ValueError(
                f"admission hysteresis band inverted: low_queue_depth="
                f"{cfg.low_queue_depth} > high_queue_depth="
                f"{cfg.high_queue_depth}")
        if cfg.low_kv_failures_per_tick > cfg.high_kv_failures_per_tick:
            raise ValueError(
                f"admission hysteresis band inverted: "
                f"low_kv_failures_per_tick={cfg.low_kv_failures_per_tick} "
                f"> high_kv_failures_per_tick="
                f"{cfg.high_kv_failures_per_tick}")
        self.config = cfg
        self.clock = clock
        self.registry = registry
        self.shedding = False
        self._last_kv_total: Optional[float] = None
        self.c_rejections = registry.counter(
            "admission_rejections_total", "requests shed (429-style, with "
            "retry-after) by the fleet admission controller before "
            "dispatch")
        self.g_shedding = registry.gauge(
            "admission_shedding", "1 while the admission controller is in "
            "its shedding state (hysteresis band tripped), else 0")
        self.g_shedding.set(0.0)

    # ------------------------------------------------------------- signals
    def kv_failures_total(self) -> float:
        """Fleet-wide sum of ``kv_alloc_failures_total`` over every label
        set (site x replica) in the shared registry."""
        m = self.registry._metrics.get("kv_alloc_failures_total")
        if m is None:
            return 0.0
        return sum(v for _, v in m.samples())

    # -------------------------------------------------------- control loop
    def update(self, queue_depth: int,
               kv_failures_total: Optional[float] = None) -> bool:
        """One control tick: fold the current signals through the
        hysteresis band and return the (possibly new) shedding state.
        ``kv_failures_total`` is injectable for tests; by default it is
        read from the shared registry."""
        cfg = self.config
        if not cfg.enabled:
            return False
        total = (self.kv_failures_total() if kv_failures_total is None
                 else float(kv_failures_total))
        if self._last_kv_total is None:
            self._last_kv_total = total
        delta = total - self._last_kv_total
        self._last_kv_total = total
        if not self.shedding:
            if (queue_depth > cfg.high_queue_depth
                    or delta >= cfg.high_kv_failures_per_tick):
                self.shedding = True
        else:
            if (queue_depth <= cfg.low_queue_depth
                    and delta <= cfg.low_kv_failures_per_tick):
                self.shedding = False
        self.g_shedding.set(1.0 if self.shedding else 0.0)
        return self.shedding

    # ------------------------------------------------------------ decision
    def decide(self, req) -> Tuple[bool, float]:
        """Admit or shed one request: ``(admitted, retry_after_s)``.
        Fires the ``admission.decide`` chaos site; the fleet catches any
        injected fault and admits (fail open)."""
        faults.fire("admission.decide", index=getattr(req, "index", None))
        if not self.config.enabled or not self.shedding:
            return True, 0.0
        self.c_rejections.inc(1)
        req.rejections += 1
        return False, self.config.retry_after_s
