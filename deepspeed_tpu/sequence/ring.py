"""Ring attention — sequence parallelism by rotating KV blocks over the ring.

Reference scope: DeepSpeed's long-context story is Ulysses (sequence/layer.py,
all-to-all head swap).  Ring attention (Liu et al., "Ring Attention with
Blockwise Transformers", PAPERS.md) is the complementary mechanism this
framework ships as a first-class alternative: sequence stays sharded the
WHOLE time — no all-to-all, no head-count divisibility constraint — while K/V
blocks rotate neighbor-to-neighbor over the ``sp`` axis.

TPU-native shape: one ``shard_map`` over ``sp``; inside, a differentiable
``lax.scan`` of sp steps, each step
  - attends the local Q block against the currently-held K/V block with a
    GLOBAL-position causal mask (so ordering is exact regardless of which
    block is visiting),
  - folds the partial result into online-softmax stats (m, l, acc) — the
    flash-attention recurrence across blocks,
  - ``ppermute``s the K/V block to the next neighbor (ICI ring — the same
    link pattern the hardware torus provides natively).

Causality note (contiguous schedule): blocks strictly "ahead" of the local Q
block contribute nothing but are still rotated through (the ring must
complete); their scores are fully masked — ~half the FLOPs are dead on
causal attention.

``schedule="zigzag"`` (round-3 verdict item 8) removes that waste: each
device owns chunks (d, 2·sp−1−d) of the sequence (the zig-zag placement from
zigzag ring attention / llama-3 context parallelism).  At every ring step
exactly TWO of the four (q-chunk × kv-chunk) sub-blocks are causally live,
and — because liveness depends only on (my, src), not on token positions —
they are FULLY live: steps 1..sp−1 run two mask-free half-size attends
(balanced across devices), and only step 0 pays within-chunk diagonal masks.
FLOPs drop to ~(2·sp+1)/(4·sp) ≈ 55% of the contiguous schedule; the ring's
own wire cost is unchanged (each device still sends its KV bytes sp−1 times,
neighbor-only), but the convenience permutation in/out of zig-zag layout —
applied inside the call so the public contract (contiguous [B, T, H, D],
token-exact vs dense) is identical — adds ~4 tensor-sized cross-device
reshuffles per call (q/k/v in, o out; again in backward), booked to the
comms logger.  A training stack that keeps activations in zig-zag layout
end-to-end (permute tokens + positions once at the embedding) amortizes
that to zero; this entry point trades that for drop-in exactness.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.comm import comms_logger

_NEG = jnp.float32(-1e30)


def _ring_body(q, k0, v0, my, sp_size, axis, causal, scale):
    """Local blockwise-softmax accumulation over sp ring steps.

    q [B, Tl, H, D]; k0/v0 the locally-held KV block.  Returns [B, Tl, H, D].
    """
    B, Tl, H, D = q.shape
    qpos = my * Tl + jnp.arange(Tl)                     # global positions
    perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

    qf = q.astype(jnp.float32)

    def accumulate(m, l, acc, kcur, vcur, s):
        src = (my - s) % sp_size                        # owner of kcur
        kpos = src * Tl + jnp.arange(Tl)
        s_log = jnp.einsum("bqhd,bkhd->bhqk", qf,
                           kcur.astype(jnp.float32)) * scale
        if causal:
            mask = kpos[None, :] <= qpos[:, None]       # [Tq, Tk] global
            s_log = jnp.where(mask[None, None], s_log, _NEG)
        m_new = jnp.maximum(m, jnp.max(s_log, axis=-1))
        p = jnp.exp(s_log - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, vcur.astype(jnp.float32))
        return m_new, l_new, acc * alpha[..., None] + pv

    def step(carry, s):
        m, l, acc, kcur, vcur = carry
        m, l, acc = accumulate(m, l, acc, kcur, vcur, s)
        # rotate KV to the next neighbor; the last visiting block is computed
        # OUTSIDE the scan so no dead final rotation is issued (sp-1 hops
        # total — matches the bytes the comms logger books)
        knext = lax.ppermute(kcur, axis, perm)
        vnext = lax.ppermute(vcur, axis, perm)
        return (m, l, acc, knext, vnext), None

    m0 = jnp.full((B, H, Tl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    (m, l, acc, klast, vlast), _ = lax.scan(
        jax.checkpoint(step), (m0, l0, acc0, k0, v0),
        jnp.arange(sp_size - 1))
    m, l, acc = accumulate(m, l, acc, klast, vlast, sp_size - 1)
    l = jnp.where(l == 0.0, 1.0, l)                     # fully-masked rows
    out = acc / l[..., None]                            # [B, H, Tl, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _zigzag_body(q, k0, v0, my, sp_size, axis, scale):
    """Causal ring over the zig-zag placement: the local block holds chunks
    (a=my, b=2·sp−1−my) as rows [:c] / [c:].  Block-level liveness depends
    only on (my, src), so steps 1..sp−1 run exactly two MASK-FREE half-size
    attends; only step 0 (own chunks) pays diagonal masks.  ~½ the FLOPs of
    the contiguous schedule at identical wire cost (module docstring)."""
    B, T2, H, D = q.shape
    c = T2 // 2
    qf = q.astype(jnp.float32)
    qa, qb = qf[:, :c], qf[:, c:]
    perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

    def scores(qh, kc):                                   # [B, H, c, c]
        return jnp.einsum("bqhd,bkhd->bhqk", qh,
                          kc.astype(jnp.float32)) * scale

    def fold(stats, h_idx, s_log, vc):
        """Online-softmax fold of one sub-block into half ``h_idx``'s stats
        (h_idx may be traced — stats are stacked [2, ...])."""
        m, l, acc = stats
        mh = lax.dynamic_index_in_dim(m, h_idx, 0, keepdims=False)
        lh = lax.dynamic_index_in_dim(l, h_idx, 0, keepdims=False)
        ah = lax.dynamic_index_in_dim(acc, h_idx, 0, keepdims=False)
        m_new = jnp.maximum(mh, jnp.max(s_log, axis=-1))
        p = jnp.exp(s_log - m_new[..., None])
        alpha = jnp.exp(mh - m_new)
        l_new = lh * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, vc.astype(jnp.float32))
        a_new = ah * alpha[..., None] + pv
        return (lax.dynamic_update_index_in_dim(m, m_new, h_idx, 0),
                lax.dynamic_update_index_in_dim(l, l_new, h_idx, 0),
                lax.dynamic_update_index_in_dim(acc, a_new, h_idx, 0))

    # step 0 — own chunks: qa×ka (diag), qb×ka (full: a < sp ≤ b), qb×kb (diag)
    ka, kb = k0[:, :c], k0[:, c:]
    va, vb = v0[:, :c], v0[:, c:]
    tri = (jnp.arange(c)[:, None] >= jnp.arange(c)[None, :])[None, None]
    m0 = jnp.full((2, B, H, c), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((2, B, H, c), jnp.float32)
    acc0 = jnp.zeros((2, B, H, c, D), jnp.float32)
    stats = (m0, l0, acc0)
    stats = fold(stats, 0, jnp.where(tri, scores(qa, ka), _NEG), va)
    stats = fold(stats, 1, scores(qb, ka), va)
    stats = fold(stats, 1, jnp.where(tri, scores(qb, kb), _NEG), vb)

    def step(carry, s):
        stats, kprev, vprev = carry
        # rotate FIRST: at step s the resident block must come from
        # src = (my − s) mod sp (step 0 consumed the un-rotated own block)
        kcur = lax.ppermute(kprev, axis, perm)
        vcur = lax.ppermute(vprev, axis, perm)
        src = (my - s) % sp_size
        ka_, kb_ = kcur[:, :c], kcur[:, c:]
        va_, vb_ = vcur[:, :c], vcur[:, c:]
        # visiting early chunk a' = src: live for qb always; for qa iff
        # src < my.  visiting late chunk b' = 2sp−1−src: live iff src > my
        # (then b' < b), and only for qb.  Exactly two fully-live sub-blocks.
        stats = fold(stats, 1, scores(qb, ka_), va_)
        early = src < my
        h2 = jnp.where(early, 0, 1).astype(jnp.int32)
        q2 = jnp.where(early, qa, qb)
        k2 = jnp.where(early, ka_, kb_)
        v2 = jnp.where(early, va_, vb_)
        stats = fold(stats, h2, scores(q2, k2), v2)
        return (stats, kcur, vcur), None

    (stats, _, _), _ = lax.scan(jax.checkpoint(step), (stats, k0, v0),
                                jnp.arange(1, sp_size))
    m, l, acc = stats
    l = jnp.where(l == 0.0, 1.0, l)
    out = acc / l[..., None]                        # [2, B, H, c, D]
    out = jnp.concatenate([out[0], out[1]], axis=2)  # [B, H, 2c, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _zigzag_perm(t: int, sp: int):
    """Global index permutation placing chunks (d, 2sp−1−d) on device d."""
    import numpy as np
    c = t // (2 * sp)
    chunks = np.arange(t).reshape(2 * sp, c)
    order = []
    for d in range(sp):
        order += [d, 2 * sp - 1 - d]
    idx = chunks[order].reshape(-1)
    inv = np.empty_like(idx)
    inv[idx] = np.arange(t)
    return jnp.asarray(idx), jnp.asarray(inv)


def ring_attention(mesh: Mesh, q, k, v, *, causal: bool = True,
                   axis: str = "sp", batch_axes=("dp", "fsdp"),
                   scale=None, schedule: str = "zigzag"):
    """Global-view entry: q/k/v [B, T, H, D] with T sharded over ``axis``.

    Equivalent math to full softmax attention (tested token-exact vs the
    dense path); peak per-device score memory is [B, H, T/sp, T/sp]
    (contiguous) or 3×[B, H, T/2sp, T/2sp] (zigzag).

    ``schedule``: "zigzag" (default — causal FLOPs ≈ halved, module
    docstring) or "contiguous".  Zig-zag needs T % (2·sp) == 0 and causal;
    other cases fall back to the contiguous schedule.
    """
    sp = mesh.shape[axis]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if sp == 1:
        from deepspeed_tpu import ops
        return ops.causal_attention(q, k, v, causal=causal, impl="xla")
    if q.shape[1] % sp:
        raise ValueError(f"seq len {q.shape[1]} not divisible by "
                         f"{axis}={sp}")
    if schedule not in ("zigzag", "contiguous"):
        raise ValueError(f"schedule must be zigzag|contiguous, "
                         f"got {schedule!r}")
    if k.shape[2] != q.shape[2]:
        # GQA: expand KV to the query head count before the ring (the rotated
        # blocks then carry nh heads instead of nkv — a grouped in-ring score
        # kernel that keeps the bandwidth benefit is a later optimization)
        g = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    comms_logger.record("ring_attention_ppermute",
                        (k.size + v.size) * k.dtype.itemsize // sp * (sp - 1),
                        axis)
    spec = P(batch_axes, axis, None, None)
    zig = (schedule == "zigzag" and causal and q.shape[1] % (2 * sp) == 0)

    if zig:
        idx, inv = _zigzag_perm(q.shape[1], sp)
        # the in/out zig-zag permutes reshard across sp — real wire traffic
        # (≈4 tensor volumes per call), booked separately from the ring hops
        comms_logger.record(
            "ring_attention_zigzag_permute",
            (q.size + k.size + v.size + q.size) * q.dtype.itemsize, axis)
        qz, kz, vz = (jnp.take(x, idx, axis=1) for x in (q, k, v))

        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=spec, check_vma=False)
        def inner_z(q_, k_, v_):
            my = lax.axis_index(axis)
            return _zigzag_body(q_, k_, v_, my, sp, axis, scale)

        return jnp.take(inner_z(qz, kz, vz), inv, axis=1)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def inner(q_, k_, v_):
        my = lax.axis_index(axis)
        return _ring_body(q_, k_, v_, my, sp, axis, causal, scale)

    return inner(q, k, v)
