"""Ring attention — sequence parallelism by rotating KV blocks over the ring.

Reference scope: DeepSpeed's long-context story is Ulysses (sequence/layer.py,
all-to-all head swap).  Ring attention (Liu et al., "Ring Attention with
Blockwise Transformers", PAPERS.md) is the complementary mechanism this
framework ships as a first-class alternative: sequence stays sharded the
WHOLE time — no all-to-all, no head-count divisibility constraint — while K/V
blocks rotate neighbor-to-neighbor over the ``sp`` axis.

TPU-native shape: one ``shard_map`` over ``sp``; inside, a differentiable
``lax.scan`` of sp steps, each step
  - attends the local Q block against the currently-held K/V block with a
    GLOBAL-position causal mask (so ordering is exact regardless of which
    block is visiting),
  - folds the partial result into online-softmax stats (m, l, acc) — the
    flash-attention recurrence across blocks,
  - ``ppermute``s the K/V block to the next neighbor (ICI ring — the same
    link pattern the hardware torus provides natively).

Causality note (contiguous schedule): blocks strictly "ahead" of the local Q
block contribute nothing but are still rotated through (the ring must
complete); their scores are fully masked — ~half the FLOPs are dead on
causal attention.

``schedule="zigzag"`` (round-3 verdict item 8) removes that waste: each
device owns chunks (d, 2·sp−1−d) of the sequence (the zig-zag placement from
zigzag ring attention / llama-3 context parallelism).  At every ring step
exactly TWO of the four (q-chunk × kv-chunk) sub-blocks are causally live,
and — because liveness depends only on (my, src), not on token positions —
they are FULLY live: steps 1..sp−1 run two mask-free half-size attends
(balanced across devices), and only step 0 pays within-chunk diagonal masks.
FLOPs drop to ~(2·sp+1)/(4·sp) ≈ 55% of the contiguous schedule; the ring's
own wire cost is unchanged (each device still sends its KV bytes sp−1 times,
neighbor-only), but the convenience permutation in/out of zig-zag layout —
applied inside the call so the public contract (contiguous [B, T, H, D],
token-exact vs dense) is identical — adds ~4 tensor-sized cross-device
reshuffles per call (q/k/v in, o out; again in backward), booked to the
comms logger.  A training stack that keeps activations in zig-zag layout
end-to-end (permute tokens + positions once at the embedding) amortizes
that to zero; this entry point trades that for drop-in exactness.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from deepspeed_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.comm import comms_logger

# numpy, NOT jnp: a module-level jnp scalar is a committed device array that
# every trace captures as a jaxpr const — under the engine's donated jit it
# becomes a lifted executable parameter and the second call fails with a
# supplied-vs-expected buffer mismatch (round 5, with the iota-perm note on
# ``_zigzag_perm``)
_NEG = np.float32(-1e30)


def _gqa_scores(qf, kc, scale):
    """q [B, Tq, H, D] × k [B, Tk, Hkv, D] → logits [B, H, Tq, Tk].

    Hkv < H (GQA): the group expansion happens INSIDE the einsum (q reshaped
    to [.., Hkv, g, D] against un-expanded KV), so the ring rotates Hkv-sized
    blocks — wire bytes drop by g = H/Hkv vs pre-expanding KV."""
    B, Tq, H, D = qf.shape
    hkv = kc.shape[2]
    if hkv == H:
        return jnp.einsum("bqhd,bkhd->bhqk", qf,
                          kc.astype(jnp.float32)) * scale
    s = jnp.einsum("bqngd,bknd->bngqk",
                   qf.reshape(B, Tq, hkv, H // hkv, D),
                   kc.astype(jnp.float32)) * scale
    return s.reshape(B, H, Tq, kc.shape[1])


def _gqa_pv(p, vc):
    """probs [B, H, Tq, Tk] × v [B, Tk, Hkv, D] → [B, H, Tq, D] (grouped)."""
    B, H, Tq, Tk = p.shape
    hkv = vc.shape[2]
    if hkv == H:
        return jnp.einsum("bhqk,bkhd->bhqd", p, vc.astype(jnp.float32))
    o = jnp.einsum("bngqk,bknd->bngqd", p.reshape(B, hkv, H // hkv, Tq, Tk),
                   vc.astype(jnp.float32))
    return o.reshape(B, H, Tq, vc.shape[3])


def _ring_body(q, k0, v0, my, sp_size, axis, causal, scale):
    """Local blockwise-softmax accumulation over sp ring steps.

    q [B, Tl, H, D]; k0/v0 the locally-held KV block (possibly fewer, GQA,
    heads).  Returns [B, Tl, H, D].
    """
    B, Tl, H, D = q.shape
    qpos = my * Tl + jnp.arange(Tl)                     # global positions
    perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

    qf = q.astype(jnp.float32)

    def accumulate(m, l, acc, kcur, vcur, s):
        src = (my - s) % sp_size                        # owner of kcur
        kpos = src * Tl + jnp.arange(Tl)
        s_log = _gqa_scores(qf, kcur, scale)
        if causal:
            mask = kpos[None, :] <= qpos[:, None]       # [Tq, Tk] global
            s_log = jnp.where(mask[None, None], s_log, _NEG)
        m_new = jnp.maximum(m, jnp.max(s_log, axis=-1))
        p = jnp.exp(s_log - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = _gqa_pv(p, vcur)
        return m_new, l_new, acc * alpha[..., None] + pv

    def step(carry, s):
        m, l, acc, kcur, vcur = carry
        m, l, acc = accumulate(m, l, acc, kcur, vcur, s)
        # rotate KV to the next neighbor; the last visiting block is computed
        # OUTSIDE the scan so no dead final rotation is issued (sp-1 hops
        # total — matches the bytes the comms logger books)
        knext = lax.ppermute(kcur, axis, perm)
        vnext = lax.ppermute(vcur, axis, perm)
        return (m, l, acc, knext, vnext), None

    m0 = jnp.full((B, H, Tl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    (m, l, acc, klast, vlast), _ = lax.scan(
        jax.checkpoint(step), (m0, l0, acc0, k0, v0),
        jnp.arange(sp_size - 1))
    m, l, acc = accumulate(m, l, acc, klast, vlast, sp_size - 1)
    l = jnp.where(l == 0.0, 1.0, l)                     # fully-masked rows
    out = acc / l[..., None]                            # [B, H, Tl, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _zigzag_body(q, k0, v0, my, sp_size, axis, scale):
    """Causal ring over the zig-zag placement: the local block holds chunks
    (a=my, b=2·sp−1−my) as rows [:c] / [c:].  Block-level liveness depends
    only on (my, src), so steps 1..sp−1 run exactly two MASK-FREE half-size
    attends; only step 0 (own chunks) pays diagonal masks.  ~½ the FLOPs of
    the contiguous schedule at identical wire cost (module docstring)."""
    B, T2, H, D = q.shape
    c = T2 // 2
    qf = q.astype(jnp.float32)
    qa, qb = qf[:, :c], qf[:, c:]
    perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

    def scores(qh, kc):                                   # [B, H, c, c]
        return _gqa_scores(qh, kc, scale)

    def fold(stats, h_idx, s_log, vc):
        """Online-softmax fold of one sub-block into half ``h_idx``'s stats
        (h_idx may be traced — stats are stacked [2, ...])."""
        m, l, acc = stats
        mh = lax.dynamic_index_in_dim(m, h_idx, 0, keepdims=False)
        lh = lax.dynamic_index_in_dim(l, h_idx, 0, keepdims=False)
        ah = lax.dynamic_index_in_dim(acc, h_idx, 0, keepdims=False)
        m_new = jnp.maximum(mh, jnp.max(s_log, axis=-1))
        p = jnp.exp(s_log - m_new[..., None])
        alpha = jnp.exp(mh - m_new)
        l_new = lh * alpha + jnp.sum(p, axis=-1)
        pv = _gqa_pv(p, vc)
        a_new = ah * alpha[..., None] + pv
        return (lax.dynamic_update_index_in_dim(m, m_new, h_idx, 0),
                lax.dynamic_update_index_in_dim(l, l_new, h_idx, 0),
                lax.dynamic_update_index_in_dim(acc, a_new, h_idx, 0))

    # step 0 — own chunks: qa×ka (diag), qb×ka (full: a < sp ≤ b), qb×kb (diag)
    ka, kb = k0[:, :c], k0[:, c:]
    va, vb = v0[:, :c], v0[:, c:]
    tri = (jnp.arange(c)[:, None] >= jnp.arange(c)[None, :])[None, None]
    m0 = jnp.full((2, B, H, c), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((2, B, H, c), jnp.float32)
    acc0 = jnp.zeros((2, B, H, c, D), jnp.float32)
    stats = (m0, l0, acc0)
    stats = fold(stats, 0, jnp.where(tri, scores(qa, ka), _NEG), va)
    stats = fold(stats, 1, scores(qb, ka), va)
    stats = fold(stats, 1, jnp.where(tri, scores(qb, kb), _NEG), vb)

    def step(carry, s):
        stats, kprev, vprev = carry
        # rotate FIRST: at step s the resident block must come from
        # src = (my − s) mod sp (step 0 consumed the un-rotated own block)
        kcur = lax.ppermute(kprev, axis, perm)
        vcur = lax.ppermute(vprev, axis, perm)
        src = (my - s) % sp_size
        ka_, kb_ = kcur[:, :c], kcur[:, c:]
        va_, vb_ = vcur[:, :c], vcur[:, c:]
        # visiting early chunk a' = src: live for qb always; for qa iff
        # src < my.  visiting late chunk b' = 2sp−1−src: live iff src > my
        # (then b' < b), and only for qb.  Exactly two fully-live sub-blocks.
        stats = fold(stats, 1, scores(qb, ka_), va_)
        early = src < my
        h2 = jnp.where(early, 0, 1).astype(jnp.int32)
        q2 = jnp.where(early, qa, qb)
        k2 = jnp.where(early, ka_, kb_)
        v2 = jnp.where(early, va_, vb_)
        stats = fold(stats, h2, scores(q2, k2), v2)
        return (stats, kcur, vcur), None

    (stats, _, _), _ = lax.scan(jax.checkpoint(step), (stats, k0, v0),
                                jnp.arange(1, sp_size))
    m, l, acc = stats
    l = jnp.where(l == 0.0, 1.0, l)
    out = acc / l[..., None]                        # [2, B, H, c, D]
    out = jnp.concatenate([out[0], out[1]], axis=2)  # [B, H, 2c, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _lse_merge(o1, l1, o2, l2):
    """Exact combine of two softmax-attention partials over disjoint key
    sets: o_i normalized outputs [B, H, Tq, D] (f32), l_i logsumexp rows
    [B, H, Tq].  The flash-decoding / ring-flash merge identity."""
    l = jnp.logaddexp(l1, l2)
    return (o1 * jnp.exp(l1 - l)[..., None]
            + o2 * jnp.exp(l2 - l)[..., None]), l


def _zigzag_body_flash(q, k0, v0, my, sp_size, axis, scale, interpret):
    """``_zigzag_body`` with the Pallas flash kernel as the inner attend —
    the [c, c] logit matrices never materialize (VMEM [bq, bk] tiles
    only), so per-device attention memory is O(inputs + outputs): the
    einsum body's peak 3×[B, H, c, c] score buffers are the last
    long-context memory wall this removes.

    Every zig-zag sub-attend is block-level causal=True (own diagonal) or
    causal=False (fully live) — liveness depends only on (my, src), never
    on token positions — so the stock flash kernels apply unmodified.
    Forward merges per-block (o, lse) with the exact logsumexp combine;
    backward is a ring-level custom_vjp in the ring-flash-attention
    style: replay the KV rotation and run the flash backward kernels per
    live sub-block with the GLOBAL lse (p = exp(s − lse_global) is then
    the true global softmax prob, so per-block dq/dk/dv sum exactly),
    accumulating dk/dv on a buffer that rotates WITH k/v and goes home in
    one reverse hop.  ``my`` enters only through a float liveness mask so
    the custom_vjp's inputs are all float (clean zero cotangents).
    Layouts inside are kernel-major [B, H, T, D].
    """
    # importlib: the ops package re-exports a flash_attention FUNCTION that
    # shadows the submodule on attribute access
    import importlib
    FA = importlib.import_module("deepspeed_tpu.ops.flash_attention")

    B, T2, H, D = q.shape
    c = T2 // 2
    perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]
    homeperm = [(i, (i - (sp_size - 1)) % sp_size) for i in range(sp_size)]
    # early[s−1] == 1.0 ⟺ ring step s's visiting block comes from an
    # EARLIER device (the where-routed sub-attend targets the qa half)
    steps = jnp.arange(1, sp_size)
    early_f = (((my - steps) % sp_size) < my).astype(jnp.float32)

    def kl(x):                         # [B, T, H, D] → kernel-major
        return jnp.transpose(x, (0, 2, 1, 3))

    def sub_fwd(qh, kc, vc, causal):
        o, lse = FA._fwd(qh, kc, vc, None, causal, scale, None, False,
                         interpret)
        # lse rides the kernels' [B, H, 1, T] stat layout — flatten for
        # the merges, re-expand in sub_bwd
        return o.astype(jnp.float32), lse[:, :, 0]  # [B,H,c,D], [B,H,c]

    def sub_bwd(qh, kc, vc, og, lg, do, causal):
        dq, dk, dv = FA._bwd_impl(qh, kc, vc, og.astype(qh.dtype),
                                  lg[:, :, None, :], do.astype(qh.dtype),
                                  None, causal, scale, None, False,
                                  interpret)
        return (dq.astype(jnp.float32), dk.astype(jnp.float32),
                dv.astype(jnp.float32))

    def fwd_scan(qx, kx, vx, ef):
        qa, qb = qx[:, :, :c], qx[:, :, c:]
        ka, kb = kx[:, :, :c], kx[:, :, c:]
        va, vb = vx[:, :, :c], vx[:, :, c:]
        # step 0 — own chunks: qa×ka diag, qb×ka full, qb×kb diag
        oa, la = sub_fwd(qa, ka, va, True)
        ob1, lb1 = sub_fwd(qb, ka, va, False)
        ob2, lb2 = sub_fwd(qb, kb, vb, True)
        ob, lb = _lse_merge(ob1, lb1, ob2, lb2)

        def step(carry, e):
            oa, la, ob, lb, kc, vc = carry
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            ka_, kb_ = kc[:, :, :c], kc[:, :, c:]
            va_, vb_ = vc[:, :, :c], vc[:, :, c:]
            o1, l1 = sub_fwd(qb, ka_, va_, False)  # qb × early chunk: live
            ob, lb = _lse_merge(ob, lb, o1, l1)
            early = e > 0.5
            q2 = jnp.where(early, qa, qb)
            k2 = jnp.where(early, ka_, kb_)
            v2 = jnp.where(early, va_, vb_)
            o2, l2 = sub_fwd(q2, k2, v2, False)
            oa_m, la_m = _lse_merge(oa, la, o2, l2)
            ob_m, lb_m = _lse_merge(ob, lb, o2, l2)
            oa = jnp.where(early, oa_m, oa)
            la = jnp.where(early, la_m, la)
            ob = jnp.where(early, ob, ob_m)
            lb = jnp.where(early, lb, lb_m)
            return (oa, la, ob, lb, kc, vc), None

        (oa, la, ob, lb, _, _), _ = lax.scan(
            step, (oa, la, ob, lb, kx, vx), ef)
        return oa, la, ob, lb

    def bwd_scan(qx, kx, vx, ef, oa, la, ob, lb, doa, dob):
        qa, qb = qx[:, :, :c], qx[:, :, c:]

        def live_sub1(kc, vc, dkc, dvc, dqb):
            """qb × visiting early chunk — live at EVERY ring step."""
            dq1, dk1, dv1 = sub_bwd(qb, kc[:, :, :c], vc[:, :, :c],
                                    ob, lb, dob, False)
            return (dkc.at[:, :, :c].add(dk1), dvc.at[:, :, :c].add(dv1),
                    dqb + dq1)

        # step 0 (resident block, run OUTSIDE the scan — its diagonal
        # sub-attends are the only causal ones, kept trace-static)
        zkv = jnp.zeros(kx.shape, jnp.float32)
        dqa = jnp.zeros((B, H, c, D), jnp.float32)
        dqb = jnp.zeros((B, H, c, D), jnp.float32)
        dkc, dvc, dqb = live_sub1(kx, vx, zkv, jnp.zeros_like(zkv), dqb)
        dq2, dk2, dv2 = sub_bwd(qa, kx[:, :, :c], vx[:, :, :c],
                                oa, la, doa, True)
        dqa = dqa + dq2
        dkc = dkc.at[:, :, :c].add(dk2)
        dvc = dvc.at[:, :, :c].add(dv2)
        dq3, dk3, dv3 = sub_bwd(qb, kx[:, :, c:], vx[:, :, c:],
                                ob, lb, dob, True)
        dqb = dqb + dq3
        dkc = dkc.at[:, :, c:].add(dk3)
        dvc = dvc.at[:, :, c:].add(dv3)

        def step(carry, e):
            kc, vc, dkc, dvc, dqa, dqb = carry
            rot = lambda x: lax.ppermute(x, axis, perm)  # noqa: E731
            kc, vc, dkc, dvc = rot(kc), rot(vc), rot(dkc), rot(dvc)
            dkc, dvc, dqb = live_sub1(kc, vc, dkc, dvc, dqb)
            early = e > 0.5
            ka_, kb_ = kc[:, :, :c], kc[:, :, c:]
            va_, vb_ = vc[:, :, :c], vc[:, :, c:]
            q2 = jnp.where(early, qa, qb)
            k2 = jnp.where(early, ka_, kb_)
            v2 = jnp.where(early, va_, vb_)
            og2 = jnp.where(early, oa, ob)
            lg2 = jnp.where(early, la, lb)
            do2 = jnp.where(early, doa, dob)
            dq2, dk2, dv2 = sub_bwd(q2, k2, v2, og2, lg2, do2, False)
            dqa = dqa + jnp.where(early, dq2, 0.0)
            dqb = dqb + jnp.where(early, 0.0, dq2)
            dkc = dkc.at[:, :, :c].add(jnp.where(early, dk2, 0.0))
            dkc = dkc.at[:, :, c:].add(jnp.where(early, 0.0, dk2))
            dvc = dvc.at[:, :, :c].add(jnp.where(early, dv2, 0.0))
            dvc = dvc.at[:, :, c:].add(jnp.where(early, 0.0, dv2))
            return (kc, vc, dkc, dvc, dqa, dqb), None

        (_, _, dkc, dvc, dqa, dqb), _ = lax.scan(
            step, (kx, vx, dkc, dvc, dqa, dqb), ef)
        # grads rotated sp−1 hops with their blocks; one permute goes home
        dkc = lax.ppermute(dkc, axis, homeperm)
        dvc = lax.ppermute(dvc, axis, homeperm)
        return jnp.concatenate([dqa, dqb], axis=2), dkc, dvc

    @jax.custom_vjp
    def zz(qx, kx, vx, ef):
        oa, _, ob, _ = fwd_scan(qx, kx, vx, ef)
        return jnp.concatenate([oa, ob], axis=2)

    def zz_fwd(qx, kx, vx, ef):
        oa, la, ob, lb = fwd_scan(qx, kx, vx, ef)
        return (jnp.concatenate([oa, ob], axis=2),
                (qx, kx, vx, ef, oa, la, ob, lb))

    def zz_bwd(res, dout):
        qx, kx, vx, ef, oa, la, ob, lb = res
        doa = dout[:, :, :c].astype(jnp.float32)
        dob = dout[:, :, c:].astype(jnp.float32)
        dq, dk, dv = bwd_scan(qx, kx, vx, ef, oa, la, ob, lb, doa, dob)
        return (dq.astype(qx.dtype), dk.astype(kx.dtype),
                dv.astype(vx.dtype), jnp.zeros_like(ef))

    zz.defvjp(zz_fwd, zz_bwd)
    out = zz(kl(q), kl(k0), kl(v0), early_f)      # [B, H, 2c, D] f32
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _zigzag_perm(t: int, sp: int):
    """Global index permutation placing chunks (d, 2sp−1−d) on device d.

    Computed in closed form from ``iota`` arithmetic rather than as a
    materialized index table: a constant array here becomes an XLA
    executable parameter under the engine's donated jit (constant hoisting),
    and the fast-path second call then fails with a supplied-vs-expected
    buffer-count mismatch — found driving the engine 30 steps, round 5.
    Iota-derived indices leave nothing to hoist (and nothing to ship from
    the host)."""
    c = t // (2 * sp)
    r = jnp.arange(t)
    # forward: row r lives on device d = r // (2c); within-device half
    # h selects chunk d (h=0) or chunk 2sp−1−d (h=1)
    d = r // (2 * c)
    w = r % (2 * c)
    chunk = jnp.where(w < c, d, 2 * sp - 1 - d)
    idx = chunk * c + w % c
    # inverse: original position i sits in chunk i//c; early chunks map to
    # (device=chunk, half 0), late ones to (device=2sp−1−chunk, half 1)
    ch_i = r // c
    early = ch_i < sp
    dev = jnp.where(early, ch_i, 2 * sp - 1 - ch_i)
    inv = dev * 2 * c + jnp.where(early, 0, c) + r % c
    return idx, inv


def zigzag_order(t: int, sp: int):
    """(idx, inv) for the zig-zag placement: ``x[:, idx]`` lays a contiguous
    sequence out so shard d of the sp axis holds chunks (d, 2·sp−1−d);
    ``z[:, inv]`` undoes it.  Row r of the zig-zag array holds the token
    whose global position is ``idx[r]`` — so ``positions = idx`` is the
    position vector of the permuted sequence (what RoPE / learned position
    embeddings must see)."""
    if t % (2 * sp):
        raise ValueError(f"seq len {t} not divisible by 2*sp={2 * sp}")
    return _zigzag_perm(t, sp)


def ring_attention(mesh: Mesh, q, k, v, *, causal: bool = True,
                   axis: str = "sp", batch_axes=("dp", "fsdp"),
                   scale=None, schedule: str = "zigzag",
                   layout: str = "contiguous", inner: str = "einsum"):
    """Global-view entry: q/k/v [B, T, H, D] with T sharded over ``axis``.

    Equivalent math to full softmax attention (tested token-exact vs the
    dense path); peak per-device score memory is [B, H, T/sp, T/sp]
    (contiguous) or 3×[B, H, T/2sp, T/2sp] (zigzag).

    ``schedule``: "zigzag" (default — causal FLOPs ≈ halved, module
    docstring) or "contiguous".  Zig-zag needs T % (2·sp) == 0 and causal;
    other cases fall back to the contiguous schedule.

    ``layout``: "contiguous" (default — rows are tokens in order; the
    zig-zag schedule permutes in/out internally, ~4 tensor volumes of wire
    per call) or "zigzag" (rows are ALREADY in zig-zag placement — row r
    holds token ``idx[r]`` of ``zigzag_order(T, sp)`` — so the schedule runs
    with ZERO permute traffic and the output stays in zig-zag layout).  The
    layout-native path is how a training stack amortizes the permutes to
    one token-id shuffle per step: permute ids + positions + labels once at
    the batch (models/gpt.py ``sp_ring_layout='native'``), keep activations
    zig-zag end-to-end — every non-attention op is position-wise and the LM
    loss is permutation-invariant.  Requires causal and T % (2·sp) == 0
    (raises otherwise: the caller re-laid the data out, silence would
    compute garbage).

    ``inner``: "einsum" (default — per-step sub-attends materialize
    [c, c] logits, c = T/(2·sp)) or "flash" (sub-attends run the Pallas
    flash kernel with logsumexp merging and a ring-level custom_vjp —
    per-device attention memory drops to O(inputs + outputs), removing the
    last long-context memory wall; see ``_zigzag_body_flash``).  "flash"
    requires the zig-zag schedule (causal, T % (2·sp) == 0), head_dim % 8
    == 0, and a per-device half-chunk divisible by a flash block (c ≥ 8);
    raises otherwise — an opt-in flag must not silently degrade.
    """
    sp = mesh.shape[axis]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"layout must be contiguous|zigzag, got {layout!r}")
    if inner not in ("einsum", "flash"):
        # validated BEFORE the sp==1 early return (round-5 advisor finding:
        # a bad inner string was silently accepted on single-shard meshes)
        raise ValueError(f"inner must be einsum|flash, got {inner!r}")
    if sp == 1:
        from deepspeed_tpu import ops
        if layout == "zigzag":
            raise ValueError("layout='zigzag' is meaningless at sp=1 — the "
                             "caller permuted for a ring that doesn't exist")
        if inner == "flash":
            # the flag asked for O(inputs) attention memory; honoring that at
            # sp=1 means the registry flash kernel (impl=None lets the op
            # registry pick Pallas where supported), NOT a silent degrade to
            # dense XLA attention with its [B, H, T, T] logits
            return ops.causal_attention(q, k, v, causal=causal, scale=scale,
                                        impl=None)
        return ops.causal_attention(q, k, v, causal=causal, scale=scale,
                                    impl="xla")
    if q.shape[1] % sp:
        raise ValueError(f"seq len {q.shape[1]} not divisible by "
                         f"{axis}={sp}")
    if schedule not in ("zigzag", "contiguous"):
        raise ValueError(f"schedule must be zigzag|contiguous, "
                         f"got {schedule!r}")
    if layout == "zigzag" and (not causal or q.shape[1] % (2 * sp)):
        raise ValueError("layout='zigzag' requires causal attention and "
                         f"seq len divisible by 2*{axis}={2 * sp} "
                         f"(got causal={causal}, T={q.shape[1]})")
    if layout == "zigzag" and schedule == "contiguous":
        raise ValueError("layout='zigzag' forces the zigzag schedule; "
                         "schedule='contiguous' would be silently ignored")
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"query heads {q.shape[2]} not divisible by kv "
                         f"heads {k.shape[2]}")
    # GQA: KV stays at nkv heads through the ring — the group expansion
    # happens inside the per-step einsum (_gqa_scores/_gqa_pv), so each hop
    # moves nkv/nh of the bytes a pre-expanded ring would
    comms_logger.record("ring_attention_ppermute",
                        (k.size + v.size) * k.dtype.itemsize // sp * (sp - 1),
                        axis)
    spec = P(batch_axes, axis, None, None)
    zig = (layout == "zigzag"
           or (schedule == "zigzag" and causal and q.shape[1] % (2 * sp) == 0))

    if inner == "flash":
        c = q.shape[1] // (2 * sp)
        # importlib, NOT `from deepspeed_tpu.ops import flash_attention`:
        # the package re-exports a FUNCTION of that name which shadows the
        # submodule on attribute access
        import importlib
        _fa = importlib.import_module("deepspeed_tpu.ops.flash_attention")
        # zig already encodes causal ∧ T % (2·sp) == 0 for this layout;
        # _block_sizes(c) is None for any c < 8.  Backward-pass hop bytes
        # (KV replay + dk/dv homing) are NOT booked, matching the einsum
        # inner whose autodiff backward ppermutes are likewise unbooked —
        # the logger records the forward ring only, for either inner.
        if not (zig and q.shape[3] % 8 == 0
                and _fa._block_sizes(c) is not None):
            raise ValueError(
                "inner='flash' needs the causal zig-zag schedule with "
                f"T % (2*sp) == 0, head_dim % 8 == 0, and half-chunk "
                f"c = T/(2*sp) >= 8 divisible by a flash block (got "
                f"T={q.shape[1]}, sp={sp}, d={q.shape[3]}, c={c})")

    if zig:
        @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=spec, check_vma=False)
        def inner_z(q_, k_, v_):
            my = lax.axis_index(axis)
            if inner == "flash":
                interp = jax.default_backend() != "tpu"
                return _zigzag_body_flash(q_, k_, v_, my, sp, axis, scale,
                                          interp)
            return _zigzag_body(q_, k_, v_, my, sp, axis, scale)

        if layout == "zigzag":
            # data already zig-zag placed: the ring hops are the ONLY wire
            return inner_z(q, k, v)

        idx, inv = _zigzag_perm(q.shape[1], sp)
        # the in/out zig-zag permutes reshard across sp — real wire traffic
        # (≈4 tensor volumes per call), booked separately from the ring hops
        comms_logger.record(
            "ring_attention_zigzag_permute",
            (q.size + k.size + v.size + q.size) * q.dtype.itemsize, axis)
        qz, kz, vz = (jnp.take(x, idx, axis=1) for x in (q, k, v))
        return jnp.take(inner_z(qz, kz, vz), inv, axis=1)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def inner(q_, k_, v_):
        my = lax.axis_index(axis)
        return _ring_body(q_, k_, v_, my, sp, axis, causal, scale)

    return inner(q, k, v)
