"""Ring attention — sequence parallelism by rotating KV blocks over the ring.

Reference scope: DeepSpeed's long-context story is Ulysses (sequence/layer.py,
all-to-all head swap).  Ring attention (Liu et al., "Ring Attention with
Blockwise Transformers", PAPERS.md) is the complementary mechanism this
framework ships as a first-class alternative: sequence stays sharded the
WHOLE time — no all-to-all, no head-count divisibility constraint — while K/V
blocks rotate neighbor-to-neighbor over the ``sp`` axis.

TPU-native shape: one ``shard_map`` over ``sp``; inside, a differentiable
``lax.scan`` of sp steps, each step
  - attends the local Q block against the currently-held K/V block with a
    GLOBAL-position causal mask (so ordering is exact regardless of which
    block is visiting),
  - folds the partial result into online-softmax stats (m, l, acc) — the
    flash-attention recurrence across blocks,
  - ``ppermute``s the K/V block to the next neighbor (ICI ring — the same
    link pattern the hardware torus provides natively).

Causality note: blocks strictly "ahead" of the local Q block contribute
nothing but are still rotated through (the ring must complete); their scores
are fully masked.  A compute-skipping schedule (zig-zag/striped sharding) is
a later optimization — the wire cost is already optimal (each device sends
exactly its KV bytes sp-1 times, neighbor-only).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.comm import comms_logger

_NEG = jnp.float32(-1e30)


def _ring_body(q, k0, v0, my, sp_size, axis, causal, scale):
    """Local blockwise-softmax accumulation over sp ring steps.

    q [B, Tl, H, D]; k0/v0 the locally-held KV block.  Returns [B, Tl, H, D].
    """
    B, Tl, H, D = q.shape
    qpos = my * Tl + jnp.arange(Tl)                     # global positions
    perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

    qf = q.astype(jnp.float32)

    def accumulate(m, l, acc, kcur, vcur, s):
        src = (my - s) % sp_size                        # owner of kcur
        kpos = src * Tl + jnp.arange(Tl)
        s_log = jnp.einsum("bqhd,bkhd->bhqk", qf,
                           kcur.astype(jnp.float32)) * scale
        if causal:
            mask = kpos[None, :] <= qpos[:, None]       # [Tq, Tk] global
            s_log = jnp.where(mask[None, None], s_log, _NEG)
        m_new = jnp.maximum(m, jnp.max(s_log, axis=-1))
        p = jnp.exp(s_log - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, vcur.astype(jnp.float32))
        return m_new, l_new, acc * alpha[..., None] + pv

    def step(carry, s):
        m, l, acc, kcur, vcur = carry
        m, l, acc = accumulate(m, l, acc, kcur, vcur, s)
        # rotate KV to the next neighbor; the last visiting block is computed
        # OUTSIDE the scan so no dead final rotation is issued (sp-1 hops
        # total — matches the bytes the comms logger books)
        knext = lax.ppermute(kcur, axis, perm)
        vnext = lax.ppermute(vcur, axis, perm)
        return (m, l, acc, knext, vnext), None

    m0 = jnp.full((B, H, Tl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    (m, l, acc, klast, vlast), _ = lax.scan(
        jax.checkpoint(step), (m0, l0, acc0, k0, v0),
        jnp.arange(sp_size - 1))
    m, l, acc = accumulate(m, l, acc, klast, vlast, sp_size - 1)
    l = jnp.where(l == 0.0, 1.0, l)                     # fully-masked rows
    out = acc / l[..., None]                            # [B, H, Tl, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention(mesh: Mesh, q, k, v, *, causal: bool = True,
                   axis: str = "sp", batch_axes=("dp", "fsdp"),
                   scale=None):
    """Global-view entry: q/k/v [B, T, H, D] with T sharded over ``axis``.

    Equivalent math to full softmax attention (tested token-exact vs the
    dense path); peak per-device score memory is [B, H, T/sp, T/sp]."""
    sp = mesh.shape[axis]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if sp == 1:
        from deepspeed_tpu import ops
        return ops.causal_attention(q, k, v, causal=causal, impl="xla")
    if q.shape[1] % sp:
        raise ValueError(f"seq len {q.shape[1]} not divisible by "
                         f"{axis}={sp}")
    if k.shape[2] != q.shape[2]:
        # GQA: expand KV to the query head count before the ring (the rotated
        # blocks then carry nh heads instead of nkv — a grouped in-ring score
        # kernel that keeps the bandwidth benefit is a later optimization)
        g = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    comms_logger.record("ring_attention_ppermute",
                        (k.size + v.size) * k.dtype.itemsize // sp * (sp - 1),
                        axis)
    spec = P(batch_axes, axis, None, None)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def inner(q_, k_, v_):
        my = lax.axis_index(axis)
        return _ring_body(q_, k_, v_, my, sp, axis, causal, scale)

    return inner(q, k, v)
