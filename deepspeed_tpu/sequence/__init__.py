from deepspeed_tpu.sequence.ring import ring_attention, zigzag_order
from deepspeed_tpu.sequence.ulysses import (DistributedAttention,
                                            ulysses_attention)

__all__ = ["DistributedAttention", "ulysses_attention", "ring_attention",
           "zigzag_order"]
