from deepspeed_tpu.sequence.ulysses import DistributedAttention, ulysses_attention

__all__ = ["DistributedAttention", "ulysses_attention"]
