"""deepspeed_tpu: a TPU-native distributed training + inference framework with the
capability surface of DeepSpeed, rebuilt on JAX/XLA/Pallas/pjit.

Top-level API parity (reference deepspeed/__init__.py):
- ``initialize()``     (reference :69)  → build a training engine from (model, config)
- ``init_inference()`` (reference :273) → build an inference engine  [milestone 7]
- ``comm``             (reference deepspeed/comm) → mesh collectives
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

from deepspeed_tpu.utils import compat as _compat  # noqa: F401 — jax shims
from deepspeed_tpu import checkpointing, comm, telemetry, zero
from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments
from deepspeed_tpu.zero import OnDevice
from deepspeed_tpu.config import DeepSpeedTPUConfig, parse_config
from deepspeed_tpu.engine import DeepSpeedTPUEngine, StepMetrics, TrainState
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.version import __version__

__all__ = [
    "initialize",
    "init_inference",
    "DeepSpeedTPUEngine",
    "DeepSpeedTPUConfig",
    "DeepSpeedDataLoader",
    "RepeatingLoader",
    "TrainState",
    "StepMetrics",
    "comm",
    "telemetry",
    "zero",
    "checkpointing",
    "get_accelerator",
    "default_inference_config",
    "add_tuning_arguments",
    "OnDevice",
    "__version__",
]


def default_inference_config() -> dict:
    """reference deepspeed.default_inference_config (:266): the default
    inference config as an editable dict."""
    from deepspeed_tpu.inference import DeepSpeedInferenceConfig
    return DeepSpeedInferenceConfig().model_dump()


def initialize(model=None,
               config=None,
               example_batch=None,
               training_data=None,
               lr_scheduler: Optional[Callable[[int], float]] = None,
               optimizer=None,
               mesh=None,
               collate_fn: Optional[Callable] = None,
               dist_init_required: Optional[bool] = None,
               args=None,
               config_params=None,
               **kwargs) -> Tuple[DeepSpeedTPUEngine, Any, Any, Any]:
    """Build the training engine (reference deepspeed.initialize,
    deepspeed/__init__.py:69; engine dispatch :166-208).

    Returns ``(engine, optimizer, dataloader, lr_scheduler)`` like the reference.
    The optimizer slot returns the engine's optax transformation; the dataloader is
    built when ``training_data`` is given.

    model: flax linen Module whose ``__call__(batch)`` returns a scalar loss, or an
    ``(init_fn, apply_fn)`` pair (see DeepSpeedTPUEngine docstring).
    example_batch: a host pytree with microbatch-shaped leaves used to trace
    ``model.init``; taken from ``training_data`` if omitted.
    """
    if config is None and config_params is None and args is not None:
        # reference deepspeed/__init__.py: the --deepspeed_config CLI flag
        # (add_config_arguments) supplies the config when none is passed
        config = (getattr(args, "deepspeed_config", None)
                  or getattr(args, "deepscale_config", None))
    cfg = parse_config(config if config is not None else config_params)
    if dist_init_required is None or dist_init_required:
        comm.init_distributed()

    dataloader = None
    if example_batch is None and training_data is not None:
        import itertools

        import jax
        import numpy as np
        it = iter(training_data)
        first = next(it)
        if it is training_data:
            # one-shot iterator/generator: don't lose the peeked example
            training_data = itertools.chain([first], it)
        example_batch = jax.tree_util.tree_map(
            lambda x: np.asarray(x)[None, ...], first)

    if example_batch is None:
        raise ValueError("initialize() needs example_batch or training_data "
                         "to trace model.init")

    if cfg.zero_optimization.offload_param.device != "none":
        # ZeRO-Infinity param offload: engine dispatch at initialize() time,
        # as the reference dispatches PipelineEngine vs DeepSpeedEngine
        # (deepspeed/__init__.py:166-208)
        from deepspeed_tpu.runtime.infinity import InfinityEngine
        if optimizer is not None:
            raise ValueError(
                "offload_param builds its own host Adam (the reference "
                "likewise swaps in DeepSpeedCPUAdam); drop the client "
                "optimizer or the offload")
        engine = InfinityEngine(model=model, config=cfg,
                                example_batch=example_batch, mesh=mesh,
                                lr_scheduler=lr_scheduler)
    else:
        engine = DeepSpeedTPUEngine(model=model, config=cfg,
                                    example_batch=example_batch, mesh=mesh,
                                    lr_scheduler=lr_scheduler,
                                    client_optimizer=optimizer)

    if training_data is not None:
        dataloader = DeepSpeedDataLoader(
            training_data,
            micro_batch_size_per_gpu=int(cfg.train_micro_batch_size_per_gpu),
            gradient_accumulation_steps=int(cfg.gradient_accumulation_steps),
            dp_world_size=engine.dp_world_size,
            collate_fn=collate_fn)

    return engine, engine.optimizer, dataloader, engine.lr_schedule


def init_inference(model=None, config=None, params=None, mesh=None, **kwargs):
    """Build an inference engine (reference deepspeed.init_inference,
    deepspeed/__init__.py:273 → inference/engine.py:39).

    model: GPT-family flax module, GPTConfig, or a path to an HF model
    directory (safetensors — llama/mistral/qwen2/gpt2, see checkpoint/hf.py);
    ``params`` takes trained weights (e.g. ``train_engine.state.params``).
    kwargs merge into the config dict for the reference's
    ``init_inference(model, tensor_parallel=.., dtype=..)`` calling style.
    """
    from deepspeed_tpu.inference import (DeepSpeedInferenceConfig,
                                         InferenceEngine)
    from deepspeed_tpu.checkpoint.hf import is_hf_model_dir, load_hf_checkpoint

    def as_dict(cfg):
        """config path/dict/model → plain dict (the shared normal form)."""
        if cfg is None:
            return {}
        if isinstance(cfg, dict):
            return dict(cfg)
        if isinstance(cfg, str):
            import json
            with open(cfg) as f:
                return json.load(f)
        if isinstance(cfg, DeepSpeedInferenceConfig):
            return cfg.model_dump(by_alias=False)
        raise TypeError(f"config must be dict/path/config model, got "
                        f"{type(cfg)!r}")

    from deepspeed_tpu.checkpoint.diffusion import is_diffusers_model_dir
    if is_diffusers_model_dir(model):
        # SD containers (reference module_inject/containers/{unet,vae}.py)
        from deepspeed_tpu.checkpoint.diffusion import _read_json
        from deepspeed_tpu.inference.config import _DTYPE_ALIASES
        from deepspeed_tpu.inference.diffusion import UNetEngine, VAEEngine
        import os as _os
        if params is not None:
            raise ValueError("pass either a diffusers model dir or params, "
                             "not both")
        if mesh is not None:
            raise ValueError("the SD containers are single-mesh jitted "
                             "forwards; mesh selection is not consumed — "
                             "drop the mesh argument")
        if isinstance(config, DeepSpeedInferenceConfig):
            # only fields the user actually SET count as intent — a full
            # model_dump would make every defaulted field warn
            merged = dict(config.model_dump(exclude_unset=True), **kwargs)
        else:
            merged = dict(as_dict(config), **kwargs)
        # fallback = the inference config class default, NOT a hardcoded
        # fp32 (they must never disagree)
        default_dt = DeepSpeedInferenceConfig().dtype
        raw_dt = str(merged.get("dtype", default_dt)).lower().replace(
            "torch.", "")
        float_aliases = {k: v for k, v in _DTYPE_ALIASES.items()
                         if v.startswith(("float", "bfloat"))}
        if raw_dt not in float_aliases:
            raise ValueError(f"SD containers serve float dtypes; got "
                             f"{merged.get('dtype')!r}, expected one of "
                             f"{sorted(float_aliases)}")
        dt = float_aliases[raw_dt]
        # inert-config-must-scream (config.warn_inert_config policy): the SD
        # engines consume only dtype/channels_last
        from deepspeed_tpu.utils.logging import logger as _logger
        for k in sorted(set(merged) - {"dtype", "channels_last"}):
            _logger.warning(f"inference config key {k!r} is not consumed by "
                            f"the SD containers (only dtype/channels_last "
                            f"are) — this run will NOT honor it")
        cls = _read_json(_os.path.join(str(model),
                                       "config.json"))["_class_name"]
        eng_cls = UNetEngine if cls == "UNet2DConditionModel" else VAEEngine
        return eng_cls(str(model), dtype=dt,
                       channels_last=bool(merged.get("channels_last",
                                                     False)))
    if is_hf_model_dir(model):
        if params is not None:
            raise ValueError("pass either an HF model dir or params, not both")
        import os as _os
        from deepspeed_tpu.checkpoint.hf import (_BERT_LIKE, _CLIP_LIKE,
                                                 _arch_of, _read_json,
                                                 load_hf_bert,
                                                 load_hf_clip_text)
        arch = _arch_of(_read_json(_os.path.join(model, "config.json")))
        if arch in _CLIP_LIKE:
            # clip text tower (reference module_inject/containers/clip.py)
            from deepspeed_tpu.inference.encoder import ClipTextEngine
            ccfg, ctree, extras = load_hf_clip_text(model)
            return ClipTextEngine(ccfg, ctree, extras,
                                  config=dict(as_dict(config), **kwargs),
                                  mesh=mesh)
        if arch in _BERT_LIKE:
            # encoder family: single-shot forward engine (reference bert
            # injection policies, module_inject/containers/bert.py)
            from deepspeed_tpu.inference.encoder import EncoderInferenceEngine
            bcfg, bparams = load_hf_bert(model)
            return EncoderInferenceEngine(bcfg, bparams,
                                          config=dict(as_dict(config),
                                                      **kwargs),
                                          mesh=mesh)
        model, params = load_hf_checkpoint(model)
    if kwargs:
        config = dict(as_dict(config), **kwargs)
    return InferenceEngine(model=model, config=config, params=params, mesh=mesh)


def add_config_arguments(parser):
    """Add the canonical DeepSpeed CLI flags to an argparse parser
    (reference deepspeed.add_config_arguments, deepspeed/__init__.py:250 →
    add_core_arguments): ``--deepspeed`` enable flag, ``--deepspeed_config``
    JSON path, ``--deepscale*`` legacy aliases."""
    group = parser.add_argument_group("DeepSpeed-TPU",
                                      "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag for user "
                            "scripts; initialize() is what activates it)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed-TPU JSON config file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated alias of --deepspeed_config")
    return parser
