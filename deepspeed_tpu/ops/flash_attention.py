"""Flash attention — Pallas TPU kernel with XLA fallback.

TPU-native replacement for the reference's fused attention kernels
(training: csrc/transformer/*_kernels.cu strided-batch-gemm + softmax path;
inference v1: csrc/transformer/inference/csrc/softmax.cu; the blocked flash in
inference/v2/kernels/ragged_ops/blocked_flash is the ragged cousin, see
inference/v2).  Online-softmax tiling keeps the [T, T] score matrix out of HBM:
VMEM-resident (bq, bk) tiles stream through the MXU with running max/denominator
rescaling, forward saves only the logsumexp row stats for the backward pass.

Variants handled IN-KERNEL (round-3: VERDICT item 3):
- alibi: per-head slope × key-position logit bias (bloom/falcon-rw;
  reference v1 kernels includes/alibi.h) — slopes ride SMEM, the bias folds
  into the online softmax and both backward kernels.
- sliding window (mistral/gpt-neo local attention): in-tile masking PLUS
  whole-tile skipping — (q, k) tiles wholly outside the window never run, so
  FLOPs scale with T·window instead of T²/2.  Fully-masked rows (a window
  that ends before the tile) are guarded so exp(s − m) cannot alias to 1.

Layout convention: public API is [B, T, N, D] (batch, seq, heads, head_dim) to
match the model code; kernels run on [B, N, T, D].
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30  # finite "minus infinity": avoids inf-inf NaNs in rescaling

# per-seq-len (bq, bk) overrides: the baked-in `_block_pair` table came from
# ONE v5e sweep (B4·H12·D64) and the T=4096 regression (r05 MFU 0.425 vs
# 0.50 dense) showed it does not transfer — so the table is overridable
# without a code change: ``configure_flash_blocks({4096: (512, 1024)})`` or
# env ``DSTPU_FLASH_BLOCKS="4096:512x1024,8192:512x1024"``.
# scripts/sweep_flash_blocks.py measures candidates on the current hardware
# and prints the winning env line.
_BLOCK_OVERRIDES = None   # None = not yet resolved from env; {} = none


def _parse_block_spec(spec: str):
    """'4096:512x1024,8192:512' → {4096: (512, 1024), 8192: (512, 512)}."""
    out = {}
    for part in spec.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            t_s, blocks = part.split(":")
            bq_s, _, bk_s = blocks.partition("x")
            bq = int(bq_s)
            bk = int(bk_s) if bk_s else bq
            out[int(t_s)] = (bq, bk)
        except ValueError as e:
            raise ValueError(
                f"bad flash block spec {part!r} (want 'T:BQxBK' or 'T:B'): "
                f"{e}") from e
    return out


def _validate_blocks(overrides) -> dict:
    mapping = {}
    for t, pair in dict(overrides).items():
        bq, bk = int(pair[0]), int(pair[1])
        if int(t) < 8 or bq < 8 or bk < 8:
            raise ValueError(
                f"flash block override T={t}: ({bq}, {bk}) — seq len and "
                f"blocks must be >= 8")
        mapping[int(t)] = (bq, bk)
    return mapping


def configure_flash_blocks(overrides=None):
    """Install (bq, bk) overrides keyed by sequence length; ``None`` resets
    to the ``DSTPU_FLASH_BLOCKS`` env (or the built-in table when unset).
    Divisibility is validated at use time (T is only known then); shape
    sanity is validated here — on BOTH paths, so a typo'd env spec raises
    a clear ValueError instead of a ZeroDivisionError inside kernel
    tracing.  Returns the active mapping."""
    global _BLOCK_OVERRIDES
    if overrides is None:
        env = os.environ.get("DSTPU_FLASH_BLOCKS", "")
        overrides = _parse_block_spec(env) if env else {}
    _BLOCK_OVERRIDES = _validate_blocks(overrides)
    return dict(_BLOCK_OVERRIDES)


def flash_block_overrides():
    """The active override table (env resolved lazily on first use)."""
    global _BLOCK_OVERRIDES
    if _BLOCK_OVERRIDES is None:
        configure_flash_blocks(None)
    return _BLOCK_OVERRIDES


def _block_sizes(t: int, prefer: int = DEFAULT_BLOCK_Q):
    for b in (prefer, 512, 256, 128, 64, 32, 16, 8):
        if b <= t and t % b == 0:
            return b
    return None


def _block_pair(t: int, d: int = 64, window=None):
    """(bq, bk) — set by the round-5 on-chip v5e sweep (B4·H12·D64,
    fwd+bwd, dispatch-amortized):

    - T=1024: whole-sequence (1024, 1024) tile, 1.25× vs 512² (per-tile
      overheads dominate at short T; the causal-skip waste of an unsplit
      K is cheaper than the extra grid steps).
    - T=2048: single K tile (512, 2048), ~1.04×.
    - T ≥ 4096: (512, 1024), 1.19× at 4096 and 1.18× at 8192 — wider K
      streams K/V in fewer tiles; 2048-wide K loses the causal skipping
      and fell back to ~1.0×, and (1024, 2048) over-fills VMEM and fails
      to compile.
    - other/smaller T (tests, odd shapes): square `_block_sizes` as before.

    Two gates keep the wide pairs inside their measured envelope:
    sliding-window attention stays on square tiles (dead-tile skipping is
    the T·window FLOP scaling — one whole-sequence K tile can never be
    skipped), and head_dim > 128 stays square (the d-scaled q/k/v/acc
    tiles stack on the D-independent 4 MB fp32 score tile; the sweep only
    validated VMEM fit up to d=128, and an over-full tile is a hard
    compile error, not a fallback).

    An entry in the override table (``configure_flash_blocks`` /
    ``DSTPU_FLASH_BLOCKS``) wins over everything INCLUDING the gates — it
    is an explicit hardware-tuned choice (scripts/sweep_flash_blocks.py);
    only T-divisibility is still enforced (a non-dividing block is a wrong
    grid, not a tuning choice)."""
    ov = flash_block_overrides()
    if t in ov:
        bq, bk = ov[t]
        if t % bq or t % bk:
            raise ValueError(
                f"flash block override for T={t}: ({bq}, {bk}) must divide "
                f"the sequence length")
        return bq, bk
    bq = _block_sizes(t)
    if window is not None or d > 128:
        return bq, bq
    if t == 1024:
        return 1024, 1024
    if t == 2048:
        return 512, 2048
    if t >= 4096 and bq == 512 and t % 1024 == 0:
        return bq, 1024
    return bq, bq


def supported(q, k, v, *, causal=True, scale=None, window=None,
              alibi_slopes=None, **_):
    """Shape predicate for the pallas path (registry.OpSpec.supported)."""
    if q.ndim != 4 or q.shape != v.shape[:2] + q.shape[2:]:
        return False
    t, d = q.shape[1], q.shape[3]
    if k.shape[1] != t:  # cross/ragged attention -> fallback
        return False
    if q.shape[2] % k.shape[2] != 0:  # GQA group must divide
        return False
    if window is not None and (not causal or int(window) <= 0):
        return False
    if alibi_slopes is not None and (not causal
                                     or np.size(alibi_slopes) != q.shape[2]):
        return False
    return _block_sizes(t) is not None and d % 8 == 0


def _run_pred(iq, ik, bq, bk, causal, window):
    """Static-shape tile liveness: causal reach ∧ window reach.  A (iq, ik)
    tile is dead when every (qpos, kpos) pair in it is masked — those tiles
    are skipped entirely (the FLOP saving)."""
    run = True
    if causal:
        run = (iq + 1) * bq > ik * bk
    if window is not None:
        # live iff the tile's max kpos reaches past min qpos - window
        run = jnp.logical_and(run, (ik + 1) * bk + window > iq * bq)
    return run


def _tile_scores(q, k, iq, ik, bq, bk, scale, causal, window, slope):
    """Scaled logits for one tile with bias and masking applied."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if slope is not None:
        s = s + slope * kpos.astype(jnp.float32)
    if causal or window is not None:
        qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        valid = qpos >= kpos if causal else (qpos == qpos)
        if window is not None:
            valid = valid & (kpos > qpos - window)
        s = jnp.where(valid, s, _NEG_INF)
    return s


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, bq, bk, window,
                has_alibi):
    if has_alibi:
        slopes_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        slopes_ref = None
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    slope = slopes_ref[pl.program_id(1)] if has_alibi else None

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    run = _run_pred(iq, ik, bq, bk, causal, window)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]                      # [bq, d]
        k = k_ref[0, 0]                      # [bk, d]
        v = v_ref[0, 0]
        s = _tile_scores(q, k, iq, ik, bq, bk, scale, causal, window, slope)
        m_prev = m_scr[:, :1]                # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)               # [bq, bk] fp32
        if window is not None:
            # a row whose window lies wholly outside this tile: m_new is still
            # -inf and exp(s - m_new) would alias masked entries to 1
            p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0, 0] = (m_scr[:, :1] + jnp.log(l))[:, 0]


def _fwd(q, k, v, slopes, causal, scale, window, has_alibi, interpret):
    b, n, t, d = q.shape
    group = n // k.shape[1]   # GQA: kv head = q head // group (no expansion)
    bq, bk = _block_pair(t, d, window)
    grid = (b, n, t // bq, t // bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, window=window,
                               has_alibi=has_alibi)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda b_, h, iq, ik: (b_, h, iq, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b_, h, iq, ik: (b_, h // group, ik, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b_, h, iq, ik: (b_, h // group, ik, 0)),
    ]
    inputs = [q, k, v]
    if has_alibi:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        inputs.append(slopes)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, iq, ik: (b_, h, iq, 0)),
            # row stats ride a [B, N, 1, T] layout: a (1, 1, 1, bq) block keeps
            # the trailing dims TPU-tileable (second-to-last == array dim)
            pl.BlockSpec((1, 1, 1, bq), lambda b_, h, iq, ik: (b_, h, 0, iq)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, n, 1, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*inputs)
    return o, lse


# ---------------------------------------------------------------- backward

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               scale, causal, bq, bk, window, has_alibi):
    if has_alibi:
        slopes_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
        slopes_ref = None
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    slope = slopes_ref[pl.program_id(1)] if has_alibi else None

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    run = _run_pred(iq, ik, bq, bk, causal, window)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, 0][:, None]      # [bq, 1]
        delta = delta_ref[0, 0, 0][:, None]
        s = _tile_scores(q, k, iq, ik, bq, bk, scale, causal, window, slope)
        p = jnp.exp(s - lse)                 # [bq, bk]
        if window is not None:
            # fully-masked row: lse is -inf and exp(-inf − -inf) aliases to 1
            p = jnp.where(lse > _NEG_INF / 2, p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                scale, causal, bq, bk, nqb, group, window, has_alibi):
    # grid dim 3 fuses (q-head-in-group, q-block): dk/dv for one KV head sum
    # over every q head in its GQA group as well as every q block, so the
    # whole fused loop accumulates into one VMEM scratch
    if has_alibi:
        slopes_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        slopes_ref = None
    ik, j = pl.program_id(2), pl.program_id(3)
    nj = pl.num_programs(3)
    iq = j % nqb
    slope = (slopes_ref[pl.program_id(1) * group + j // nqb]
             if has_alibi else None)

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    run = _run_pred(iq, ik, bq, bk, causal, window)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, 0][:, None]
        delta = delta_ref[0, 0, 0][:, None]
        # NOTE the transpose of roles: scores here are [bq, bk] with q rows
        s = _tile_scores(q, k, iq, ik, bq, bk, scale, causal, window, slope)
        p = jnp.exp(s - lse)                 # [bq, bk]
        if window is not None:
            p = jnp.where(lse > _NEG_INF / 2, p, 0.0)
        # dv += p^T @ do
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale        # [bq, bk]
        # dk += ds^T @ q
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_impl(q, k, v, o, lse, do, slopes, causal, scale, window, has_alibi,
              interpret):
    b, n, t, d = q.shape
    nkv = k.shape[1]
    group = n // nkv
    bq, bk = _block_pair(t, d, window)
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)[:, :, None, :]                   # [b, n, 1, t]
    qkv_spec = pl.BlockSpec((1, 1, bq, d), lambda b_, h, iq, ik: (b_, h, iq, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d),
                           lambda b_, h, iq, ik: (b_, h // group, ik, 0))
    row_spec = pl.BlockSpec((1, 1, 1, bq), lambda b_, h, iq, ik: (b_, h, 0, iq))
    dq_in_specs = [qkv_spec, kv_spec, kv_spec, qkv_spec, row_spec, row_spec]
    dq_inputs = [q, k, v, do, lse, delta]
    if has_alibi:
        dq_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dq_inputs.append(slopes)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
                          window=window, has_alibi=has_alibi),
        grid=(b, n, t // bq, t // bk),
        in_specs=dq_in_specs,
        out_specs=qkv_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*dq_inputs)

    # kv-major grid over KV heads: (q-head-in-group, q-block) fused innermost so
    # dk/dv accumulate the whole GQA group in VMEM scratch
    nqb = t // bq
    q_spec2 = pl.BlockSpec(
        (1, 1, bq, d),
        lambda b_, h, ik, j: (b_, h * group + j // nqb, j % nqb, 0))
    kv_spec2 = pl.BlockSpec((1, 1, bk, d), lambda b_, h, ik, j: (b_, h, ik, 0))
    row_spec2 = pl.BlockSpec(
        (1, 1, 1, bq),
        lambda b_, h, ik, j: (b_, h * group + j // nqb, 0, j % nqb))
    dkv_in_specs = [q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2]
    dkv_inputs = [q, k, v, do, lse, delta]
    if has_alibi:
        dkv_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dkv_inputs.append(slopes)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
                          nqb=nqb, group=group, window=window,
                          has_alibi=has_alibi),
        grid=(b, nkv, t // bk, group * nqb),
        in_specs=dkv_in_specs,
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*dkv_inputs)
    return dq, dk, dv


# ------------------------------------------------------- custom_vjp plumbing

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, slopes, causal, scale, window, has_alibi, interpret):
    o, _ = _fwd(q, k, v, slopes, causal, scale, window, has_alibi, interpret)
    return o


def _flash_fwd(q, k, v, slopes, causal, scale, window, has_alibi, interpret):
    o, lse = _fwd(q, k, v, slopes, causal, scale, window, has_alibi,
                  interpret)
    return o, (q, k, v, slopes, o, lse)


def _flash_bwd(causal, scale, window, has_alibi, interpret, res, do):
    q, k, v, slopes, o, lse = res
    dq, dk, dv = _bwd_impl(q, k, v, o, lse, do, slopes, causal, scale,
                           window, has_alibi, interpret)
    return dq, dk, dv, jnp.zeros_like(slopes)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    window: Optional[int] = None,
                    alibi_slopes=None,
                    interpret: Optional[bool] = None):
    """Flash attention over [B, T, N, D] inputs (returns same layout).

    GQA (fewer kv heads) is consumed natively: the kernels index the kv head as
    ``q_head // group`` so K/V are never expanded in HBM (the reference
    blocked_flash consumes grouped KV the same way), and dk/dv accumulate the
    whole group inside the kv-major backward kernel.

    ``window``: sliding-window causal attention (key within the last
    ``window`` positions) with dead tiles skipped — FLOPs scale with
    T·window.  ``alibi_slopes`` [N]: per-head key-position bias.
    """
    if not supported(q, k, v, causal=causal, window=window,
                     alibi_slopes=alibi_slopes):
        raise ValueError(
            "flash_attention: unsupported shapes "
            f"q={q.shape} k={k.shape} v={v.shape} window={window}; requires "
            "[B, T, N, D] with equal q/kv seq len, kv heads dividing q heads, "
            "seq len divisible by a power-of-two block (>=8), head_dim % 8 "
            "== 0, and window/alibi only with causal=True "
            "(ops.causal_attention dispatches to the XLA path for these)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if scale is None:
        scale = q.shape[-1] ** -0.5
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    has_alibi = alibi_slopes is not None
    slopes = (jnp.asarray(alibi_slopes, jnp.float32).reshape(q.shape[2])
              if has_alibi else jnp.zeros((q.shape[2],), jnp.float32))
    o = _flash(qt, kt, vt, slopes, causal, float(scale),
               int(window) if window is not None else None, has_alibi,
               bool(interpret))
    return jnp.transpose(o, (0, 2, 1, 3))
