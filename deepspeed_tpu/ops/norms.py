"""Normalization ops — the ONE RMSNorm/LayerNorm body in the codebase.

Reference analog: csrc/transformer/inference/csrc/{layer_norm,rms_norm}.cu.
On TPU these are bandwidth-trivial elementwise chains XLA fuses into the
surrounding matmuls; the reason to centralize them is numeric discipline, not
speed: round-1 review found three drifting copies (models/gpt.py,
pipe/module.py, inference/v2/model.py) with different dtype behavior.

Canonical discipline: statistics in fp32, normalized output cast back to the
input dtype, scale/bias applied in the input dtype.  Callers that want a full
fp32 norm (the pipeline's final-norm+loss) pass fp32 inputs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

RMS_EPS = 1e-6
LN_EPS = 1e-5


def rms_norm(x, scale, eps: float = RMS_EPS):
    """RMSNorm: x * rsqrt(mean(x^2) + eps) * scale, fp32 statistics."""
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps).astype(x.dtype)
    return y * scale.astype(x.dtype)


def layer_norm(x, scale, bias, eps: float = LN_EPS):
    """LayerNorm with fp32 statistics, output in x.dtype."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    return y * scale.astype(x.dtype) + bias.astype(x.dtype)
