"""W8A16 matmul — int8 weights streamed through VMEM, dequantized per tile.

Reference parity: the FP6-LLM W6A16 quantized GEMM
(``inference/v2/modules/implementations/linear/quantized_linear.py:205`` +
``inference/v2/kernels/core_ops/cuda_linear/``) — the weight matrix stays
quantized THROUGH the matmul; full-precision weight values exist only in
on-chip memory, one tile at a time.

TPU shape of the idea: decode is weight-bandwidth-bound, so the win is HBM
traffic — the kernel reads int8 codes (1 byte/param) + per-group fp32
scales (≈3% overhead at group 128) instead of bf16 (2 bytes/param),
halving the weight stream.  Each grid step loads a [g, bn] int8 tile and
its [1, bn] scale row, dequantizes in VMEM registers, and feeds the MXU:

    y[M, N] = x[M, K] @ (codes[K, N] · scales[K/g, N])

The K-tile size equals the quantization group ``g`` so the scale is a
single broadcastable row per tile — no in-kernel gather/reshape.

``wq_matmul`` falls back to dequantize-then-matmul (XLA) off-TPU shapes or
for layouts the kernel doesn't cover (the store's dim-0 must be the
contraction dim, g % 32 == 0, dims tile-aligned).  Serving-only: no VJP is
defined (the store is inference-time state).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.quantization import (dequantize_weight,
                                            is_quantized_weight)


def _pick(total, prefer):
    for b in (prefer, 512, 256, 128, 64, 32, 16, 8):
        if b <= total and total % b == 0:
            return b
    return None


_warned_shapes = set()


def kernel_supported(x, store) -> bool:
    """True when the Pallas path can run (M is NOT constrained — wq_matmul
    pads the token dim to the tile).  Unsupported 2-D stores warn ONCE per
    shape: a silent fallback would let an operator benchmark 'the W8A16
    kernel' while measuring the dequant path (e.g. GPT-2's prime-ish vocab
    50257 can never N-tile)."""
    if not is_quantized_weight(store):
        return False
    v, s = store["v"], store["s"]
    if v.ndim != 2 or x.ndim != 2 or x.shape[1] != v.shape[0]:
        return False
    if s.shape[1:] != v.shape[1:]:
        return False                   # kernel assumes dim-0 grouping
    k, n = v.shape
    g = k // s.shape[0]
    ok = (k % g == 0 and g % 32 == 0 and g >= 32
          and _pick(n, 512) is not None)
    if not ok and (k, n, g) not in _warned_shapes:
        _warned_shapes.add((k, n, g))
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "wq_matmul: store [%d, %d] (group %d) cannot tile for the "
            "W8A16 kernel (needs group %% 32 == 0 and an N divisor ≤ 512); "
            "falling back to dequantize-then-matmul — the int8 HBM-traffic "
            "saving does NOT engage for this weight", k, n, g)
    return ok


def _kernel(x_ref, w_ref, s_ref, o_ref, acc, *, nk, contract):
    """Shared body for both orientations: dequantize one weight tile
    (codes · broadcast scale row) and accumulate the dot.  ``contract`` is
    the weight-side contraction dim: 0 for ``x @ W`` ([g, bn] tiles), 1 for
    ``x @ Wᵀ`` ([g, bk] tiles)."""
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc[...] = jnp.zeros(acc.shape, jnp.float32)

    x = x_ref[...]
    w = (w_ref[...].astype(jnp.float32)
         * s_ref[...].astype(jnp.float32))
    acc[...] += jax.lax.dot_general(
        x.astype(jnp.float32), w, (((1,), (contract,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _done():
        o_ref[...] = acc[...].astype(o_ref.dtype)


def kernel_t_supported(x, store) -> bool:
    """Transposed variant (``x @ storeᵀ``, tied-embedding unembed): store is
    [V, H] grouped along dim 0 (the embed gather's required layout), so the
    scale varies along the CONTRACTION dim within each g-row output tile —
    still a single broadcastable row per tile.  The output tile width is
    structurally pinned to g, so g must be lane-aligned (128)."""
    if not is_quantized_weight(store):
        return False
    v, s = store["v"], store["s"]
    if v.ndim != 2 or x.ndim != 2 or x.shape[1] != v.shape[1]:
        return False
    if s.shape[1:] != v.shape[1:]:
        return False                   # dim-0 grouping only
    vocab, h = v.shape
    g = vocab // s.shape[0]
    ok = (vocab % g == 0 and g % 128 == 0 and _pick(h, 512) is not None)
    if not ok and (vocab, h, g, "t") not in _warned_shapes:
        _warned_shapes.add((vocab, h, g, "t"))
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "wq_matmul_t: tied store [%d, %d] (group %d) cannot tile for "
            "the transposed W8A16 kernel (the output tile width IS the "
            "group, so it needs group %% 128 == 0, plus an H divisor "
            "≤ 512); falling back to dequantize-then-matmul", vocab, h, g)
    return ok


def wq_matmul_t(x, store, *, interpret: Optional[bool] = None):
    """``x [M, H] @ dequant(store [V, H]).T`` → [M, V] with the table kept
    int8 in HBM — the tied-embedding unembed (bloom/falcon-class models
    whose vocab divides the group; GPT-2's 50257 cannot tile and falls
    back).  One output tile per scale-group row keeps the dequant a single
    broadcast multiply."""
    if not kernel_t_supported(x, store):
        return x @ dequantize_weight(store, x.dtype).T
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v, s = store["v"], store["s"]
    vocab, h = v.shape
    m0 = x.shape[0]
    pad = (-m0) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    m = x.shape[0]
    g = vocab // s.shape[0]
    bm = _pick(m, 256)
    bk = _pick(h, 512)
    nk = h // bk
    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk, contract=1),
        grid=(m // bm, vocab // g, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda im, jv, ik: (im, ik)),
            pl.BlockSpec((g, bk), lambda im, jv, ik: (jv, ik)),
            pl.BlockSpec((1, bk), lambda im, jv, ik: (jv, ik)),
        ],
        out_specs=pl.BlockSpec((bm, g), lambda im, jv, ik: (im, jv)),
        out_shape=jax.ShapeDtypeStruct((m, vocab), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, g), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, v, s)
    return out[:m0] if pad else out


def wq_matmul(x, store, *, interpret: Optional[bool] = None):
    """``x [M, K] @ dequant(store [K, N])`` with the weight kept int8 in HBM.

    store: ``ops/quantization.quantize_weight`` dict (dim-0 = contraction
    dim).  Returns [M, N] in ``x.dtype``.  Falls back to the XLA
    dequantize-then-matmul for unsupported layouts.
    """
    if not kernel_supported(x, store):
        return x @ dequantize_weight(store, x.dtype)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v, s = store["v"], store["s"]
    k, n = v.shape
    m0 = x.shape[0]
    pad = (-m0) % 8                     # decode token counts tile to 8 rows
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    m = x.shape[0]
    g = k // s.shape[0]
    bm = _pick(m, 256)
    bn = _pick(n, 512)
    nk = k // g
    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk, contract=0),
        grid=(m // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, g), lambda im, jn, ik: (im, ik)),
            pl.BlockSpec((g, bn), lambda im, jn, ik: (ik, jn)),
            pl.BlockSpec((1, bn), lambda im, jn, ik: (ik, jn)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda im, jn, ik: (im, jn)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, v, s)
    return out[:m0] if pad else out
