"""Quantized-weight matmuls — int8/int4 weights streamed through VMEM,
dequantized per tile.

Reference parity: the FP6-LLM W6A16 quantized GEMM
(``inference/v2/modules/implementations/linear/quantized_linear.py:205`` +
``inference/v2/kernels/core_ops/cuda_linear/``) — the weight matrix stays
quantized THROUGH the matmul; full-precision weight values exist only in
on-chip memory, one tile at a time.

TPU shape of the idea: decode is weight-bandwidth-bound, so the win is HBM
traffic — the kernel reads int8 codes (1 byte/param) + per-group fp32
scales (≈3% overhead at group 128) instead of bf16 (2 bytes/param),
halving the weight stream; the W4A16 variant reads nibble-PACKED codes
(½ byte/param), quartering it.  Each grid step loads a [g, bn] int8 tile
(W4: a [g/2, bn] byte tile holding nibble pairs) and its [1, bn] scale
row, dequantizes in VMEM registers, and feeds the MXU:

    y[M, N] = x[M, K] @ (codes[K, N] · scales[K/g, N])

The K-tile size equals the quantization group ``g`` so the scale is a
single broadcastable row per tile — no in-kernel gather/reshape.

N does NOT need to tile: the grid rounds the column dim up and Mosaic
masks the trailing partial block (same idea as the M-pad), so real vocabs
like GPT-2's 50257 run the kernel (round-4 verdict: the silent fallback
meant the flagship bench's unembed never engaged).  K must tile exactly —
it is contracted, and garbage in an out-of-bounds K block would pollute
every output.

Tensor-parallel reach (``wq_matmul_tp``): GSPMD cannot partition the
Mosaic custom call, so a tp-sharded store is run through a manual
``shard_map`` over the tp axis — each shard calls the kernel on its slice
(the reference's per-rank quantized GEMM under AutoTP,
``module_inject/auto_tp.py:273``), with a psum closing row-parallel
(contraction-sharded) layouts.

``wq_matmul`` falls back to dequantize-then-matmul (XLA) for layouts the
kernel doesn't cover (the store's dim-0 must be the contraction dim,
g % 32 == 0 — W4: g % 64; K tile-aligned).  Serving-only: no VJP is
defined (the store is inference-time state).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.quantization import (dequantize_weight,
                                            dequantize_weight4,
                                            is_quantized_weight,
                                            is_quantized_weight4,
                                            unpack_nibbles_f32)


def _on_tpu(interpret: Optional[bool]) -> bool:
    """True when the kernel will hit the real Mosaic lowering (which
    enforces (8, 128)-aligned-or-full block tiles) rather than interpret
    mode (which accepts anything — the round-4 kernels were interpret-clean
    and still failed on first chip contact)."""
    if interpret is not None:
        return not interpret
    return jax.default_backend() == "tpu"


def _pick(total, prefer):
    for b in (prefer, 512, 256, 128, 64, 32, 16, 8):
        if b <= total and total % b == 0:
            return b
    return None


def _lane_ok(block, dim) -> bool:
    """Mosaic lane rule for a block's LAST dim: divisible by 128 or equal to
    the full array dim."""
    return block % 128 == 0 or block == dim


def _sublane(dtype) -> int:
    """Min sublane multiple for a dtype's native tile: fp32 (8, 128),
    bf16/f16 (16, 128), int8/fp8 (32, 128).  M pads to this so the x/out
    block's second-minor dim is always tile-legal (a block equal to the
    full dim is also legal, which the padded M satisfies when m == bm)."""
    return {4: 8, 2: 16, 1: 32}.get(jnp.dtype(dtype).itemsize, 8)


def _pick_n(total, prefer=512):
    """Column-dim block size: a 128-aligned exact divisor when one exists
    (Mosaic's lane rule — the last block dim must be %128 or the full dim),
    else the full dim when small, else the preferred tile rounded to 128
    with an out-of-bounds trailing block (Mosaic masks the partial write;
    the N dim is never contracted, so the padding lanes' garbage stays in
    columns the caller's out_shape doesn't include)."""
    for b in (prefer, 512, 384, 256, 128):
        if b <= total and total % b == 0 and b % 128 == 0:
            return b
    if total <= prefer:
        return total                    # block == full dim: always legal
    return -(-prefer // 128) * 128


_warned_shapes = set()

# trace-time counters: how many pallas-kernel calls were STAGED per variant
# (tests assert the kernel path engaged instead of the silent dequant
# fallback — the same reasoning as the warn-once below, made checkable)
trace_counts = {"w8": 0, "w8t": 0, "w4": 0}


def _tile_legal(block, array_shape) -> bool:
    """Mosaic's block-shape rule (jax pallas/mosaic/lowering.py
    ``_check_block_mappings``): for rank >= 2, the block's last dim must be
    % 128 or equal the array's, and its second-minor must be % 8 or equal
    the array's."""
    if len(block) < 2:
        return block[0] == array_shape[0] or block[0] % 128 == 0
    b0, a0 = block[-1], array_shape[-1]
    b1, a1 = block[-2], array_shape[-2]
    return (b0 == a0 or b0 % 128 == 0) and (b1 == a1 or b1 % 8 == 0)


def _preflight(variant: str, blocks, interpret: bool) -> bool:
    """True when every (block, array_shape) pair the kernel is about to
    stage satisfies Mosaic's tiling rule (interpret mode accepts anything).
    The eligibility gates above should make this unreachable — but the
    round-5 on-chip sweep recorded a serving leg dying inside an unguarded
    block-shape raise (BENCH_MEASURED_r05 ``serving_wq_error``), so the rule
    is re-checked against the EXACT blocks before ``pallas_call`` and an
    illegal combination takes the dequant fallback (warn-once) instead of
    erroring out of the caller's step."""
    for block, ashape in blocks:
        # a None block (no usable tile divisor) falls back on ANY backend;
        # interpret mode otherwise accepts every block shape
        if block is None or (not interpret
                             and not _tile_legal(block, ashape)):
            key = ("preflight", variant) + tuple(
                tuple(b) if b else b for b, _ in blocks)
            if key not in _warned_shapes:
                _warned_shapes.add(key)
                from deepspeed_tpu.utils.logging import logger
                logger.warning(
                    "%s: staged block shapes %s are not Mosaic-legal "
                    "(last two block dims must be %%(8, 128) or equal the "
                    "array dims); falling back to dequantize-then-matmul",
                    variant, [b for b, _ in blocks])
            return False
    return True


def kernel_supported(x, store, interpret: Optional[bool] = None) -> bool:
    """True when the Pallas path can run (M and N are NOT constrained —
    both pad to the tile).  Unsupported 2-D stores warn ONCE per shape: a
    silent fallback would let an operator benchmark 'the W8A16 kernel'
    while measuring the dequant path.

    On the real Mosaic lowering the activation tile is [bm, g], whose lane
    dim is the GROUP — so g must be %128 (or the whole K): found on first
    chip contact, round 5."""
    if not is_quantized_weight(store):
        return False
    v, s = store["v"], store["s"]
    if v.ndim != 2 or x.ndim != 2 or x.shape[1] != v.shape[0]:
        return False
    if s.shape[1:] != v.shape[1:]:
        return False                   # kernel assumes dim-0 grouping
    k, n = v.shape
    g = k // s.shape[0]
    ok = k % g == 0 and g % 32 == 0 and g >= 32
    why = "group % 32 == 0"
    if ok and _on_tpu(interpret) and not _lane_ok(g, k):
        ok, why = False, "group % 128 == 0 on TPU (x tile lane dim)"
    if not ok and (k, n, g) not in _warned_shapes:
        _warned_shapes.add((k, n, g))
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "wq_matmul: store [%d, %d] (group %d) cannot tile for the "
            "W8A16 kernel (needs %s); falling back to "
            "dequantize-then-matmul — the int8 HBM-traffic saving does "
            "NOT engage for this weight", k, n, g, why)
    return ok


def kernel4_supported(x, store, interpret: Optional[bool] = None) -> bool:
    """W4A16 eligibility: nibble-packed ``quantize_weight4`` store, dim-0
    contraction, g % 64 == 0 (the kernel reads [g/2, bn] byte tiles, so
    the packed sublane dim must stay int8-tileable).  On the real Mosaic
    lowering the de-interleaved activation tile is [bm, g/2] — its lane
    dim g/2 must be %128 (or the whole K/2), i.e. g % 256 == 0."""
    if not is_quantized_weight4(store):
        return False
    p, s = store["v4"], store["s"]
    if p.ndim != 2 or x.ndim != 2 or x.shape[1] != 2 * p.shape[0]:
        return False
    if s.shape[1:] != p.shape[1:]:
        return False
    k = 2 * p.shape[0]
    g = k // s.shape[0]
    ok = k % g == 0 and g % 64 == 0
    why = "group % 64 == 0"
    if ok and _on_tpu(interpret) and not _lane_ok(g // 2, k // 2):
        ok, why = False, ("group % 256 == 0 on TPU (de-interleaved x tile "
                          "lane dim is group/2)")
    if not ok and (k, p.shape[1], g, "w4") not in _warned_shapes:
        _warned_shapes.add((k, p.shape[1], g, "w4"))
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "wq_matmul4: packed store [%d, %d] (group %d) cannot tile for "
            "the W4A16 kernel (needs %s); falling back to "
            "dequantize-then-matmul", k, p.shape[1], g, why)
    return ok


def _kernel(x_ref, w_ref, s_ref, o_ref, acc, *, nk, contract):
    """Shared body for both W8 orientations: dequantize one weight tile
    (codes · broadcast scale row) and accumulate the dot.  ``contract`` is
    the weight-side contraction dim: 0 for ``x @ W`` ([g, bn] tiles), 1 for
    ``x @ Wᵀ`` ([g, bk] tiles).  The scale arrives as a [1, 1, bn] block of
    the 3-D [K/g, 1, N] view (a flat [1, bn] block would have sublane dim 1
    — illegal under Mosaic's (8, 128) tiling unless the array is one row);
    ``s_ref[0]`` recovers the broadcastable row."""
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc[...] = jnp.zeros(acc.shape, jnp.float32)

    # dequantize with an f32 product, cast ONCE into the ACTIVATION dtype,
    # and let the MXU accumulate in f32: bf16 activations then ride the
    # MXU's native bf16 multipliers (an all-f32 dot here measured the whole
    # kernel BELOW the bf16 baseline on chip — fp32 matmul throughput is a
    # fraction of bf16's), and the f32-product-then-cast exactly matches
    # ``dequantize_weight``'s rounding, so the kernel agrees with the
    # fallback path element-for-element.
    x = x_ref[...]
    w = (w_ref[...].astype(jnp.float32)
         * s_ref[0].astype(jnp.float32)).astype(x.dtype)
    acc[...] += jax.lax.dot_general(
        x, w, (((1,), (contract,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _done():
        o_ref[...] = acc[...].astype(o_ref.dtype)


def _kernel4(xe_ref, xo_ref, p_ref, s_ref, o_ref, acc, *, nk):
    """W4A16 body: one [g/2, bn] byte tile unpacks to the group's EVEN rows
    (low nibbles) and ODD rows (high nibbles) — ``pack_nibbles`` folds
    adjacent dim-0 pairs — which contract against the pre-de-interleaved
    activation halves xe = x[:, 0::2], xo = x[:, 1::2].  Both halves share
    the tile's single scale row (even and odd rows belong to the same
    group), so dequant stays one broadcast multiply per nibble."""
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc[...] = jnp.zeros(acc.shape, jnp.float32)

    lo, hi = unpack_nibbles_f32(p_ref[...])   # shift-free: Mosaic has no
    s = s_ref[0].astype(jnp.float32)    # int8 vector shifts ([1,1,bn]→row)
    # dequant in f32 (exact nibble × scale), then cast to the activation
    # dtype so bf16 rides the MXU's native multipliers (same finding as
    # ``_kernel``: all-f32 dots ran the kernel below the bf16 baseline)
    xdt = xe_ref.dtype
    dot = functools.partial(jax.lax.dot_general,
                            dimension_numbers=(((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    acc[...] += dot(xe_ref[...], (lo * s).astype(xdt))
    acc[...] += dot(xo_ref[...], (hi * s).astype(xdt))

    @pl.when(ik == nk - 1)
    def _done():
        o_ref[...] = acc[...].astype(o_ref.dtype)


def kernel_t_supported(x, store, interpret: Optional[bool] = None) -> bool:
    """Transposed variant (``x @ storeᵀ``, tied-embedding unembed): store is
    [V, H] grouped along dim 0 (the embed gather's required layout), so the
    scale varies along the CONTRACTION dim within each g-row output tile —
    still a single broadcastable row per tile.  The output tile width is
    structurally pinned to g, so g must be lane-aligned (128).  H is
    contracted and must tile exactly (vocab-padded stores make V % g == 0
    by construction)."""
    if not is_quantized_weight(store):
        return False
    v, s = store["v"], store["s"]
    if v.ndim != 2 or x.ndim != 2 or x.shape[1] != v.shape[1]:
        return False
    if s.shape[1:] != v.shape[1:]:
        return False                   # dim-0 grouping only
    vocab, h = v.shape
    g = vocab // s.shape[0]
    bk = _pick(h, 512)
    ok = (vocab % g == 0 and g % 128 == 0 and bk is not None)
    why = "group % 128 == 0, plus an H divisor <= 512"
    if ok and _on_tpu(interpret) and not _lane_ok(bk, h):
        ok, why = False, "an H block divisor that is % 128 on TPU"
    if not ok and (vocab, h, g, "t") not in _warned_shapes:
        _warned_shapes.add((vocab, h, g, "t"))
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "wq_matmul_t: tied store [%d, %d] (group %d) cannot tile for "
            "the transposed W8A16 kernel (the output tile width IS the "
            "group, so it needs %s); falling back to "
            "dequantize-then-matmul", vocab, h, g, why)
    return ok


def wq_matmul_t(x, store, *, interpret: Optional[bool] = None):
    """``x [M, H] @ dequant(store [V, H]).T`` → [M, V] with the table kept
    int8 in HBM — the tied-embedding unembed.  One output tile per
    scale-group row keeps the dequant a single broadcast multiply.  Vocabs
    that don't group-tile are padded at STORE CREATION (engine packer), not
    here — padding the table per call would re-stream the whole weight."""
    if not kernel_t_supported(x, store, interpret):
        return x @ dequantize_weight(store, x.dtype).T
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v, s = store["v"], store["s"]
    vocab, h = v.shape
    m0 = x.shape[0]
    pad = (-m0) % _sublane(x.dtype)
    m = m0 + pad
    g = vocab // s.shape[0]
    bm = _pick(m, 256)
    bk = _pick(h, 512)
    if bm is None or bk is None or not _preflight("wq_matmul_t", [
            ((bm, bk), (m, h)), ((g, bk), (vocab, h)),
            ((1, 1, bk), (vocab // g, 1, h)), ((bm, g), (m, vocab))],
            interpret):
        return x @ dequantize_weight(store, x.dtype).T
    trace_counts["w8t"] += 1
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    nk = h // bk
    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk, contract=1),
        grid=(m // bm, vocab // g, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda im, jv, ik: (im, ik)),
            pl.BlockSpec((g, bk), lambda im, jv, ik: (jv, ik)),
            pl.BlockSpec((1, 1, bk), lambda im, jv, ik: (jv, 0, ik)),
        ],
        out_specs=pl.BlockSpec((bm, g), lambda im, jv, ik: (im, jv)),
        out_shape=jax.ShapeDtypeStruct((m, vocab), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, g), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, v, s[:, None, :])
    return out[:m0] if pad else out


def wq_matmul(x, store, *, interpret: Optional[bool] = None):
    """``x [M, K] @ dequant(store [K, N])`` with the weight kept int8 in HBM.

    store: ``ops/quantization.quantize_weight`` dict (dim-0 = contraction
    dim).  Returns [M, N] in ``x.dtype``.  Falls back to the XLA
    dequantize-then-matmul for unsupported layouts.
    """
    if not kernel_supported(x, store, interpret):
        return x @ dequantize_weight(store, x.dtype)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v, s = store["v"], store["s"]
    k, n = v.shape
    m0 = x.shape[0]
    pad = (-m0) % _sublane(x.dtype)     # decode token counts tile to rows
    m = m0 + pad
    g = k // s.shape[0]
    bm = _pick(m, 256)
    bn = _pick_n(n, 512)
    if not _preflight("wq_matmul", [
            (None if bm is None else (bm, g), (m, k)),
            ((g, bn), (k, n)), ((1, 1, bn), (k // g, 1, n)),
            (None if bm is None else (bm, bn), (m, n))], interpret):
        return x @ dequantize_weight(store, x.dtype)
    trace_counts["w8"] += 1
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    nk = k // g
    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk, contract=0),
        grid=(m // bm, -(-n // bn), nk),
        in_specs=[
            pl.BlockSpec((bm, g), lambda im, jn, ik: (im, ik)),
            pl.BlockSpec((g, bn), lambda im, jn, ik: (ik, jn)),
            pl.BlockSpec((1, 1, bn), lambda im, jn, ik: (ik, 0, jn)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda im, jn, ik: (im, jn)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, v, s[:, None, :])
    return out[:m0] if pad else out


def wq_matmul4(x, store, *, interpret: Optional[bool] = None):
    """``x [M, K] @ dequant4(store)`` with the weight kept nibble-PACKED in
    HBM — ¼ the bf16 weight stream (reference FP6-LLM sub-8-bit GEMM,
    ``cuda_linear.py``: the weight is unpacked on-chip, never in HBM).

    store: ``ops/quantization.quantize_weight4`` dict
    ({"v4": int8 [K/2, N] nibble pairs, "s": f32 [K/g, N]}).  The
    activation is de-interleaved ONCE outside the kernel (xe = even K
    columns, xo = odd) so each byte tile's two nibble planes contract
    against clean contiguous tiles — no in-kernel row interleave."""
    if not kernel4_supported(x, store, interpret):
        return x @ dequantize_weight4(store, x.dtype)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    p, s = store["v4"], store["s"]
    kh, n = p.shape                     # kh = K/2
    k = 2 * kh
    m0 = x.shape[0]
    pad = (-m0) % _sublane(x.dtype)
    m = m0 + pad
    g = k // s.shape[0]
    gh = g // 2
    bm = _pick(m, 256)
    bn = _pick_n(n, 512)
    if not _preflight("wq_matmul4", [
            (None if bm is None else (bm, gh), (m, kh)),
            ((gh, bn), (kh, n)), ((1, 1, bn), (k // g, 1, n)),
            (None if bm is None else (bm, bn), (m, n))], interpret):
        return x @ dequantize_weight4(store, x.dtype)
    trace_counts["w4"] += 1
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    xe = x[:, 0::2]                     # [M, K/2] — O(M·K) shuffle, free
    xo = x[:, 1::2]                     # next to the GEMM it feeds
    nk = k // g
    out = pl.pallas_call(
        functools.partial(_kernel4, nk=nk),
        grid=(m // bm, -(-n // bn), nk),
        in_specs=[
            pl.BlockSpec((bm, gh), lambda im, jn, ik: (im, ik)),
            pl.BlockSpec((bm, gh), lambda im, jn, ik: (im, ik)),
            pl.BlockSpec((gh, bn), lambda im, jn, ik: (ik, jn)),
            pl.BlockSpec((1, 1, bn), lambda im, jn, ik: (ik, 0, jn)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda im, jn, ik: (im, jn)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(xe, xo, p, s[:, None, :])
    return out[:m0] if pad else out


def wq_any(x, store, *, interpret: Optional[bool] = None):
    """Dispatch a 2-D quantized store to its kernel (int8 → wq_matmul,
    nibble-packed → wq_matmul4)."""
    if is_quantized_weight4(store):
        return wq_matmul4(x, store, interpret=interpret)
    return wq_matmul(x, store, interpret=interpret)


# ------------------------------------------------------------ 2-D store views
def store_as_2d(store):
    """A free (row-major reshape) 2-D view of a 3-D quantized store whose
    flattened layout keeps uniform dim-0 grouping, or None.

    Two cases cover the attention projections (round-4 verdict item 3):
    - grouped along dim 0 (qkv [H, heads, hd]): flatten the TRAILING dims
      into N — rows keep their group.
    - grouped along dim 1 of 3 (attn-out [heads, hd, H], group g | hd):
      flatten the LEADING two dims into K.  Flat row r = head·hd + d maps
      to scale row r // g = head·(hd/g) + d//g exactly because g divides
      hd — grouping stays uniform.
    Packed (v4) stores only support the dim-0-grouped case (nibble pairs
    fold dim 0).
    """
    if is_quantized_weight(store):
        v, s = store["v"], store["s"]
        if v.ndim != 3:
            return None
        if s.shape[1:] == v.shape[1:]:          # grouped dim 0
            return {"v": v.reshape(v.shape[0], -1),
                    "s": s.reshape(s.shape[0], -1)}
        if (s.shape[0] == v.shape[0] and s.shape[2:] == v.shape[2:]
                and v.shape[1] % s.shape[1] == 0):   # grouped dim 1
            return {"v": v.reshape(-1, v.shape[2]),
                    "s": s.reshape(-1, s.shape[2])}
        return None
    if is_quantized_weight4(store):
        p, s = store["v4"], store["s"]
        if p.ndim != 3 or s.shape[1:] != p.shape[1:]:
            return None
        return {"v4": p.reshape(p.shape[0], -1),
                "s": s.reshape(s.shape[0], -1)}
    return None


# ------------------------------------------------------------- TP shard_map
def wq_matmul_tp(x, store, mesh, mode: str, axis: str = "tp", *,
                 interpret: Optional[bool] = None):
    """Run a quantized-weight matmul with the store SHARDED over ``axis``,
    keeping the Pallas kernel engaged per shard (GSPMD cannot partition the
    Mosaic custom call, so the round-3 design bypassed the kernel for tp>1
    — exactly the bandwidth-hungriest configs; reference AutoTP runs its
    quantized GEMM per rank, ``module_inject/auto_tp.py:273``).

    ``mode``:
    - "col": store [K, N] sharded on N (qkv / MLP-in / untied lm_head).
      x is replicated; output comes back N-sharded.
    - "row": store [K, N] sharded on K (attn-out / MLP-out).  x arrives
      K-sharded, each shard computes a partial product, a psum closes it.
    - "tcol": transposed tied-unembed store [V, H] sharded on V; output
      comes back V-sharded.
    x: [M, K] (2-D; callers flatten leading dims).  Inside each shard the
    usual eligibility checks run on LOCAL shapes, so an unsupported slice
    falls back to dequant-matmul per shard — still correctly partitioned.
    """
    from deepspeed_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    if mesh is None or mesh.shape.get(axis, 1) == 1:
        if mode == "tcol":
            return wq_matmul_t(x, store, interpret=interpret)
        return wq_any(x, store, interpret=interpret)

    packed = is_quantized_weight4(store)
    key = "v4" if packed else "v"
    size = mesh.shape[axis]
    v, s = store[key], store["s"]
    d = 1 if mode == "col" else 0
    if (v.shape[d] % size or s.shape[d] % size
            or (mode == "row" and x.shape[1] % size)):
        # shard boundary would split a group / nibble pair — stay on the
        # GSPMD dequant path, which partitions any layout correctly
        w = (dequantize_weight4(store, x.dtype) if packed
             else dequantize_weight(store, x.dtype))
        return x @ (w.T if mode == "tcol" else w)
    if mode == "col":
        wspec = {key: P(None, axis), "s": P(None, axis)}
        xspec, ospec = P(), P(None, axis)
    elif mode == "row":
        wspec = {key: P(axis, None), "s": P(axis, None)}
        xspec, ospec = P(None, axis), P()
    elif mode == "tcol":
        if packed:
            # no packed transposed kernel exists — keep the documented
            # graceful-fallback contract (dequant partitions fine)
            return x @ dequantize_weight4(store, x.dtype).T
        wspec = {key: P(axis, None), "s": P(axis, None)}
        xspec, ospec = P(), P(None, axis)
    else:
        raise ValueError(f"mode must be col|row|tcol, got {mode!r}")

    def local(xs, vs, ss):
        st = {key: vs, "s": ss}
        if mode == "tcol":
            return wq_matmul_t(xs, st, interpret=interpret)
        y = wq_any(xs, st, interpret=interpret)
        if mode == "row":
            y = jax.lax.psum(y, axis)
        return y

    return shard_map(
        local, mesh=mesh, in_specs=(xspec, wspec[key], wspec["s"]),
        out_specs=ospec, check_vma=False)(x, store[key], store["s"])
