"""deepspeed_tpu.ops — kernel layer (reference: deepspeed/ops + csrc/ + op_builder/).

Every op has an XLA reference implementation and, where it pays, a Pallas TPU
kernel; selection goes through the registry (ops/registry.py, the op_builder
analog).  Public surface:

- ``causal_attention(q, k, v, ...)``      fused flash attention w/ fallback
- ``flash_attention(...)``                direct Pallas kernel entry
- ``lm_cross_entropy(...)``               chunked unembed + softmax CE
- ``op_report()``                         ds_report-style compatibility matrix
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from deepspeed_tpu.ops import registry
from deepspeed_tpu.ops.cross_entropy import lm_cross_entropy, masked_nll_sum
from deepspeed_tpu.ops.flash_attention import (configure_flash_blocks,
                                               flash_attention)
from deepspeed_tpu.ops.norms import layer_norm, rms_norm
from deepspeed_tpu.ops.registry import dispatch, list_ops, op_report, register_op


def _attention_xla(q, k, v, *, causal=True, scale=None, dropout_fn=None,
                   mask=None, bias=None, window=None, alibi_slopes=None,
                   interpret=None):
    """Plain attention on [B, T, N, D] — numeric ground truth for the kernel.

    The ONE XLA softmax-attention body in the codebase: causal tril masking, or
    an explicit [B, Tq, S] boolean mask (the KV-cache / padded-prefill path;
    all-False rows produce zeros, not NaN, so left-pad garbage never reaches
    later layers' V inputs).  ``bias`` [B|1, N, Tq|1, S] is added to the fp32
    logits pre-softmax (alibi; reference bloom/falcon-rw baddbmm bias).
    ``window``/``alibi_slopes`` are the FIRST-CLASS forms of the same
    semantics over canonical (arange) positions — the forms the Pallas kernel
    consumes in-kernel.
    """
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    import jax
    t, s = q.shape[1], k.shape[1]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("btnd,bsnd->bnts", q, k).astype(jnp.float32) * scale
    if alibi_slopes is not None:
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(q.shape[2])
        logits = logits + (sl[None, :, None, None]
                           * jnp.arange(s, dtype=jnp.float32))
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    neg = jnp.finfo(jnp.float32).min
    if window is not None:
        rel = jnp.arange(t)[:, None] - jnp.arange(s)[None, :]
        wtri = (rel >= 0) & (rel < window)
        logits = jnp.where(wtri[None, None], logits, neg)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(jnp.any(wtri[None, None], axis=-1, keepdims=True),
                          probs, 0.0)
    elif mask is not None:
        m = mask[:, None]                                # [B, 1, Tq, S]
        logits = jnp.where(m, logits, neg)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(jnp.any(m, axis=-1, keepdims=True), probs, 0.0)
    else:
        if causal:
            tri = jnp.tril(jnp.ones((t, s), dtype=bool))
            logits = jnp.where(tri[None, None], logits, neg)
        probs = jax.nn.softmax(logits, axis=-1)
    probs = probs.astype(q.dtype)
    if dropout_fn is not None:
        probs = dropout_fn(probs)
    return jnp.einsum("bnts,bsnd->btnd", probs, v)


def _attention_pallas(q, k, v, *, causal=True, scale=None, dropout_fn=None,
                      mask=None, bias=None, window=None, alibi_slopes=None,
                      interpret=None):
    if dropout_fn is not None:
        raise ValueError(
            "the pallas flash-attention kernel has no probs-dropout; use "
            "impl='xla', dropout=0, or output dropout (Ulysses-branch style)")
    if mask is not None:
        raise ValueError("the pallas flash-attention kernel takes no explicit "
                         "mask; use impl='xla' for the KV-cache/padded path "
                         "(sliding windows go through window=, not mask=)")
    if bias is not None:
        raise ValueError("the pallas flash-attention kernel takes no free-"
                         "form logit bias; alibi goes through alibi_slopes=, "
                         "other biases through impl='xla'")
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           window=window, alibi_slopes=alibi_slopes,
                           interpret=interpret)


def _attention_supported(q, k, v, *, causal=True, scale=None, dropout_fn=None,
                         mask=None, bias=None, window=None, alibi_slopes=None,
                         interpret=None):
    from deepspeed_tpu.ops.flash_attention import supported as flash_supported
    return (dropout_fn is None and mask is None and bias is None
            and flash_supported(q, k, v, causal=causal, window=window,
                                alibi_slopes=alibi_slopes))


register_op("causal_attention", xla=_attention_xla, pallas=_attention_pallas,
            supported=_attention_supported)

from deepspeed_tpu.ops import paged_attention as _paged  # noqa: E402
from deepspeed_tpu.ops.paged_attention import (  # noqa: E402
    paged_attention, ragged_prefill_attention)

register_op("paged_attention", xla=_paged.xla_paged_attention,
            pallas=_paged.pallas_paged_attention, supported=_paged.supported)
register_op("ragged_prefill_attention", xla=_paged.xla_ragged_prefill,
            pallas=_paged.pallas_ragged_prefill,
            supported=_paged.ragged_prefill_supported)

from deepspeed_tpu.ops.evoformer import evoformer_attention  # noqa: E402

register_op("evoformer_attention", xla=evoformer_attention)

from deepspeed_tpu.ops import sparse_attention as _sparse  # noqa: E402

register_op("sparse_attention", xla=_sparse._sparse_xla,
            pallas=_sparse._sparse_pallas,
            supported=_sparse.block_sparse_supported)

# ring collective-matmul fusions (registers all_gather_matmul /
# matmul_reduce_scatter / row_parallel_matmul on import)
from deepspeed_tpu.ops import collective_matmul  # noqa: E402
from deepspeed_tpu.ops.collective_matmul import (  # noqa: E402
    all_gather_matmul, matmul_reduce_scatter, row_parallel_matmul)

from deepspeed_tpu.ops import lora_matmul as _lora  # noqa: E402

register_op("lora_matmul", xla=_lora.xla_lora_matmul,
            pallas=_lora.pallas_lora_matmul, supported=_lora.lora_supported)


def lora_matmul(x, a_pages, b_pages, adapter_ids, scales, *,
                impl: Optional[str] = None):
    """Batched-gather LoRA delta: ``y[i] = (x[i] @ A[id_i]) @ B[id_i] ·
    s[id_i]`` over packed per-slot adapter tables (ops/lora_matmul.py)."""
    return dispatch("lora_matmul", x, a_pages, b_pages, adapter_ids, scales,
                    impl=impl)


def causal_attention(q, k, v, *, causal: bool = True,
                     scale: Optional[float] = None,
                     dropout_fn: Optional[Callable] = None,
                     mask=None, bias=None, window: Optional[int] = None,
                     alibi_slopes=None,
                     impl: Optional[str] = None):
    """Dispatching attention entry used by the model layer.

    ``window``/``alibi_slopes`` assume canonical positions (query t at
    position t) — the training fast path; models with gathered/shifted
    positions (random-LTD, KV-cache) express the same semantics through
    ``mask``/``bias`` and ride the XLA body."""
    return dispatch("causal_attention", q, k, v, causal=causal, scale=scale,
                    dropout_fn=dropout_fn, mask=mask, bias=bias,
                    window=window, alibi_slopes=alibi_slopes, impl=impl)


__all__ = ["causal_attention", "flash_attention", "configure_flash_blocks",
           "paged_attention", "lora_matmul",
           "ragged_prefill_attention", "evoformer_attention",
           "all_gather_matmul", "matmul_reduce_scatter",
           "row_parallel_matmul", "collective_matmul",
           "lm_cross_entropy", "masked_nll_sum", "rms_norm", "layer_norm",
           "op_report", "register_op", "dispatch", "list_ops", "registry"]
