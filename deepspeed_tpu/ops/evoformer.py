"""Evoformer attention (DS4Science analog).

Reference parity: ``csrc/deepspeed4science/evoformer_attn/`` +
``deepspeed/ops/deepspeed4science/evoformer_attn.py`` — AlphaFold2-style
attention over [B, N, S, H, D] (N = MSA rows / residue pairs) with two
broadcastable bias terms folded into the logits:

    out = softmax(Q·Kᵀ·d^-1/2 + bias1 + bias2) · V
    bias1: [B, N, 1, 1, S]   (per-key mask bias, e.g. -1e9 padding)
    bias2: [B, 1, H, S, S]   (pair-representation bias, shared over N)

The reference builds this on CUTLASS fMHA; on TPU the fused einsum chain is
exactly what XLA maps onto the MXU, and the bias adds fuse into the softmax —
the op exists for API/semantics parity and as the numeric ground truth for a
future Pallas blockwise version at long S.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def evoformer_attention(q, k, v, bias1: Optional[jax.Array] = None,
                        bias2: Optional[jax.Array] = None):
    """q/k/v: [B, N, S, H, D]; bias1 broadcastable to [B, N, 1, 1, S];
    bias2 broadcastable to [B, 1, H, S, S].  Returns [B, N, S, H, D].

    reference evoformer_attn.py:DS4Sci_EvoformerAttention (inputs validated
    the same way: 5-D tensors, biases optional)."""
    if q.ndim != 5:
        raise ValueError(f"evoformer attention expects [B, N, S, H, D] "
                         f"tensors, got rank {q.ndim}")
    scale = q.shape[-1] ** -0.5
    # [B, N, H, S, S]
    logits = jnp.einsum("bnqhd,bnkhd->bnhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if bias1 is not None:
        # [B, N, 1, 1, S] broadcasts over heads + query positions
        logits = logits + jnp.asarray(bias1, jnp.float32)
    if bias2 is not None:
        # [B, 1, H, S, S] broadcasts over N
        logits = logits + jnp.asarray(bias2, jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnhqk,bnkhd->bnqhd", probs,
                     v.astype(jnp.float32))
    return out.astype(q.dtype)
