"""Evoformer attention (DS4Science analog) — Pallas blockwise kernel + XLA
ground truth.

Reference parity: ``csrc/deepspeed4science/evoformer_attn/`` (CUTLASS fMHA,
14.9k LoC — kernel_forward.h / kernel_backward.h) +
``deepspeed/ops/deepspeed4science/evoformer_attn.py`` — AlphaFold2-style
attention over [B, N, S, H, D] (N = MSA rows / residue pairs) with two
broadcastable bias terms folded into the logits:

    out = softmax(Q·Kᵀ·d^-1/2 + bias1 + bias2) · V
    bias1: [B, N, 1, 1, S]   (per-key mask bias, e.g. -1e9 padding)
    bias2: [B, 1, H, S, S]   (pair-representation bias, shared over N)

The reference subtree exists to avoid materializing the [B, N, H, S, S]
logits at long S; ``evoformer_attention`` here does the same with a
flash-style online-softmax Pallas kernel: (bq, bk) logit tiles live only in
VMEM, the two bias terms stream in per tile (bias2 is itself S×S but only
[bq, bk] of it is resident), and the forward saves just the per-row
logsumexp.  Peak HBM is O(B·N·S·H·D + B·H·S²·|bias2|) instead of
O(B·N·H·S²) — the N-factor on the score tensor is gone.

Backward is four Pallas passes sharing one tile recompute recipe: dq (and
dk/dv) mirror ops/flash_attention.py; dbias2 accumulates ds over the N MSA
rows with N innermost in the grid; dbias1 accumulates ds over heads and
query rows.  Unused bias cotangents DCE away under jit.

``_evoformer_xla`` keeps the einsum ground truth for numerics tests and
unsupported shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _evoformer_xla(q, k, v, bias1=None, bias2=None):
    """Numeric ground truth: full [B, N, H, S, S] fp32 logits (the memory
    shape the Pallas kernel exists to avoid)."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bnqhd,bnkhd->bnhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if bias1 is not None:
        logits = logits + jnp.asarray(bias1, jnp.float32)
    if bias2 is not None:
        logits = logits + jnp.asarray(bias2, jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnhqk,bnkhd->bnqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _block_sizes(s: int, prefer: int = 512):
    """512-tiles measured ~5% faster fwd+bwd than 256 at S=2048 on v5e
    (round-5 on-chip sweep: 225 ms vs 236 ms; 1024 over-fills VMEM and
    fails to compile); shorter S falls back through the divisor ladder."""
    for b in (prefer, 512, 256, 128, 64, 32, 16, 8):
        if b <= s and s % b == 0:
            return b
    return None


def supported(q, k, v, bias1=None, bias2=None):
    if q.ndim != 5 or k.shape != q.shape or v.shape != q.shape:
        return False
    s, d = q.shape[2], q.shape[4]
    return _block_sizes(s) is not None and d % 8 == 0 and s >= 8


def _tile_scores(q, k, b1_ref, b2_ref, scale, has_b1, has_b2):
    """One [bq, bk] logit tile: scaled q·kᵀ + streamed bias tiles."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if has_b1:
        s = s + b1_ref[0, 0].astype(jnp.float32)       # [1, bk] → rows
    if has_b2:
        s = s + b2_ref[0, 0].astype(jnp.float32)       # [bq, bk]
    return s


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, has_b1, has_b2):
    ik, nk = pl.program_id(3), pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    s = _tile_scores(q_ref[0, 0], k_ref[0, 0], b1_ref, b2_ref, scale,
                     has_b1, has_b2)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    # a fully -inf-masked row (bias1 = -1e9 padding over every key) must not
    # alias exp(-inf − -inf) to 1
    p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
    v = v_ref[0, 0]
    pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    acc_scr[...] = acc_scr[...] * alpha + pv
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0, 0] = (m_scr[:, :1] + jnp.log(l))[:, 0]


def _layout(q, k, v, bias1, bias2):
    """[B, N, S, H, D] → kernel layout [BN, H, S, D] (+ flattened biases)."""
    b, n, s, h, d = q.shape
    qt = q.transpose(0, 1, 3, 2, 4).reshape(b * n, h, s, d)
    kt = k.transpose(0, 1, 3, 2, 4).reshape(b * n, h, s, d)
    vt = v.transpose(0, 1, 3, 2, 4).reshape(b * n, h, s, d)
    b1 = (jnp.broadcast_to(jnp.asarray(bias1), (b, n, 1, 1, s))
          .reshape(b * n, 1, s) if bias1 is not None else
          jnp.zeros((1, 1, 8), jnp.float32))
    b2 = (jnp.broadcast_to(jnp.asarray(bias2), (b, 1, h, s, s))
          .reshape(b, h, s, s) if bias2 is not None else
          jnp.zeros((1, 1, 8, 8), jnp.float32))
    return qt, kt, vt, b1, b2


def _bias_specs(bq, bk, n, has_b1, has_b2):
    """Index maps for the streamed bias tiles on the (bn, h, iq, ik) grid."""
    b1_spec = (pl.BlockSpec((1, 1, bk), lambda bn, h, iq, ik: (bn, 0, ik))
               if has_b1 else
               pl.BlockSpec((1, 1, 8), lambda bn, h, iq, ik: (0, 0, 0)))
    b2_spec = (pl.BlockSpec((1, 1, bq, bk),
                            lambda bn, h, iq, ik: (bn // n, h, iq, ik))
               if has_b2 else
               pl.BlockSpec((1, 1, 8, 8), lambda bn, h, iq, ik: (0, 0, 0, 0)))
    return b1_spec, b2_spec


def _fwd(q, k, v, bias1, bias2, interpret):
    b, n, s, h, d = q.shape
    qt, kt, vt, b1, b2 = _layout(q, k, v, bias1, bias2)
    has_b1, has_b2 = bias1 is not None, bias2 is not None
    bq = bk = _block_sizes(s)
    scale = d ** -0.5
    qkv_spec = pl.BlockSpec((1, 1, bq, d), lambda bn, h_, iq, ik: (bn, h_, iq, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d), lambda bn, h_, iq, ik: (bn, h_, ik, 0))
    b1_spec, b2_spec = _bias_specs(bq, bk, n, has_b1, has_b2)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, has_b1=has_b1,
                          has_b2=has_b2),
        grid=(b * n, h, s // bq, s // bk),
        in_specs=[qkv_spec, kv_spec, kv_spec, b1_spec, b2_spec],
        out_specs=[
            qkv_spec,
            pl.BlockSpec((1, 1, 1, bq), lambda bn, h_, iq, ik: (bn, h_, 0, iq)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * n, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * n, h, 1, s), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, b1, b2)
    return o, lse


# ---------------------------------------------------------------- backward

def _tile_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, b1_ref, b2_ref,
             *, scale, has_b1, has_b2):
    """Shared backward tile recompute: (p, ds) for one (bq, bk) tile.
    ds is the UNSCALED logit cotangent (bias grads); q/k grads multiply by
    ``scale`` at their use sites."""
    s = _tile_scores(q_ref[0, 0], k_ref[0, 0], b1_ref, b2_ref, scale,
                     has_b1, has_b2)
    lse = lse_ref[0, 0, 0][:, None]
    p = jnp.exp(s - lse)
    p = jnp.where(lse > _NEG_INF / 2, p, 0.0)
    do = do_ref[0, 0]
    dp = jax.lax.dot_general(do, v_ref[0, 0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0, 0, 0][:, None])
    return p, ds


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, b1_ref,
               b2_ref, dq_ref, dq_scr, *, scale, has_b1, has_b2):
    ik, nk = pl.program_id(3), pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    _, ds = _tile_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     b1_ref, b2_ref, scale=scale, has_b1=has_b1,
                     has_b2=has_b2)
    k = k_ref[0, 0]
    dq_scr[...] += scale * jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, b1_ref,
                b2_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale, has_b1,
                has_b2):
    iq, nq = pl.program_id(3), pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    p, ds = _tile_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     b1_ref, b2_ref, scale=scale, has_b1=has_b1,
                     has_b2=has_b2)
    do = do_ref[0, 0]
    q = q_ref[0, 0]
    dv_scr[...] += jax.lax.dot_general(p.astype(do.dtype), do,
                                       (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
    dk_scr[...] += scale * jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _db2_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, b1_ref,
                b2_ref, db2_ref, db2_scr, *, scale, has_b1, has_b2):
    """dbias2[b, h, q, k] = Σ_n ds — N is the innermost (arbitrary) grid dim
    so the sum accumulates in VMEM while the output tile stays put."""
    jn, nn = pl.program_id(4), pl.num_programs(4)

    @pl.when(jn == 0)
    def _init():
        db2_scr[...] = jnp.zeros(db2_scr.shape, jnp.float32)

    _, ds = _tile_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     b1_ref, b2_ref, scale=scale, has_b1=has_b1,
                     has_b2=has_b2)
    db2_scr[...] += ds

    @pl.when(jn == nn - 1)
    def _finalize():
        db2_ref[0, 0] = db2_scr[...].astype(db2_ref.dtype)


def _db1_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, b1_ref,
                b2_ref, db1_ref, db1_scr, *, scale, has_b1, has_b2):
    """dbias1[bn, k] = Σ_{h, q} ds — (h, iq) fused innermost."""
    j, nj = pl.program_id(2), pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        db1_scr[...] = jnp.zeros(db1_scr.shape, jnp.float32)

    _, ds = _tile_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     b1_ref, b2_ref, scale=scale, has_b1=has_b1,
                     has_b2=has_b2)
    db1_scr[:1, :] += jnp.sum(ds, axis=0, keepdims=True)

    @pl.when(j == nj - 1)
    def _finalize():
        db1_ref[0, 0] = db1_scr[:1, :][0].astype(db1_ref.dtype)


def _bwd_impl(q, k, v, bias1, bias2, o, lse, do, interpret):
    b, n, s, h, d = q.shape
    qt, kt, vt, b1, b2 = _layout(q, k, v, bias1, bias2)
    dot = do.transpose(0, 1, 3, 2, 4).reshape(b * n, h, s, d)
    ot = o.transpose(0, 1, 3, 2, 4).reshape(b * n, h, s, d)
    has_b1, has_b2 = bias1 is not None, bias2 is not None
    bq = bk = _block_sizes(s)
    scale = d ** -0.5
    delta = jnp.sum(ot.astype(jnp.float32) * dot.astype(jnp.float32),
                    axis=-1)[:, :, None, :]                  # [BN, H, 1, S]
    kw = dict(scale=scale, has_b1=has_b1, has_b2=has_b2)
    sem = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bn, h_, iq, ik: (bn, h_, iq, 0))
    k_spec = pl.BlockSpec((1, 1, bk, d), lambda bn, h_, iq, ik: (bn, h_, ik, 0))
    row_spec = pl.BlockSpec((1, 1, 1, bq),
                            lambda bn, h_, iq, ik: (bn, h_, 0, iq))
    b1_spec, b2_spec = _bias_specs(bq, bk, n, has_b1, has_b2)
    args = (qt, kt, vt, dot, lse, delta, b1, b2)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **kw),
        grid=(b * n, h, s // bq, s // bk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec,
                  b1_spec, b2_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * n, h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=sem, interpret=interpret)(*args)

    # dkv: swap loop order — (bn, h, ik, iq), q-blocks innermost
    q_spec2 = pl.BlockSpec((1, 1, bq, d),
                           lambda bn, h_, ik, iq: (bn, h_, iq, 0))
    k_spec2 = pl.BlockSpec((1, 1, bk, d),
                           lambda bn, h_, ik, iq: (bn, h_, ik, 0))
    row_spec2 = pl.BlockSpec((1, 1, 1, bq),
                             lambda bn, h_, ik, iq: (bn, h_, 0, iq))
    b1_spec2 = (pl.BlockSpec((1, 1, bk), lambda bn, h_, ik, iq: (bn, 0, ik))
                if has_b1 else
                pl.BlockSpec((1, 1, 8), lambda bn, h_, ik, iq: (0, 0, 0)))
    b2_spec2 = (pl.BlockSpec((1, 1, bq, bk),
                             lambda bn, h_, ik, iq: (bn // n, h_, iq, ik))
                if has_b2 else
                pl.BlockSpec((1, 1, 8, 8),
                             lambda bn, h_, ik, iq: (0, 0, 0, 0)))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **kw),
        grid=(b * n, h, s // bk, s // bq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2,
                  b1_spec2, b2_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((b * n, h, s, d), q.dtype),
                   jax.ShapeDtypeStruct((b * n, h, s, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=sem, interpret=interpret)(*args)

    db1 = db2 = None
    if has_b2:
        # grid (b, h, iq, ik, n): n innermost accumulates Σ_n in VMEM
        db2 = pl.pallas_call(
            functools.partial(_db2_kernel, **kw),
            grid=(b, h, s // bq, s // bk, n),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d),
                             lambda b_, h_, iq, ik, jn: (b_ * n + jn, h_, iq, 0)),
                pl.BlockSpec((1, 1, bk, d),
                             lambda b_, h_, iq, ik, jn: (b_ * n + jn, h_, ik, 0)),
                pl.BlockSpec((1, 1, bk, d),
                             lambda b_, h_, iq, ik, jn: (b_ * n + jn, h_, ik, 0)),
                pl.BlockSpec((1, 1, bq, d),
                             lambda b_, h_, iq, ik, jn: (b_ * n + jn, h_, iq, 0)),
                pl.BlockSpec((1, 1, 1, bq),
                             lambda b_, h_, iq, ik, jn: (b_ * n + jn, h_, 0, iq)),
                pl.BlockSpec((1, 1, 1, bq),
                             lambda b_, h_, iq, ik, jn: (b_ * n + jn, h_, 0, iq)),
                (pl.BlockSpec((1, 1, bk),
                              lambda b_, h_, iq, ik, jn: (b_ * n + jn, 0, ik))
                 if has_b1 else
                 pl.BlockSpec((1, 1, 8),
                              lambda b_, h_, iq, ik, jn: (0, 0, 0))),
                pl.BlockSpec((1, 1, bq, bk),
                             lambda b_, h_, iq, ik, jn: (b_, h_, iq, ik)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, bq, bk), lambda b_, h_, iq, ik, jn: (b_, h_, iq, ik)),
            out_shape=jax.ShapeDtypeStruct((b, h, s, s), jnp.float32),
            scratch_shapes=[pltpu.VMEM((bq, bk), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "parallel", "arbitrary")),
            interpret=interpret)(*args)
        db2 = db2.reshape(b, 1, h, s, s)
    if has_b1:
        nqb = s // bq
        db1 = pl.pallas_call(
            functools.partial(_db1_kernel, **kw),
            grid=(b * n, s // bk, h * nqb),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d),
                             lambda bn, ik, j: (bn, j // nqb, j % nqb, 0)),
                pl.BlockSpec((1, 1, bk, d),
                             lambda bn, ik, j: (bn, j // nqb, ik, 0)),
                pl.BlockSpec((1, 1, bk, d),
                             lambda bn, ik, j: (bn, j // nqb, ik, 0)),
                pl.BlockSpec((1, 1, bq, d),
                             lambda bn, ik, j: (bn, j // nqb, j % nqb, 0)),
                pl.BlockSpec((1, 1, 1, bq),
                             lambda bn, ik, j: (bn, j // nqb, 0, j % nqb)),
                pl.BlockSpec((1, 1, 1, bq),
                             lambda bn, ik, j: (bn, j // nqb, 0, j % nqb)),
                (pl.BlockSpec((1, 1, bk), lambda bn, ik, j: (bn, 0, ik))
                 if has_b1 else
                 pl.BlockSpec((1, 1, 8), lambda bn, ik, j: (0, 0, 0))),
                (pl.BlockSpec((1, 1, bq, bk),
                              lambda bn, ik, j: (bn // n, j // nqb, j % nqb,
                                                 ik))
                 if has_b2 else
                 pl.BlockSpec((1, 1, 8, 8), lambda bn, ik, j: (0, 0, 0, 0))),
            ],
            out_specs=pl.BlockSpec((1, 1, bk), lambda bn, ik, j: (bn, 0, ik)),
            out_shape=jax.ShapeDtypeStruct((b * n, 1, s), jnp.float32),
            scratch_shapes=[pltpu.VMEM((8, bk), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret)(*args)
        db1 = db1.reshape(b, n, 1, 1, s)
    return dq, dk, dv, db1, db2


# ------------------------------------------------------- custom_vjp plumbing

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _evo(q, k, v, b1, b2, has_b1, has_b2, interpret):
    o, _ = _fwd(q, k, v, b1 if has_b1 else None, b2 if has_b2 else None,
                interpret)
    b, n, s, h, d = q.shape
    return o.reshape(b, n, h, s, d).transpose(0, 1, 3, 2, 4)


def _evo_fwd(q, k, v, b1, b2, has_b1, has_b2, interpret):
    o, lse = _fwd(q, k, v, b1 if has_b1 else None, b2 if has_b2 else None,
                  interpret)
    b, n, s, h, d = q.shape
    out = o.reshape(b, n, h, s, d).transpose(0, 1, 3, 2, 4)
    return out, (q, k, v, b1, b2, out, lse)


def _evo_bwd(has_b1, has_b2, interpret, res, do):
    q, k, v, b1, b2, o, lse = res
    dq, dk, dv, db1, db2 = _bwd_impl(
        q, k, v, b1 if has_b1 else None, b2 if has_b2 else None, o, lse, do,
        interpret)
    b, n, s, h, d = q.shape
    un = lambda x: x.reshape(b, n, h, s, d).transpose(0, 1, 3, 2, 4)  # noqa: E731
    db1 = (db1.astype(b1.dtype) if has_b1 else jnp.zeros_like(b1))
    db2 = (db2.astype(b2.dtype) if has_b2 else jnp.zeros_like(b2))
    return un(dq), un(dk), un(dv), db1, db2


_evo.defvjp(_evo_fwd, _evo_bwd)


_warned_fallback = set()


def evoformer_attention(q, k, v, bias1: Optional[jax.Array] = None,
                        bias2: Optional[jax.Array] = None,
                        interpret: Optional[bool] = None):
    """q/k/v: [B, N, S, H, D]; bias1 broadcastable to [B, N, 1, 1, S];
    bias2 broadcastable to [B, 1, H, S, S].  Returns [B, N, S, H, D].

    reference evoformer_attn.py:DS4Sci_EvoformerAttention (inputs validated
    the same way: 5-D tensors, biases optional).  Dispatches to the Pallas
    blockwise kernel (module docstring) when shapes allow.  Sequence
    lengths that don't block-tile are PADDED to the tile (padded keys
    masked through bias1, padded query rows sliced off) — real MSA stacks
    have odd S, and a silent einsum fallback would cost the O(S²) score
    tensor the kernel exists to avoid (round-4 verdict item 6).  The
    remaining einsum fallbacks (d % 8 != 0, mismatched shapes) warn once
    per shape."""
    if q.ndim != 5:
        raise ValueError(f"evoformer attention expects [B, N, S, H, D] "
                         f"tensors, got rank {q.ndim}")
    if not supported(q, k, v, bias1, bias2):
        b, n, s0, h, d = q.shape
        if k.shape == q.shape and v.shape == q.shape and d % 8 == 0:
            # pad S to the block grid and recurse onto the kernel path.
            # Next multiple of 32 (not 128): block 32 still tiles the MXU
            # acceptably while capping pad waste at <32 keys — at 128 an
            # S=129 input would pad to 256, ~doubling FLOPs and bias2 HBM
            tgt = 32 if s0 >= 32 else 8
            s_pad = -(-s0 // tgt) * tgt
            padw = ((0, 0), (0, 0), (0, s_pad - s0), (0, 0), (0, 0))
            qp, kp, vp = (jnp.pad(x, padw) for x in (q, k, v))
            b1 = (jnp.broadcast_to(jnp.asarray(bias1), (b, n, 1, 1, s0))
                  if bias1 is not None
                  else jnp.zeros((b, n, 1, 1, s0), jnp.float32))
            b1p = jnp.pad(b1, ((0, 0),) * 4 + ((0, s_pad - s0),),
                          constant_values=-1e9)       # mask padded keys
            b2p = (jnp.pad(jnp.broadcast_to(jnp.asarray(bias2),
                                            (b, 1, h, s0, s0)),
                           ((0, 0), (0, 0), (0, 0),
                            (0, s_pad - s0), (0, s_pad - s0)))
                   if bias2 is not None else None)
            out = evoformer_attention(qp, kp, vp, b1p, b2p,
                                      interpret=interpret)
            return out[:, :, :s0]
        key = (q.shape, k.shape, v.shape)
        if key not in _warned_fallback:
            _warned_fallback.add(key)
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                "evoformer_attention: shapes q=%s k=%s v=%s cannot run the "
                "blockwise Pallas kernel (needs matching shapes and head "
                "dim %% 8 == 0); falling back to the einsum path, which "
                "MATERIALIZES the [B, N, H, S, S] score tensor",
                q.shape, k.shape, v.shape)
        return _evoformer_xla(q, k, v, bias1, bias2)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, n, s, h, d = q.shape
    has_b1, has_b2 = bias1 is not None, bias2 is not None
    b1 = (jnp.broadcast_to(jnp.asarray(bias1), (b, n, 1, 1, s))
          if has_b1 else jnp.zeros((1,), jnp.float32))
    b2 = (jnp.broadcast_to(jnp.asarray(bias2), (b, 1, h, s, s))
          if has_b2 else jnp.zeros((1,), jnp.float32))
    return _evo(q, k, v, b1, b2, has_b1, has_b2, bool(interpret))
