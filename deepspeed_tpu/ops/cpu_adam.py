"""DeepSpeedCPUAdam analog — ctypes binding over the native host Adam kernel.

Reference: deepspeed/ops/adam/cpu_adam.py (DeepSpeedCPUAdam) wrapping
csrc/adam/cpu_adam.cpp.  The binding operates on flat fp32 numpy buffers
in place and can emit bf16 weights in the same pass (the stream-back copy for
the device).  Falls back to a numpy implementation with identical op order if
the native build is unavailable.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

_lib = None
_native_failed = False


def _load():
    global _lib, _native_failed
    if _lib is not None or _native_failed:
        return _lib
    try:
        from deepspeed_tpu.ops.builder import load_op
        lib = load_op("cpu_adam")
        lib.ds_adam_update.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_void_p, ctypes.c_int]
        lib.ds_adam_update.restype = None
        lib.ds_sumsq.argtypes = [ctypes.POINTER(ctypes.c_float),
                                 ctypes.c_int64]
        lib.ds_sumsq.restype = ctypes.c_double
        _lib = lib
    except Exception as e:  # noqa: BLE001
        logger.warning(f"native cpu_adam unavailable ({e}); "
                       "using the numpy fallback")
        _native_failed = True
    return _lib


def native_available() -> bool:
    return _load() is not None


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def adam_update(w: np.ndarray, g: np.ndarray, m: np.ndarray, v: np.ndarray, *,
                lr: float, beta1: float = 0.9, beta2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0,
                adamw_mode: bool = True, step: int = 1,
                grad_scale: float = 1.0,
                w_bf16: Optional[np.ndarray] = None,
                threads: Optional[int] = None) -> None:
    """In-place fused Adam(W) on flat fp32 buffers; optionally emits bf16
    weights into ``w_bf16`` (a uint16 view array of the same length)."""
    assert w.dtype == np.float32 and g.dtype == np.float32
    assert m.dtype == np.float32 and v.dtype == np.float32
    n = w.size
    bias_c1 = 1.0 - beta1 ** step
    bias_c2 = 1.0 - beta2 ** step
    lib = _load()
    if threads is None:
        threads = min(8, os.cpu_count() or 1)
    if lib is not None and all(a.flags["C_CONTIGUOUS"] for a in (w, g, m, v)):
        out_ptr = (w_bf16.ctypes.data_as(ctypes.c_void_p)
                   if w_bf16 is not None else None)
        lib.ds_adam_update(_f32p(w), _f32p(g), _f32p(m), _f32p(v),
                           n, lr, beta1, beta2, eps, weight_decay,
                           int(adamw_mode), bias_c1, bias_c2, grad_scale,
                           out_ptr, threads)
        return
    # ---- numpy fallback: identical op order ----
    grad = g * np.float32(grad_scale)
    if not adamw_mode and weight_decay:
        grad = grad + np.float32(weight_decay) * w
    m *= np.float32(beta1)
    m += np.float32(1 - beta1) * grad
    v *= np.float32(beta2)
    v += np.float32(1 - beta2) * grad * grad
    mhat = m / np.float32(bias_c1)
    vhat = v / np.float32(bias_c2)
    update = mhat / (np.sqrt(vhat) + np.float32(eps))
    if adamw_mode and weight_decay:
        update = update + np.float32(weight_decay) * w
    w -= np.float32(lr) * update
    if w_bf16 is not None:
        _f32_to_bf16_np(w, w_bf16)


def _f32_to_bf16_np(src: np.ndarray, dst_u16: np.ndarray) -> None:
    """Round-to-nearest-even fp32 -> bf16 bit pattern (numpy fallback)."""
    bits = src.view(np.uint32)
    lsb = (bits >> 16) & 1
    rounded = bits + np.uint32(0x7FFF) + lsb
    out = (rounded >> 16).astype(np.uint16)
    nan = (bits & 0x7FFFFFFF) > 0x7F800000
    out[nan] = ((bits[nan] >> 16) | 0x0040).astype(np.uint16)
    dst_u16[...] = out


def sumsq(x: np.ndarray) -> float:
    lib = _load()
    if lib is not None and x.dtype == np.float32 and x.flags["C_CONTIGUOUS"]:
        return float(lib.ds_sumsq(_f32p(x), x.size))
    return float(np.sum(np.square(x.astype(np.float64))))
