"""ctypes binding for the threaded NVMe I/O op (csrc/aio.cpp).

Reference: deepspeed/ops/aio (AsyncIOBuilder) wrapping
csrc/aio/py_lib/deepspeed_py_aio_handle.cpp.  ``AIOFile`` is the handle;
reads/writes release the GIL inside the C call, so wrapping them in a
ThreadPoolExecutor future gives the reference's async swap semantics
(async_swapper.py AsyncTensorSwapper) with plain Python plumbing.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_lib = None


def _load():
    global _lib
    if _lib is None:
        from deepspeed_tpu.ops.builder import load_op
        lib = load_op("aio")
        lib.ds_aio_open.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int]
        lib.ds_aio_open.restype = ctypes.c_int
        lib.ds_aio_close.argtypes = [ctypes.c_int]
        for f in (lib.ds_aio_pread, lib.ds_aio_pwrite):
            f.argtypes = [ctypes.c_int, ctypes.c_void_p, ctypes.c_int64,
                          ctypes.c_int64, ctypes.c_int]
            f.restype = ctypes.c_int64
        _lib = lib
    return _lib


def available() -> bool:
    try:
        _load()
        return True
    except Exception:  # noqa: BLE001
        return False


class AIOFile:
    """One file-backed tensor store (reference: swap file per tensor group,
    partitioned_param_swapper.py)."""

    def __init__(self, path: str, size_bytes: int, threads: int = 4,
                 o_direct: bool = False):
        self.path = path
        self.threads = threads
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        fd = _load().ds_aio_open(path.encode(), size_bytes, int(o_direct))
        if fd < 0:
            raise OSError(-fd, f"ds_aio_open({path}) failed")
        self.fd = fd

    def pread(self, buf: np.ndarray, offset: int = 0) -> None:
        n = buf.nbytes
        got = _load().ds_aio_pread(self.fd, buf.ctypes.data_as(ctypes.c_void_p),
                                   n, offset, self.threads)
        if got != n:
            raise OSError(f"short read {got}/{n} from {self.path}")

    def pwrite(self, buf: np.ndarray, offset: int = 0) -> None:
        n = buf.nbytes
        put = _load().ds_aio_pwrite(self.fd,
                                    buf.ctypes.data_as(ctypes.c_void_p),
                                    n, offset, self.threads)
        if put != n:
            raise OSError(f"short write {put}/{n} to {self.path}")

    def close(self) -> None:
        if self.fd >= 0:
            _load().ds_aio_close(self.fd)
            self.fd = -1

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
