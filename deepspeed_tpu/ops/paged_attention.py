"""Paged-attention decode — Pallas TPU kernel over a block-table KV pool.

TPU-native replacement for the reference's blocked flash decode kernels
(inference/v2/kernels/ragged_ops/blocked_flash/ + atom_builder): each serving
slot owns a list of fixed-size KV pages; decode attends one query token per
slot over exactly that slot's pages.

Kernel design (vs the XLA fallback, which masks over gathered pages):
- grid = (slots, kv_heads, kv_splits) — flash-decoding style.  Each step
  runs an in-kernel double-buffered HBM→VMEM DMA loop over ITS SHARE of the
  slot's live pages (block table via scalar prefetch), with online-softmax
  m/l/acc scratch, and emits unnormalized partials that a tiny XLA epilogue
  merges (logsumexp-weighted).  One split (the default — Pallas TPU grids
  run sequentially per core, so splits don't parallelize under current
  dispatch) degenerates to the single-pass kernel; the split knob exists
  for explicit experimentation on dispatch modes where the axis can run
  concurrently.  Bandwidth always scales with tokens
  actually attended (only live pages are ever read — the property the
  reference kernel gets from its atom decomposition), and a sliding window
  additionally starts the loop past wholly-out-of-window pages.
- GQA native: q arrives [S, nkv, group, hd]; one grid step attends the whole
  group for one kv head (scores [group, bs] on the MXU).
- alibi: per-head slope × key-position bias folded into the online softmax.

Layouts: q [S, nkv, g, hd]; k_pages/v_pages [NB, nkv, bs, hd] (bs = tokens
per page); block_table [S, MB] int32; kv_lens [S] int32 (0 ⇒ inactive slot →
zero output).  Output [S, nkv, g, hd].

kv-major layout (``kv_major=True``): pages are stored TRANSPOSED,
[NB, nkv, hd, bs].  Mosaic requires a DMA slab's lane (last) dimension to be
128-aligned; with the standard layout that means hd % 128 == 0, which
excludes hd∈{64, 80, 96} — a large slice of the zoo (GPT-2, BLOOM-ish
configs, small llamas).  Putting the TOKEN axis on lanes instead makes the
constraint bs % 128 == 0 (a framework-controlled knob: the engine bumps
kv_block_size to 128), and the two kernel matmuls become the natural MXU
layouts: scores = q·K (contract hd = K's sublane axis) and out = P·Vᵀ
(contract bs = V's lane axis) — no transposes at all.  The engine picks
kv-major automatically whenever hd % 128 != 0 (model.py kv_major_layout).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _quant_inputs_ok(k_pages, v_pages, k_scale, v_scale, NB, nkv, bs) -> bool:
    """Shared int8-KV input contract for the decode and prefill gates: both
    pools int8 with matching per-(page, head, token) scale arrays."""
    return (v_scale is not None
            and k_pages.dtype == jnp.int8
            and v_pages.dtype == jnp.int8
            and k_scale.shape == (NB, nkv, bs)
            and v_scale.shape == (NB, nkv, bs))


def _dequant_page(k, v, ks, vs, kv_major, dtype):
    """int8 page codes × per-token fp32 scale row → compute dtype.  The token
    axis is the LANE axis of a kv-major page ([hd, bs]) and the SUBLANE axis
    otherwise ([bs, hd]) — single source of truth for both kernels."""
    if kv_major:
        k = (k.astype(jnp.float32) * ks[None, :]).astype(dtype)
        v = (v.astype(jnp.float32) * vs[None, :]).astype(dtype)
    else:
        k = (k.astype(jnp.float32) * ks[:, None]).astype(dtype)
        v = (v.astype(jnp.float32) * vs[:, None]).astype(dtype)
    return k, v


def _gather_pages(pages, block_table, kv_major):
    """Gather each slot's pages THEN normalize the layout — transposing only
    the [S, MB, …] gather result, never the whole pool.  Returns
    [S, MB*bs, nkv, hd]."""
    got = pages[block_table]               # [S, MB, nkv, bs|hd, hd|bs]
    S, MB = got.shape[:2]
    nkv = got.shape[2]
    if kv_major:                           # [S, MB, nkv, hd, bs]
        got = jnp.transpose(got, (0, 1, 4, 2, 3))
        hd = got.shape[4]
    else:                                  # [S, MB, nkv, bs, hd]
        got = jnp.swapaxes(got, 2, 3)
        hd = got.shape[4]
    return got.reshape(S, -1, nkv, hd)


def _gather_scales(scale_pages, block_table):
    """Gather per-(page, head, token) scales [NB, nkv, bs] for each slot →
    [S, MB*bs, nkv] (token-major, matching _gather_pages row order)."""
    got = scale_pages[block_table]         # [S, MB, nkv, bs]
    S = got.shape[0]
    got = jnp.swapaxes(got, 2, 3)          # [S, MB, bs, nkv]
    return got.reshape(S, -1, got.shape[-1])


def _dequant_seq(seq, scales, out_dtype):
    """seq [S, K, nkv, hd] int8 codes × scales [S, K, nkv] → out_dtype."""
    return (seq.astype(jnp.float32) * scales[..., None]).astype(out_dtype)


def xla_paged_attention(q, k_pages, v_pages, block_table, kv_lens, *,
                        scale: Optional[float] = None, alibi_slopes=None,
                        window=None, interpret=None, mesh=None,
                        kv_major=False, k_scale=None, v_scale=None):
    """Ground-truth XLA path: gather this slot's pages, masked softmax.

    ``mesh`` is accepted for signature parity with the Pallas path; the XLA
    body is einsum/gather code the SPMD partitioner shards on its own.
    ``k_scale``/``v_scale`` [NB, nkv, bs]: the pages are int8 codes —
    dequantize after the gather (only the slot's own pages are touched)."""
    S, nkv, g, hd = q.shape
    if kv_major:
        NB, _, _, bs = k_pages.shape
    else:
        NB, _, bs, _ = k_pages.shape
    MB = block_table.shape[1]
    if scale is None:
        scale = hd ** -0.5
    k_seq = _gather_pages(k_pages, block_table, kv_major)   # [S, MB*bs, nkv, hd]
    v_seq = _gather_pages(v_pages, block_table, kv_major)
    if k_scale is not None:
        k_seq = _dequant_seq(k_seq, _gather_scales(k_scale, block_table),
                             q.dtype)
        v_seq = _dequant_seq(v_seq, _gather_scales(v_scale, block_table),
                             q.dtype)
    kvpos = jnp.arange(MB * bs)
    mask = kvpos[None, :] < kv_lens[:, None]                  # [S, K]
    if window is not None:
        # decode query position is kv_len-1; keep the last `window` keys
        mask = mask & (kvpos[None, :] > kv_lens[:, None] - 1 - window)
    s_log = jnp.einsum("sngd,sknd->sngk", q, k_seq,
                       preferred_element_type=jnp.float32) * scale
    if alibi_slopes is not None:
        # key-position bias per GLOBAL head h = kv_group·g + g_idx
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(nkv, g)
        s_log = s_log + sl[None, :, :, None] * kvpos[None, None, None, :]
    s_log = jnp.where(mask[:, None, None, :], s_log,
                      jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(s_log, axis=-1)
    probs = jnp.where(mask[:, None, None, :].any(-1, keepdims=True),
                      probs, 0.0)
    return jnp.einsum("sngk,sknd->sngd", probs.astype(q.dtype), v_seq)


def _split_kernel(*refs, bs, scale, window, has_alibi, n_splits, kv_major,
                  quant=False):
    """Flash-decoding-SHAPED kernel (one grid step = one KV split of one
    (slot, kv-head)): the page loop covers only this split's share of the
    slot's live pages and emits UNNORMALIZED partials (acc, m, l) that a
    tiny XLA epilogue merges with the standard logsumexp-weighted combine.
    n_splits=1 (the default) IS the single-pass decode kernel; more splits
    only help where the grid axis can actually run concurrently — see the
    module docstring.

    Alibi slopes ride in SMEM scalar prefetch ([nkv, g] f32): a (1, g)
    VMEM BlockSpec is rejected by Mosaic when nkv > 1 (sublane block of 1
    against an nkv-sized axis), and per-head scalars are SMEM-natured
    anyway.

    ``quant``: pages are int8 codes and two extra HBM inputs carry the
    per-(page, head, token) fp32 scales — the page loop DMAs the scale rows
    alongside the pages (double-buffered the same way) and dequantizes in
    VMEM right before the dots.  The HBM traffic that decode is bound by is
    the int8 payload: half the bf16 bytes."""
    if quant:
        if has_alibi:
            bt_ref, len_ref, slopes_ref, q_ref, k_hbm, v_hbm, ks_hbm, \
                vs_hbm, o_ref, m_ref, l_ref, k_buf, v_buf, ks_buf, vs_buf, \
                sem = refs
        else:
            bt_ref, len_ref, q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, \
                o_ref, m_ref, l_ref, k_buf, v_buf, ks_buf, vs_buf, sem = refs
            slopes_ref = None
    elif has_alibi:
        bt_ref, len_ref, slopes_ref, q_ref, k_hbm, v_hbm, \
            o_ref, m_ref, l_ref, k_buf, v_buf, sem = refs
    else:
        bt_ref, len_ref, q_ref, k_hbm, v_hbm, \
            o_ref, m_ref, l_ref, k_buf, v_buf, sem = refs
        slopes_ref = None
    if not quant:
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    s, h, sp = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    length = len_ref[s]
    n_pages = (length + bs - 1) // bs
    g, hd = q_ref.shape[2], q_ref.shape[3]
    q = q_ref[0, 0]                                # [g, hd]
    if window is None:
        lo_page = jnp.int32(0)
        lo = jnp.int32(0)
    else:
        lo = jnp.maximum(length - window, 0)
        lo_page = lo // bs
    live_pages = jnp.maximum(n_pages - lo_page, 0)
    per = (live_pages + n_splits - 1) // n_splits
    p_start = lo_page + sp * per
    p_end = jnp.minimum(p_start + per, n_pages)

    def dma(hbm, buf, slot, p, way):
        return pltpu.make_async_copy(
            hbm.at[bt_ref[s, p], h], buf.at[slot], sem.at[way * 2 + slot])

    def start_page(slot, p):
        dma(k_hbm, k_buf, slot, p, 0).start()
        dma(v_hbm, v_buf, slot, p, 1).start()
        if quant:
            dma(ks_hbm, ks_buf, slot, p, 2).start()
            dma(vs_hbm, vs_buf, slot, p, 3).start()

    @pl.when(p_end > p_start)
    def _warmup():
        start_page(jax.lax.rem(p_start, 2), p_start)

    def body(p, carry):
        m, l, acc = carry
        slot = jax.lax.rem(p, 2)
        nxt = jax.lax.rem(p + 1, 2)

        @pl.when(p + 1 < p_end)
        def _prefetch():
            start_page(nxt, p + 1)

        dma(k_hbm, k_buf, slot, p, 0).wait()
        dma(v_hbm, v_buf, slot, p, 1).wait()
        k = k_buf[slot]                # [bs, hd] or [hd, bs] (kv-major)
        v = v_buf[slot]
        if quant:
            dma(ks_hbm, ks_buf, slot, p, 2).wait()
            dma(vs_hbm, vs_buf, slot, p, 3).wait()
            k, v = _dequant_page(k, v, ks_buf[slot], vs_buf[slot],
                                 kv_major, q.dtype)
        k_dims = ((1,), (0,)) if kv_major else ((1,), (1,))
        scores = jax.lax.dot_general(
            q, k, (k_dims, ((), ())),
            preferred_element_type=jnp.float32) * scale
        kvpos = p * bs + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        if has_alibi:
            sl = jnp.stack([slopes_ref[h, i] for i in range(g)])
            scores = scores + sl[:, None] * kvpos.astype(jnp.float32)
        valid = kvpos < length
        if window is not None:
            valid = valid & (kvpos >= lo)
        scores = jnp.where(valid, scores, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=1, keepdims=True))
        pr = jnp.exp(scores - m_new)
        pr = jnp.where(m_new > _NEG_INF / 2, pr, 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(pr, axis=1, keepdims=True)
        v_dims = ((1,), (1,)) if kv_major else ((1,), (0,))
        pv = jax.lax.dot_general(pr.astype(v.dtype), v,
                                 (v_dims, ((), ())),
                                 preferred_element_type=jnp.float32)
        return m_new, l, acc * alpha + pv

    m0 = jnp.full((g, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    acc0 = jnp.zeros((g, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(p_start, p_end, body, (m0, l0, acc0))
    o_ref[0, 0, 0] = acc                           # fp32 partial
    m_ref[0, 0, 0] = m[:, 0]
    l_ref[0, 0, 0] = l[:, 0]


def pallas_paged_attention(q, k_pages, v_pages, block_table, kv_lens, *,
                           alibi_slopes=None, window=None,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           num_kv_splits: Optional[int] = None,
                           mesh=None, kv_major=False,
                           k_scale=None, v_scale=None):
    """Mesh-aware entry: with a ``tp`` axis the kv-head dim is sharded, and the
    kernel runs per-shard under shard_map (attention is independent per kv
    head, so TP needs no collective here — the reference shards its blocked
    flash the same way, model_implementations/sharding/attn.py)."""
    if (mesh is not None and mesh.shape.get("tp", 1) > 1
            and q.shape[1] % mesh.shape["tp"] == 0):
        from deepspeed_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P
        inner = functools.partial(_pallas_paged_attention_local,
                                  scale=scale, window=window,
                                  interpret=interpret,
                                  num_kv_splits=num_kv_splits,
                                  kv_major=kv_major)
        kv_spec = P(None, "tp", None, None)
        in_specs = [kv_spec, kv_spec, kv_spec, P(None, None), P(None)]
        args = [q, k_pages, v_pages, block_table, kv_lens]
        n_scales = 0
        if k_scale is not None:        # [NB, nkv, bs]: kv-head axis shards
            args += [k_scale, v_scale]
            in_specs += [P(None, "tp", None)] * 2
            n_scales = 2
        if alibi_slopes is not None:
            # slopes [nkv, g] shard with the kv-head axis
            args.append(jnp.asarray(alibi_slopes, jnp.float32).reshape(
                q.shape[1], q.shape[2]))
            in_specs.append(P("tp", None))

        def wrapped(q_, k_, v_, bt_, lens_, *rest):
            sc = rest[:n_scales]
            sl = rest[n_scales:]
            return inner(q_, k_, v_, bt_, lens_,
                         k_scale=sc[0] if sc else None,
                         v_scale=sc[1] if sc else None,
                         alibi_slopes=sl[0] if sl else None)
        return shard_map(
            wrapped, mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=kv_spec, check_vma=False,
        )(*args)
    return _pallas_paged_attention_local(q, k_pages, v_pages, block_table,
                                         kv_lens, alibi_slopes=alibi_slopes,
                                         window=window, scale=scale,
                                         interpret=interpret,
                                         num_kv_splits=num_kv_splits,
                                         kv_major=kv_major,
                                         k_scale=k_scale, v_scale=v_scale)


def _pallas_paged_attention_local(q, k_pages, v_pages, block_table, kv_lens, *,
                                  alibi_slopes=None, window=None,
                                  scale: Optional[float] = None,
                                  interpret: Optional[bool] = None,
                                  num_kv_splits: Optional[int] = None,
                                  kv_major=False, k_scale=None, v_scale=None):
    S, nkv, g, hd = q.shape
    if kv_major:
        NB, _, _, bs = k_pages.shape
    else:
        NB, _, bs, _ = k_pages.shape
    MB = block_table.shape[1]
    if scale is None:
        scale = hd ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_table = block_table.astype(jnp.int32)
    kv_lens = kv_lens.astype(jnp.int32)
    if num_kv_splits is None:
        # DEFAULT 1: Pallas TPU executes grid dimensions sequentially on a
        # core (and this DMA-loop kernel must not be megacore-partitioned),
        # so extra splits do not parallelize on current single-core
        # dispatch — they only pay partial-writeback + combine.  The knob
        # exists for explicit experimentation (e.g. future megacore-safe
        # variants or very small slot×head grids); measure before enabling.
        num_kv_splits = 1
    return _pallas_paged_attention_split(
        q, k_pages, v_pages, block_table, kv_lens,
        alibi_slopes=alibi_slopes, window=window, scale=float(scale),
        interpret=interpret, num_kv_splits=int(num_kv_splits),
        kv_major=kv_major, k_scale=k_scale, v_scale=v_scale)


def _pallas_paged_attention_split(q, k_pages, v_pages, block_table, kv_lens,
                                  *, alibi_slopes, window, scale, interpret,
                                  num_kv_splits: int, kv_major: bool,
                                  k_scale=None, v_scale=None):
    """Grid (S, nkv, splits) of unnormalized partials + logsumexp-weighted
    XLA combine (flash-decoding shape).  Inputs arrive NORMALIZED (int32
    tables, float scale) from _pallas_paged_attention_local — the only
    caller."""
    S, nkv, g, hd = q.shape
    bs = k_pages.shape[3] if kv_major else k_pages.shape[2]
    NS = num_kv_splits
    quant = k_scale is not None
    kernel = functools.partial(
        _split_kernel, bs=bs, scale=float(scale),
        window=int(window) if window is not None else None,
        has_alibi=alibi_slopes is not None, n_splits=NS, kv_major=kv_major,
        quant=quant)
    n_prefetch = 2
    prefetch = [block_table, kv_lens]
    if alibi_slopes is not None:
        n_prefetch = 3
        prefetch.append(jnp.asarray(alibi_slopes, jnp.float32).reshape(
            nkv, g))
    in_specs = [
        pl.BlockSpec((1, 1, g, hd), lambda s, h, sp, *_: (s, h, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    inputs = [q, k_pages, v_pages]
    buf_shape = (2, hd, bs) if kv_major else (2, bs, hd)
    scratch = [
        pltpu.VMEM(buf_shape, k_pages.dtype),
        pltpu.VMEM(buf_shape, v_pages.dtype),
    ]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        inputs += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
        scratch += [pltpu.VMEM((2, bs), jnp.float32),
                    pltpu.VMEM((2, bs), jnp.float32)]
    scratch.append(pltpu.SemaphoreType.DMA((8 if quant else 4,)))
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=n_prefetch,
            grid=(S, nkv, NS),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, 1, g, hd),
                             lambda s, h, sp, *_: (s, h, sp, 0, 0)),
                pl.BlockSpec((1, 1, 1, g),
                             lambda s, h, sp, *_: (s, h, sp, 0)),
                pl.BlockSpec((1, 1, 1, g),
                             lambda s, h, sp, *_: (s, h, sp, 0)),
            ],
            scratch_shapes=scratch,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((S, nkv, NS, g, hd), jnp.float32),
            jax.ShapeDtypeStruct((S, nkv, NS, g), jnp.float32),
            jax.ShapeDtypeStruct((S, nkv, NS, g), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*prefetch, *inputs)
    # combine: o = Σ exp(m_s − m*) acc_s / Σ exp(m_s − m*) l_s
    m_star = jnp.max(m, axis=2, keepdims=True)              # [S, nkv, 1, g]
    w = jnp.exp(m - m_star)                                 # [S, nkv, NS, g]
    num = jnp.sum(acc * w[..., None], axis=2)               # [S, nkv, g, hd]
    den = jnp.sum(l * w, axis=2)                            # [S, nkv, g]
    den = jnp.where(den == 0.0, 1.0, den)                   # inactive slots
    return (num / den[..., None]).astype(q.dtype)


def _dma_layout_ok(hd: int, bs: int, kv_major: bool,
                   quant: bool = False) -> bool:
    """Mosaic constraint on the per-page DMA slab: its LANE (last) dim must
    be 128-aligned and its sublane dim 8-aligned (padded lane dims make the
    slice non-contiguous and the compile is rejected — found on real v5e).
    int8 pages tile (32, 128), so the sublane requirement tightens to 32;
    the [bs] f32 scale slab additionally needs bs % 128 == 0."""
    sub = 32 if quant else 8
    if kv_major:
        return bs % 128 == 0 and hd % sub == 0
    return (hd % 128 == 0 and bs % sub == 0
            and (not quant or bs % 128 == 0))


def supported(q, k_pages, v_pages, block_table, kv_lens, *, scale=None,
              alibi_slopes=None, window=None, interpret=None, mesh=None,
              kv_major=False, k_scale=None, v_scale=None):
    if q.ndim != 4 or k_pages.ndim != 4:
        return False
    S, nkv, g, hd = q.shape
    if kv_major:
        NB, nkv2, hd2, bs = k_pages.shape
    else:
        NB, nkv2, bs, hd2 = k_pages.shape
    quant = k_scale is not None
    if quant and not _quant_inputs_ok(k_pages, v_pages, k_scale, v_scale,
                                      NB, nkv2, bs):
        return False
    if alibi_slopes is not None and np.size(alibi_slopes) != nkv * g:
        return False
    if window is not None and int(window) <= 0:
        return False
    return (nkv == nkv2 and hd == hd2
            and _dma_layout_ok(hd, bs, kv_major, quant=quant)
            and block_table.ndim == 2 and block_table.shape[0] == S)


def paged_attention(q, k_pages, v_pages, block_table, kv_lens, *,
                    scale: Optional[float] = None,
                    alibi_slopes=None, window=None,
                    impl: Optional[str] = None,
                    interpret: Optional[bool] = None,
                    mesh=None, kv_major=False, k_scale=None, v_scale=None):
    """Registry entry (ops/__init__ registers this like causal_attention)."""
    from deepspeed_tpu.ops.registry import dispatch
    return dispatch("paged_attention", q, k_pages, v_pages, block_table,
                    kv_lens, scale=scale, alibi_slopes=alibi_slopes,
                    window=window, impl=impl, interpret=interpret, mesh=mesh,
                    kv_major=kv_major, k_scale=k_scale, v_scale=v_scale)


# ===================================================================
# Ragged prefill (VERDICT r2 item 4 — reference blocked_flash + atom_builder)
# ===================================================================
#
# Mixed prefill/decode batches arrive as a dense-per-slot query layout
# [S, Q, nkv, g, hd] where slot s owns ``q_counts[s]`` live rows holding the
# CONTIGUOUS positions [q_starts[s], q_starts[s] + q_counts[s]); its KV —
# including the rows just appended — lives in ``kv_lens[s]`` tokens across
# the slot's block-table pages.  The XLA fallback gathers every slot's full
# page span and runs one masked-dense attention (cost O(S · Q · MBmax·bs));
# the Pallas kernel instead grids over (slot, kv head, q-chunk) and runs the
# decode kernel's double-buffered HBM→VMEM DMA loop over ONLY the pages the
# chunk can causally see — dead (slot, chunk) pairs are skipped outright, so
# FLOPs and bandwidth scale with Σ live tokens, not S × longest.


def xla_ragged_prefill(q, k_pages, v_pages, block_table, kv_lens, q_starts,
                       q_counts, *, scale: Optional[float] = None,
                       alibi_slopes=None, window=None, interpret=None,
                       mesh=None, kv_major=False, k_scale=None, v_scale=None):
    """Ground-truth gather + masked-dense path (the round-2 prefill body).
    ``k_scale``/``v_scale``: int8-KV dequant after the gather (see
    xla_paged_attention)."""
    S, Q, nkv, g, hd = q.shape
    if kv_major:
        NB, _, _, bs = k_pages.shape
    else:
        NB, _, bs, _ = k_pages.shape
    MB = block_table.shape[1]
    if scale is None:
        scale = hd ** -0.5
    k_seq = _gather_pages(k_pages, block_table, kv_major)
    v_seq = _gather_pages(v_pages, block_table, kv_major)
    if k_scale is not None:
        k_seq = _dequant_seq(k_seq, _gather_scales(k_scale, block_table),
                             q.dtype)
        v_seq = _dequant_seq(v_seq, _gather_scales(v_scale, block_table),
                             q.dtype)
    kvpos = jnp.arange(MB * bs)                                # [K]
    rows = jnp.arange(Q)
    qpos = q_starts[:, None] + rows[None, :]                   # [S, Q]
    live = rows[None, :] < q_counts[:, None]                   # [S, Q]
    mask = (kvpos[None, None, :] <= qpos[:, :, None]) \
        & (kvpos[None, None, :] < kv_lens[:, None, None]) \
        & live[:, :, None]                                     # [S, Q, K]
    if window is not None:
        mask = mask & (kvpos[None, None, :] > qpos[:, :, None] - window)
    s_log = jnp.einsum("sqngd,sknd->snqgk", q, k_seq,
                       preferred_element_type=jnp.float32) * scale
    if alibi_slopes is not None:
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(nkv, g)
        s_log = s_log + (sl[None, :, None, :, None]
                         * kvpos[None, None, None, None, :].astype(
                             jnp.float32))
    m = mask[:, None, :, None, :]                              # [S,1,Q,1,K]
    s_log = jnp.where(m, s_log, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(s_log, axis=-1)
    probs = jnp.where(m.any(-1, keepdims=True), probs, 0.0)
    return jnp.einsum("snqgk,sknd->sqngd", probs.astype(q.dtype), v_seq)


def _prefill_kernel(*refs, bs, cq, g, scale, window, has_alibi, kv_major,
                    quant=False):
    if quant:
        if has_alibi:
            bt_ref, len_ref, start_ref, count_ref, slopes_ref, \
                q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref, \
                k_buf, v_buf, ks_buf, vs_buf, sem = refs
        else:
            bt_ref, len_ref, start_ref, count_ref, \
                q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref, \
                k_buf, v_buf, ks_buf, vs_buf, sem = refs
            slopes_ref = None
    elif has_alibi:
        bt_ref, len_ref, start_ref, count_ref, slopes_ref, \
            q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf, sem = refs
    else:
        bt_ref, len_ref, start_ref, count_ref, \
            q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf, sem = refs
        slopes_ref = None
    if not quant:
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    s, h, c = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    count = count_ref[s]
    start = start_ref[s]
    length = len_ref[s]
    hd = q_ref.shape[4]
    row0 = c * cq
    live = row0 < count
    # pages the chunk can causally see: up to its LAST live row's position
    last_pos = start + jnp.minimum(count, row0 + cq) - 1
    n_pages = jnp.where(live, (last_pos + bs) // bs, 0)
    if window is None:
        p_start = jnp.int32(0)
    else:
        # the chunk's FIRST row's window start bounds every row's from below
        p_start = jnp.maximum(start + row0 - window + 1, 0) // bs

    def dma(hbm, buf, slot, p, way):
        return pltpu.make_async_copy(
            hbm.at[bt_ref[s, p], h], buf.at[slot], sem.at[way * 2 + slot])

    def start_page(slot, p):
        dma(k_hbm, k_buf, slot, p, 0).start()
        dma(v_hbm, v_buf, slot, p, 1).start()
        if quant:
            dma(ks_hbm, ks_buf, slot, p, 2).start()
            dma(vs_hbm, vs_buf, slot, p, 3).start()

    @pl.when(n_pages > p_start)
    def _warmup():
        start_page(jax.lax.rem(p_start, 2), p_start)

    q = q_ref[0, :, 0].reshape(cq * g, hd)         # [cq·g, hd] row r=(j·g+gi)
    rown = jax.lax.broadcasted_iota(jnp.int32, (cq * g, bs), 0) // g
    qpos = start + row0 + rown                     # [cq·g, bs]
    row_live = row0 + rown < count
    if has_alibi:
        # SMEM scalar-prefetch slopes [nkv, g]: row r = j·g+gi needs
        # slopes[h, r % g] — tile the per-group column cq times
        sl = jnp.stack([slopes_ref[h, i] for i in range(g)]).reshape(g, 1)
        slope_rows = jnp.tile(sl, (cq, 1))         # [cq·g, 1]

    def body(p, carry):
        m, l, acc = carry
        slot = jax.lax.rem(p, 2)
        nxt = jax.lax.rem(p + 1, 2)

        @pl.when(p + 1 < n_pages)
        def _prefetch():
            start_page(nxt, p + 1)

        dma(k_hbm, k_buf, slot, p, 0).wait()
        dma(v_hbm, v_buf, slot, p, 1).wait()
        k = k_buf[slot]                # [bs, hd] or [hd, bs] (kv-major)
        v = v_buf[slot]
        if quant:
            dma(ks_hbm, ks_buf, slot, p, 2).wait()
            dma(vs_hbm, vs_buf, slot, p, 3).wait()
            k, v = _dequant_page(k, v, ks_buf[slot], vs_buf[slot],
                                 kv_major, q.dtype)
        k_dims = ((1,), (0,)) if kv_major else ((1,), (1,))
        scores = jax.lax.dot_general(
            q, k, (k_dims, ((), ())),
            preferred_element_type=jnp.float32) * scale       # [cq·g, bs]
        kvpos = p * bs + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        if has_alibi:
            scores = scores + slope_rows * kvpos.astype(jnp.float32)
        valid = (kvpos <= qpos) & (kvpos < length) & row_live
        if window is not None:
            valid = valid & (kvpos > qpos - window)
        scores = jnp.where(valid, scores, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=1, keepdims=True))
        pr = jnp.exp(scores - m_new)
        # a row with no valid key in this page AND none so far: m_new is
        # still -inf and exp aliases to 1 — zero it (dead rows, early rows
        # of a later page under a window)
        pr = jnp.where(m_new > _NEG_INF / 2, pr, 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(pr, axis=1, keepdims=True)
        v_dims = ((1,), (1,)) if kv_major else ((1,), (0,))
        pv = jax.lax.dot_general(pr.astype(v.dtype), v,
                                 (v_dims, ((), ())),
                                 preferred_element_type=jnp.float32)
        return m_new, l, acc * alpha + pv

    m0 = jnp.full((cq * g, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((cq * g, 1), jnp.float32)
    acc0 = jnp.zeros((cq * g, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(p_start, n_pages, body, (m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)                # dead rows -> zeros
    o_ref[0, :, 0] = (acc / l).reshape(cq, g, hd).astype(o_ref.dtype)


def pallas_ragged_prefill(q, k_pages, v_pages, block_table, kv_lens, q_starts,
                          q_counts, *, scale: Optional[float] = None,
                          alibi_slopes=None, window=None,
                          interpret: Optional[bool] = None, mesh=None,
                          kv_major=False, k_scale=None, v_scale=None):
    if (mesh is not None and mesh.shape.get("tp", 1) > 1
            and q.shape[2] % mesh.shape["tp"] == 0):
        from deepspeed_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P
        inner = functools.partial(_pallas_ragged_prefill_local, scale=scale,
                                  window=window, interpret=interpret,
                                  kv_major=kv_major)
        q_spec = P(None, None, "tp", None, None)
        kv_spec = P(None, "tp", None, None)
        in_specs = [q_spec, kv_spec, kv_spec, P(None, None), P(None),
                    P(None), P(None)]
        args = [q, k_pages, v_pages, block_table, kv_lens, q_starts, q_counts]
        n_scales = 0
        if k_scale is not None:        # [NB, nkv, bs]: kv-head axis shards
            args += [k_scale, v_scale]
            in_specs += [P(None, "tp", None)] * 2
            n_scales = 2
        if alibi_slopes is not None:
            args.append(jnp.asarray(alibi_slopes, jnp.float32).reshape(
                q.shape[2], q.shape[3]))
            in_specs.append(P("tp", None))

        def wrapped(q_, k_, v_, bt_, lens_, st_, ct_, *rest):
            sc = rest[:n_scales]
            sl = rest[n_scales:]
            return inner(q_, k_, v_, bt_, lens_, st_, ct_,
                         k_scale=sc[0] if sc else None,
                         v_scale=sc[1] if sc else None,
                         alibi_slopes=sl[0] if sl else None)
        return shard_map(
            wrapped, mesh=mesh, in_specs=tuple(in_specs),
            out_specs=q_spec, check_vma=False,
        )(*args)
    return _pallas_ragged_prefill_local(
        q, k_pages, v_pages, block_table, kv_lens, q_starts, q_counts,
        scale=scale, alibi_slopes=alibi_slopes, window=window,
        interpret=interpret, kv_major=kv_major,
        k_scale=k_scale, v_scale=v_scale)


def _prefill_chunk(Q: int) -> Optional[int]:
    for cq in (128, 64, 32, 16, 8, 4, 2, 1):
        if cq <= Q and Q % cq == 0:
            return cq
    return None


def _pallas_ragged_prefill_local(q, k_pages, v_pages, block_table, kv_lens,
                                 q_starts, q_counts, *,
                                 scale: Optional[float] = None,
                                 alibi_slopes=None, window=None,
                                 interpret: Optional[bool] = None,
                                 kv_major=False, k_scale=None, v_scale=None):
    S, Q, nkv, g, hd = q.shape
    bs = k_pages.shape[3] if kv_major else k_pages.shape[2]
    if scale is None:
        scale = hd ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cq = _prefill_chunk(Q)
    block_table = block_table.astype(jnp.int32)
    kv_lens = kv_lens.astype(jnp.int32)
    q_starts = q_starts.astype(jnp.int32)
    q_counts = q_counts.astype(jnp.int32)
    has_alibi = alibi_slopes is not None
    quant = k_scale is not None

    grid = (S, nkv, Q // cq)
    kernel = functools.partial(
        _prefill_kernel, bs=bs, cq=cq, g=g, scale=float(scale),
        window=int(window) if window is not None else None,
        has_alibi=has_alibi, kv_major=kv_major, quant=quant)
    n_prefetch = 4
    prefetch = [block_table, kv_lens, q_starts, q_counts]
    if has_alibi:
        n_prefetch = 5
        prefetch.append(jnp.asarray(alibi_slopes, jnp.float32).reshape(
            nkv, g))
    in_specs = [
        pl.BlockSpec((1, cq, 1, g, hd),
                     lambda s, h, c, *_: (s, c, h, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    inputs = [q, k_pages, v_pages]
    buf_shape = (2, hd, bs) if kv_major else (2, bs, hd)
    scratch = [
        pltpu.VMEM(buf_shape, k_pages.dtype),
        pltpu.VMEM(buf_shape, v_pages.dtype),
    ]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        inputs += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
        scratch += [pltpu.VMEM((2, bs), jnp.float32),
                    pltpu.VMEM((2, bs), jnp.float32)]
    scratch.append(pltpu.SemaphoreType.DMA((8 if quant else 4,)))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=n_prefetch,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, cq, 1, g, hd),
                                   lambda s, h, c, *_: (s, c, h, 0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((S, Q, nkv, g, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*prefetch, *inputs)
    return out


def ragged_prefill_supported(q, k_pages, v_pages, block_table, kv_lens,
                             q_starts, q_counts, *, scale=None,
                             alibi_slopes=None, window=None, interpret=None,
                             mesh=None, kv_major=False,
                             k_scale=None, v_scale=None):
    if q.ndim != 5 or k_pages.ndim != 4:
        return False
    S, Q, nkv, g, hd = q.shape
    if kv_major:
        NB, nkv2, hd2, bs = k_pages.shape
    else:
        NB, nkv2, bs, hd2 = k_pages.shape
    quant = k_scale is not None
    if quant and not _quant_inputs_ok(k_pages, v_pages, k_scale, v_scale,
                                      NB, nkv2, bs):
        return False
    if alibi_slopes is not None and np.size(alibi_slopes) != nkv * g:
        return False
    if window is not None and int(window) <= 0:
        return False
    return (nkv == nkv2 and hd == hd2
            and _dma_layout_ok(hd, bs, kv_major, quant=quant)
            and _prefill_chunk(Q) is not None
            and block_table.ndim == 2 and block_table.shape[0] == S)


def ragged_prefill_attention(q, k_pages, v_pages, block_table, kv_lens,
                             q_starts, q_counts, *,
                             scale: Optional[float] = None,
                             alibi_slopes=None, window=None,
                             impl: Optional[str] = None,
                             interpret: Optional[bool] = None, mesh=None,
                             kv_major=False, k_scale=None, v_scale=None):
    """Registry entry for the ragged prefill kernel."""
    from deepspeed_tpu.ops.registry import dispatch
    return dispatch("ragged_prefill_attention", q, k_pages, v_pages,
                    block_table, kv_lens, q_starts, q_counts, scale=scale,
                    alibi_slopes=alibi_slopes, window=window, impl=impl,
                    interpret=interpret, mesh=mesh, kv_major=kv_major,
                    k_scale=k_scale, v_scale=v_scale)
