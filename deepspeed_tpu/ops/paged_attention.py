"""Paged-attention decode — Pallas TPU kernel over a block-table KV pool.

TPU-native replacement for the reference's blocked flash decode kernels
(inference/v2/kernels/ragged_ops/blocked_flash/ + atom_builder): each serving
slot owns a list of fixed-size KV pages; decode attends one query token per
slot over exactly that slot's pages.

Kernel design (vs the XLA fallback, which masks over gathered pages):
- grid = (slots, kv_heads, max_blocks); the innermost block axis runs an
  online-softmax accumulation (m/l/acc scratch), like flash attention.
- the block table rides scalar prefetch (PrefetchScalarGridSpec), so the
  K/V BlockSpec index maps can look up each slot's b-th physical page.
- past a slot's last used page the index map CLAMPS to the last used page:
  Pallas skips the DMA when consecutive grid steps map the same block, so a
  slot with 3 live pages moves exactly 3 pages of KV through VMEM no matter
  how large max_blocks is — bandwidth scales with tokens actually attended,
  the property the reference kernel gets from its atom decomposition.
- GQA native: q arrives [S, nkv, group, hd]; one grid step attends the whole
  group for one kv head (scores [group, bs] on the MXU).

Layouts: q [S, nkv, g, hd]; k_pages/v_pages [NB, nkv, bs, hd] (bs = tokens
per page); block_table [S, MB] int32; kv_lens [S] int32 (0 ⇒ inactive slot →
zero output).  Output [S, nkv, g, hd].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def xla_paged_attention(q, k_pages, v_pages, block_table, kv_lens, *,
                        scale: Optional[float] = None, interpret=None):
    """Ground-truth XLA path: gather this slot's pages, masked softmax."""
    S, nkv, g, hd = q.shape
    NB, _, bs, _ = k_pages.shape
    MB = block_table.shape[1]
    if scale is None:
        scale = hd ** -0.5
    # [S, MB, nkv, bs, hd] -> [S, nkv, MB*bs, hd]
    k_seq = jnp.swapaxes(k_pages[block_table], 2, 3).reshape(
        S, MB * bs, nkv, hd)
    v_seq = jnp.swapaxes(v_pages[block_table], 2, 3).reshape(
        S, MB * bs, nkv, hd)
    kvpos = jnp.arange(MB * bs)
    mask = kvpos[None, :] < kv_lens[:, None]                  # [S, K]
    s_log = jnp.einsum("sngd,sknd->sngk", q, k_seq,
                       preferred_element_type=jnp.float32) * scale
    s_log = jnp.where(mask[:, None, None, :], s_log,
                      jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(s_log, axis=-1)
    probs = jnp.where(mask[:, None, None, :].any(-1, keepdims=True),
                      probs, 0.0)
    return jnp.einsum("sngk,sknd->sngd", probs.astype(q.dtype), v_seq)


def _kernel(bt_ref, len_ref,                       # scalar prefetch
            q_ref, k_ref, v_ref, o_ref,            # blocks
            m_scr, l_scr, acc_scr, *, bs, scale):
    s, b = pl.program_id(0), pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(b == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    length = len_ref[s]

    @pl.when(b * bs < length)
    def _body():
        q = q_ref[0, 0]                            # [g, hd]
        k = k_ref[0, 0]                            # [bs, hd]
        v = v_ref[0, 0]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [g, bs]
        kvpos = b * bs + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        scores = jnp.where(kvpos < length, scores, _NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)                # [g, bs]
        alpha = jnp.exp(m_prev - m_new)
        l_scr[...] = jnp.broadcast_to(
            alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(b == nb - 1)
    def _finalize():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)            # inactive slot -> zeros
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)


def pallas_paged_attention(q, k_pages, v_pages, block_table, kv_lens, *,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    S, nkv, g, hd = q.shape
    NB, _, bs, _ = k_pages.shape
    MB = block_table.shape[1]
    if scale is None:
        scale = hd ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_table = block_table.astype(jnp.int32)
    kv_lens = kv_lens.astype(jnp.int32)

    def page_map(s, h, b, bt, lens):
        # clamp past-the-end to the last used page: same index as the
        # previous step ⇒ Pallas elides the DMA, so dead blocks cost nothing
        used_minus1 = jnp.maximum(lens[s] + bs - 1, bs) // bs - 1
        return (bt[s, jnp.minimum(b, used_minus1)], h, 0, 0)

    grid = (S, nkv, MB)
    kernel = functools.partial(_kernel, bs=bs, scale=float(scale))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, g, hd),
                             lambda s, h, b, bt, lens: (s, h, 0, 0)),
                pl.BlockSpec((1, 1, bs, hd), page_map),
                pl.BlockSpec((1, 1, bs, hd), page_map),
            ],
            out_specs=pl.BlockSpec((1, 1, g, hd),
                                   lambda s, h, b, bt, lens: (s, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((g, 128), jnp.float32),
                pltpu.VMEM((g, 128), jnp.float32),
                pltpu.VMEM((g, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((S, nkv, g, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(block_table, kv_lens, q, k_pages, v_pages)
    return out


def supported(q, k_pages, v_pages, block_table, kv_lens, *, scale=None,
              interpret=None):
    if q.ndim != 4 or k_pages.ndim != 4:
        return False
    S, nkv, g, hd = q.shape
    NB, nkv2, bs, hd2 = k_pages.shape
    return (nkv == nkv2 and hd == hd2 and hd % 8 == 0 and bs % 8 == 0
            and block_table.ndim == 2 and block_table.shape[0] == S)


def paged_attention(q, k_pages, v_pages, block_table, kv_lens, *,
                    scale: Optional[float] = None,
                    impl: Optional[str] = None,
                    interpret: Optional[bool] = None):
    """Registry entry (ops/__init__ registers this like causal_attention)."""
    from deepspeed_tpu.ops.registry import dispatch
    return dispatch("paged_attention", q, k_pages, v_pages, block_table,
                    kv_lens, scale=scale, impl=impl, interpret=interpret)
