"""Batched-gather LoRA matmul — N adapters in ONE ragged dispatch.

Punica (arXiv:2310.18547) shape of the idea: a multi-tenant batch carries a
per-row adapter id, and the LoRA delta

    y[i] += (x[i] @ A[id_i]) @ B[id_i] · s[id_i]

is computed for ALL rows in one segmented (SGMV-style) matmul instead of
splitting the batch per tenant — which is what keeps N ≫ 1 adapters at
near-single-adapter throughput.  The adapter pages live PACKED in device
tables ``a_pages [S, H, r]`` / ``b_pages [S, r, O]`` (S = pool slots, one
slot per resident adapter; slot 0 is the base-model identity — the
AdapterPool keeps its pages zero, so id-0 rows pay a zero delta, not a
branch).

Two implementations behind the op registry, the ``wq_matmul`` convention:

- **xla** (reference + numeric ground truth): per-row gather of the A/B
  pages feeding two batched einsums.  Row-independent by construction —
  the per-request-loop exactness tests lean on this.
- **pallas** (fast slot): grid ``(M/bm, S)`` — each token block visits
  every adapter slot once, computes the dense rank-r delta for the whole
  block, and masks it onto the rows whose id matches the slot.  Dense
  over slots (BGMV-style) rather than sorted-segment SGMV: the ragged
  engine's row order is schedule-determined and a sort would reorder the
  batch the caller packed; the wasted flops are ``(S-1)/S`` of an
  O(M·H·r) term with r ≪ H, noise next to the base projections.  All
  staged blocks equal their array dims except the row tile, so the
  Mosaic (8, 128) preflight (re-checked against the EXACT blocks, the
  ``wq_matmul`` pattern) passes for any lane-aligned H/O and falls back
  warn-once to the XLA gather otherwise.

Serving-only: no VJP is defined (adapter pages are inference-time state;
training a LoRA happens upstream of the pool).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.wq_matmul import (_pick, _preflight, _sublane,
                                         _warned_shapes)

# trace-time counter: how many pallas-kernel calls were STAGED (tests assert
# the kernel path engaged instead of the silent gather fallback)
trace_counts = {"lora": 0}


def _shapes_ok(x, a_pages, b_pages, adapter_ids, scales) -> bool:
    if x.ndim != 2 or a_pages.ndim != 3 or b_pages.ndim != 3:
        return False
    s, h, r = a_pages.shape
    if b_pages.shape[:2] != (s, r) or x.shape[1] != h:
        return False
    if adapter_ids.ndim != 1 or adapter_ids.shape[0] != x.shape[0]:
        return False
    return scales.ndim == 1 and scales.shape[0] == s


def xla_lora_matmul(x, a_pages, b_pages, adapter_ids, scales, *,
                    interpret: Optional[bool] = None):
    """Gather reference: ``y[i] = (x[i] @ A[id_i]) @ B[id_i] · s[id_i]``.

    x [M, H], a_pages [S, H, r], b_pages [S, r, O], adapter_ids [M] int,
    scales [S] → [M, O] in ``x.dtype``.  Rank products accumulate in f32
    and cast back through the activation dtype between the two dots —
    the same rounding the Pallas kernel applies, so the two impls agree
    to accumulation order."""
    del interpret
    ids = adapter_ids.astype(jnp.int32)
    a = jnp.take(a_pages, ids, axis=0)               # [M, H, r]
    b = jnp.take(b_pages, ids, axis=0)               # [M, r, O]
    u = jnp.einsum("mh,mhr->mr", x, a,
                   preferred_element_type=jnp.float32)
    y = jnp.einsum("mr,mro->mo", u.astype(x.dtype), b,
                   preferred_element_type=jnp.float32)
    y = y * jnp.take(scales, ids).astype(jnp.float32)[:, None]
    return y.astype(x.dtype)


def lora_supported(x, a_pages, b_pages, adapter_ids, scales, *,
                   interpret: Optional[bool] = None) -> bool:
    """Kernel eligibility.  Every staged block equals its array dim except
    the padded row tile, so the only structural demands are 2-D/3-D
    layouts and a usable row divisor; unsupported layouts warn ONCE per
    shape (the ``wq_matmul`` rule: a silent fallback would let an
    operator benchmark 'the batched-gather kernel' while measuring the
    XLA gather)."""
    del interpret
    if not _shapes_ok(x, a_pages, b_pages, adapter_ids, scales):
        key = ("lora", tuple(x.shape), tuple(a_pages.shape),
               tuple(b_pages.shape))
        if key not in _warned_shapes:
            _warned_shapes.add(key)
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                "lora_matmul: layout x%s / A%s / B%s does not fit the "
                "batched-gather kernel (x [M,H], A [S,H,r], B [S,r,O], "
                "ids [M], scales [S]); falling back to the XLA gather",
                tuple(x.shape), tuple(a_pages.shape), tuple(b_pages.shape))
        return False
    return True


def _kernel(ids_ref, x_ref, a_ref, b_ref, s_ref, o_ref, acc, *, ns):
    """One (row-block, adapter-slot) grid step: dense delta for the block
    through slot ``js``'s pages, masked onto the matching rows.  f32
    accumulator across the slot dim (arbitrary semantics); the rank
    product casts back through the activation dtype between the two dots
    so bf16 activations ride the MXU's native multipliers (the
    ``wq_matmul`` finding: all-f32 dots ran BELOW the bf16 baseline)."""
    js = pl.program_id(1)

    @pl.when(js == 0)
    def _init():
        acc[...] = jnp.zeros(acc.shape, jnp.float32)

    x = x_ref[...]
    u = jax.lax.dot_general(x, a_ref[0], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    d = jax.lax.dot_general(u.astype(x.dtype), b_ref[0],
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    hit = (ids_ref[...] == js).astype(jnp.float32)   # [bm, 1] row mask
    acc[...] += d * (hit * s_ref[0, 0, 0].astype(jnp.float32))

    @pl.when(js == ns - 1)
    def _done():
        o_ref[...] = acc[...].astype(o_ref.dtype)


def pallas_lora_matmul(x, a_pages, b_pages, adapter_ids, scales, *,
                       interpret: Optional[bool] = None):
    """Batched-gather LoRA delta with the adapter tables resident in HBM —
    one kernel for the whole mixed-adapter batch."""
    if not lora_supported(x, a_pages, b_pages, adapter_ids, scales):
        return xla_lora_matmul(x, a_pages, b_pages, adapter_ids, scales)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s, h, r = a_pages.shape
    o = b_pages.shape[2]
    m0 = x.shape[0]
    pad = (-m0) % _sublane(x.dtype)     # decode token counts tile to rows
    m = m0 + pad
    bm = _pick(m, 256)
    if not _preflight("lora_matmul", [
            (None if bm is None else (bm, h), (m, h)),
            (None if bm is None else (bm, 1), (m, 1)),
            ((1, h, r), (s, h, r)), ((1, r, o), (s, r, o)),
            ((1, 1, 1), (s, 1, 1)),
            (None if bm is None else (bm, o), (m, o))], interpret):
        return xla_lora_matmul(x, a_pages, b_pages, adapter_ids, scales)
    trace_counts["lora"] += 1
    ids = adapter_ids.astype(jnp.int32)[:, None]     # [M, 1] sublane-tiled
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        ids = jnp.pad(ids, ((0, pad), (0, 0)), constant_values=-1)
    out = pl.pallas_call(
        functools.partial(_kernel, ns=s),
        grid=(m // bm, s),
        in_specs=[
            pl.BlockSpec((bm, 1), lambda im, js: (im, 0)),
            pl.BlockSpec((bm, h), lambda im, js: (im, 0)),
            pl.BlockSpec((1, h, r), lambda im, js: (js, 0, 0)),
            pl.BlockSpec((1, r, o), lambda im, js: (js, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda im, js: (js, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, o), lambda im, js: (im, 0)),
        out_shape=jax.ShapeDtypeStruct((m, o), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, o), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(ids, x, a_pages, b_pages, scales[:, None, None])
    return out[:m0] if pad else out
