"""LM cross-entropy — chunked/rematerialized softmax over the vocab.

The fp32 [B, T, V] logits of a GPT-2-scale vocab dominate activation memory
(B=32, T=1024, V=50304 → 6.6 GB fp32 counting logits + log-probs).  The
reference never materializes this on the optimizer side but pays it in the torch
autograd graph; here we scan over token chunks with ``jax.checkpoint`` so the
backward pass recomputes each chunk's logits instead of storing them —
the rematerialization trade the reference makes with activation checkpointing
(runtime/activation_checkpointing/checkpointing.py), applied to the unembed.

Peak logits memory drops to O(chunk_size × V) regardless of B×T.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _chunk_loss(x, w, labels, mask, bias=None):
    """Sum NLL over one flat chunk of tokens.  x:[C,H] w:[H,V] labels/mask:[C]."""
    logits = (x @ w).astype(jnp.float32)            # [C, V]
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)         # [C]
    ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.sum((lse - ll) * mask)


def masked_nll_sum(x, unembed, labels, mask, bias=None):
    """Sum of masked token NLLs of ``x @ unembed`` (no mean) — the shared loss
    body for callers that aggregate their own denominator across microbatches
    (pipe/module.py's per-microbatch scan).  x: [..., H]; labels/mask: [...]."""
    h = x.shape[-1]
    return _chunk_loss(x.reshape(-1, h), unembed,
                       labels.reshape(-1).astype(jnp.int32),
                       mask.reshape(-1).astype(jnp.float32), bias)


def lm_cross_entropy(x, unembed, labels, mask,
                     chunk_size: Optional[int] = 512, bias=None):
    """Mean masked cross entropy of ``x @ unembed (+ bias)`` against
    ``labels``.

    x: [B, T, H] hidden states; unembed: [H, V]; labels/mask: [B, T];
    bias: optional [V] unembed bias (phi-style lm_head).
    ``chunk_size=None`` computes the loss in one shot (ground truth path).
    """
    b, t, h = x.shape
    n = b * t
    xf = x.reshape(n, h)
    lf = labels.reshape(n).astype(jnp.int32)
    mf = mask.reshape(n).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mf), 1.0)

    if not chunk_size or chunk_size >= n:
        return _chunk_loss(xf, unembed, lf, mf, bias) / denom

    c = int(chunk_size)
    pad = (-n) % c
    if pad:
        xf = jnp.pad(xf, ((0, pad), (0, 0)))
        lf = jnp.pad(lf, (0, pad))
        mf = jnp.pad(mf, (0, pad))               # padded tokens carry mask 0
    num_chunks = xf.shape[0] // c
    xc = xf.reshape(num_chunks, c, h)
    lc = lf.reshape(num_chunks, c)
    mc = mf.reshape(num_chunks, c)

    chunk_fn = jax.checkpoint(_chunk_loss)

    def body(total, inputs):
        xi, li, mi = inputs
        return total + chunk_fn(xi, unembed, li, mi, bias), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (xc, lc, mc))
    return total / denom
