"""LM cross-entropy — chunked softmax over the vocab, with a fused-gradient
fast path.

The fp32 [B, T, V] logits of a GPT-2-scale vocab dominate activation memory
(B=32, T=1024, V=50304 → 6.6 GB fp32 counting logits + log-probs).  The
reference never materializes this on the optimizer side but pays it in the torch
autograd graph; here we scan over token chunks so peak logits memory is
O(chunk_size × V) regardless of B×T.  Two chunked strategies:

- ``jax.checkpoint`` remat (the round-1 path, kept as ground truth): backward
  recomputes each chunk's logits — 4 unembed-GEMM units per step (fwd, remat
  fwd, dgrad, wgrad).
- **fused** (default): a ``custom_vjp`` whose FORWARD pass computes the loss
  AND both gradients per chunk — ``dlogits = softmax − onehot`` never leaves
  the chunk: ``gx = dlogits @ Wᵀ`` and ``dW += xᵀ @ dlogits`` are accumulated
  on the spot and the backward is just a scale by the upstream cotangent.
  3 unembed-GEMM units (the autodiff minimum) at chunked memory — strictly
  less work than remat, and the [B, T, V] logits never hit HBM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _chunk_loss(x, w, labels, mask, bias=None):
    """Sum NLL over one flat chunk of tokens.  x:[C,H] w:[H,V] labels/mask:[C]."""
    logits = (x @ w).astype(jnp.float32)            # [C, V]
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)         # [C]
    ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.sum((lse - ll) * mask)


def masked_nll_sum(x, unembed, labels, mask, bias=None):
    """Sum of masked token NLLs of ``x @ unembed`` (no mean) — the shared loss
    body for callers that aggregate their own denominator across microbatches
    (pipe/module.py's per-microbatch scan).  x: [..., H]; labels/mask: [...]."""
    h = x.shape[-1]
    return _chunk_loss(x.reshape(-1, h), unembed,
                       labels.reshape(-1).astype(jnp.int32),
                       mask.reshape(-1).astype(jnp.float32), bias)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused_nll_sum(xc, w, lc, mc, bias, c):
    """Sum NLL over pre-chunked tokens (xc: [num, c, H]) with gradients
    computed IN the forward chunk loop (see module docstring)."""
    total, _ = _fused_fwd(xc, w, lc, mc, bias, c)
    return total


def _fused_fwd(xc, w, lc, mc, bias, c):
    def body(dw_dbias, inputs):
        dw, dbias = dw_dbias
        xi, li, mi = inputs                         # [c,H] [c] [c]
        logits = (xi @ w).astype(jnp.float32)       # [c, V]
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, li[:, None], axis=-1)[:, 0]
        loss_i = jnp.sum((lse - ll) * mi)
        # dlogits of the masked NLL SUM: (softmax - onehot) * mask; softmax
        # reuses the lse so there is no second max/sum pass over the logits
        p = jnp.exp(logits - lse[:, None])
        p = (p - jax.nn.one_hot(li, logits.shape[-1],
                                dtype=jnp.float32)) * mi[:, None]
        pb = p.astype(w.dtype)                      # MXU-friendly matmuls
        gx_i = pb @ w.T                             # [c, H]
        dw = dw + (xi.T @ pb).astype(jnp.float32)   # [H, V] fp32 accumulator
        if bias is not None:
            dbias = dbias + jnp.sum(p, axis=0)
        return (dw, dbias), (loss_i, gx_i, lse - ll)

    dw0 = jnp.zeros(w.shape, jnp.float32)
    dbias0 = (jnp.zeros(bias.shape, jnp.float32)
              if bias is not None else jnp.float32(0.0))
    (dw, dbias), (losses, gx, gm) = jax.lax.scan(
        body, (dw0, dbias0), (xc, lc, mc))
    total = jnp.sum(losses)
    # cotangents must land in the primals' dtypes (fp32 accumulation above)
    dbias = dbias.astype(bias.dtype) if bias is not None else dbias
    return total, (gx.astype(xc.dtype), dw.astype(w.dtype), dbias, gm)


def _fused_bwd(c, res, g):
    import numpy as np
    gx, dw, dbias, gm = res
    bias_ct = ((g.astype(dbias.dtype) * dbias) if dbias.ndim else None)
    return (gx * g.astype(gx.dtype), g.astype(dw.dtype) * dw,
            np.zeros(gx.shape[:2], dtype=jax.dtypes.float0),    # labels
            g.astype(gm.dtype) * gm,    # mask: d(nll_sum)/dm = lse - ll
            bias_ct)


_fused_nll_sum.defvjp(_fused_fwd, _fused_bwd)


def lm_cross_entropy(x, unembed, labels, mask,
                     chunk_size: Optional[int] = 512, bias=None,
                     fused: bool = True):
    """Mean masked cross entropy of ``x @ unembed (+ bias)`` against
    ``labels``.

    x: [B, T, H] hidden states; unembed: [H, V]; labels/mask: [B, T];
    bias: optional [V] unembed bias (phi-style lm_head).
    ``chunk_size=None`` computes the loss in one shot (ground truth path).
    ``fused`` picks the in-forward-gradient chunk loop over jax.checkpoint
    remat (same numerics, one fewer unembed GEMM per step).
    """
    b, t, h = x.shape
    n = b * t
    xf = x.reshape(n, h)
    lf = labels.reshape(n).astype(jnp.int32)
    mf = mask.reshape(n).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mf), 1.0)

    if not chunk_size or chunk_size >= n:
        return _chunk_loss(xf, unembed, lf, mf, bias) / denom

    c = int(chunk_size)
    pad = (-n) % c
    if pad:
        xf = jnp.pad(xf, ((0, pad), (0, 0)))
        lf = jnp.pad(lf, (0, pad))
        mf = jnp.pad(mf, (0, pad))               # padded tokens carry mask 0
    num_chunks = xf.shape[0] // c
    xc = xf.reshape(num_chunks, c, h)
    lc = lf.reshape(num_chunks, c)
    mc = mf.reshape(num_chunks, c)

    if fused:
        return _fused_nll_sum(xc, unembed, lc, mc, bias, c) / denom

    chunk_fn = jax.checkpoint(_chunk_loss)

    def body(total, inputs):
        xi, li, mi = inputs
        return total + chunk_fn(xi, unembed, li, mi, bias), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (xc, lc, mc))
    return total / denom
