"""Native-op JIT builder — compile C++ host ops with g++ at first use.

Analog of the reference op_builder (op_builder/builder.py:108 OpBuilder,
jit_load :510): the reference JIT-compiles CUDA/C++ extensions through torch's
cpp_extension; here host ops are plain shared objects built with g++ and bound
through ctypes (pybind11 isn't in the image).  Build artifacts are cached under
``csrc/_build`` keyed by a source hash, so rebuilds happen only when the source
changes.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional

from deepspeed_tpu.utils.logging import logger

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "csrc")
_BUILD = os.path.join(_CSRC, "_build")
_cache = {}


def build_error(name: str) -> Optional[str]:
    """Why the native op isn't available (None if it built fine)."""
    try:
        load_op(name)
        return None
    except Exception as e:  # noqa: BLE001
        return str(e)


def load_op(name: str, extra_flags: Optional[list] = None) -> ctypes.CDLL:
    """Compile (if stale) and dlopen ``csrc/<name>.cpp``."""
    if name in _cache:
        return _cache[name]
    src = os.path.join(_CSRC, f"{name}.cpp")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    os.makedirs(_BUILD, exist_ok=True)
    so = os.path.join(_BUILD, f"{name}-{digest}.so")
    if not os.path.exists(so):
        cmd = ["g++", "-O3", "-march=native", "-fPIC", "-shared", "-std=c++17",
               "-o", so + ".tmp", src, "-lpthread"] + (extra_flags or [])
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native op {name} failed to compile: {e.stderr}") from e
        os.replace(so + ".tmp", so)
        logger.info(f"built native op {name} -> {so}")
    lib = ctypes.CDLL(so)
    _cache[name] = lib
    return lib
