"""Block-sparse attention — sparsity patterns + layout-masked attention.

Reference parity: ``deepspeed/ops/sparse_attention/`` — ``SparsityConfig``
family (sparsity_config.py: Fixed, BigBird, BSLongformer, Variable) and the
block-sparse ``SparseSelfAttention`` (sparse_self_attention.py) built on
Triton matmul/softmax kernels (matmul.py, softmax.py).

TPU-native: the sparsity pattern is a STATIC [nb, nb] block layout computed
on the host; attention applies it as a block-expanded mask through the ops
attention path, which XLA fuses (the masked dense form — correct everywhere).
A Pallas kernel that *skips* dead blocks entirely (flash-style inner loop over
each row-block's active blocks, the Triton analog) is the designated fast
path for long sequences; the layout contract here is what it will consume.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SparsityConfig:
    """Base pattern config (reference sparsity_config.py:15)."""

    block: int = 16
    different_layout_per_head: bool = False   # parity knob; one layout here

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class DenseSparsityConfig(SparsityConfig):
    """All blocks active (reference DenseSparsityConfig) — debugging/parity."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        nb = _nblocks(seq_len, self.block)
        return np.ones((nb, nb), bool)


@dataclasses.dataclass(frozen=True)
class FixedSparsityConfig(SparsityConfig):
    """Local windows + periodic global blocks
    (reference FixedSparsityConfig:67, the Sparse-Transformer 'fixed'
    pattern)."""

    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        nb = _nblocks(seq_len, self.block)
        lay = np.zeros((nb, nb), bool)
        nl, ng = self.num_local_blocks, self.num_global_blocks
        for i in range(nb):
            w0 = (i // nl) * nl
            lay[i, w0:i + 1] = True              # local window (causal)
        # last ng blocks of every preceding window attend globally
        for w0 in range(0, nb, nl):
            g0 = max(w0 + nl - ng, 0)
            for i in range(nb):
                if i >= w0 + nl:
                    lay[i, g0:w0 + nl] = True
        return lay


@dataclasses.dataclass(frozen=True)
class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + designated global blocks
    (reference BSLongformerSparsityConfig:296)."""

    num_sliding_window_blocks: int = 3
    global_block_indices: tuple = (0,)

    def make_layout(self, seq_len: int) -> np.ndarray:
        nb = _nblocks(seq_len, self.block)
        lay = np.zeros((nb, nb), bool)
        w = self.num_sliding_window_blocks
        for i in range(nb):
            lay[i, max(0, i - w + 1):i + 1] = True
        for g in self.global_block_indices:
            if g < nb:
                lay[:, g] = True                  # everyone sees global
                lay[g, :] = True                  # global sees everyone
        return lay


@dataclasses.dataclass(frozen=True)
class BigBirdSparsityConfig(SparsityConfig):
    """Random + window + global blocks (reference BigBirdSparsityConfig:218).

    Random blocks are drawn with a fixed seed so the layout is deterministic
    per (seq_len, config) — the layout must be static under jit."""

    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        nb = _nblocks(seq_len, self.block)
        lay = np.zeros((nb, nb), bool)
        w = self.num_sliding_window_blocks
        rng = np.random.default_rng(self.seed)
        for i in range(nb):
            lay[i, max(0, i - w + 1):i + 1] = True
            lo = min(i + 1, nb)
            if lo > 0 and self.num_random_blocks:
                picks = rng.choice(lo, min(self.num_random_blocks, lo),
                                   replace=False)
                lay[i, picks] = True
        g = self.num_global_blocks
        lay[:, :g] = True
        lay[:g, :] = True
        return lay


def _nblocks(seq_len: int, block: int) -> int:
    if seq_len % block:
        raise ValueError(f"seq_len {seq_len} not divisible by block {block}")
    return seq_len // block


def expand_layout_mask(layout: np.ndarray, block: int,
                       causal: bool = True) -> np.ndarray:
    """[nb, nb] block layout → [T, T] boolean attention mask (∧ causal)."""
    mask = np.kron(layout, np.ones((block, block), bool))
    if causal:
        T = mask.shape[0]
        mask &= np.tril(np.ones((T, T), bool))
    return mask


def sparse_attention(q, k, v, config: SparsityConfig, *,
                     causal: bool = True, dropout_fn=None,
                     impl: Optional[str] = None):
    """Block-sparse attention on [B, T, N, D] (reference
    SparseSelfAttention.forward): the static layout masks the score matrix;
    fully-masked rows would be NaN, so the layout always includes the
    diagonal (every pattern above does)."""
    T = q.shape[1]
    layout = config.make_layout(T)
    mask = jnp.asarray(expand_layout_mask(layout, config.block, causal))
    from deepspeed_tpu import ops
    return ops.causal_attention(q, k, v, causal=False,
                                mask=jnp.broadcast_to(mask, (q.shape[0],) +
                                                      mask.shape),
                                dropout_fn=dropout_fn, impl=impl)


def sparsity_ratio(config: SparsityConfig, seq_len: int,
                   causal: bool = True) -> float:
    """Fraction of ACTIVE attention entries — the compute/memory saving a
    block-skipping kernel realizes."""
    m = expand_layout_mask(config.make_layout(seq_len), config.block, causal)
    denom = np.tril(np.ones(m.shape, bool)).sum() if causal else m.size
    return float(m.sum() / denom)
