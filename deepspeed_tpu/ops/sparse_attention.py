"""Block-sparse attention — sparsity patterns + a block-SKIPPING kernel.

Reference parity: ``deepspeed/ops/sparse_attention/`` — ``SparsityConfig``
family (sparsity_config.py: Fixed, BigBird, BSLongformer, Variable) and the
block-sparse ``SparseSelfAttention`` (sparse_self_attention.py) built on
Triton matmul/softmax kernels (matmul.py SDD/DSD skip dead blocks,
softmax.py).

TPU-native: the sparsity pattern is a STATIC [nb, nb] block layout computed
on the host.  Two implementations:

- masked dense (XLA): the layout expands to a [T, T] mask through the ops
  attention path — correct everywhere, zero FLOPs saved (the round-2 form).
- Pallas block-sparse flash (round 3, VERDICT item 5): per row-block the
  kernel iterates ONLY that row's active column blocks via scalar-prefetched
  index tables (the Triton ``lut`` analog), with online softmax; the
  backward runs the same tables row-major for dq and a transposed table
  col-major for dk/dv.  FLOPs and K/V bandwidth scale with the layout
  density — ``sparsity_ratio()`` is the measured saving.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


@dataclasses.dataclass(frozen=True)
class SparsityConfig:
    """Base pattern config (reference sparsity_config.py:15)."""

    block: int = 16
    different_layout_per_head: bool = False   # parity knob; one layout here

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class DenseSparsityConfig(SparsityConfig):
    """All blocks active (reference DenseSparsityConfig) — debugging/parity."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        nb = _nblocks(seq_len, self.block)
        return np.ones((nb, nb), bool)


@dataclasses.dataclass(frozen=True)
class FixedSparsityConfig(SparsityConfig):
    """Local windows + periodic global blocks
    (reference FixedSparsityConfig:67, the Sparse-Transformer 'fixed'
    pattern)."""

    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        nb = _nblocks(seq_len, self.block)
        lay = np.zeros((nb, nb), bool)
        nl, ng = self.num_local_blocks, self.num_global_blocks
        for i in range(nb):
            w0 = (i // nl) * nl
            lay[i, w0:i + 1] = True              # local window (causal)
        # last ng blocks of every preceding window attend globally
        for w0 in range(0, nb, nl):
            g0 = max(w0 + nl - ng, 0)
            for i in range(nb):
                if i >= w0 + nl:
                    lay[i, g0:w0 + nl] = True
        return lay


@dataclasses.dataclass(frozen=True)
class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + designated global blocks
    (reference BSLongformerSparsityConfig:296)."""

    num_sliding_window_blocks: int = 3
    global_block_indices: tuple = (0,)

    def make_layout(self, seq_len: int) -> np.ndarray:
        nb = _nblocks(seq_len, self.block)
        lay = np.zeros((nb, nb), bool)
        w = self.num_sliding_window_blocks
        for i in range(nb):
            lay[i, max(0, i - w + 1):i + 1] = True
        for g in self.global_block_indices:
            if g < nb:
                lay[:, g] = True                  # everyone sees global
                lay[g, :] = True                  # global sees everyone
        return lay


@dataclasses.dataclass(frozen=True)
class BigBirdSparsityConfig(SparsityConfig):
    """Random + window + global blocks (reference BigBirdSparsityConfig:218).

    Random blocks are drawn with a fixed seed so the layout is deterministic
    per (seq_len, config) — the layout must be static under jit."""

    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        nb = _nblocks(seq_len, self.block)
        lay = np.zeros((nb, nb), bool)
        w = self.num_sliding_window_blocks
        rng = np.random.default_rng(self.seed)
        for i in range(nb):
            lay[i, max(0, i - w + 1):i + 1] = True
            lo = min(i + 1, nb)
            if lo > 0 and self.num_random_blocks:
                picks = rng.choice(lo, min(self.num_random_blocks, lo),
                                   replace=False)
                lay[i, picks] = True
        g = self.num_global_blocks
        lay[:, :g] = True
        lay[:g, :] = True
        return lay


def _nblocks(seq_len: int, block: int) -> int:
    if seq_len % block:
        raise ValueError(f"seq_len {seq_len} not divisible by block {block}")
    return seq_len // block


def expand_layout_mask(layout: np.ndarray, block: int,
                       causal: bool = True) -> np.ndarray:
    """[nb, nb] block layout → [T, T] boolean attention mask (∧ causal)."""
    mask = np.kron(layout, np.ones((block, block), bool))
    if causal:
        T = mask.shape[0]
        mask &= np.tril(np.ones((T, T), bool))
    return mask


_NEG_INF = -1e30


def _layout_tables(layout: np.ndarray, causal: bool):
    """[nb, nb] layout → (row-major cols table, counts; col-major rows table,
    counts) padded with each entry's last valid index (repeated indices keep
    Pallas from issuing fresh DMAs on dead steps)."""
    lay = layout.astype(bool).copy()
    if causal:
        lay &= np.tril(np.ones(lay.shape, bool))
    nb = lay.shape[0]
    max_r = max(1, int(lay.sum(1).max()))
    max_c = max(1, int(lay.sum(0).max()))
    cols = np.zeros((nb, max_r), np.int32)
    nact_r = lay.sum(1).astype(np.int32)
    rows = np.zeros((nb, max_c), np.int32)
    nact_c = lay.sum(0).astype(np.int32)
    for i in range(nb):
        idx = np.flatnonzero(lay[i])
        if idx.size:
            cols[i, :idx.size] = idx
            cols[i, idx.size:] = idx[-1]
        jdx = np.flatnonzero(lay[:, i])
        if jdx.size:
            rows[i, :jdx.size] = jdx
            rows[i, jdx.size:] = jdx[-1]
    return cols, nact_r, rows, nact_c


def _sp_tile(q, k, iq, jb, bs, scale, causal):
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = iq * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        kpos = jb * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    return s


def _sp_fwd_kernel(cols_ref, nact_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, *, bs, scale, causal):
    iq, a = pl.program_id(2), pl.program_id(3)
    na = pl.num_programs(3)

    @pl.when(a == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(a < nact_ref[iq])
    def _body():
        jb = cols_ref[iq, a]
        s = _sp_tile(q_ref[0, 0], k_ref[0, 0], iq, jb, bs, scale, causal)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)   # exotic layouts guard
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0, 0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(a == na - 1)
    def _finalize():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0, 0] = (m_scr[:, :1] + jnp.log(l))[:, 0]


def _sp_dq_kernel(cols_ref, nact_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                  delta_ref, dq_ref, dq_scr, *, bs, scale, causal):
    iq, a = pl.program_id(2), pl.program_id(3)
    na = pl.num_programs(3)

    @pl.when(a == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    @pl.when(a < nact_ref[iq])
    def _body():
        jb = cols_ref[iq, a]
        k = k_ref[0, 0]
        s = _sp_tile(q_ref[0, 0], k, iq, jb, bs, scale, causal)
        lse = lse_ref[0, 0, 0][:, None]
        p = jnp.exp(s - lse)
        p = jnp.where(lse > _NEG_INF / 2, p, 0.0)
        dp = jax.lax.dot_general(do_ref[0, 0], v_ref[0, 0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0, 0][:, None]) * scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(a == na - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _sp_dkv_kernel(rows_ref, nact_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, bs, scale,
                   causal, max_c):
    ik, t = pl.program_id(2), pl.program_id(3)
    nt = pl.num_programs(3)
    a = t % max_c                       # active-row step within the GQA head

    @pl.when(t == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    @pl.when(a < nact_ref[ik])
    def _body():
        ib = rows_ref[ik, a]
        q = q_ref[0, 0]
        s = _sp_tile(q, k_ref[0, 0], ib, ik, bs, scale, causal)
        lse = lse_ref[0, 0, 0][:, None]
        p = jnp.exp(s - lse)
        p = jnp.where(lse > _NEG_INF / 2, p, 0.0)
        do = do_ref[0, 0]
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0, 0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0, 0][:, None]) * scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


@functools.lru_cache(maxsize=64)
def _make_block_sparse_fn(layout_key, nb, bs, causal, scale, interpret):
    """Build (and cache) the custom_vjp block-sparse attention for one static
    layout — caching keeps the function identity stable so jit caches the
    enclosing trace."""
    layout = np.frombuffer(layout_key, bool).reshape(nb, nb)
    cols, nact_r, rows, nact_c = _layout_tables(layout, causal)
    max_r, max_c = cols.shape[1], rows.shape[1]
    cols_j, nr_j = jnp.asarray(cols), jnp.asarray(nact_r)
    rows_j, nc_j = jnp.asarray(rows), jnp.asarray(nact_c)

    def fwd_impl(q, k, v):
        b, n, t, d = q.shape
        group = n // k.shape[1]
        o, lse = pl.pallas_call(
            functools.partial(_sp_fwd_kernel, bs=bs, scale=scale,
                              causal=causal),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b, n, nb, max_r),
                in_specs=[
                    pl.BlockSpec((1, 1, bs, d),
                                 lambda b_, h, iq, a, c, na: (b_, h, iq, 0)),
                    pl.BlockSpec((1, 1, bs, d),
                                 lambda b_, h, iq, a, c, na:
                                 (b_, h // group, c[iq, a], 0)),
                    pl.BlockSpec((1, 1, bs, d),
                                 lambda b_, h, iq, a, c, na:
                                 (b_, h // group, c[iq, a], 0)),
                ],
                out_specs=[
                    pl.BlockSpec((1, 1, bs, d),
                                 lambda b_, h, iq, a, c, na: (b_, h, iq, 0)),
                    pl.BlockSpec((1, 1, 1, bs),
                                 lambda b_, h, iq, a, c, na: (b_, h, 0, iq)),
                ],
                scratch_shapes=[
                    pltpu.VMEM((bs, 128), jnp.float32),
                    pltpu.VMEM((bs, 128), jnp.float32),
                    pltpu.VMEM((bs, d), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((b, n, t, d), q.dtype),
                jax.ShapeDtypeStruct((b, n, 1, t), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(cols_j, nr_j, q, k, v)
        return o, lse

    def bwd_impl(q, k, v, o, lse, do):
        b, n, t, d = q.shape
        nkv = k.shape[1]
        group = n // nkv
        delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                        axis=-1)[:, :, None, :]
        q_spec = pl.BlockSpec((1, 1, bs, d),
                              lambda b_, h, iq, a, c, na: (b_, h, iq, 0))
        kv_spec = pl.BlockSpec((1, 1, bs, d),
                               lambda b_, h, iq, a, c, na:
                               (b_, h // group, c[iq, a], 0))
        row_spec = pl.BlockSpec((1, 1, 1, bs),
                                lambda b_, h, iq, a, c, na: (b_, h, 0, iq))
        dq = pl.pallas_call(
            functools.partial(_sp_dq_kernel, bs=bs, scale=scale,
                              causal=causal),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b, n, nb, max_r),
                in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec,
                          row_spec],
                out_specs=q_spec,
                scratch_shapes=[pltpu.VMEM((bs, d), jnp.float32)],
            ),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(cols_j, nr_j, q, k, v, do, lse, delta)

        # col-major pass: grid dim 3 fuses (q-head-in-group, active row)
        q_spec2 = pl.BlockSpec(
            (1, 1, bs, d),
            lambda b_, h, ik, tt, r, na:
            (b_, h * group + tt // max_c, r[ik, tt % max_c], 0))
        kv_spec2 = pl.BlockSpec((1, 1, bs, d),
                                lambda b_, h, ik, tt, r, na: (b_, h, ik, 0))
        row_spec2 = pl.BlockSpec(
            (1, 1, 1, bs),
            lambda b_, h, ik, tt, r, na:
            (b_, h * group + tt // max_c, 0, r[ik, tt % max_c]))
        dk, dv = pl.pallas_call(
            functools.partial(_sp_dkv_kernel, bs=bs, scale=scale,
                              causal=causal, max_c=max_c),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b, nkv, nb, group * max_c),
                in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2,
                          row_spec2],
                out_specs=[kv_spec2, kv_spec2],
                scratch_shapes=[pltpu.VMEM((bs, d), jnp.float32),
                                pltpu.VMEM((bs, d), jnp.float32)],
            ),
            out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(rows_j, nc_j, q, k, v, do, lse, delta)
        return dq, dk, dv

    @jax.custom_vjp
    def attend(q, k, v):
        o, _ = fwd_impl(q, k, v)
        return o

    def attend_fwd(q, k, v):
        o, lse = fwd_impl(q, k, v)
        return o, (q, k, v, o, lse)

    def attend_bwd(res, do):
        q, k, v, o, lse = res
        return bwd_impl(q, k, v, o, lse, do)

    attend.defvjp(attend_fwd, attend_bwd)
    return attend


def block_sparse_flash(q, k, v, config: SparsityConfig, *,
                       causal: bool = True,
                       scale: Optional[float] = None,
                       interpret: Optional[bool] = None):
    """Block-skipping sparse attention on [B, T, N, D] — FLOPs scale with the
    layout's active fraction (``sparsity_ratio``)."""
    T, d = q.shape[1], q.shape[3]
    layout = config.make_layout(T)
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    fn = _make_block_sparse_fn(layout.astype(bool).tobytes(),
                               layout.shape[0], config.block, bool(causal),
                               float(scale), bool(interpret))
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    return jnp.transpose(fn(qt, kt, vt), (0, 2, 1, 3))


def block_sparse_supported(q, k, v, config: SparsityConfig, *,
                           causal: bool = True, dropout_fn=None, **_):
    if dropout_fn is not None or q.ndim != 4:
        return False
    T, d = q.shape[1], q.shape[3]
    return (config.block % 8 == 0 and d % 8 == 0 and T % config.block == 0
            and q.shape[2] % k.shape[2] == 0)


def sparse_attention(q, k, v, config: SparsityConfig, *,
                     causal: bool = True, dropout_fn=None,
                     impl: Optional[str] = None):
    """Block-sparse attention on [B, T, N, D] (reference
    SparseSelfAttention.forward): the static layout masks the score matrix;
    fully-masked rows would be NaN, so the layout always includes the
    diagonal (every pattern above does).  Dispatches to the block-skipping
    Pallas kernel when supported (registry gating), else the masked-dense
    XLA path."""
    from deepspeed_tpu.ops.registry import dispatch
    return dispatch("sparse_attention", q, k, v, config, causal=causal,
                    dropout_fn=dropout_fn, impl=impl)


def _sparse_xla(q, k, v, config: SparsityConfig, *, causal: bool = True,
                dropout_fn=None, interpret=None):
    T = q.shape[1]
    layout = config.make_layout(T)
    mask = jnp.asarray(expand_layout_mask(layout, config.block, causal))
    from deepspeed_tpu import ops
    return ops.causal_attention(q, k, v, causal=False,
                                mask=jnp.broadcast_to(mask, (q.shape[0],) +
                                                      mask.shape),
                                dropout_fn=dropout_fn, impl="xla")


def _sparse_pallas(q, k, v, config: SparsityConfig, *, causal: bool = True,
                   dropout_fn=None, interpret=None):
    if dropout_fn is not None:
        raise ValueError("the block-sparse kernel has no probs-dropout; "
                         "use impl='xla'")
    return block_sparse_flash(q, k, v, config, causal=causal,
                              interpret=interpret)


def sparsity_ratio(config: SparsityConfig, seq_len: int,
                   causal: bool = True) -> float:
    """Fraction of ACTIVE attention entries — the compute/memory saving a
    block-skipping kernel realizes."""
    m = expand_layout_mask(config.make_layout(seq_len), config.block, causal)
    denom = np.tril(np.ones(m.shape, bool)).sum() if causal else m.size
    return float(m.sum() / denom)
