"""Op registry — TPU-native analog of the reference's op_builder system.

The reference selects between JIT-compiled CUDA ops and fallbacks via
``OpBuilder.load()`` (reference op_builder/builder.py:108,491,510) and reports
compatibility via ``ds_report`` (env_report.py:30).  On TPU the axis of choice is
*Pallas kernel vs plain-XLA lowering* of the same math: every op registered here
carries an ``xla`` reference implementation (always available, also the numeric
ground truth in tests) and optionally a ``pallas`` fast path with a
``supported(*args, **kw)`` predicate.

Dispatch happens at trace time: the pallas path is taken when (a) it exists,
(b) the default backend is TPU (or interpret mode is forced), (c) the shape/dtype
predicate accepts, and (d) it isn't disabled via env ``DSTPU_DISABLE_PALLAS=1``
or per-call ``impl="xla"`` — the analog of the reference's ``DS_BUILD_*`` flags.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import logger


@dataclasses.dataclass
class OpSpec:
    name: str
    xla: Callable
    pallas: Optional[Callable] = None
    supported: Optional[Callable[..., bool]] = None  # shape/dtype predicate

    def available_impls(self):
        impls = ["xla"]
        if self.pallas is not None:
            impls.insert(0, "pallas")
        return impls


_REGISTRY: Dict[str, OpSpec] = {}


def register_op(name: str, *, xla: Callable, pallas: Optional[Callable] = None,
                supported: Optional[Callable[..., bool]] = None) -> OpSpec:
    spec = OpSpec(name=name, xla=xla, pallas=pallas, supported=supported)
    _REGISTRY[name] = spec
    return spec


def pallas_enabled() -> bool:
    return os.environ.get("DSTPU_DISABLE_PALLAS", "0") != "1"


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend not initialized yet
        return False


def dispatch(name: str, *args, impl: Optional[str] = None, **kwargs) -> Any:
    """Call op ``name``, choosing the best implementation.

    ``impl`` forces "pallas" or "xla" (forcing pallas off-TPU runs the kernel in
    interpret mode — used by the numeric unit tests).
    """
    spec = _REGISTRY[name]
    if impl not in (None, "pallas", "xla"):
        raise ValueError(f"unknown impl {impl!r} for op {name!r}; "
                         f"expected 'pallas', 'xla', or None (auto)")
    if impl == "xla" or spec.pallas is None:
        return spec.xla(*args, **kwargs)
    if impl == "pallas":
        return spec.pallas(*args, **kwargs)
    if (pallas_enabled() and _on_tpu()
            and (spec.supported is None or spec.supported(*args, **kwargs))):
        return spec.pallas(*args, **kwargs)
    return spec.xla(*args, **kwargs)


def would_use_pallas(name: str) -> bool:
    """True when dispatch(name, ...) would consider the Pallas path at all
    (before the per-call shape predicate).  Engines that must pre-commit a
    layout/shape choice to satisfy a kernel's constraints (e.g. the v2
    engine's kv page size) ask HERE instead of re-deriving the gate."""
    spec = _REGISTRY.get(name)
    return (spec is not None and spec.pallas is not None
            and pallas_enabled() and _on_tpu())


def op_report() -> str:
    """``ds_report``-style op compatibility matrix (reference env_report.py)."""
    lines = ["op name".ljust(28) + "impls".ljust(16) + "selected"]
    on_tpu = _on_tpu()
    for name, spec in sorted(_REGISTRY.items()):
        sel = ("pallas" if spec.pallas is not None and pallas_enabled() and on_tpu
               else "xla")
        lines.append(name.ljust(28) + ",".join(spec.available_impls()).ljust(16)
                     + sel)
    return "\n".join(lines)


def list_ops():
    return dict(_REGISTRY)
