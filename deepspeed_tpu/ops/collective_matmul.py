"""Ring collective-matmul fusions — overlap TP collectives with the matmuls
that produce/consume them.

The TP hot path has two collective shapes (reference: Megatron-style
row/column-parallel linears, module_inject/auto_tp.py; here the matmuls in
linear.py / models/gpt.py):

- **all-gather → matmul**: activations sharded on a sequence/row dim must be
  gathered before a matmul consumes every row.
- **matmul → reduce-scatter / all-reduce**: a contraction-dim-sharded matmul
  produces partial sums that must be reduced (row-parallel linear).

XLA emits each as one blocking collective at the matmul boundary.  The
decomposition here (Wang et al. "Overlap Communication with Dependent
Computation via Decomposition", ASPLOS'23; T3 arXiv:2401.16677; the same
``ppermute`` ring ``sequence/ring.py`` uses for KV rotation) splits the
matmul into ``axis``-many chunk matmuls and replaces the collective with
neighbor ``ppermute`` hops issued BETWEEN them — each hop's wire time
overlaps the previous chunk's MXU time, and the scheduler needs no
heroics: the dependence structure itself is overlapped.

Selection rides the op registry (ops/registry.py) like every other op:
``xla`` is the unfused reference (the numeric ground truth — one collective
at the boundary, what GSPMD would do), and the fast path carries the ring
decomposition.  The fast slot is registered under the registry's ``pallas``
key for dispatch parity (TPU-gated auto selection, ``impl=`` forcing,
``DSTPU_DISABLE_PALLAS``) — it is a shard_map/ppermute program, not a
Pallas kernel, but the dispatch semantics are identical and the ring only
wins where ppermute rides ICI.

All entries are numerics-exact vs their unfused reference: the gather
fusion is pure data movement (bitwise); the reduce fusions sum the same
per-device partials in ring order (tolerance-exact — summation order may
differ from XLA's reduction tree).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.comm import comms_logger
from deepspeed_tpu.telemetry.registry import record_collective
from deepspeed_tpu.utils.compat import shard_map


def _batch_spec(b: int, mesh: Mesh, batch_axes: Tuple[str, ...]):
    """Batch-dim spec entry: the (dp, fsdp) product when it divides B, else
    replicated (serving-sized batches must not force a batch reshard)."""
    axes = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1)
    size = 1
    for a in axes:
        size *= mesh.shape[a]
    if not axes or b % size:
        return None
    return axes if len(axes) > 1 else axes[0]


def _check(cond: bool, msg: str):
    if not cond:
        raise ValueError(msg)


def _log_ring(kind: str, nbytes: int, axis: str):
    comms_logger.record(kind, nbytes, axis)
    # per-link split rides along, same as comm/collectives._log: ring
    # ppermute hops crossing a host boundary book as dcn, the rest ici
    from deepspeed_tpu.comm.collectives import axis_dcn_fraction
    record_collective(kind, nbytes, axis,
                      dcn_fraction=axis_dcn_fraction(axis))


# --------------------------------------------------------- all-gather → matmul

def _ag_matmul_xla(x, w, mesh, axis, batch_axes):
    """Unfused reference: one all-gather of x's sequence dim, then the full
    matmul — the boundary collective GSPMD inserts."""
    bspec = _batch_spec(x.shape[0], mesh, batch_axes)

    def body(xl, wl):
        xg = lax.all_gather(xl, axis, axis=1, tiled=True)
        return xg @ wl

    return shard_map(body, mesh=mesh,
                     in_specs=(P(bspec, axis, None), P(None, None)),
                     out_specs=P(bspec, None, None), check_vma=False)(x, w)


def _ag_matmul_ring(x, w, mesh, axis, batch_axes):
    """Fused ring: at step s each device matmuls the x block it currently
    holds (owner ``(me − s) mod n``) into that owner's output rows, then
    rotates the block one neighbor on.  n−1 hops total, each overlapping
    the previous block's matmul.  Bitwise-equal to the reference: every
    block meets the same weights, only the schedule changes."""
    n = mesh.shape[axis]
    bspec = _batch_spec(x.shape[0], mesh, batch_axes)
    perm = [(i, (i + 1) % n) for i in range(n)]
    _log_ring("ag_matmul_ring_ppermute",
              x.size * x.dtype.itemsize // n * (n - 1), axis)

    def body(xl, wl):
        me = lax.axis_index(axis)
        tl = xl.shape[1]
        out = jnp.zeros((xl.shape[0], tl * n, wl.shape[1]),
                        jnp.promote_types(xl.dtype, wl.dtype))
        cur = xl
        for s in range(n):
            src = (me - s) % n
            out = lax.dynamic_update_slice_in_dim(out, cur @ wl, src * tl,
                                                  axis=1)
            if s < n - 1:
                cur = lax.ppermute(cur, axis, perm)
        return out

    return shard_map(body, mesh=mesh,
                     in_specs=(P(bspec, axis, None), P(None, None)),
                     out_specs=P(bspec, None, None), check_vma=False)(x, w)


def all_gather_matmul(x, w, mesh: Mesh, *, axis: str = "tp",
                      batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
                      impl: Optional[str] = None):
    """``all_gather(x over seq) @ w`` with the gather fused into the matmul.

    x: [B, T, K] with T sharded over ``axis``; w: [K, N] replicated over
    ``axis``.  Returns [B, T, N] replicated over ``axis``.  Registry op
    ``all_gather_matmul``.
    """
    from deepspeed_tpu.ops.registry import dispatch
    _check(x.ndim == 3 and w.ndim == 2 and x.shape[2] == w.shape[0],
           f"all_gather_matmul expects x [B, T, K] and w [K, N], got "
           f"{x.shape} @ {w.shape}")
    _check(x.shape[1] % mesh.shape[axis] == 0,
           f"all_gather_matmul: seq dim {x.shape[1]} not divisible by "
           f"{axis}={mesh.shape[axis]}")
    return dispatch("all_gather_matmul", x, w, mesh, axis, batch_axes,
                    impl=impl)


# --------------------------------------------------- matmul → reduce-scatter

def _matmul_rs_xla(x, w, mesh, axis, batch_axes):
    """Unfused reference: full partial product, then one psum_scatter over
    the sequence dim."""
    bspec = _batch_spec(x.shape[0], mesh, batch_axes)

    def body(xl, wl):
        part = (xl @ wl).astype(jnp.float32)
        return lax.psum_scatter(part, axis, scatter_dimension=1, tiled=True)

    return shard_map(body, mesh=mesh,
                     in_specs=(P(bspec, None, axis), P(axis, None)),
                     out_specs=P(bspec, axis, None), check_vma=False)(x, w)


def _matmul_rs_ring(x, w, mesh, axis, batch_axes):
    """Fused ring: a one-chunk accumulator travels the ring; at step s each
    device adds its partial product for the chunk that accumulator will
    deliver (owner schedule ``(me − s − 1) mod n``).  After n steps device
    ``me`` holds the fully-reduced chunk ``me`` — psum_scatter decomposed
    into n−1 hops interleaved with n chunk matmuls."""
    n = mesh.shape[axis]
    bspec = _batch_spec(x.shape[0], mesh, batch_axes)
    perm = [(i, (i + 1) % n) for i in range(n)]
    chunk_bytes = x.shape[0] * (x.shape[1] // n) * w.shape[1] * 4
    _log_ring("matmul_rs_ring_ppermute", chunk_bytes * (n - 1), axis)

    def body(xl, wl):
        me = lax.axis_index(axis)
        c = xl.shape[1] // n
        acc = jnp.zeros((xl.shape[0], c, wl.shape[1]), jnp.float32)
        for s in range(n):
            if s:
                acc = lax.ppermute(acc, axis, perm)
            idx = (me - s - 1) % n
            xc = lax.dynamic_slice_in_dim(xl, idx * c, c, axis=1)
            acc = acc + (xc @ wl).astype(jnp.float32)
        return acc

    return shard_map(body, mesh=mesh,
                     in_specs=(P(bspec, None, axis), P(axis, None)),
                     out_specs=P(bspec, axis, None), check_vma=False)(x, w)


def matmul_reduce_scatter(x, w, mesh: Mesh, *, axis: str = "tp",
                          batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
                          impl: Optional[str] = None):
    """``psum_scatter(x @ w over seq)`` with the reduce fused into the
    matmul (the row-parallel linear's scatter half).

    x: [B, T, K] with K (the contraction) sharded over ``axis``; w: [K, N]
    sharded on K.  Returns [B, T, N] with T sharded over ``axis``, fp32
    accumulation.  Requires T % axis == 0.  Registry op
    ``matmul_reduce_scatter``.
    """
    from deepspeed_tpu.ops.registry import dispatch
    _check(x.ndim == 3 and w.ndim == 2 and x.shape[2] == w.shape[0],
           f"matmul_reduce_scatter expects x [B, T, K] and w [K, N], got "
           f"{x.shape} @ {w.shape}")
    n = mesh.shape[axis]
    _check(x.shape[1] % n == 0,
           f"matmul_reduce_scatter: seq dim {x.shape[1]} not divisible by "
           f"{axis}={n}")
    _check(x.shape[2] % n == 0,
           f"matmul_reduce_scatter: contraction dim {x.shape[2]} not "
           f"divisible by {axis}={n}")
    return dispatch("matmul_reduce_scatter", x, w, mesh, axis, batch_axes,
                    impl=impl)


# ------------------------------------------------- row-parallel (all-reduce)

def _row_parallel_xla(x, w, mesh, axis, batch_axes, out_dtype):
    """Unfused reference: partial product + one blocking psum — the
    boundary all-reduce GSPMD inserts after a row-parallel matmul."""
    bspec = _batch_spec(x.shape[0], mesh, batch_axes)

    def body(xl, wl):
        part = (xl @ wl).astype(jnp.float32)
        return lax.psum(part, axis).astype(out_dtype)

    return shard_map(body, mesh=mesh,
                     in_specs=(P(bspec, None, axis), P(axis, None)),
                     out_specs=P(bspec, None, None), check_vma=False)(x, w)


def _row_parallel_ring(x, w, mesh, axis, batch_axes, out_dtype):
    """Fused ring: the all-reduce decomposed as ring matmul-reduce-scatter
    (chunk matmuls interleaved with n−1 accumulator hops) followed by a
    ring all-gather of the reduced chunks (n−1 more hops) — 2·(n−1)
    neighbor hops total, the bandwidth-optimal all-reduce schedule, with
    every hop overlappable against a chunk matmul."""
    n = mesh.shape[axis]
    bspec = _batch_spec(x.shape[0], mesh, batch_axes)
    perm = [(i, (i + 1) % n) for i in range(n)]
    chunk_elems = x.shape[0] * (x.shape[1] // n) * w.shape[1]
    _log_ring("row_parallel_ring_ppermute",
              chunk_elems * 4 * (n - 1)                       # RS leg, fp32
              + chunk_elems * jnp.dtype(out_dtype).itemsize * (n - 1),  # AG
              axis)

    def body(xl, wl):
        me = lax.axis_index(axis)
        c = xl.shape[1] // n
        acc = jnp.zeros((xl.shape[0], c, wl.shape[1]), jnp.float32)
        for s in range(n):
            if s:
                acc = lax.ppermute(acc, axis, perm)
            idx = (me - s - 1) % n
            xc = lax.dynamic_slice_in_dim(xl, idx * c, c, axis=1)
            acc = acc + (xc @ wl).astype(jnp.float32)
        # acc = fully-reduced chunk ``me``; ring-gather chunks back to full
        acc = acc.astype(out_dtype)
        out = jnp.zeros((xl.shape[0], c * n, wl.shape[1]), out_dtype)
        cur = acc
        for s in range(n):
            idx = (me - s) % n
            out = lax.dynamic_update_slice_in_dim(out, cur, idx * c, axis=1)
            if s < n - 1:
                cur = lax.ppermute(cur, axis, perm)
        return out

    return shard_map(body, mesh=mesh,
                     in_specs=(P(bspec, None, axis), P(axis, None)),
                     out_specs=P(bspec, None, None), check_vma=False)(x, w)


def row_parallel_matmul(x, w, mesh: Mesh, *, axis: str = "tp",
                        batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
                        out_dtype=None, impl: Optional[str] = None):
    """Row-parallel linear ``psum(x @ w)`` with the all-reduce decomposed
    into ring reduce-scatter + ring all-gather chunk schedules.

    x: [B, T, K] with K sharded over ``axis``; w: [K, N] sharded on K.
    Returns the full [B, T, N] (replicated over ``axis``), accumulated in
    fp32 and cast to ``out_dtype`` (default: x's dtype).  Requires
    T % axis == 0 and K % axis == 0.  Registry op ``row_parallel_matmul`` —
    the entry the TP matmuls in models/gpt.py and linear.py route through
    under ``overlap.collective_matmul``.
    """
    from deepspeed_tpu.ops.registry import dispatch
    _check(x.ndim == 3 and w.ndim == 2 and x.shape[2] == w.shape[0],
           f"row_parallel_matmul expects x [B, T, K] and w [K, N], got "
           f"{x.shape} @ {w.shape}")
    n = mesh.shape[axis]
    _check(x.shape[1] % n == 0,
           f"row_parallel_matmul: seq dim {x.shape[1]} not divisible by "
           f"{axis}={n} (the ring chunks the sequence)")
    _check(x.shape[2] % n == 0,
           f"row_parallel_matmul: contraction dim {x.shape[2]} not "
           f"divisible by {axis}={n}")
    out_dtype = out_dtype if out_dtype is not None else x.dtype
    return dispatch("row_parallel_matmul", x, w, mesh, axis, batch_axes,
                    out_dtype, impl=impl)


def _register():
    from deepspeed_tpu.ops.registry import register_op
    register_op("all_gather_matmul", xla=_ag_matmul_xla,
                pallas=_ag_matmul_ring)
    register_op("matmul_reduce_scatter", xla=_matmul_rs_xla,
                pallas=_matmul_rs_ring)
    register_op("row_parallel_matmul", xla=_row_parallel_xla,
                pallas=_row_parallel_ring)


_register()
