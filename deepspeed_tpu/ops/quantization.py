"""Block quantization ops — int8/int4 symmetric, per-block scales.

TPU-native analog of the reference quantizer kernels
(csrc/quantization/quantize.cu, fake_quantizer.cu; python surface
deepspeed/ops/quantizer + inference/quantization).  Semantics match the
reference's symmetric blocked quantizer: a tensor is viewed as flat blocks of
``block_size`` values; each block stores int values in [-(2^(bits-1)-1),
2^(bits-1)-1] plus one fp scale.  On TPU this is a handful of elementwise ops
+ a reduce per block — XLA fuses it into surrounding code; there is no kernel
to write, the value is the WIRE/STORAGE format (quantized collectives, ZeRO++
weight gathers, ZeRO-Inference weight storage).

int4 packs two values per int8 byte (reference quantize_int4.cu) so the wire
moves 4 bits/value.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class QuantizedBlocks(NamedTuple):
    """values: int8 [N/bs, bs] (int4: packed [N/bs, bs/2]); scales fp32
    [N/bs, 1]; meta carries the original shape/dtype/bits for dequant."""

    values: jax.Array
    scales: jax.Array
    shape: Tuple[int, ...]
    dtype: object
    bits: int
    block_size: int


def _pad_to_blocks(flat, block_size):
    n = flat.shape[0]
    pad = (-n) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, n


def pack_nibbles(q):
    """Fold int8 values (range [-7, 7]) pairwise along dim 0 into bytes:
    low nibble = even index, high = odd.  Shared by the blockwise wire
    format and the packed weight store."""
    lo = q[0::2] & 0x0F
    hi = (q[1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8)


def unpack_nibbles(p):
    """Inverse of ``pack_nibbles``: (lo, hi) sign-extended int8 halves."""
    lo = (p << 4).astype(jnp.int8) >> 4
    hi = p >> 4                                      # arithmetic shift
    return lo, hi


def unpack_nibbles_f32(p):
    """Shift-free ``unpack_nibbles`` returning float32 — the in-kernel
    variant: Mosaic cannot legalize shifts on int8 vectors
    (``arith.shli : vector<..xi8>``, found on first chip contact round 5),
    so the byte is widened to f32 (exact for [-128, 127]) and the nibbles
    split with floor/multiply VPU arithmetic (all quantities are small
    integers, exact in f32)."""
    b = p.astype(jnp.float32)
    ub = jnp.where(b < 0, b + 256.0, b)              # unsigned byte view
    hi4 = jnp.floor(ub * 0.0625)                     # ub // 16
    lo4 = ub - hi4 * 16.0
    lo = lo4 - jnp.where(lo4 >= 8.0, 16.0, 0.0)      # sign-extend 4-bit
    hi = hi4 - jnp.where(hi4 >= 8.0, 16.0, 0.0)
    return lo, hi


def quantize_blockwise(x, *, bits: int = 8,
                       block_size: int = 256) -> QuantizedBlocks:
    """Symmetric per-block quantization (reference quantize.cu semantics:
    scale = max|x| / qmax per block, stochastic-free round-to-nearest)."""
    if bits not in (2, 4, 8):
        raise ValueError(f"bits must be 2, 4, or 8, got {bits}")
    orig_shape, orig_dtype = x.shape, x.dtype
    flat, _ = _pad_to_blocks(x.reshape(-1).astype(jnp.float32), block_size)
    blocks = flat.reshape(-1, block_size)
    qmax = float(2 ** (bits - 1) - 1)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scales = absmax / qmax
    inv = jnp.where(scales > 0, 1.0 / jnp.maximum(scales, 1e-30), 0.0)
    q = jnp.clip(jnp.round(blocks * inv), -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        # pack pairs along the block dim: transpose in/out of the shared
        # dim-0 packer
        q = pack_nibbles(q.T).T
    return QuantizedBlocks(values=q, scales=scales, shape=orig_shape,
                           dtype=orig_dtype, bits=bits, block_size=block_size)


def dequantize_blockwise(qb: QuantizedBlocks) -> jax.Array:
    q = qb.values
    if qb.bits == 4:
        lo, hi = unpack_nibbles(q)
        q = jnp.stack([lo, hi], axis=-1).reshape(q.shape[0], -1)
    x = q.astype(jnp.float32) * qb.scales
    n = 1
    for d in qb.shape:
        n *= d
    return x.reshape(-1)[:n].reshape(qb.shape).astype(qb.dtype)


def quantize_dequantize(x, *, bits: int = 8, block_size: int = 256):
    """Fake-quant (reference fake_quantizer.cu): the QDQ roundtrip used for
    error injection / compression emulation inside fp math."""
    return dequantize_blockwise(quantize_blockwise(x, bits=bits,
                                                   block_size=block_size))


# ---------------------------------------------------------------- collectives
def quantized_all_gather(x, mesh, axis: str, *, bits: int = 8,
                         block_size: int = 256, gather_dim: int = 0):
    """All-gather ``x`` (sharded on ``gather_dim`` over mesh axis) moving int
    values + fp scales on the wire instead of full-precision values — the
    ZeRO++ qwZ quantized weight all-gather
    (reference runtime/zero/stage3.py:1497 all_gather_coalesced with
    quantization=..., csrc/quantization/ kernels).

    Returns the gathered, dequantized array (replicated over ``axis``).
    Compression: bits/16 of the bf16 wire volume (+ scales overhead).
    """
    from deepspeed_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    size = mesh.shape[axis]
    if size == 1:
        return x
    if x.shape[gather_dim] % size:
        raise ValueError(f"dim {gather_dim} ({x.shape[gather_dim]}) not "
                         f"divisible by mesh axis {axis}={size}")

    in_spec = [None] * x.ndim
    in_spec[gather_dim] = axis

    def local(xs):
        return qag_local(xs, axis, size, gather_dim,
                         bits=bits, block_size=block_size)

    return shard_map(local, mesh=mesh, in_specs=P(*in_spec),
                     out_specs=P(), check_vma=False)(x)


def _wire_block(n: int, block_size: int) -> int:
    """Effective wire block for an ``n``-element slice: the configured size,
    halved (min 8) while the slice wouldn't even half-fill it.  Blockwise
    padding is pure wire waste — a 4-element bias slice padded to a 256
    block ships 64x its data; real models carry many such small leaves
    (biases, norms) next to the big matrices."""
    b = block_size
    while b > 8 and n <= b // 2:
        b //= 2
    return b


def _log_qwire(kind: str, bits: int, payload_bytes: int, axis: str,
               size: int, ring_factor) -> None:
    """Trace-time wire accounting for the quantized collective bodies: the
    telemetry byte counters see the int codes + fp32 scales at their WIRE
    width, tagged with the format (``all_gather_q8``, ``all_to_all_q4``) —
    comm/collectives.log_wire.  ``ring_factor(payload, n)`` maps payload to
    per-participant ring bytes per the convention in collectives.py."""
    from deepspeed_tpu.comm.collectives import log_wire
    log_wire(f"{kind}_q{bits}", ring_factor(payload_bytes, size), axis)


def _qb_bytes(qb: QuantizedBlocks) -> int:
    return (int(qb.values.size) * qb.values.dtype.itemsize
            + int(qb.scales.size) * qb.scales.dtype.itemsize)


def q_gather_rows(flat, axis: str, size: int, *, bits: int = 8,
                  block_size: int = 256):
    """Quantized stacked all-gather of one flat buffer, inside
    ``shard_map`` over ``axis``: ``[B] -> [size, B]``.  Int codes + fp32
    block scales on the wire, per-member dequant back to ``flat.dtype``.
    THE quantized-gather wire core — ``qag_local`` and the composable
    pipeline's ``_qwire_exchange`` forward (runtime/zero.py) both run
    through here, so the wire format and its byte accounting live once."""
    qb = quantize_blockwise(flat, bits=bits,
                            block_size=_wire_block(flat.size, block_size))
    _log_qwire("all_gather", bits, _qb_bytes(qb), axis, size,
               lambda b, n: b * (n - 1))
    vg = jax.lax.all_gather(qb.values, axis)             # int8 on the wire
    sg = jax.lax.all_gather(qb.scales, axis)
    return jnp.stack([
        dequantize_blockwise(qb._replace(values=vg[i], scales=sg[i]))
        for i in range(size)])


def q_reduce_rows(rows, axis: str, size: int, *, bits: int = 8,
                  block_size: int = 256):
    """Quantized reduce-scatter of pre-split rows, inside ``shard_map``
    over ``axis``: ``rows[j]`` is this device's contribution to member j;
    returns the sum over devices of their row for THIS member (``[size,
    B] -> [B]``, ``rows.dtype``).  Each row quantizes independently
    (blocks never straddle member boundaries), one all-to-all moves the
    codes + scales.  THE quantized-reduce wire core — ``qrs_local`` and
    ``_qwire_exchange``'s backward both run through here."""
    bs = _wire_block(rows.shape[1], block_size)
    qbs = [quantize_blockwise(rows[i], bits=bits, block_size=bs)
           for i in range(size)]
    _log_qwire("all_to_all", bits, sum(_qb_bytes(q) for q in qbs), axis,
               size, lambda b, n: b * (n - 1) // n)
    v = jax.lax.all_to_all(jnp.stack([q.values for q in qbs]),
                           axis, 0, 0, tiled=False)
    s = jax.lax.all_to_all(jnp.stack([q.scales for q in qbs]),
                           axis, 0, 0, tiled=False)
    total = jnp.zeros(rows.shape[1:], jnp.float32)
    for i in range(size):
        qi = qbs[0]._replace(values=v[i], scales=s[i])
        total = total + dequantize_blockwise(qi).astype(jnp.float32)
    return total.astype(rows.dtype)


def q_all_to_all(x, axis: str, size: int, split_axis: int, concat_axis: int,
                 *, bits: int = 8, block_size: int = 256):
    """Quantized all-to-all, inside ``shard_map`` over ``axis``: the exact
    data movement of ``lax.all_to_all(x, axis, split_axis, concat_axis,
    tiled=True)`` with int codes + fp32 block scales on the wire instead of
    full-width values.  Each destination's slice quantizes INDEPENDENTLY
    (blocks never straddle destinations, same invariant as
    ``q_reduce_rows``); one stacked a2a pair moves codes + scales; each
    received slice dequants back to ``x.dtype`` and concats along
    ``concat_axis``.  THE quantized-a2a wire core — the MoE expert
    dispatch/combine exchanges (moe/comm.py) run through here, so the wire
    format and its ``all_to_all_q{bits}`` byte accounting live once."""
    parts = jnp.split(x, size, axis=split_axis)
    bs = _wire_block(parts[0].size, block_size)
    qbs = [quantize_blockwise(p, bits=bits, block_size=bs) for p in parts]
    _log_qwire("all_to_all", bits, sum(_qb_bytes(q) for q in qbs), axis,
               size, lambda b, n: b * (n - 1) // n)
    v = jax.lax.all_to_all(jnp.stack([q.values for q in qbs]),
                           axis, 0, 0, tiled=False)
    s = jax.lax.all_to_all(jnp.stack([q.scales for q in qbs]),
                           axis, 0, 0, tiled=False)
    return jnp.concatenate([
        dequantize_blockwise(qbs[0]._replace(values=v[i], scales=s[i]))
        for i in range(size)], axis=concat_axis).astype(x.dtype)


def qag_local(xs, axis: str, size: int, gather_dim: int = 0, *,
              bits: int = 8, block_size: int = 256):
    """Per-device body of a quantized all-gather (inside ``shard_map`` over
    ``axis``): int values + fp32 block scales on the wire, per-member dequant,
    concat along ``gather_dim``.  Shared by ``quantized_all_gather`` and
    ``qpsum_local``."""
    rows = q_gather_rows(xs.reshape(-1), axis, size, bits=bits,
                         block_size=block_size)
    return jnp.concatenate([rows[i].reshape(xs.shape) for i in range(size)],
                           axis=gather_dim)


def qrs_local(xs, axis: str, size: int, scatter_dim: int = 0, *,
              bits: int = 8, block_size: int = 256):
    """Per-device body of a quantized reduce-scatter, for use INSIDE an
    existing ``shard_map`` over ``axis`` (the engine's qgZ grad path calls
    this directly; ``quantized_psum_scatter`` wraps it for standalone use).

    ``xs`` is this device's full-shape partial contribution.  Quantize each
    target shard's slice INDEPENDENTLY (blocks never straddle shard
    boundaries), all_to_all so member i receives every member's contribution
    for slice i, dequant + sum.  Wire format: int values + fp32 block scales
    — bits/32 of the fp32 reduce volume (+ scales overhead).
    Returns this device's reduced slice (shape[scatter_dim] / size).
    """
    parts = jnp.split(xs, size, axis=scatter_dim)
    rows = jnp.stack([p.reshape(-1) for p in parts])
    total = q_reduce_rows(rows, axis, size, bits=bits,
                          block_size=block_size)
    return total.reshape(parts[0].shape)


def qpsum_local(xs, axis: str, size: int, scatter_dim: int = 0, *,
                bits: int = 8, block_size: int = 256):
    """Quantized all-reduce body (inside ``shard_map`` over ``axis``):
    quantized reduce-scatter then a quantized all-gather of the reduced
    slices, so BOTH wire phases move ints — total ≈ (1 + 1/size)·bits/32 of
    one fp32 ring allreduce.  Used for qgZ leaves whose layout stays
    replicated.  Returns the full reduced array on every device."""
    total = qrs_local(xs, axis, size, scatter_dim,
                      bits=bits, block_size=block_size)
    return qag_local(total, axis, size, scatter_dim,
                     bits=bits, block_size=block_size).astype(xs.dtype)


def quantized_psum_scatter(x, mesh, axis: str, *, bits: int = 8,
                           block_size: int = 256, scatter_dim: int = 0):
    """Reduce-scatter with int-quantized wire format + fp32 scale exchange —
    the qgZ quantized gradient reduce direction (reference
    runtime/zero/stage3.py quantized_reduce_scatter path,
    csrc/quantization/swizzled_quantize.cu).  all-to-all of quantized shard
    contributions, local dequant + sum.

    x is replicated per-shard-group input (leading dim divisible by axis
    size); returns this shard's reduced slice.
    """
    from deepspeed_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    size = mesh.shape[axis]
    if size == 1:
        return x
    if x.shape[scatter_dim] % size:
        raise ValueError(f"dim {scatter_dim} ({x.shape[scatter_dim]}) not "
                         f"divisible by mesh axis {axis}={size}")

    out_spec = [None] * x.ndim
    out_spec[scatter_dim] = axis

    def local(xs):
        return qrs_local(xs, axis, size, scatter_dim,
                         bits=bits, block_size=block_size)

    return shard_map(local, mesh=mesh, in_specs=P(),
                     out_specs=P(*out_spec), check_vma=False)(x)


def quantized_weight_gather(x, mesh, axis: str, gather_dim: int, *,
                            bits: int = 8, block_size: int = 256):
    """Differentiable ZeRO++ qwZ gather: forward moves int values on the wire
    (quantized_all_gather); backward constrains the cotangent back to the
    sharded layout so XLA emits the ordinary grad reduce-scatter — weight
    quantization never biases gradients (reference: qwZ quantizes the fwd/bwd
    weight all-gather only, runtime/zero/stage3.py:1497)."""
    import jax as _jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = [None] * x.ndim
    spec[gather_dim] = axis
    shard_sharding = NamedSharding(mesh, P(*spec))
    dtype = x.dtype

    @_jax.custom_vjp
    def gather(v):
        return quantized_all_gather(v, mesh, axis, bits=bits,
                                    block_size=block_size,
                                    gather_dim=gather_dim)

    def fwd(v):
        return gather(v), None

    def bwd(_, ct):
        return (_jax.lax.with_sharding_constraint(
            ct.astype(dtype), shard_sharding),)

    gather.defvjp(fwd, bwd)
    return gather(x)


def weight_group_size(shape, group: int, min_group: int = 16) -> int:
    """Effective dim-0 group for ``quantize_weight``: the largest power-of-2
    divisor of shape[0] that is ≤ ``group``; 0 (= don't quantize) if even
    ``min_group`` doesn't divide."""
    if not shape:
        return 0
    g = 1
    while g * 2 <= group and shape[0] % (g * 2) == 0:
        g *= 2
    return g if g >= min_group else 0


def quantize_weight(w, *, bits: int = 8, group: int = 128, dim: int = 0):
    """Shape-preserving group-wise symmetric weight quantization — the
    serving weight-storage format (reference
    inference/v2/modules/implementations/linear/quantized_linear.py:205 FP6
    W6A16 and inference/quantization/layers.py:114 matmul-time dequant; here
    int8 codes + per-(group-along-``dim`` × channel) fp32 scales).

    w → {"v": int8 same shape, "s": f32 with shape[dim] → shape[dim]/g}.
    Keeping the LEAF SHAPE (unlike the flat ``quantize_blockwise`` wire
    format) means the store shards exactly like the weight it replaces — the
    quant × tensor-parallel composition falls out — and consumers dequantize
    at their use site, so the full-precision tree never exists at rest.
    ``dim`` defaults to 0 (the usual contraction dim); MoE expert stacks
    [E, in, out] group along dim=1.
    """
    w = jnp.asarray(w)
    g = weight_group_size((w.shape[dim],), group)
    if g == 0:
        raise ValueError(f"dim {dim} of {w.shape} has no usable group "
                         f"≤ {group}")
    qmax = float(2 ** (bits - 1) - 1)
    wm = jnp.moveaxis(w, dim, 0)
    d0 = wm.shape[0]
    wf = wm.astype(jnp.float32).reshape((d0 // g, g) + wm.shape[1:])
    absmax = jnp.max(jnp.abs(wf), axis=1)                  # [d0/g, *rest]
    s = absmax / qmax
    inv = jnp.where(s > 0, 1.0 / jnp.maximum(s, 1e-30), 0.0)
    q = jnp.clip(jnp.round(wf * inv[:, None]), -qmax, qmax)
    return {"v": jnp.moveaxis(q.reshape(wm.shape).astype(jnp.int8), 0, dim),
            "s": jnp.moveaxis(s, 0, dim)}


def _store_dim(d) -> int:
    """The grouped dim of a store: where codes and scales disagree."""
    v, s = d["v"], d["s"]
    for i, (a, b) in enumerate(zip(v.shape, s.shape)):
        if a != b:
            return i
    return 0


def dequantize_weight(d, dtype=jnp.bfloat16):
    """Inverse of ``quantize_weight`` (jit-safe; the per-consumer call)."""
    v, s = d["v"], d["s"]
    dim = _store_dim(d)
    vm = jnp.moveaxis(v, dim, 0)
    sm = jnp.moveaxis(s, dim, 0)
    g = vm.shape[0] // sm.shape[0]
    wf = vm.astype(jnp.float32).reshape((sm.shape[0], g) + vm.shape[1:])
    return jnp.moveaxis((wf * sm[:, None]).reshape(vm.shape), 0,
                        dim).astype(dtype)


def is_quantized_weight(leaf) -> bool:
    return (isinstance(leaf, dict) and set(leaf) == {"v", "s"}
            and getattr(leaf["v"], "dtype", None) == jnp.int8)


def quantize_weight4(w, *, group: int = 128):
    """int4 NIBBLE-PACKED weight store: ¼ the bf16 bytes (vs the
    shape-preserving int8 store's ½) — the ZeRO-Inference single-chip
    HBM-fit format (reference inference/quantization int4 path,
    csrc/quantization/quantize_int4.cu).

    Packing folds dim-0 PAIRS into one byte (low nibble = even row, high =
    odd row), so codes are [d0/2, *rest] — NOT the weight's shape.  That
    breaks the shard-like-the-weight property, so this format is for
    UNSHARDED (single-shard / mesh-free) serving only; sharded or
    kernel-eligible paths use ``quantize_weight``.
    Returns {"v4": int8 [d0/2, *rest], "s": f32 [d0/g, *rest]}."""
    w = jnp.asarray(w)
    if w.shape[0] % 2:
        raise ValueError(f"dim 0 of {w.shape} is odd — nibble packing "
                         f"folds row pairs")
    q = quantize_weight(w, bits=4, group=group)      # shared scale math
    return {"v4": pack_nibbles(q["v"]), "s": q["s"]}


def is_quantized_weight4(leaf) -> bool:
    return (isinstance(leaf, dict) and set(leaf) == {"v4", "s"}
            and getattr(leaf["v4"], "dtype", None) == jnp.int8)


def quantized_codes(leaf):
    """The codes array of a quantized store leaf (int8 ``v`` or packed
    ``v4``), or None when ``leaf`` is not a store — the one place consumers
    ask "is this quantized, and what shape is it"."""
    if is_quantized_weight(leaf):
        return leaf["v"]
    if is_quantized_weight4(leaf):
        return leaf["v4"]
    return None


def dequantize_weight4(d, dtype=jnp.bfloat16):
    """Inverse of ``quantize_weight4`` (jit-safe; the per-consumer call)."""
    p, s = d["v4"], d["s"]
    lo, hi = unpack_nibbles(p)
    d0 = 2 * p.shape[0]
    q = jnp.stack([lo, hi], axis=1).reshape((d0,) + p.shape[1:])
    return dequantize_weight({"v": q, "s": s}, dtype)


def store_shardings(store, shardings, mesh):
    """NamedSharding tree for a ``quantize_weight`` param store: codes take
    the replaced weight's sharding verbatim (shape-preserving format); scales
    take it too unless the grouped-dim group count doesn't divide over the
    sharded axis, in which case the small scale tensor just replicates.
    This is what makes quant × tensor-parallel compose (round-3 verdict item
    4: the old flat store dropped ``in_shardings`` and rejected tp>1).

    Nibble-packed (v4) leaves shard like the weight too — "pack after
    shard": byte row r holds global rows 2r/2r+1, so a dim-0 shard of the
    packed codes IS the packed shard of the weight as long as the shard
    boundary never splits a row pair or a scale group (checked per dim;
    fall back to replicating the leaf when it would)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def axis_size(ax):
        axes = (ax,) if isinstance(ax, str) else ax
        n = 1
        for a in axes:
            n *= mesh.shape[a]
        return n

    def f(p, sh):
        if is_quantized_weight4(p):
            spec = list(sh.spec)
            spec += [None] * (p["v4"].ndim - len(spec))
            s_spec = list(spec)
            for d, ax in enumerate(spec):
                if ax is None:
                    continue
                n = axis_size(ax)
                if p["v4"].shape[d] % n:
                    spec[d] = None          # would split a nibble pair
                if p["s"].shape[d] % n:
                    s_spec[d] = None        # would split a scale group
            return {"v4": NamedSharding(mesh, P(*spec)),
                    "s": NamedSharding(mesh, P(*s_spec))}
        if not is_quantized_weight(p):
            return sh
        spec = list(sh.spec)
        spec += [None] * (p["v"].ndim - len(spec))
        s_spec = list(spec)
        d = _store_dim(p)
        ax = s_spec[d]
        if ax is not None and p["s"].shape[d] % axis_size(ax):
            s_spec[d] = None
        # vocab-padded stores: codes may be longer than the weight was —
        # re-check the padded dim still divides
        for dd, a in enumerate(spec):
            if a is not None and p["v"].shape[dd] % axis_size(a):
                spec[dd] = None
        return {"v": NamedSharding(mesh, P(*spec)),
                "s": NamedSharding(mesh, P(*s_spec))}
    return jax.tree_util.tree_map(
        f, store, shardings,
        is_leaf=lambda x: is_quantized_weight(x) or is_quantized_weight4(x))


def make_param_store(params, *, bits: int = 8, block_size: int = 128,
                     pack4: bool = False):
    """Pack a param tree into int-quantized storage + a jit-safe materializer
    — ZeRO-Inference weight storage (reference inference/quantization/
    __init__.py _init_group_wise_weight_quantization: weights live in HBM at
    ``bits``/16 of their bf16 size; each consumer dequantizes on the fly and
    XLA frees the transient fp buffer after use).

    Returns (stored, materialize): ``stored`` is a pytree holding
    {"v": int8, "s": fp32} (shape-preserving ``quantize_weight`` format, so
    the store inherits the weight's sharding) for quantized leaves and the
    raw leaf otherwise; ``materialize(stored)`` rebuilds the original tree
    inside jit.
    """
    leaves, treedef = jax.tree_util.tree_flatten(params)
    stored, metas = [], []
    for leaf in leaves:
        leaf = jnp.asarray(leaf)
        if (jnp.issubdtype(leaf.dtype, jnp.floating)
                and leaf.ndim >= 2        # matmul weights only: quantizing
                # 1-D norm scales/biases costs accuracy for negligible bytes
                # (matches the v2 pack() policy and the reference's
                # linear-weights-only restriction)
                and leaf.size >= block_size
                and weight_group_size(leaf.shape, block_size)):
            if pack4 and leaf.shape[0] % 2 == 0:
                stored.append(quantize_weight4(leaf, group=block_size))
            else:
                stored.append(quantize_weight(leaf, bits=bits,
                                              group=block_size))
            metas.append(leaf.dtype)
        else:
            stored.append(leaf)
            metas.append(None)

    def materialize(stored_tree):
        leaves_in = jax.tree_util.tree_leaves(
            stored_tree,
            is_leaf=lambda x: (is_quantized_weight(x)
                               or is_quantized_weight4(x)))
        out = []
        for item, meta in zip(leaves_in, metas):
            if meta is None:
                out.append(item)
            elif is_quantized_weight4(item):
                out.append(dequantize_weight4(item, meta))
            else:
                out.append(dequantize_weight(item, meta))
        return jax.tree_util.tree_unflatten(treedef, out)

    # the store keeps the PARAM TREE structure (quantized leaves become
    # {"v", "s"} subtrees) so sharding trees map over it directly
    return jax.tree_util.tree_unflatten(treedef, stored), materialize


# ------------------------------------------------------------- fp8 (FP6-LLM)
_FP8_MAX = {"e4m3": 448.0, "e5m2": 57344.0}


def quantize_fp8(x, *, fmt: str = "e4m3",
                 block_size: int = 256) -> QuantizedBlocks:
    """Blockwise-scaled fp8 quantization — the FP-quantizer analog
    (reference csrc/fp_quantizer/fp_quantize.cu: FP6/FP8/FP12 bit-packed
    formats for weight storage).  On TPU the natural targets are the NATIVE
    XLA fp8 dtypes (float8_e4m3fn / float8_e5m2); each block carries one fp32
    scale so the fp8 dynamic range is centered on the block's magnitude.

    values dtype is jnp.float8_*; fp8 blocks dequantize with
    ``dequantize_fp8`` (the int path keeps ``dequantize_blockwise``)."""
    if fmt not in _FP8_MAX:
        raise ValueError(f"fmt must be one of {sorted(_FP8_MAX)}, got {fmt!r}")
    dt = jnp.float8_e4m3fn if fmt == "e4m3" else jnp.float8_e5m2
    orig_shape, orig_dtype = x.shape, x.dtype
    flat, _ = _pad_to_blocks(x.reshape(-1).astype(jnp.float32), block_size)
    blocks = flat.reshape(-1, block_size)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scales = absmax / _FP8_MAX[fmt]
    inv = jnp.where(scales > 0, 1.0 / jnp.maximum(scales, 1e-30), 0.0)
    q = (blocks * inv).astype(dt)
    return QuantizedBlocks(values=q, scales=scales, shape=orig_shape,
                           dtype=orig_dtype, bits=8, block_size=block_size)


def dequantize_fp8(qb: QuantizedBlocks) -> jax.Array:
    # fp8 values cast-to-fp32 ARE their numeric values, so the generic
    # astype-multiply-trim path applies unchanged (bits=8 ⇒ no nibble unpack)
    return dequantize_blockwise(qb)


def quantize_dequantize_fp8(x, *, fmt: str = "e4m3", block_size: int = 256):
    return dequantize_fp8(quantize_fp8(x, fmt=fmt, block_size=block_size))
