"""Optimized sharded linear + LoRA.

Reference parity: ``deepspeed/linear/optimized_linear.py`` (OptimizedLinear
:35 — LoRA-adapted linear with sharded, optionally quantized base weight),
``config.py`` (LoRAConfig, QuantizationConfig).

TPU-native translation:
- base-weight sharding is a LOGICAL AXIS annotation (in/out axis names mapped
  by parallel/partition.py — fsdp/tp shard placement falls out of the mesh),
  not the reference's rank-strided torch shards;
- the frozen base is expressed as an optax mask (``lora_trainable_mask``)
  rather than requires_grad — chain ``optax.masked`` (or pass
  ``client_optimizer``) to train adapters only;
- base quantization is QDQ straight-through in the forward (ZeroQuant-style
  QAT semantics).  int-STORED frozen weights are the serving engines' job
  (inference ``quant`` config, ops/quantization.make_param_store).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    """reference: linear/config.py LoRAConfig."""

    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1      # >1 = shard base over fsdp (annotation)


@dataclasses.dataclass(frozen=True)
class QuantizationConfig:
    """reference: linear/config.py QuantizationConfig."""

    q_bits: int = 8
    group_size: int = 256


class OptimizedLinear(nn.Module):
    """y = x @ W (+ x @ A @ B * alpha/r) with W frozen-by-mask.

    reference optimized_linear.py:35 OptimizedLinear / LoRAOptimizedLinear.
    """

    input_dim: int
    output_dim: int
    use_bias: bool = False
    lora_config: Optional[LoRAConfig] = None
    quantization_config: Optional[QuantizationConfig] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    # logical axes for the base weight (partition.py DEFAULT_RULES map these
    # to mesh axes; "embed"/"mlp" gives the usual tp/fsdp placement)
    axis_names: Tuple[str, str] = ("embed", "mlp")
    # route a ROW-parallel base matmul (input axis mapped to tp — e.g.
    # axis_names=("mlp", "embed")) through the ppermute-ring fusion
    # (ops/collective_matmul.py): the output all-reduce decomposes into
    # chunk matmuls interleaved with neighbor hops.  Needs ``mesh``; inert
    # for column-parallel placements (no boundary collective to fuse).
    mesh: Optional[Any] = None
    collective_matmul: bool = False

    @nn.compact
    def __call__(self, x):
        lc, qc = self.lora_config, self.quantization_config
        shard_axes = self.axis_names
        if lc is not None and lc.base_weight_sharding <= 1:
            shard_axes = (None, None)   # replicated base (reference default)
        w = self.param(
            "weight",
            nn.with_partitioning(nn.initializers.normal(0.02), shard_axes),
            (self.input_dim, self.output_dim), self.param_dtype)
        w = w.astype(self.dtype)
        if qc is not None:
            from deepspeed_tpu.ops.quantization import quantize_dequantize
            # straight-through QDQ: forward sees the quantized grid, grads
            # pass through (training-time analog of QuantizedParameter)
            w = w + jax.lax.stop_gradient(
                quantize_dequantize(w, bits=qc.q_bits,
                                    block_size=qc.group_size) - w)
        ring = False
        if self.collective_matmul and self.mesh is not None:
            from deepspeed_tpu.parallel.partition import DEFAULT_RULES
            tp = self.mesh.shape.get("tp", 1)
            ring = (tp > 1
                    and dict(DEFAULT_RULES).get(shard_axes[0]) == "tp")
            if ring and (x.ndim != 3 or x.shape[1] % tp
                         or self.input_dim % tp):
                raise ValueError(
                    f"collective_matmul row-parallel base needs [B, T, in] "
                    f"input with T and in dividing tp={tp}, got x "
                    f"{x.shape}, in={self.input_dim}")
        if ring:
            from deepspeed_tpu.ops import collective_matmul as cm_ops
            y = cm_ops.row_parallel_matmul(x.astype(self.dtype), w,
                                           self.mesh)
        else:
            y = x.astype(self.dtype) @ w
        if lc is not None and lc.lora_r > 0:
            a = self.param(
                "lora_a",
                nn.with_partitioning(
                    nn.initializers.normal(1.0 / lc.lora_r),
                    (self.axis_names[0], None)),
                (self.input_dim, lc.lora_r), self.param_dtype)
            b = self.param(
                "lora_b",
                nn.with_partitioning(nn.initializers.zeros,
                                     (None, self.axis_names[1])),
                (lc.lora_r, self.output_dim), self.param_dtype)
            y = y + (x.astype(self.dtype) @ a.astype(self.dtype)
                     @ b.astype(self.dtype)) * (lc.lora_alpha / lc.lora_r)
        if self.use_bias:
            y = y + self.param(
                "bias", nn.with_partitioning(nn.initializers.zeros,
                                             (self.axis_names[1],)),
                (self.output_dim,), self.param_dtype).astype(self.dtype)
        return y


def lora_optimizer(inner, params):
    """Wrap an optax transform so base ``weight`` leaves are FROZEN and only
    adapters/biases train (reference: requires_grad=False on the base).
    ``optax.masked`` alone would pass the raw gradient through for masked-out
    leaves — freezing needs set_to_zero on them."""
    import optax
    mask = lora_trainable_mask(params)
    labels = jax.tree_util.tree_map(
        lambda m: "train" if m else "freeze", mask)
    return optax.multi_transform(
        {"train": inner, "freeze": optax.set_to_zero()}, labels)


def lora_trainable_mask(params) -> Any:
    """True-for-trainable mask over a param tree: LoRA adapters + biases
    train, base ``weight`` leaves freeze.  Feed to ``lora_optimizer`` (or
    build your own multi_transform); pass the result as the engine's
    ``client_optimizer``."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    marks = []
    for path, _ in flat:
        keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        frozen = any(k == "weight" for k in keys)
        marks.append(not frozen)
    return jax.tree_util.tree_unflatten(treedef, marks)


class TiledLinear(nn.Module):
    """y = x @ W split into an (in_splits × out_splits) tile grid.

    Reference: runtime/zero/tiling.py TiledLinear — under ZeRO-3 each tile is
    a separate parameter, so only ONE tile's weight is ever fully gathered at
    a time (peak live weight memory drops from in·out to
    in·out/(in_splits·out_splits)); the tile loop also bounds activation
    scratch for very wide linears.

    TPU shape: tiles are independent flax params carrying the same logical
    axes as a dense kernel (fsdp/tp sharding falls out of partition.py);
    ``remat_tiles=True`` wraps each tile matmul in jax.checkpoint so the
    backward regathers instead of saving — the reference's
    memory-for-compute trade, expressed to XLA."""

    in_features: int
    out_features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    remat_tiles: bool = False
    param_dtype: Any = jnp.float32
    axis_names: Tuple[str, str] = ("embed", "mlp")  # logical (in, out) axes

    @nn.compact
    def __call__(self, x):
        if self.in_features % self.in_splits or \
                self.out_features % self.out_splits:
            raise ValueError(
                f"in/out features ({self.in_features},{self.out_features}) "
                f"must divide the tile grid ({self.in_splits},"
                f"{self.out_splits})")
        tin = self.in_features // self.in_splits
        tout = self.out_features // self.out_splits
        init = nn.initializers.normal(stddev=0.02)

        def tile_mm(xi, w):
            return xi @ w.astype(x.dtype)

        if self.remat_tiles:
            tile_mm = jax.checkpoint(tile_mm)

        outs = []
        for j in range(self.out_splits):
            acc = None
            for i in range(self.in_splits):
                w = self.param(
                    f"tile_{i}_{j}",
                    nn.with_partitioning(init, self.axis_names),
                    (tin, tout), self.param_dtype)
                y = tile_mm(x[..., i * tin:(i + 1) * tin], w)
                acc = y if acc is None else acc + y
            outs.append(acc)
        y = jnp.concatenate(outs, axis=-1)
        if self.use_bias:
            b = self.param("bias",
                           nn.with_partitioning(nn.initializers.zeros,
                                                (self.axis_names[1],)),
                           (self.out_features,), self.param_dtype)
            y = y + b.astype(x.dtype)
        return y
