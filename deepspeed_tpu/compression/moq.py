"""Mixture of Quantization (MoQ) — eigenvalue-adaptive quantization schedule.

Reference: runtime/quantize.py Quantizer (``quantize`` :51 — when
``q_eigenvalue`` is on, each layer's quantization period is stretched by
``factor = 1 + floor(eigenvalue_norm * 4)``, :70) fed by runtime/eigenvalue.py
power iteration; engine hooks at runtime/engine.py:334,2160.

TPU shape: eigenvalues come from runtime/eigenvalue.py (jvp-of-grad power
iteration); the stretched schedule is expressed as per-layer scoped
CompressionSpec overrides, so the whole MoQ schedule still compiles into the
single staged-QDQ program (compression/basic.py scheduled_weight_qdq)."""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, List, Sequence

from deepspeed_tpu.compression.basic import CompressionSpec
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue


def moq_adjusted_specs(specs: Sequence[CompressionSpec],
                       eigenvalues: Dict[str, float],
                       multiplier: int = 4) -> List[CompressionSpec]:
    """Per-layer schedule stretch.

    For every base spec with a halving schedule (quantization_period > 0) and
    every layer path with a normalized eigenvalue r, emit a scoped override
    whose period is ``period * (1 + floor(r * multiplier))`` — high-curvature
    layers quantize later (reference quantize.py:70).  Base specs stay as
    fallbacks for layers without an eigenvalue.
    """
    ratios = Eigenvalue.quantization_ratios(eigenvalues)
    # stretch only the UNSCOPED base specs: prior MoQ overrides are replaced,
    # not compounded, so calling this again (curriculum boundaries) is
    # idempotent in count and period
    base = [s for s in specs if not s.scope]
    out: List[CompressionSpec] = []
    for s in base:
        if s.quantization_period > 0:
            for path, r in ratios.items():
                factor = 1 + math.floor(r * multiplier)
                # "(/|$)" anchors the layer boundary — block_1 must not
                # swallow block_10..19 under first-match-wins
                out.append(dataclasses.replace(
                    s,
                    scope=re.escape(path.replace(".", "/")) + "(/|$)",
                    quantization_period=s.quantization_period * factor))
    out.extend(base)           # fallback for unmatched layers
    return out
