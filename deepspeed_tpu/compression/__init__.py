"""Compression library — staged quantization-aware training + layer reduction
(reference deepspeed/compression/)."""

from deepspeed_tpu.compression.basic import (  # noqa: F401
    CompressionSpec, layer_reduction_init, parse_compression_config,
    scheduled_weight_qdq)
