"""Compression library — staged quantization-aware training, layer reduction,
pruning family, activation quantization (reference deepspeed/compression/)."""

from deepspeed_tpu.compression.basic import (  # noqa: F401
    CompressionSpec, layer_reduction_init, parse_compression_config,
    scheduled_weight_qdq)
from deepspeed_tpu.compression.pruning import (  # noqa: F401
    PruningSpec, parse_activation_quant_config, parse_pruning_config,
    quant_act, scheduled_pruning)
