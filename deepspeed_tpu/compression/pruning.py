"""Pruning family + activation quantization (reference basic_layer.py
LinearLayer_Compress sparse/row/head pruning + QuantAct, config.py
get_sparse_pruning/get_row_pruning/get_head_pruning/
get_activation_quantization).

TPU-native: like the weight-QAT ladder (compression/basic.py), pruning is a
PURE FUNCTION over the param tree applied inside the jitted loss once the
step clock passes the group's ``schedule_offset`` — no module surgery.  The
mask is recomputed from the live weights each step (the reference's l1
method recomputes per forward too), so "pruned" weights stop contributing
and receive zero gradient, letting the survivors recover accuracy.

- sparse (unstructured l1): keep the top ``dense_ratio`` fraction of each
  matching weight by |w|;
- row: keep the top fraction of OUTPUT rows by row L2 norm (structured);
- head: keep the top fraction of attention heads — a head's slice is found
  by the axis whose length equals ``num_heads`` ([H, nh, hd] projections and
  [nh, hd, H] output layouts both work), scored by its L2 norm;
- activation quantization (QuantAct): symmetric dynamic fake-quant on
  activations, exposed as ``quant_act`` for model layers (GPT/BERT wire it
  through their config's ``act_quant_bits``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class PruningSpec:
    """One pruning group (reference different_groups entry)."""

    kind: str                   # "sparse" | "row" | "head"
    pattern: str                # regex over the "/"-joined param path
    dense_ratio: float = 0.5    # fraction KEPT
    schedule_offset: int = 0    # step the mask activates
    num_heads: int = 0          # head pruning only


def parse_pruning_config(cfg: Dict[str, Any],
                         num_heads: int = 0) -> List[PruningSpec]:
    """compression_training.{sparse,row,head}_pruning → specs (reference
    config.py get_*_pruning)."""
    specs: List[PruningSpec] = []
    for kind, key in (("sparse", "sparse_pruning"), ("row", "row_pruning"),
                      ("head", "head_pruning")):
        block = (cfg or {}).get(key) or {}
        shared = block.get("shared_parameters", {})
        if not shared.get("enabled", False):
            continue
        offset = int(shared.get("schedule_offset", 0))
        default_ratio = float(shared.get("dense_ratio", 0.5))
        groups = block.get("different_groups") or {}
        if not groups:
            groups = {"all": {"params": {"dense_ratio": default_ratio},
                              "modules": [".*"]}}
        for g in groups.values():
            ratio = float(g.get("params", {}).get("dense_ratio",
                                                  default_ratio))
            for m in g.get("modules", [".*"]):
                specs.append(PruningSpec(
                    kind=kind, pattern=m, dense_ratio=ratio,
                    schedule_offset=offset,
                    num_heads=int(shared.get("num_heads", num_heads))))
    return specs


def parse_activation_quant_config(cfg: Dict[str, Any]) -> int:
    """→ activation fake-quant bits, or 0 (reference
    get_activation_quantization; 'dynamic' range method is what the
    symmetric per-tensor QDQ here implements).

    One GLOBAL bit-width is supported (the model config carries it into
    every layer); a config asking for per-module activation groups with
    differing bits must FAIL rather than silently apply the first group
    everywhere."""
    block = (cfg or {}).get("activation_quantization") or {}
    shared = block.get("shared_parameters", {})
    if not shared.get("enabled", False):
        return 0
    groups = block.get("different_groups") or {}
    bits_seen = {int(g.get("params", {}).get("bits", 8))
                 for g in groups.values()}
    scoped = [m for g in groups.values()
              for m in g.get("modules", [".*"]) if m != ".*"]
    if len(bits_seen) > 1 or scoped:
        raise NotImplementedError(
            "activation_quantization supports ONE global bit-width (the "
            "model applies it in every attention/MLP input); per-module "
            f"groups are not wired — got bits={sorted(bits_seen)}, "
            f"modules={scoped}")
    if bits_seen:
        return bits_seen.pop()
    return int(shared.get("bits", 8))


def _keep_threshold(scores, dense_ratio):
    """Value s.t. ``dense_ratio`` of scores are >= it (jnp.quantile)."""
    return jnp.quantile(scores.reshape(-1).astype(jnp.float32),
                        1.0 - dense_ratio)


def _sparse_mask(w, ratio):
    a = jnp.abs(w).astype(jnp.float32)
    return (a >= _keep_threshold(a, ratio)).astype(w.dtype)


def _row_mask(w, ratio):
    # output rows: the LAST axis is the output features in the [in, out]
    # convention used across the models' kernel layouts — prune rows of the
    # transposed view, i.e. output channels
    flat = w.reshape(-1, w.shape[-1]).astype(jnp.float32)
    norms = jnp.linalg.norm(flat, axis=0)                  # [out]
    keep = (norms >= _keep_threshold(norms, ratio))
    shape = (1,) * (w.ndim - 1) + (w.shape[-1],)
    return keep.reshape(shape).astype(w.dtype)


def _head_mask(w, ratio, num_heads):
    axis = next((i for i, d in enumerate(w.shape) if d == num_heads), None)
    if axis is None:
        return None
    others = tuple(i for i in range(w.ndim) if i != axis)
    norms = jnp.sqrt(jnp.sum(jnp.square(w.astype(jnp.float32)),
                             axis=others))                 # [nh]
    keep = (norms >= _keep_threshold(norms, ratio))
    shape = tuple(num_heads if i == axis else 1 for i in range(w.ndim))
    return keep.reshape(shape).astype(w.dtype)


def scheduled_pruning(params, specs: Sequence[PruningSpec], step):
    """Apply each group's mask to matching leaves once ``step`` passes its
    offset (step may be traced — jnp.where keeps one compiled program)."""
    if not specs:
        return params
    compiled = [(re.compile(s.pattern), s) for s in specs]

    def visit(path, leaf):
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            return leaf
        name = "/".join(str(getattr(p, "key", getattr(p, "name", p)))
                        for p in path)
        out = leaf
        for rx, s in compiled:
            if not rx.search(name):
                continue
            if s.kind == "sparse":
                mask = _sparse_mask(out, s.dense_ratio)
            elif s.kind == "row":
                mask = _row_mask(out, s.dense_ratio)
            elif s.kind == "head":
                if not s.num_heads:
                    raise ValueError("head pruning needs num_heads (set "
                                     "shared_parameters.num_heads or pass "
                                     "num_heads to parse_pruning_config)")
                mask = _head_mask(out, s.dense_ratio, s.num_heads)
                if mask is None:
                    continue           # leaf has no head axis
            else:
                raise ValueError(f"unknown pruning kind {s.kind!r}")
            out = jnp.where(step >= s.schedule_offset, out * mask, out)
        return out

    return jax.tree_util.tree_map_with_path(visit, params)


def quant_act(x, bits: int):
    """QuantAct (reference basic_layer.py QuantAct, dynamic range): symmetric
    per-tensor fake-quant with a straight-through estimator."""
    if not bits or bits >= 16:
        return x
    levels = 2.0 ** (bits - 1) - 1
    amax = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32))), 1e-6)
    scale = amax / levels
    q = jnp.round(x.astype(jnp.float32) / scale) * scale
    q = q.astype(x.dtype)
    return x + jax.lax.stop_gradient(q - x)
