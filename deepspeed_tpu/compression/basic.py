"""Compression core — scheduled weight QAT + layer reduction.

Reference parity: ``deepspeed/compression/`` — ``init_compression``
(compress.py:44 wires LinearLayer_Compress modules per config group),
``basic_layer.py`` (QuantAct/Embedding/Linear compress layers with staged
bit schedules), ``helper.py`` (layer reduction / student init from teacher
layers; the XTC recipe "extreme compression": 32→8→ternary staged QAT).

TPU-native: no module surgery — compression is a pure function over the param
tree applied inside the jitted loss:

- each config group = (param-path regex, bit schedule); matching leaves get
  straight-through QDQ at the bits the STEP CLOCK dictates (`jnp.where`
  selects the stage in-graph, so one compiled program covers the whole
  schedule — no re-jit at stage boundaries);
- ``layer_reduction_init`` builds a shallower student tree from teacher
  layers (reference compression/helper.py student initialization).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class CompressionSpec:
    """One weight-quantization group (reference config different_groups)."""

    pattern: str               # regex over the "/"-joined param path
    start_bits: int = 8
    target_bits: int = 8
    quantization_period: int = 0   # steps between stage halvings (0 = fixed)
    offset: int = 0                # step when quantization begins
    scope: str = ""            # extra regex that must ALSO match (MoQ
    #                            per-layer overrides, compression/moq.py)

    def stages(self) -> List[Tuple[int, int]]:
        """[(step_threshold, bits)] — start_bits at ``offset``, halving every
        ``quantization_period`` steps down to target_bits (reference
        basic_layer.py Quantizer period schedule; XTC's staged ladder).
        Bits snap to the quantizer's supported grid {≥16: off, 8, 4, 2} —
        reference configs use values like 12/14 that have no blockwise-int
        representation here."""
        def snap(b):
            return b if b >= 16 else (8 if b >= 8 else (4 if b >= 4 else 2))
        out = [(self.offset, snap(self.start_bits))]
        bits, step = self.start_bits, self.offset
        while bits > self.target_bits and self.quantization_period > 0:
            bits = max(bits // 2, self.target_bits)
            step += self.quantization_period
            if snap(bits) != out[-1][1]:
                out.append((step, snap(bits)))
        if self.quantization_period == 0 and \
                self.target_bits != self.start_bits:
            out = [(self.offset, snap(self.target_bits))]
        return out


def parse_compression_config(cfg: Dict[str, Any]) -> List[CompressionSpec]:
    """reference compress.py get_compress_methods: read
    compression_training.weight_quantization.different_groups."""
    wq = (cfg or {}).get("weight_quantization", {})
    shared = wq.get("shared_parameters", {})
    if not shared.get("enabled", bool(wq.get("different_groups"))):
        return []
    specs = []
    for name, group in (wq.get("different_groups") or {}).items():
        p = group.get("params", {})
        modules = group.get("modules", [".*"])
        for m in modules:
            specs.append(CompressionSpec(
                pattern=m,
                start_bits=int(p.get("start_bits", 8)),
                target_bits=int(p.get("target_bits",
                                      p.get("start_bits", 8))),
                quantization_period=int(p.get("quantization_period", 0)),
                offset=int(shared.get("schedule_offset", 0))))
    return specs


def _qdq_ste(w, bits: int, block_size: int = 256):
    from deepspeed_tpu.ops.quantization import quantize_dequantize
    q = quantize_dequantize(w, bits=bits, block_size=block_size)
    return w + jax.lax.stop_gradient(q - w)


def scheduled_weight_qdq(params, specs: Sequence[CompressionSpec], step):
    """Apply each group's staged QDQ to matching leaves.  ``step`` may be a
    traced scalar — stages select via jnp.where so the whole schedule lives
    in one compiled program."""
    if not specs:
        return params
    compiled = [(re.compile(s.pattern),
                 re.compile(s.scope) if s.scope else None,
                 s.stages()) for s in specs]

    def visit(path, leaf):
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            return leaf
        name = "/".join(str(getattr(p, "key", getattr(p, "name", p)))
                        for p in path)
        for rx, scope_rx, stages in compiled:
            if rx.search(name) and (scope_rx is None
                                    or scope_rx.search(name)):
                out = leaf
                for thr, bits in stages:
                    if bits >= 16:       # ≥16 bits ≡ uncompressed on TPU
                        continue
                    # stages() snapped bits to {8,4,2}; 2 = XTC ternary
                    q = _qdq_ste(leaf, bits)
                    out = jnp.where(step >= thr, q, out)
                return out
        return leaf

    return jax.tree_util.tree_map_with_path(visit, params)


def layer_reduction_init(params: Dict[str, Any], keep_layers: Sequence[int],
                         num_layers: int) -> Dict[str, Any]:
    """Student tree from teacher layers (reference compression/helper.py
    student_initialization: copy `teacher_layer` list into consecutive
    student slots; embeddings/head shared)."""
    params = dict(params)
    inner = params.get("params", params)
    bb = dict(inner["backbone"])
    for i, src in enumerate(keep_layers):
        if f"block_{src}" not in bb:
            raise ValueError(f"teacher layer {src} not found")
        bb[f"block_{i}"] = inner["backbone"][f"block_{src}"]
    for j in range(len(keep_layers), num_layers):
        bb.pop(f"block_{j}", None)
    out = dict(inner)
    out["backbone"] = bb
    return {"params": out} if "params" in params else out
