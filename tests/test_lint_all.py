"""Unified lint driver (scripts/lint_all.py).

ONE subprocess run replaces the four separate repo-green lint wirings
(check_no_sync in test_health, check_metrics + the serving check_no_sync
main() run in test_serving_telemetry, and the new check_bench fixture
lint): the driver runs all four in one process and prints a PASS/FAIL
table.  The per-lint violation/behavior tests remain in their original
files as unit tests.
"""

import os
import subprocess
import sys

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
SCRIPT = os.path.join(REPO, "scripts", "lint_all.py")
LINTS = ("check_no_sync", "check_overlap", "check_metrics", "check_bench")


class TestLintAll:
    def test_all_lints_green_in_one_process(self):
        """The repo passes every lint — the single CI wiring for all
        four."""
        r = subprocess.run([sys.executable, SCRIPT],
                           capture_output=True, text=True, timeout=560)
        assert r.returncode == 0, r.stdout + r.stderr
        for lint in LINTS:
            assert lint in r.stdout, r.stdout
        assert r.stdout.count("PASS") >= len(LINTS)
        assert "lints clean" in r.stdout

    def test_only_subset_and_unknown_lint(self):
        """--only runs a subset (no jax compile needed for these two);
        an unknown lint name is a usage error, not a silent pass."""
        r = subprocess.run(
            [sys.executable, SCRIPT, "--only", "check_bench",
             "check_metrics"],
            capture_output=True, text=True, timeout=240)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "check_bench" in r.stdout
        assert "check_no_sync" not in r.stdout.replace(
            "lint_all: unified lint summary", "")
        r = subprocess.run([sys.executable, SCRIPT, "--only", "nope"],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 2
        assert "unknown" in r.stderr

    def test_failure_surfaces_output_and_exit_code(self, tmp_path,
                                                   monkeypatch):
        """A failing lint flips the exit code and prints that lint's
        buffered output (here: check_metrics against a tree with an
        undocumented metric, via a copied driver pointed at a bad
        package)."""
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import check_metrics
        finally:
            sys.path.pop(0)
        bad = tmp_path / "bad.py"
        bad.write_text("def f(reg):\n"
                       "    reg.counter('totally_undocumented_total', 'h')\n")
        sites, errors = check_metrics.collect_sites(str(tmp_path))
        assert not errors
        violations = check_metrics.check(sites, doc_text="")
        assert violations  # the unit hook lint_all relies on still bites
