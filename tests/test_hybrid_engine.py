"""Hybrid engine (RLHF train↔generate) tests — reference pattern:
tests/unit/hybrid_engine/test_he_*.py (generate matches, weights track
training)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT, GPTConfig


@pytest.fixture(scope="module")
def setup():
    cfg = GPTConfig.tiny(vocab_size=96, max_seq_len=64)
    config = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 5e-2}},
        "mesh": {"dp": 1},
        "steps_per_print": 0,
        "hybrid_engine": {"enabled": True},
    }
    rng = np.random.default_rng(0)
    pool = rng.integers(0, 96, size=(8, 64)).astype(np.int32)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT(cfg), config=config, example_batch={"input_ids": pool})
    return cfg, engine, pool


class TestHybridEngine:
    def test_generate_matches_standalone_v2(self, setup, rng):
        """Hybrid rollouts must be token-exact vs a fresh v2 engine given the
        same weights (the relayout is exact, reference he_all tests)."""
        from deepspeed_tpu.inference.v2 import InferenceEngineV2

        cfg, engine, _ = setup
        hybrid = engine.hybrid_engine(
            {"dtype": "fp32", "generation": {"do_sample": False},
             "state_manager": {"max_tracked_sequences": 4,
                               "kv_block_size": 8}})
        prompts = [rng.integers(0, 96, size=n).astype(np.int32)
                   for n in (7, 12)]
        got = hybrid.generate(prompts, max_new_tokens=8)

        fresh = InferenceEngineV2(
            cfg, {"dtype": "fp32", "generation": {"do_sample": False},
                  "state_manager": {"max_tracked_sequences": 4,
                                    "kv_block_size": 8}},
            params=hybrid._train_params())
        want = fresh.generate(prompts, max_new_tokens=8)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def test_weights_resync_after_training(self, setup, rng):
        """Training between generate phases must change rollouts (the bridge
        re-syncs on the step clock)."""
        cfg, engine, pool = setup
        hybrid = engine.hybrid_engine()
        prompts = [rng.integers(0, 96, size=10).astype(np.int32)]
        before = hybrid.generate(prompts, max_new_tokens=12, do_sample=False)
        step0 = hybrid._synced_step
        for _ in range(30):
            engine.train_batch({"input_ids": pool})
        after = hybrid.generate(prompts, max_new_tokens=12, do_sample=False)
        assert hybrid._synced_step > step0
        assert not np.array_equal(before[0], after[0])

    def test_requires_gpt_family(self):
        class Fake:
            pass
        from deepspeed_tpu.runtime.hybrid_engine import HybridEngine
        fake_engine = type("E", (), {"model": Fake(), "config": None,
                                     "global_steps": 0})()
        with pytest.raises(TypeError, match="GPT-family"):
            HybridEngine(fake_engine)
